"""Graph IR + pass system (reference: paddle/fluid/framework/ir/ —
Pass base/registry ir/pass.h:38,184,273; GraphPatternDetector
ir/graph_pattern_detector.h; 85 REGISTER_PASS'd passes, Appendix B of
SURVEY.md).

TPU inversion: the reference needs its pass zoo because an interpreted op
loop can't fuse or plan memory — every fusion must be materialised as a
graph rewrite into a hand-written fused kernel, and every memory/schedule
decision as a pass. On this build XLA owns fusion, layout, scheduling and
memory planning for everything inside the jitted step, so the pass system
has two jobs only:

1. *Program-level* rewrites that change which ops get traced — useful to
   shrink trace size, canonicalise inference programs (fold BN into conv
   weights, drop dropout, strip fake-quant), and exercise the same fused
   ops serialized reference inference programs contain.
2. API parity: `Graph`, `Pass`, `PassManager`, `get_pass`, and the
   registered pass-name namespace, so tooling written against the
   reference keeps working. Passes whose capability is absorbed by XLA
   (memory reuse, op scheduling, mkldnn/cudnn placement) are registered
   as documented no-ops.

Pattern matching is a small backtracking DAG matcher over op nodes
(`OpPattern`) rather than the reference's PDNode/PDPattern machinery —
programs here are metadata-only and small, so exhaustive matching is fine.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "Graph", "IrGraph", "Pass", "PassManager", "register_pass", "get_pass",
    "all_registered_passes", "apply_inference_passes",
    "BlockSegment", "analyze_block_segments", "op_island_reason",
    "segment_summary",
]


# --------------------------------------------------------------------------
# Graph: a live view over one Program block
# --------------------------------------------------------------------------
class Graph:
    """Op/var graph over ``program``'s block ``idx`` (reference ir/graph.h:
    nodes are OpDesc/VarDesc; here the Operator/Variable objects themselves
    are the nodes and the block stays the source of truth, so a Graph is
    always convertible back to a Program for free — the reference needs an
    explicit graph_to_program_pass)."""

    def __init__(self, program, idx: int = 0, for_test: bool = False):
        self.program = program
        self.block = program.block(idx)
        self.for_test = for_test
        self._attrs: Dict[str, Any] = {}

    # -- nodes ------------------------------------------------------------
    def all_op_nodes(self):
        return list(self.block.ops)

    def all_var_nodes(self):
        return list(self.block.vars.values())

    def op_index(self, op) -> int:
        return self.block.ops.index(op)

    # -- dataflow ---------------------------------------------------------
    def var_producer(self, name: str, before: Optional[int] = None):
        """Last op writing ``name`` (before position ``before`` if given)."""
        ops = self.block.ops if before is None else self.block.ops[:before]
        for op in reversed(ops):
            if name in op.output_arg_names:
                return op
        return None

    def var_consumers(self, name: str) -> List:
        return [op for op in self.block.ops if name in op.input_arg_names]

    def is_internal(self, name: str) -> bool:
        """True if ``name`` is a pure intermediate: produced AND consumed
        here, not persistable. Consumer-less outputs may be fetch targets
        (the fetch list isn't part of the program), so they are never
        internal — the reference guards these as graph outputs."""
        v = self.block.vars.get(name)
        if v is None:
            return False
        if getattr(v, "persistable", False):
            return False
        if name in self.get("protected_vars", ()):
            return False  # fetch targets named by the caller
        if self.var_producer(name) is None:
            return False
        return len(self.var_consumers(name)) > 0

    # -- mutation ---------------------------------------------------------
    def insert_op_at(self, index: int, type: str, inputs, outputs, attrs):
        from .framework import Operator
        op = Operator(self.block, type, inputs=inputs, outputs=outputs,
                      attrs=attrs)
        self.block.ops.insert(index, op)
        self.program._version += 1
        return op

    def remove_ops(self, ops: Sequence) -> None:
        dead = set(id(o) for o in ops)
        self.block.ops = [o for o in self.block.ops if id(o) not in dead]
        self.program._version += 1

    def fuse(self, matched_ops: Sequence, type: str, inputs, outputs,
             attrs) -> Any:
        """Replace ``matched_ops`` with one op of ``type`` placed at the
        position of the LAST matched op (all inputs are defined by then;
        consumers of the fused output come later) — the standard rewrite
        step of every fusion pass."""
        pos = max(self.op_index(o) for o in matched_ops)
        new_op = self.insert_op_at(pos + 1, type, inputs, outputs, attrs)
        self.remove_ops(matched_ops)
        return new_op

    def drop_orphan_vars(self) -> int:
        """Remove non-persistable vars that no op reads or writes."""
        used = set()
        for op in self.block.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
        dead = [n for n, v in self.block.vars.items()
                if n not in used and not getattr(v, "persistable", False)
                and not getattr(v, "is_data", False)]
        for n in dead:
            del self.block.vars[n]
        return len(dead)

    # -- attrs (reference Pass::Set/Get) ----------------------------------
    def set(self, key: str, val: Any):
        self._attrs[key] = val

    def get(self, key: str, default: Any = None):
        return self._attrs.get(key, default)

    def to_program(self):
        return self.program


# Alias used by the slim/quantization surface (reference pybind IrGraph).
IrGraph = Graph


# --------------------------------------------------------------------------
# Pattern matching
# --------------------------------------------------------------------------
class OpPattern:
    """A DAG of op specs with symbolic var links.

    Each spec is ``(op_type, input_links, output_links)`` where links map
    slot name -> "$sym" (or a list of "$sym"). Two specs sharing a symbol
    are connected through that var. ``match()`` yields dicts
    ``{"$sym": var_name, "#i": op}`` for each non-overlapping match, in
    program order. Symbols appearing as one spec's output and another's
    input are required to be *internal* single-consumer vars unless listed
    in ``shared`` (the reference expresses this with
    AsIntermediate() — graph_pattern_detector.h)."""

    def __init__(self, specs, shared: Sequence[str] = ()):
        self.specs = specs
        self.shared = set(shared)
        produced = set()
        consumed = set()
        for _, ins, outs in specs:
            for v in self._syms(ins):
                consumed.add(v)
            for v in self._syms(outs):
                produced.add(v)
        self.intermediate = (produced & consumed) - self.shared

    @staticmethod
    def _syms(links):
        for v in (links or {}).values():
            if isinstance(v, (list, tuple)):
                yield from v
            else:
                yield v

    def _bind(self, op, links, env) -> Optional[Dict[str, str]]:
        """Try binding one op's slots against symbolic links."""
        new = {}
        slots_of = {True: op.inputs, False: op.outputs}
        for is_in, side in ((True, links[0]), (False, links[1])):
            for slot, sym in (side or {}).items():
                names = slots_of[is_in].get(slot, [])
                syms = sym if isinstance(sym, (list, tuple)) else [sym]
                if len(names) != len(syms):
                    return None
                for s, n in zip(syms, names):
                    bound = env.get(s, new.get(s))
                    if bound is None:
                        new[s] = n
                    elif bound != n:
                        return None
        return new

    def match(self, graph: Graph):
        ops = graph.all_op_nodes()
        taken: set = set()
        results = []
        first_type = self.specs[0][0]
        for anchor in ops:
            if anchor.type != first_type or id(anchor) in taken:
                continue
            env: Dict[str, Any] = {}
            chosen: List = []

            def try_specs(i) -> bool:
                if i == len(self.specs):
                    return True
                op_type, ins, outs = self.specs[i]
                cands = [anchor] if i == 0 else [
                    o for o in ops
                    if o.type == op_type and id(o) not in taken
                    and o not in chosen]
                for cand in cands:
                    new = self._bind(cand, (ins, outs), env)
                    if new is None:
                        continue
                    env.update(new)
                    chosen.append(cand)
                    if try_specs(i + 1):
                        return True
                    chosen.pop()
                    for k in new:
                        env.pop(k, None)
                return False

            if not try_specs(0):
                continue
            # intermediates must be single-consumer internal vars
            ok = True
            for sym in self.intermediate:
                name = env[sym]
                if not graph.is_internal(name):
                    ok = False
                    break
                cons = graph.var_consumers(name)
                if len(cons) != 1 or cons[0] not in chosen:
                    ok = False
                    break
            if not ok:
                continue
            for o in chosen:
                taken.add(id(o))
            m = dict(env)
            for i, o in enumerate(chosen):
                m[f"#{i}"] = o
            m["#ops"] = list(chosen)
            results.append(m)
        return results


# --------------------------------------------------------------------------
# Pass base + registry
# --------------------------------------------------------------------------
class Pass:
    """reference ir/pass.h:38 — apply(graph) -> graph, with Set/Get attrs
    (param scope etc.)."""

    name = "pass"
    note = ""

    def __init__(self):
        self._attrs: Dict[str, Any] = {}

    def set(self, key: str, val: Any) -> "Pass":
        self._attrs[key] = val
        return self

    def get(self, key: str, default=None):
        return self._attrs.get(key, default)

    def apply(self, graph: Graph) -> Graph:
        graph = self.apply_impl(graph)
        graph.drop_orphan_vars()
        return graph

    def apply_impl(self, graph: Graph) -> Graph:
        return graph


_PASS_REGISTRY: Dict[str, Callable[[], Pass]] = {}


def register_pass(name: str):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return deco


def get_pass(name: str) -> Pass:
    try:
        return _PASS_REGISTRY[name]()
    except KeyError:
        raise ValueError(f"ir pass '{name}' is not registered") from None


def all_registered_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


class PassManager:
    """Ordered pass pipeline (reference inference/analysis/ir_pass_manager.cc
    + pybind PassBuilder)."""

    def __init__(self, names: Sequence[str], scope=None):
        self.passes = [get_pass(n) for n in names]
        self.scope = scope

    def apply(self, program, idx: int = 0, for_test: bool = False,
              protected: Sequence[str] = ()):
        """``protected``: var names the caller will fetch — the fetch list
        is not part of the program, so passes must be told which outputs
        may not be fused away (the reference protects these as graph
        outputs in each pass's subgraph detector)."""
        graph = Graph(program, idx, for_test=for_test)
        graph.set("protected_vars", set(protected))
        for p in self.passes:
            if self.scope is not None:
                p.set("param_scope", self.scope)
            graph = p.apply(graph)
        return graph.to_program()


# --------------------------------------------------------------------------
# Helper: scope param access for weight-folding passes
# --------------------------------------------------------------------------
def _scope_get(scope, name: str) -> Optional[np.ndarray]:
    var = scope.find_var(name)
    if var is None:
        return None
    return np.asarray(var.get_tensor().array)


def _scope_set(scope, name: str, arr: np.ndarray) -> None:
    scope.var(name).get_tensor().set(np.ascontiguousarray(arr))


# --------------------------------------------------------------------------
# Real passes
# --------------------------------------------------------------------------
@register_pass("is_test_pass")
class IsTestPass(Pass):
    """Set is_test=True on every op carrying the attr (ir/is_test_pass.cc)."""

    def apply_impl(self, graph):
        for op in graph.all_op_nodes():
            if "is_test" in op.attrs:
                op.attrs["is_test"] = True
        return graph


@register_pass("simplify_with_basic_ops_pass")
class SimplifyWithBasicOpsPass(Pass):
    """Inference canonicalisation (ir/simplify_with_basic_ops_pass.cc):
    dropout(is_test) becomes identity (upscale_in_train) or scale(1-p)."""

    def apply_impl(self, graph):
        for op in list(graph.all_op_nodes()):
            if op.type != "dropout":
                continue
            if not op.attr("is_test"):
                continue  # the reference only simplifies is_test dropouts
            x = op.input("X")[0]
            y = op.output("Out")[0]
            impl = op.attr("dropout_implementation") or "downgrade_in_infer"
            if impl == "upscale_in_train":
                graph.fuse([op], "assign", {"X": [x]}, {"Out": [y]}, {})
            else:
                p = float(op.attr("dropout_prob") or 0.0)
                graph.fuse([op], "scale", {"X": [x]}, {"Out": [y]},
                           {"scale": 1.0 - p, "bias": 0.0,
                            "bias_after_scale": True})
        return graph


@register_pass("identity_scale_op_clean_pass")
class IdentityScaleOpCleanPass(Pass):
    """Drop scale(scale=1, bias=0) ops, rewiring consumers
    (ir/identity_scale_op_clean_pass.cc)."""

    def apply_impl(self, graph):
        for op in list(graph.all_op_nodes()):
            if op.type != "scale":
                continue
            if op.input("ScaleTensor"):
                continue
            s = op.attr("scale")
            b = op.attr("bias")
            if float(1.0 if s is None else s) != 1.0 or \
               float(0.0 if b is None else b) != 0.0:
                continue
            x, y = op.input("X")[0], op.output("Out")[0]
            if not graph.is_internal(y):
                continue  # output is fetched/persistable: keep the copy
            for c in graph.var_consumers(y):
                c._rename_input(y, x)
            graph.remove_ops([op])
        return graph


@register_pass("delete_quant_dequant_op_pass")
class DeleteQuantDequantOpPass(Pass):
    """Strip fake quant/dequant ops for deployment
    (ir/delete_quant_dequant_op_pass.cc)."""

    _TYPES = ("fake_quantize_dequantize_moving_average_abs_max",
              "fake_quantize_dequantize_abs_max")

    def apply_impl(self, graph):
        for op in list(graph.all_op_nodes()):
            if op.type not in self._TYPES:
                continue
            x, y = op.input("X")[0], op.output("Out")[0]
            consumers = graph.var_consumers(y)
            if graph.is_internal(y):
                for c in consumers:
                    c._rename_input(y, x)
                graph.remove_ops([op])
            else:
                graph.fuse([op], "assign", {"X": [x]}, {"Out": [y]}, {})
        return graph


@register_pass("fc_fuse_pass")
class FcFusePass(Pass):
    """mul + elementwise_add -> fc, optionally absorbing a following relu
    into activation_type (ir/fc_fuse_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("mul", {"X": "$x", "Y": "$w"}, {"Out": "$mm"}),
            ("elementwise_add", {"X": "$mm", "Y": "$b"}, {"Out": "$out"}),
        ])
        for m in pat.match(graph):
            mul_op = m["#0"]
            bias = graph.block._find_var_recursive(m["$b"])
            if bias is None or not getattr(bias, "persistable", False):
                continue  # fc requires a real bias parameter
            if int(mul_op.attr("y_num_col_dims") or 1) != 1:
                continue
            matched = list(m["#ops"])
            out_name = m["$out"]
            act = ""
            consumers = graph.var_consumers(out_name)
            if (len(consumers) == 1 and consumers[0].type == "relu"
                    and graph.is_internal(out_name)):
                act_op = consumers[0]
                matched.append(act_op)
                out_name = act_op.output("Out")[0]
                act = "relu"
            graph.fuse(matched, "fc",
                       {"Input": [m["$x"]], "W": [m["$w"]], "Bias": [m["$b"]]},
                       {"Out": [out_name]},
                       {"in_num_col_dims":
                        int(mul_op.attr("x_num_col_dims") or 1),
                        "activation_type": act})
        return graph


class _FcRecurrentFuseBase(Pass):
    """Shared rewrite for fc_gru/fc_lstm fusion (ir/fc_gru_fuse_pass.cc,
    ir/fc_lstm_fuse_pass.cc): the input projection mul(X, Wx) feeding a
    LoD recurrence becomes the fused op's WeightX leg, and the mul's
    output IS the fused op's XX output — so consumers of either name
    keep resolving and the wire shape matches the reference's fused
    inference graphs. The fc-with-bias variant is left unfused (folding
    the fc bias into the recurrence bias would need scope rewriting)."""

    _recur_type = None      # "dynamic_gru" / "dynamic_lstm"
    _fused_type = None      # "fusion_gru" / "fusion_lstm"
    _extra_outs = ()        # extra recurrence outputs to carry over
    _attr_names = ()

    def apply_impl(self, graph):
        pat = OpPattern([
            ("mul", {"X": "$x", "Y": "$wx"}, {"Out": "$xx"}),
            (self._recur_type, {"Input": "$xx", "Weight": "$wh"},
             {"Hidden": "$h"}),
        ])
        for m in pat.match(graph):
            mul_op, rec_op = m["#0"], m["#1"]
            if int(mul_op.attr("x_num_col_dims") or 1) != 1 or \
                    int(mul_op.attr("y_num_col_dims") or 1) != 1:
                continue
            # $xx internality/single-consumer is guaranteed by the
            # matcher (OpPattern intermediates)
            inputs = {"X": [m["$x"]], "WeightX": [m["$wx"]],
                      "WeightH": [m["$wh"]]}
            for slot in ("Bias", "H0", "C0"):
                names = rec_op.input(slot)
                if names:
                    inputs[slot] = list(names)
            outputs = {"Hidden": [m["$h"]], "XX": [m["$xx"]]}
            for slot in self._extra_outs:
                names = rec_op.output(slot)
                if names:
                    outputs[slot] = list(names)
            attrs = {k: rec_op.attr(k) for k in self._attr_names
                     if rec_op.attr(k) is not None}
            graph.fuse([mul_op, rec_op], self._fused_type,
                       inputs, outputs, attrs)
        return graph


@register_pass("fc_gru_fuse_pass")
class FcGruFusePass(_FcRecurrentFuseBase):
    """mul + dynamic_gru -> fusion_gru (ir/fc_gru_fuse_pass.cc)."""
    _recur_type = "dynamic_gru"
    _fused_type = "fusion_gru"
    _attr_names = ("is_reverse", "origin_mode", "gate_activation",
                   "activation")


@register_pass("fc_lstm_fuse_pass")
class FcLstmFusePass(_FcRecurrentFuseBase):
    """mul + dynamic_lstm -> fusion_lstm (ir/fc_lstm_fuse_pass.cc)."""
    _recur_type = "dynamic_lstm"
    _fused_type = "fusion_lstm"
    _extra_outs = ("Cell",)
    _attr_names = ("use_peepholes", "is_reverse", "gate_activation",
                   "cell_activation", "candidate_activation")


@register_pass("seq_concat_fc_fuse_pass")
class SeqConcatFcFusePass(Pass):
    """sequence_expand(x_i, ref) fan-in + concat(axis=1) + fc ->
    fusion_seqexpand_concat_fc (ir/seq_concat_fc_fuse_pass.cc) — the
    reference's fused attention-input block: per-sequence rows broadcast
    to each timestep of the reference sequence, concatenated, projected
    through one fc."""

    _ACTS = {"relu", "tanh", "sigmoid"}

    def apply_impl(self, graph):
        for concat in [op for op in graph.block.ops
                       if op.type == "concat"]:
            xs = list(concat.input("X"))
            if len(xs) < 2 or concat.input("AxisTensor"):
                continue
            if int(concat.attr("axis") or 0) != 1:
                continue
            ref = xs[0]  # the LoD sequence every expand broadcasts to
            expands, raw = [], []
            ok = True
            for n in xs[1:]:
                prods = [op for op in graph.block.ops
                         if n in op.output("Out")
                         and op.type == "sequence_expand"]
                if len(prods) != 1 or not graph.is_internal(n) \
                        or len(graph.var_consumers(n)) != 1 \
                        or prods[0].input("Y") != [ref]:
                    ok = False
                    break
                expands.append(prods[0])
                raw.append(prods[0].input("X")[0])
            cat_out = concat.output("Out")[0]
            consumers = graph.var_consumers(cat_out)
            if not ok or not expands or len(consumers) != 1 \
                    or consumers[0].type != "mul" \
                    or not graph.is_internal(cat_out):
                continue
            mul = consumers[0]
            if int(mul.attr("x_num_col_dims") or 1) != 1:
                continue
            matched = expands + [concat, mul]
            out_name = mul.output("Out")[0]
            bias = None
            act = "identity"
            nxt = graph.var_consumers(out_name)
            if len(nxt) == 1 and nxt[0].type == "elementwise_add" \
                    and graph.is_internal(out_name):
                bv = graph.block._find_var_recursive(nxt[0].input("Y")[0])
                if bv is not None and getattr(bv, "persistable", False):
                    bias = nxt[0].input("Y")[0]
                    matched.append(nxt[0])
                    out_name = nxt[0].output("Out")[0]
                    after = graph.var_consumers(out_name)
                    if len(after) == 1 and after[0].type in self._ACTS \
                            and graph.is_internal(out_name):
                        act = after[0].type
                        matched.append(after[0])
                        out_name = after[0].output("Out")[0]
            inputs = {"X": [ref] + raw,
                      "FCWeight": [mul.input("Y")[0]]}
            if bias is not None:
                inputs["FCBias"] = [bias]
            graph.fuse(matched, "fusion_seqexpand_concat_fc",
                       inputs, {"Out": [out_name]},
                       {"fc_activation": act})
        return graph


@register_pass("seqconv_eltadd_relu_fuse_pass")
class SeqconvEltaddReluFusePass(Pass):
    """sequence_conv + elementwise_add(bias) + relu ->
    fusion_seqconv_eltadd_relu (ir/seqconv_eltadd_relu_fuse_pass.cc) —
    the reference's fused CTR text-conv inference block."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("sequence_conv", {"X": "$x", "Filter": "$f"}, {"Out": "$c"}),
            ("elementwise_add", {"X": "$c", "Y": "$b"}, {"Out": "$cb"}),
            ("relu", {"X": "$cb"}, {"Out": "$out"}),
        ])
        for m in pat.match(graph):
            conv = m["#0"]
            bias = graph.block._find_var_recursive(m["$b"])
            if bias is None or not getattr(bias, "persistable", False):
                continue
            graph.fuse(list(m["#ops"]), "fusion_seqconv_eltadd_relu",
                       {"X": [m["$x"]], "Filter": [m["$f"]],
                        "Bias": [m["$b"]]},
                       {"Out": [m["$out"]]},
                       {k: conv.attr(k) for k in
                        ("contextLength", "contextStart", "contextStride")
                        if conv.attr(k) is not None})
        return graph


@register_pass("seqpool_concat_fuse_pass")
class SeqpoolConcatFusePass(Pass):
    """N sequence_pool (same SUM/AVERAGE pooltype) feeding one concat ->
    fusion_seqpool_concat (ir/seqpool_concat_fuse_pass.cc). Hand-rolled
    matching: the shape is a FAN-IN (N parallel producers into one
    consumer), which the chain-based OpPattern doesn't express."""

    def apply_impl(self, graph):
        for concat in [op for op in graph.block.ops
                       if op.type == "concat"]:
            xs = list(concat.input("X"))
            if len(xs) < 2:
                continue
            axis = concat.attr("axis")
            if axis is None or int(axis) != 1:
                continue  # the fused kernel concats pooled FEATURES
            if concat.input("AxisTensor"):
                continue  # runtime axis can't fold into a static attr
            pools = []
            ptype = None
            ok = True
            for n in xs:
                prods = [op for op in graph.block.ops
                         if n in op.output("Out")
                         and op.type == "sequence_pool"]
                if len(prods) != 1 or not graph.is_internal(n) \
                        or len(graph.var_consumers(n)) != 1:
                    ok = False
                    break
                p = prods[0]
                pt = (p.attr("pooltype") or "SUM").upper()
                if pt not in ("SUM", "AVERAGE") or \
                        (ptype is not None and pt != ptype):
                    ok = False
                    break
                if float(p.attr("pad_value") or 0.0) != 0.0:
                    # empty sequences pool to pad_value; the fused
                    # kernel has no pad_value leg
                    ok = False
                    break
                ptype = pt
                pools.append(p)
            if not ok or not pools:
                continue
            graph.fuse(pools + [concat], "fusion_seqpool_concat",
                       {"X": [p.input("X")[0] for p in pools]},
                       {"Out": [concat.output("Out")[0]]},
                       {"pooltype": ptype, "axis": 1})
        return graph


@register_pass("fuse_elewise_add_act_pass")
class FuseElewiseAddActPass(Pass):
    """elementwise_add + {relu,tanh,sigmoid,scale} ->
    fused_elemwise_activation (ir/fuse_elewise_add_act_pass.cc). Training-
    safe: the fused op registers grads via jax.vjp."""

    _ACTS = ("relu", "tanh", "sigmoid", "scale")

    def apply_impl(self, graph):
        for act in self._ACTS:
            pat = OpPattern([
                ("elementwise_add", {"X": "$x", "Y": "$y"}, {"Out": "$mid"}),
                (act, {"X": "$mid"}, {"Out": "$out"}),
            ])
            for m in pat.match(graph):
                add_op, act_op = m["#0"], m["#1"]
                if int(add_op.attr("axis") if add_op.attr("axis") is not None
                       else -1) != -1:
                    continue
                functor = act
                attrs = {"functor_list": [functor, "elementwise_add"],
                         "axis": -1, "save_intermediate_out": False}
                if act == "scale":
                    if act_op.input("ScaleTensor"):
                        continue  # runtime scale can't fold into an attr
                    b = act_op.attr("bias")
                    if float(0.0 if b is None else b) != 0.0:
                        continue
                    s = act_op.attr("scale")
                    attrs["scale"] = float(1.0 if s is None else s)
                inter = graph.block.create_var(
                    name=m["$out"] + ".fused_intermediate")
                graph.fuse(m["#ops"], "fused_elemwise_activation",
                           {"X": [m["$x"]], "Y": [m["$y"]]},
                           {"Out": [m["$out"]],
                            "IntermediateOut": [inter.name]}, attrs)
        return graph


@register_pass("fuse_bn_act_pass")
class FuseBnActPass(Pass):
    """batch_norm + relu -> fused_batch_norm_act (ir/fuse_bn_act_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("batch_norm",
             {"X": "$x", "Scale": "$scale", "Bias": "$bias",
              "Mean": "$mean", "Variance": "$var"},
             {"Y": "$y"}),
            ("relu", {"X": "$y"}, {"Out": "$out"}),
        ])
        for m in pat.match(graph):
            bn = m["#0"]
            outs = {"Y": [m["$out"]]}
            for slot in ("MeanOut", "VarianceOut", "SavedMean",
                         "SavedVariance", "ReserveSpace"):
                names = bn.output(slot)
                if names:
                    outs[slot] = names
            attrs = {k: bn.attr(k) for k in
                     ("momentum", "epsilon", "data_layout", "is_test",
                      "use_global_stats") if bn.attr(k) is not None}
            attrs["act_type"] = "relu"
            graph.fuse(m["#ops"], "fused_batch_norm_act",
                       {"X": [m["$x"]], "Scale": [m["$scale"]],
                        "Bias": [m["$bias"]], "Mean": [m["$mean"]],
                        "Variance": [m["$var"]]}, outs, attrs)
        return graph


@register_pass("multihead_matmul_fuse_pass_v2")
class MultiheadMatmulFusePassV2(Pass):
    """Generic BERT/ERNIE attention subgraph → one ``multihead_matmul`` op
    (reference: ir/multihead_matmul_fuse_pass.cc:435 MultiHeadMatmulV2FusePass,
    pattern ops per MultiHeadMatmulPattern at :235).

    Matches the 19-op decomposed attention that a reference-serialized
    transformer program carries — three mul/elementwise_add/reshape2/
    transpose2 projection chains, the Q-side scale, QKᵀ matmul, +BiasQK,
    softmax, PV matmul, and the transpose2+reshape2 head merge — packs
    Wq/Wk/Wv into the combined [N, 3, H·D] weight and [3, H·D] bias the
    fused op takes (reference packing: multihead_matmul_fuse_pass.cc:470),
    and rewrites the whole subgraph to one op, which this framework then
    dispatches onto the fused XLA attention path. Requires ``param_scope``
    for the weight packing."""

    _PAT = OpPattern([
        # Q path (the branch carrying the softmax scale)
        ("mul", {"X": "$x", "Y": "$wq"}, {"Out": "$q_mm"}),
        ("elementwise_add", {"X": "$q_mm", "Y": "$bq"}, {"Out": "$q_add"}),
        ("reshape2", {"X": "$q_add"}, {"Out": "$q_rs"}),
        ("transpose2", {"X": "$q_rs"}, {"Out": "$q_tr"}),
        ("scale", {"X": "$q_tr"}, {"Out": "$q_sc"}),
        # K path
        ("mul", {"X": "$x", "Y": "$wk"}, {"Out": "$k_mm"}),
        ("elementwise_add", {"X": "$k_mm", "Y": "$bk"}, {"Out": "$k_add"}),
        ("reshape2", {"X": "$k_add"}, {"Out": "$k_rs"}),
        ("transpose2", {"X": "$k_rs"}, {"Out": "$k_tr"}),
        # V path
        ("mul", {"X": "$x", "Y": "$wv"}, {"Out": "$v_mm"}),
        ("elementwise_add", {"X": "$v_mm", "Y": "$bv"}, {"Out": "$v_add"}),
        ("reshape2", {"X": "$v_add"}, {"Out": "$v_rs"}),
        ("transpose2", {"X": "$v_rs"}, {"Out": "$v_tr"}),
        # attention core
        ("matmul", {"X": "$q_sc", "Y": "$k_tr"}, {"Out": "$qk"}),
        ("elementwise_add", {"X": "$qk", "Y": "$mask"}, {"Out": "$qk_b"}),
        ("softmax", {"X": "$qk_b"}, {"Out": "$attn"}),
        ("matmul", {"X": "$attn", "Y": "$v_tr"}, {"Out": "$ctx"}),
        ("transpose2", {"X": "$ctx"}, {"Out": "$ctx_tr"}),
        ("reshape2", {"X": "$ctx_tr"}, {"Out": "$out"}),
    ])

    def apply_impl(self, graph):
        scope = self.get("param_scope")
        if scope is None:
            return graph  # weight packing needs the parameters
        dead_candidates = set()
        for m in self._PAT.match(graph):
            qk_op, pv_op = m["#13"], m["#16"]
            if not qk_op.attr("transpose_Y") or pv_op.attr("transpose_Y"):
                continue
            # a structurally matching subgraph with different perm/axis
            # attrs would be silently mis-fused — gate on the exact
            # head-split/merge layout the fused op implements
            # (reference: multihead_matmul_fuse_pass.cc pattern attrs)
            if any(list(m[f"#{i}"].attr("axis") or []) != [0, 2, 1, 3]
                   for i in (3, 8, 12, 17)):
                continue
            sm_axis = m["#15"].attr("axis")
            if sm_axis is not None and int(sm_axis) not in (-1, 3):
                continue
            mask_axis = m["#14"].attr("axis")
            if mask_axis is not None and int(mask_axis) not in (-1, 0):
                continue
            scale_op = m["#4"]
            sb = scale_op.attr("bias")
            if float(0.0 if sb is None else sb) != 0.0:
                continue
            alpha = float(scale_op.attr("scale") or 1.0) \
                * float(qk_op.attr("alpha") or 1.0)
            rs_shape = m["#2"].attr("shape") or []
            if len(rs_shape) != 4:
                continue
            head_number = int(rs_shape[2])
            wq, wk, wv = (_scope_get(scope, m[s])
                          for s in ("$wq", "$wk", "$wv"))
            bq, bk, bv = (_scope_get(scope, m[s])
                          for s in ("$bq", "$bk", "$bv"))
            if any(a is None for a in (wq, wk, wv, bq, bk, bv)):
                continue
            comb_w = np.stack([wq, wk, wv], axis=1)          # [N, 3, H·D]
            comb_b = np.stack([bq.reshape(-1), bk.reshape(-1),
                               bv.reshape(-1)], axis=0)      # [3, H·D]
            w_name = m["$out"] + ".multihead_w"
            b_name = m["$out"] + ".multihead_bias"
            graph.block.create_var(name=w_name, shape=list(comb_w.shape),
                                   dtype="float32", persistable=True)
            graph.block.create_var(name=b_name, shape=list(comb_b.shape),
                                   dtype="float32", persistable=True)
            _scope_set(scope, w_name, comb_w)
            _scope_set(scope, b_name, comb_b)
            graph.fuse(m["#ops"], "multihead_matmul",
                       {"Input": [m["$x"]], "W": [w_name],
                        "Bias": [b_name], "BiasQK": [m["$mask"]]},
                       {"Out": [m["$out"]]},
                       {"alpha": alpha, "head_number": head_number,
                        "transpose_Q": False, "transpose_K": True,
                        "transpose_V": False})
            dead_candidates.update(
                m[s] for s in ("$wq", "$wk", "$wv", "$bq", "$bk", "$bv"))
        if dead_candidates:
            # the per-branch weights are dead after packing (reference
            # erases them) — one usage sweep after all rewrites, then
            # drop any candidate no remaining op reads
            still_used = set()
            for op in graph.block.ops:
                still_used.update(op.input_arg_names)
            for name in dead_candidates - still_used:
                scope.erase(name)
                graph.block.vars.pop(name, None)
        return graph


@register_pass("multihead_matmul_fuse_pass")
class MultiheadMatmulFusePass(MultiheadMatmulFusePassV2):
    """v1 name; same semantic subgraph on this framework (reference v1
    matched an older stack-based emission — ir/multihead_matmul_fuse_pass.cc:46)."""


class _ConvBnFoldBase(Pass):
    """Shared weight-folding logic for the conv+bn family. Requires
    ``param_scope`` (reference passes fetch it with
    Get<Scope>(kParamScopeAttr)); numerical folding happens eagerly on the
    host exactly like conv_bn_fuse_pass.cc:ConvBNFuser."""

    eltwise_before_bn = False

    def _fold(self, graph, conv, bn, extra_bias_name=None):
        scope = self.get("param_scope")
        if scope is None:
            return False
        w = _scope_get(scope, conv.input("Filter")[0])
        scale = _scope_get(scope, bn.input("Scale")[0])
        bias = _scope_get(scope, bn.input("Bias")[0])
        mean = _scope_get(scope, bn.input("Mean")[0])
        var = _scope_get(scope, bn.input("Variance")[0])
        if any(a is None for a in (w, scale, bias, mean, var)):
            return False
        eps = float(bn.attr("epsilon") or 1e-5)
        inv_std = 1.0 / np.sqrt(var + eps)
        alpha = scale * inv_std                         # [C_out]
        _scope_set(scope, conv.input("Filter")[0],
                   (w * alpha[:, None, None, None]).astype(w.dtype))
        prior = np.zeros_like(bias)
        if conv.input("Bias"):
            b0 = _scope_get(scope, conv.input("Bias")[0])
            if b0 is not None:
                prior = b0
        if extra_bias_name is not None:
            eb = _scope_get(scope, extra_bias_name)
            if eb is not None:
                prior = prior + eb.reshape(-1)
        new_bias = (prior - mean) * alpha + bias
        return new_bias.astype(w.dtype)

    def _rewrite(self, graph, conv, bn, matched, out_name, new_bias):
        scope = self.get("param_scope")
        bias_name = conv.output("Output")[0] + ".bn_folded_bias"
        graph.block.create_var(name=bias_name, shape=[len(new_bias)],
                               dtype="float32", persistable=True)
        _scope_set(scope, bias_name, new_bias)
        ins = {"Input": conv.input("Input"), "Filter": conv.input("Filter"),
               "Bias": [bias_name]}
        graph.fuse(matched, "conv2d_fusion", ins, {"Output": [out_name]},
                   {**{k: conv.attr(k) for k in
                       ("strides", "paddings", "dilations", "groups",
                        "padding_algorithm", "data_format")
                       if conv.attr(k) is not None},
                    "activation": "identity"})


@register_pass("conv_bn_fuse_pass")
class ConvBnFusePass(_ConvBnFoldBase):
    """conv2d + batch_norm(is_test) -> conv2d_fusion with folded weights
    (ir/conv_bn_fuse_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("conv2d", {"Input": "$in", "Filter": "$w"}, {"Output": "$conv"}),
            ("batch_norm", {"X": "$conv"}, {"Y": "$y"}),
        ])
        for m in pat.match(graph):
            conv, bn = m["#0"], m["#1"]
            if not (bn.attr("is_test") or bn.attr("use_global_stats")):
                continue
            new_bias = self._fold(graph, conv, bn)
            if new_bias is False:
                continue
            self._rewrite(graph, conv, bn, m["#ops"], m["$y"], new_bias)
        return graph


@register_pass("conv_eltwiseadd_bn_fuse_pass")
class ConvEltwiseAddBnFusePass(_ConvBnFoldBase):
    """conv2d + elementwise_add(bias param) + batch_norm(is_test) ->
    conv2d_fusion (ir/conv_eltwiseadd_bn_fuse_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("conv2d", {"Input": "$in", "Filter": "$w"}, {"Output": "$conv"}),
            ("elementwise_add", {"X": "$conv", "Y": "$b"}, {"Out": "$add"}),
            ("batch_norm", {"X": "$add"}, {"Y": "$y"}),
        ])
        for m in pat.match(graph):
            conv, add_op, bn = m["#0"], m["#1"], m["#2"]
            if not (bn.attr("is_test") or bn.attr("use_global_stats")):
                continue
            bvar = graph.block._find_var_recursive(m["$b"])
            if bvar is None or not getattr(bvar, "persistable", False):
                continue
            new_bias = self._fold(graph, conv, bn, extra_bias_name=m["$b"])
            if new_bias is False:
                continue
            self._rewrite(graph, conv, bn, m["#ops"], m["$y"], new_bias)
        return graph


@register_pass("conv_affine_channel_fuse_pass")
class ConvAffineChannelFusePass(_ConvBnFoldBase):
    """conv2d + affine_channel -> conv2d_fusion with folded weights
    (ir/conv_affine_channel_fuse_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("conv2d", {"Input": "$in", "Filter": "$w"}, {"Output": "$conv"}),
            ("affine_channel", {"X": "$conv", "Scale": "$s", "Bias": "$b"},
             {"Out": "$y"}),
        ])
        for m in pat.match(graph):
            scope = self.get("param_scope")
            if scope is None:
                break
            conv = m["#0"]
            w = _scope_get(scope, conv.input("Filter")[0])
            scale = _scope_get(scope, m["$s"])
            bias = _scope_get(scope, m["$b"])
            if any(a is None for a in (w, scale, bias)):
                continue
            _scope_set(scope, conv.input("Filter")[0],
                       (w * scale[:, None, None, None]).astype(w.dtype))
            prior = np.zeros_like(bias)
            if conv.input("Bias"):
                b0 = _scope_get(scope, conv.input("Bias")[0])
                if b0 is not None:
                    prior = b0
            self._rewrite(graph, conv, m["#1"], m["#ops"], m["$y"],
                          (prior * scale + bias).astype(w.dtype))
        return graph


@register_pass("fc_elementwise_layernorm_fuse_pass")
class FcElementwiseLayerNormFusePass(Pass):
    """fc + elementwise_add(residual) + layer_norm ->
    fused_fc_elementwise_layernorm
    (ir/fc_elementwise_layernorm_fuse_pass.cc). Run after fc_fuse_pass."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("fc", {"Input": "$x", "W": "$w", "Bias": "$b0"},
             {"Out": "$fc"}),
            ("elementwise_add", {"X": "$fc", "Y": "$res"}, {"Out": "$add"}),
            ("layer_norm", {"X": "$add", "Scale": "$s", "Bias": "$b1"},
             {"Y": "$y"}),
        ])
        for m in pat.match(graph):
            fc, ln = m["#0"], m["#2"]
            if fc.attr("activation_type"):
                continue
            add_var = graph.block._find_var_recursive(m["$add"])
            shape = getattr(add_var, "shape", None) if add_var else None
            if not shape or int(ln.attr("begin_norm_axis") or 1) != \
                    len(shape) - 1:
                # the fused kernel normalises the last axis only
                continue
            graph.fuse(m["#ops"], "fused_fc_elementwise_layernorm",
                       {"X": [m["$x"]], "W": [m["$w"]], "Bias0": [m["$b0"]],
                        "Y": [m["$res"]], "Scale": [m["$s"]],
                        "Bias1": [m["$b1"]]},
                       {"Out": [m["$y"]]},
                       {"epsilon": float(ln.attr("epsilon") or 1e-5),
                        "begin_norm_axis":
                        int(ln.attr("begin_norm_axis") or 1),
                        "x_num_col_dims":
                        int(fc.attr("in_num_col_dims") or 1)})
        return graph


@register_pass("skip_layernorm_fuse_pass")
class SkipLayerNormFusePass(Pass):
    """elementwise_add + layer_norm -> skip_layernorm (residual-add fused
    into the norm; ir/skip_layernorm_fuse_pass.cc)."""

    def apply_impl(self, graph):
        pat = OpPattern([
            ("elementwise_add", {"X": "$x", "Y": "$y"}, {"Out": "$add"}),
            ("layer_norm", {"X": "$add", "Scale": "$s", "Bias": "$b"},
             {"Y": "$out"}),
        ])
        for m in pat.match(graph):
            ln = m["#1"]
            add_var = graph.block._find_var_recursive(m["$add"])
            shape = getattr(add_var, "shape", None) if add_var else None
            if not shape or int(ln.attr("begin_norm_axis") or 1) != \
                    len(shape) - 1:
                continue  # skip_layernorm normalises the last axis only;
                # no shape metadata -> can't prove legality, don't fuse
            graph.fuse(m["#ops"], "skip_layernorm",
                       {"X": [m["$x"]], "Y": [m["$y"]], "Scale": [m["$s"]],
                        "Bias": [m["$b"]]},
                       {"Out": [m["$out"]]},
                       {"epsilon": float(ln.attr("epsilon") or 1e-5),
                        "begin_norm_axis":
                        int(ln.attr("begin_norm_axis") or 1)})
        return graph


@register_pass("embedding_eltwise_layernorm_fuse_pass")
class EmbeddingEltwiseLayerNormFusePass(Pass):
    """k x lookup_table + (k-1) adds + layer_norm ->
    fused_embedding_eltwise_layernorm
    (ir/embedding_eltwise_layernorm_fuse_pass.cc). Matches the BERT-style
    2- and 3-embedding input stacks."""

    @staticmethod
    def _patterns():
        for lt in ("lookup_table", "lookup_table_v2"):
            yield OpPattern([
                (lt, {"W": "$w1", "Ids": "$id1"}, {"Out": "$e1"}),
                (lt, {"W": "$w2", "Ids": "$id2"}, {"Out": "$e2"}),
                (lt, {"W": "$w3", "Ids": "$id3"}, {"Out": "$e3"}),
                ("elementwise_add", {"X": "$e1", "Y": "$e2"}, {"Out": "$a1"}),
                ("elementwise_add", {"X": "$a1", "Y": "$e3"}, {"Out": "$a2"}),
                ("layer_norm", {"X": "$a2", "Scale": "$s", "Bias": "$b"},
                 {"Y": "$y"}),
            ]), 3
            yield OpPattern([
                (lt, {"W": "$w1", "Ids": "$id1"}, {"Out": "$e1"}),
                (lt, {"W": "$w2", "Ids": "$id2"}, {"Out": "$e2"}),
                ("elementwise_add", {"X": "$e1", "Y": "$e2"}, {"Out": "$a1"}),
                ("layer_norm", {"X": "$a1", "Scale": "$s", "Bias": "$b"},
                 {"Y": "$y"}),
            ]), 2

    def apply_impl(self, graph):
        for pat, k in self._patterns():
            for m in pat.match(graph):
                lookups = m["#ops"][:k]
                # the fused kernel has no padding handling — only fuse
                # lookups without a padding row (padding_idx zeroes the
                # padding token's embedding in the unfused op)
                if any(int(op.attr("padding_idx")
                           if op.attr("padding_idx") is not None else -1)
                       >= 0 for op in lookups):
                    continue
                ln = m["#ops"][-1]
                add_name = m["$a2"] if k == 3 else m["$a1"]
                add_var = graph.block._find_var_recursive(add_name)
                shape = getattr(add_var, "shape", None) if add_var else None
                if not shape or int(ln.attr("begin_norm_axis") or 1) != \
                        len(shape) - 1:
                    continue  # fused kernel normalises the last axis only
                ids = [m[f"$id{i}"] for i in range(1, k + 1)]
                embs = [m[f"$w{i}"] for i in range(1, k + 1)]
                graph.fuse(m["#ops"], "fused_embedding_eltwise_layernorm",
                           {"Ids": ids, "Embs": embs,
                            "Scale": [m["$s"]], "Bias": [m["$b"]]},
                           {"Out": [m["$y"]]},
                           {"epsilon": float(ln.attr("epsilon") or 1e-5)})
        return graph


@register_pass("graph_viz_pass")
class GraphVizPass(Pass):
    """Dump the graph as graphviz dot (ir/graph_viz_pass.cc). Set
    'graph_viz_path' for the output file."""

    def apply_impl(self, graph):
        path = self.get("graph_viz_path", "/tmp/paddle_tpu_graph.dot")
        lines = ["digraph G {"]
        for i, op in enumerate(graph.all_op_nodes()):
            lines.append(f'  op{i} [label="{op.type}" shape=box '
                         'style=filled fillcolor=lightskyblue];')
            for n in op.input_arg_names:
                lines.append(f'  "{n}" -> op{i};')
            for n in op.output_arg_names:
                lines.append(f'  op{i} -> "{n}";')
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return graph


@register_pass("graph_to_program_pass")
class GraphToProgramPass(Pass):
    """Identity here: Graph is a live view of the Program
    (ir/graph_to_program_pass.cc exists because the reference's Graph is a
    separate structure)."""


# --------------------------------------------------------------------------
# Absorbed passes: capability owned by XLA on this build. Registered so the
# reference pass-name namespace resolves; apply() is the identity.
# --------------------------------------------------------------------------
class AbsorbedPass(Pass):
    """A pass whose job the XLA compiler performs inside the jitted step."""


def _register_absorbed(name: str, note: str):
    cls = type(name.title().replace("_", ""), (AbsorbedPass,),
               {"note": note, "__doc__": note})
    register_pass(name)(cls)


for _n, _note in {
    # memory planning — XLA buffer assignment + donation
    "eager_deletion_pass": "scope GC; XLA buffer liveness handles it",
    "reference_count_pass": "refcount GC plan; XLA buffer liveness",
    "buffer_shared_inplace_pass": "inplace reuse; XLA buffer assignment",
    "buffer_shared_cross_op_memory_reuse_pass":
        "cross-op reuse; XLA buffer assignment",
    "memory_optimize_pass": "memory planning; XLA buffer assignment",
    "inplace_op_pass": "inplace rewrite; XLA aliasing/donation",
    "while_op_eager_deletion_pass": "while scope GC; lax.while_loop scoping",
    "recurrent_op_eager_deletion_pass": "recurrent GC; lax.scan scoping",
    "conditional_block_op_eager_deletion_pass":
        "cond-block GC; lax.cond scoping",
    # scheduling/dependency — everything is one XLA computation
    "all_reduce_deps_pass": "allreduce ordering; XLA schedules collectives",
    "backward_optimizer_op_deps_pass": "dep edges; single XLA computation",
    "sequential_execution_pass": "serial order; XLA schedule",
    "modify_op_lock_and_record_event_pass": "stream events; XLA streams",
    "add_reader_dependency_pass": "reader deps; host input pipeline",
    "runtime_context_cache_pass": "op ctx cache; no per-op dispatch here",
    "lock_free_optimize_pass": "lock-free updates; functional updates",
    # multi-device graph building — sharding metadata instead of rewrites
    "fuse_all_reduce_op_pass": "allreduce bucketing; XLA fuses collectives",
    "coalesce_grad_tensor_pass": "grad bucketing; XLA fuses collectives",
    "multi_batch_merge_pass":
        "batch-merge replication; gradient-merge loop in the jitted step",
    "multi_devices_check_pass": "SSA graph validation; pjit partitioner",
    "multi_devices_print_pass": "SSA graph dump; use graph_viz_pass",
    "sync_batch_norm_pass":
        "sync_batch_norm swap; psum of batch stats inside the step",
    # optimizer-op fusion — one jitted update already
    "fuse_adam_op_pass": "N adam ops -> 1; XLA fuses the update",
    "fuse_sgd_op_pass": "N sgd ops -> 1; XLA fuses the update",
    "fuse_momentum_op_pass": "N momentum ops -> 1; XLA fuses the update",
    # elementwise/matmul micro-fusions — XLA fusion pass
    "fuse_relu_depthwise_conv_pass": "XLA fuses relu into conv",
    "squared_mat_sub_fuse_pass": "XLA fuses the expression",
    "repeated_fc_relu_fuse_pass": "XLA fuses chained fc+relu",
    "seqpool_cvm_concat_fuse_pass": "XLA fuses",
    "transpose_flatten_concat_fuse_pass": "XLA fuses",
    "shuffle_channel_detect_pass": "XLA fuses",
    "matmul_transpose_reshape_fuse_pass": "XLA fuses",
    "scale_matmul_fuse_pass": "XLA folds scale into dot",
    "fusion_group_pass": "runtime CUDA codegen; XLA codegen",
    "fuse_elewise_add_act_ops_pass_placeholder":
        "see fuse_elewise_add_act_pass",
    # backend-placement passes — single TPU backend
    "cudnn_placement_pass": "cudnn kernel choice; XLA picks TPU kernels",
    "mkldnn_placement_pass": "mkldnn placement; n/a on TPU",
    "mkldnn_inplace_pass": "mkldnn inplace; n/a on TPU",
    "conv_bias_mkldnn_fuse_pass": "mkldnn; XLA fuses conv+bias",
    "conv3d_bias_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_activation_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_relu_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_relu6_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_leaky_relu_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_swish_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_concat_relu_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_elementwise_add_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "conv_transpose_bias_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "depthwise_conv_mkldnn_pass": "mkldnn; XLA lowers depthwise conv",
    "fc_mkldnn_pass": "mkldnn fc; XLA dot",
    "reshape_transpose_matmul_mkldnn_fuse_pass": "mkldnn; XLA fuses",
    "cpu_quantize_pass": "int8 CPU; out of scope on TPU",
    "cpu_quantize_placement_pass": "int8 CPU; out of scope on TPU",
    "cpu_quantize_squash_pass": "int8 CPU; out of scope on TPU",
    # misc fusion passes with cudnn-era kernels
    "conv_elementwise_add_fuse_pass": "XLA fuses conv+add",
    "conv_elementwise_add_act_fuse_pass": "XLA fuses conv+add+act",
    "conv_elementwise_add2_act_fuse_pass": "XLA fuses",
    "conv_eltwiseadd_affine_channel_fuse_pass":
        "covered by conv_affine_channel_fuse_pass + XLA",
    "conv_transpose_bn_fuse_pass": "XLA folds at inference const-folding",
    "conv_transpose_eltwiseadd_bn_fuse_pass": "XLA folds",
    "attention_lstm_fuse_pass": "attention_lstm op exists; XLA fuses",
    "embedding_fc_lstm_fuse_pass": "XLA fuses",
    "mul_gru_fuse_pass": "XLA fuses",
    "mul_lstm_fuse_pass": "XLA fuses",
    "quant_conv2d_dequant_fuse_pass": "int8 deploy; out of scope on TPU",
}.items():
    _register_absorbed(_n, _note)


# --------------------------------------------------------------------------
# Block segmentation analysis (segmented compilation)
# --------------------------------------------------------------------------
# The whole-block compiled path is all-or-nothing: ONE stateful/host op
# (auc, print, read, save, ...) among hundreds routes the entire block to
# the op-by-op interpreter with per-op host dispatch. The reference pays
# per-op dispatch everywhere by design (executor.cc:469-475); a TPU build
# should pay it only where it must. This analysis partitions a block's op
# list into maximal *compiled* runs (pure ops, traceable into one jitted
# XLA computation each) separated by *island* runs (stateful/host ops the
# interpreter executes eagerly). `fluid/executor.py:_SegmentedBlock`
# executes the partition; the `block_segmentation_pass` below makes it
# inspectable from the pass system without running anything.

# ops whose compiled lowering traces sub-blocks to lax primitives on the
# whole-block path. In a MIXED block they are executed as islands instead:
# the interpreter's single-branch/scope semantics compose with island
# side effects, while the compiled conditional lowering's both-branch
# trace + mask-merge would not.
_SEG_CONTROL = frozenset({"while", "conditional_block",
                          "conditional_block_infer", "select_input",
                          "select_output"})


# --------------------------------------------------------------------------
# numeric fault plane helpers (docs/FAULT_TOLERANCE.md "Numeric faults")
# --------------------------------------------------------------------------
def fused_health(values) -> Any:
    """ONE boolean health scalar over every inexact-dtype array in
    ``values``: True iff every element of every float tensor is finite.
    This is the per-step reduction the FLAGS_check_nan_inf guard fuses
    into the jitted step (and rides the lax.scan carry on the windowed
    path): each tensor contributes a single ``isfinite().all()`` that
    XLA fuses into the producer loop already writing it, and the flags
    AND into one scalar — no per-op host sync, unlike the reference's
    per-op ``CheckVarHasNanOrInf`` device→host copies
    (framework/details/nan_inf_utils_detail.cc). Non-float tensors
    (int counters, bool masks) are skipped; an empty list is healthy."""
    import jax.numpy as jnp
    acc = None
    for v in values:
        if v is None or not hasattr(v, "dtype") \
                or not jnp.issubdtype(v.dtype, jnp.inexact):
            continue
        flag = jnp.all(jnp.isfinite(v))
        acc = flag if acc is None else jnp.logical_and(acc, flag)
    return jnp.bool_(True) if acc is None else acc


def guarded_float_names(names, env) -> List[str]:
    """The subset of ``names`` whose current ``env`` value is an
    inexact-dtype array — the vars a health reduction actually covers
    (observability: segment_summary/tests report these)."""
    import jax.numpy as jnp
    out = []
    for n in names:
        v = env.get(n)
        if v is not None and hasattr(v, "dtype") \
                and jnp.issubdtype(v.dtype, jnp.inexact):
            out.append(n)
    return out


def op_island_reason(op) -> Optional[str]:
    """None when ``op`` can be traced into a jitted segment; otherwise a
    short reason string ('stateful' | 'host_inputs' | 'unregistered' |
    'control_flow')."""
    from ..ops.registry import resolve_base_info
    info = resolve_base_info(op.type)
    if info is None:
        return "unregistered"
    if info.stateful:
        return "stateful"
    if info.host_inputs:
        return "host_inputs"
    if op.type in _SEG_CONTROL or op.attrs.get("sub_block") is not None:
        return "control_flow"
    return None


class BlockSegment:
    """One maximal run of a block's op list: ``kind`` is 'compiled' (pure
    ops, jitted as one computation) or 'island' (dispatched per-op by the
    interpreter). ``start`` is the index of the first op in the analyzed
    (feed/fetch-free) op list — the executor folds per-op rng keys from
    these global indices so segmented and fused runs draw identically."""

    __slots__ = ("kind", "start", "ops", "island_reasons",
                 # filled by the executor when it builds a step plan
                 "in_names", "donated_names", "out_names", "_cache",
                 "op_io",
                 # float out_names covered by the per-segment fused
                 # finite check when the numeric fault guard is on
                 # (executor._SegmentedBlock; fused_health above)
                 "guard_names")

    def __init__(self, kind: str, start: int):
        self.kind = kind
        self.start = start
        self.ops: List[Any] = []
        self.island_reasons: List[Optional[str]] = []

    @property
    def stop(self) -> int:
        return self.start + len(self.ops)

    def __repr__(self):
        kinds = ",".join(o.type for o in self.ops[:4])
        more = "..." if len(self.ops) > 4 else ""
        return (f"<BlockSegment {self.kind} [{self.start}:{self.stop}) "
                f"{kinds}{more}>")


def analyze_block_segments(ops) -> List["BlockSegment"]:
    """Partition ``ops`` (a feed/fetch-free op list) into maximal
    compiled/island segments. Adjacent ops of the same kind merge, so the
    result alternates kinds; the partition covers every op exactly once."""
    segments: List[BlockSegment] = []
    for idx, op in enumerate(ops):
        reason = op_island_reason(op)
        kind = "island" if reason is not None else "compiled"
        if not segments or segments[-1].kind != kind:
            segments.append(BlockSegment(kind, idx))
        segments[-1].ops.append(op)
        if kind == "island":
            segments[-1].island_reasons.append(reason)
    return segments


def segment_summary(segments) -> List[Dict[str, Any]]:
    """JSON-ish view of a partition (what the pass stores on the graph)."""
    return [{"kind": s.kind, "start": s.start, "stop": s.stop,
             "n_ops": len(s.ops), "op_types": [o.type for o in s.ops],
             "island_reasons": list(s.island_reasons),
             "guard_names": list(getattr(s, "guard_names", ()) or ())}
            for s in segments]


@register_pass("block_segmentation_pass")
class BlockSegmentationPass(Pass):
    """Analysis-only: compute the compiled/island partition the segmented
    executor will use for this block and store it on the graph attr
    'segments' and the program attr ``_segment_plan``. Mutates nothing —
    run it to see where a training program falls off the compiled path
    and why (reference analog: there is none — the reference interprets
    everywhere; here per-op dispatch is the exception and this pass makes
    each exception visible)."""

    def apply(self, graph: Graph) -> Graph:  # no drop_orphan_vars
        ops = [op for op in graph.block.ops
               if op.type not in ("feed", "fetch")]
        summary = segment_summary(analyze_block_segments(ops))
        graph.set("segments", summary)
        graph.program._segment_plan = summary
        return graph


# --------------------------------------------------------------------------
# Canonical pipelines
# --------------------------------------------------------------------------
# reference: inference/api/paddle_pass_builder.cc GpuPassStrategy
INFERENCE_PASSES = [
    "is_test_pass",
    "simplify_with_basic_ops_pass",
    "delete_quant_dequant_op_pass",
    # must run before fc_fuse_pass, which would eat the projection
    # mul+elementwise_add pairs the attention pattern anchors on
    "multihead_matmul_fuse_pass_v2",
    "conv_affine_channel_fuse_pass",
    "conv_eltwiseadd_bn_fuse_pass",
    "conv_bn_fuse_pass",
    "embedding_eltwise_layernorm_fuse_pass",
    # before fc_fuse_pass: the recurrence patterns anchor on the raw
    # projection mul feeding dynamic_gru/dynamic_lstm
    "fc_gru_fuse_pass",
    "fc_lstm_fuse_pass",
    "fc_fuse_pass",
    "fc_elementwise_layernorm_fuse_pass",
    "identity_scale_op_clean_pass",
]


def apply_inference_passes(program, scope=None, extra: Sequence[str] = ()):
    """Run the inference canonicalisation pipeline in place (reference
    AnalysisPredictor::OptimizeInferenceProgram,
    analysis_predictor.cc:497)."""
    pm = PassManager(list(INFERENCE_PASSES) + list(extra), scope=scope)
    return pm.apply(program, for_test=True)
