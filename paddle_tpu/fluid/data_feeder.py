"""DataFeeder (reference: python/paddle/fluid/data_feeder.py) — converts
minibatch row tuples into the feed dict of LoDTensors."""
from __future__ import annotations

import numpy as np

from . import core
from .core import LoDTensor
from .framework import Variable, default_main_program

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(np.dtype(core.dtype_to_np(each_var.dtype)))
        self.place = place

    def feed(self, iterable):
        cols = [[] for _ in self.feed_names]
        for row in iterable:
            for i, cell in enumerate(row):
                cols[i].append(cell)
        res = {}
        for name, dtype, shape, lod_level, col in zip(
                self.feed_names, self.feed_dtypes, self.feed_shapes,
                self.feed_lod_level, cols):
            if lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                want = [s for s in shape if s != -1]
                if arr.ndim == len(shape) - 1 and -1 not in shape[1:]:
                    arr = arr.reshape([len(col)] + list(shape[1:]))
                t = LoDTensor()
                t.set(arr, self.place)
                res[name] = t
            else:
                flat = np.concatenate(
                    [np.asarray(c, dtype=dtype).reshape(-1, *np.asarray(c).shape[1:])
                     for c in col], axis=0)
                t = LoDTensor()
                t.set(flat, self.place)
                t.set_recursive_sequence_lengths(
                    [[len(np.asarray(c)) for c in col]])
                res[name] = t
        return res
