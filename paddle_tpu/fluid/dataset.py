"""Dataset API over the native C++ data-feed engine (reference:
python/paddle/fluid/dataset.py — DatasetFactory:24, DatasetBase:53,
InMemoryDataset:168, QueueDataset:...; backed by the C++ dataset of
framework/data_set.h via paddle_tpu/native/datafeed.cpp).

The slot file format and the API (set_use_var/set_batch_size/set_thread/
set_filelist/load_into_memory/local_shuffle) match the reference; batches
come back as packed LoD arrays ready for the jitted TPU step. Sparse
(int64) slots produce LoD level-1 tensors; dense float slots with fixed
dim reshape to [batch, dim]."""
from __future__ import annotations

import ctypes
from typing import List, Optional

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    """reference dataset.py:24 — create_dataset("InMemoryDataset")."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            return globals()[datafeed_class]()
        except KeyError:
            raise ValueError(f"unknown dataset type {datafeed_class!r}")


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist: List[str] = []
        self._use_vars = []
        self._handle = None
        self._pipe_command = None
        self._hdfs = None

    # ----------------------------------------------------- configuration
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        """Slot order and dtypes come from the vars, like the reference's
        data_feed.proto generation (dataset.py set_use_var)."""
        self._use_vars = list(var_list)

    def set_pipe_command(self, cmd):
        self._pipe_command = cmd  # accepted for parity; not used

    def set_hdfs_config(self, fs_name, fs_ugi):
        self._hdfs = (fs_name, fs_ugi)

    # ----------------------------------------------------------- engine
    def _spec(self) -> str:
        from .core import VarDesc
        parts = []
        for v in self._use_vars:
            isf = v.dtype in (VarDesc.VarType.FP32, VarDesc.VarType.FP64)
            dims = [d for d in (v.shape or []) if d and d > 0]
            dim = int(np.prod(dims)) if dims else 1
            parts.append(f"{v.name}:{'f' if isf else 'i'}:{dim}")
        return ",".join(parts)

    def _ensure_handle(self):
        if self._handle is None:
            from ..native import datafeed_lib
            self._lib = datafeed_lib()
            self._handle = self._lib.df_create(self._spec().encode())
        files = (ctypes.c_char_p * len(self._filelist))(
            *[f.encode() for f in self._filelist])
        self._lib.df_set_filelist(self._handle, files, len(self._filelist))
        self._lib.df_set_batch(self._handle, self._batch_size)
        self._lib.df_set_threads(self._handle, self._thread_num)

    def _load(self):
        self._ensure_handle()
        self._lib.df_load_into_memory(self._handle)

    def get_memory_data_size(self, fleet=None):
        if self._handle is None:
            return 0
        return int(self._lib.df_memory_size(self._handle))

    def release_memory(self):
        if self._handle is not None:
            self._lib.df_release(self._handle)
            self._handle = None

    # ------------------------------------------------------- iteration
    def _iter_batches(self):
        """Yields feed dicts {var_name: LoDTensor} per batch."""
        from . import core
        from .core import VarDesc
        import jax.numpy as jnp
        self._lib.df_epoch_begin(self._handle)
        while True:
            n = self._lib.df_next_batch(self._handle)
            if n <= 0:
                return
            feed = {}
            for s, v in enumerate(self._use_vars):
                total = self._lib.df_slot_total(self._handle, s)
                isf = v.dtype in (VarDesc.VarType.FP32, VarDesc.VarType.FP64)
                vals = np.empty(int(total), np.float32 if isf else np.int64)
                lod = np.empty(n + 1, np.int64)
                self._lib.df_slot_copy(
                    self._handle, s, vals.ctypes.data_as(ctypes.c_void_p),
                    lod.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
                lens = np.diff(lod)
                t = core.LoDTensor()
                if isf and (lens == lens[0]).all():
                    # dense fixed-dim slot → [batch, dim]
                    t.set(vals.reshape(n, -1), None)
                else:
                    t.set(vals.reshape(-1, 1), None)
                    t.set_lod([list(map(int, lod))])
                feed[v.name] = t
            yield feed


class InMemoryDataset(DatasetBase):
    """reference dataset.py:168 — load files into host RAM, shuffle, feed."""

    def load_into_memory(self):
        self._load()

    def local_shuffle(self, seed: Optional[int] = None):
        self._lib.df_local_shuffle(
            self._handle, 0 if seed is None else int(seed))

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host build: global == local shuffle (the reference
        shuffles across trainers via the fleet channel)."""
        self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        if thread_num:
            self.set_thread(thread_num)
        self._load()

    def wait_preload_done(self):
        pass


class QueueDataset(DatasetBase):
    """reference QueueDataset — streaming; this build parses eagerly and
    streams batches from memory (same observable behavior, host RAM
    permitting)."""

    def _prepare_to_run(self):
        self._load()
