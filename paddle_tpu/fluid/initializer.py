"""Parameter initializers (reference: python/paddle/fluid/initializer.py —
ConstantInitializer, UniformInitializer, NormalInitializer,
TruncatedNormalInitializer, XavierInitializer, MSRAInitializer,
BilinearInitializer, NumpyArrayInitializer).

Each initializer appends an init op to the startup program; the startup run
executes them once on device (same contract as the reference, where startup
ops fill parameter memory)."""
from __future__ import annotations

import math

import numpy as np

from . import framework, core
from .core import VarDesc

__all__ = [
    "Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier", "MSRA",
    "Bilinear", "NumpyArrayInitializer", "ConstantInitializer",
    "UniformInitializer", "NormalInitializer", "TruncatedNormalInitializer",
    "XavierInitializer", "MSRAInitializer", "BilinearInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def _compute_fans(self, var):
        shape = var.shape
        if not shape:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        if len(shape) == 2:
            return shape[0], shape[1]
        receptive = int(np.prod(shape[2:]))
        return shape[1] * receptive, shape[0] * receptive


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = float(value)

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": self._value})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": self._low, "max": self._high, "seed": self._seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": self._mean, "std": self._std, "seed": self._seed})


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform, self._seed = uniform, seed
        self._fan_in, self._fan_out = fan_in, fan_out

    def __call__(self, var, block):
        fin, fout = self._compute_fans(var)
        fin = self._fan_in if self._fan_in is not None else fin
        fout = self._fan_out if self._fan_out is not None else fout
        if self._uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            return block.append_op(
                type="uniform_random", outputs={"Out": var},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "min": -limit, "max": limit, "seed": self._seed})
        std = math.sqrt(2.0 / (fin + fout))
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": 0.0, "std": std, "seed": self._seed})


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._seed, self._fan_in = uniform, seed, fan_in

    def __call__(self, var, block):
        fin, _ = self._compute_fans(var)
        fin = self._fan_in if self._fan_in is not None else fin
        if self._uniform:
            limit = math.sqrt(6.0 / fin)
            return block.append_op(
                type="uniform_random", outputs={"Out": var},
                attrs={"shape": list(var.shape), "dtype": var.dtype,
                       "min": -limit, "max": limit, "seed": self._seed})
        std = math.sqrt(2.0 / fin)
        return block.append_op(
            type="gaussian_random", outputs={"Out": var},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": 0.0, "std": std, "seed": self._seed})


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        idx = np.arange(size)
        x = (idx % shape[3]).astype(np.float64)
        y = ((idx // shape[3]) % shape[2]).astype(np.float64)
        vals = (1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))
        weight.flat[:] = vals
        return block.append_op(
            type="assign_value", outputs={"Out": var},
            attrs={"shape": list(shape), "dtype": var.dtype,
                   "fp32_values": [float(v) for v in weight.flatten()]})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        v = self._value
        if v.dtype in (np.float32, np.float64, np.float16):
            attr = {"fp32_values": [float(x) for x in v.astype(np.float32).flatten()]}
        else:
            attr = {"int32_values": [int(x) for x in v.flatten()]}
        return block.append_op(
            type="assign_value", outputs={"Out": var},
            attrs={"shape": list(v.shape), "dtype": var.dtype, **attr})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer

_global_weight_initializer_ = None
_global_bias_initializer_ = None


def _global_weight_initializer():
    return _global_weight_initializer_


def _global_bias_initializer():
    return _global_bias_initializer_
