"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
set_gradient_clip, append_gradient_clip_ops)."""
from __future__ import annotations

from .layer_helper import LayerHelper
from . import layers

__all__ = ["set_gradient_clip", "ErrorClipByValue", "GradientClipByValue",
           "GradientClipByNorm", "GradientClipByGlobalNorm"]


class BaseGradientClipAttr:
    def _process(self, param, grad):
        raise NotImplementedError


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _process(self, param, grad):
        return param, layers.clip(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, param, grad):
        return param, layers.clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_group(self, params_grads):
        sq_sums = []
        for p, g in params_grads:
            if g is None:
                continue
            helper = LayerHelper("global_norm")
            sq = helper.create_variable_for_type_inference(g.dtype)
            sq.shape = (1,)
            helper.append_op(type="squared_l2_norm", inputs={"X": [g]},
                             outputs={"Out": [sq]}, attrs={"op_role": 1})
            sq_sums.append(sq)
        global_sq = layers.sums(sq_sums)
        global_norm = layers.sqrt(global_sq)
        clip_var = layers.fill_constant([1], "float32", self.clip_norm)
        scale = layers.elementwise_div(
            clip_var, layers.elementwise_max(global_norm, clip_var))
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.elementwise_mul(g, scale)))
        return out


_gradient_clip_attr = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _gradient_clip_attr
    _gradient_clip_attr = clip
    if param_list is not None:
        for p in param_list:
            if not isinstance(p, str):
                p.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grads):
    global_clips = [(_gradient_clip_attr, param_grads)] \
        if isinstance(_gradient_clip_attr, GradientClipByGlobalNorm) else None
    if global_clips:
        return _gradient_clip_attr._process_group(param_grads)
    res = []
    for p, g in param_grads:
        clip = getattr(p, "gradient_clip_attr", None) or _gradient_clip_attr
        if g is None or clip is None:
            res.append((p, g))
        else:
            res.append(clip._process(p, g))
    return res
