"""Model save/load — wire-compatible with the reference's tensor stream
format (reference: paddle/fluid/framework/tensor_util.cc:386 TensorToStream,
lod_tensor.cc:220 SerializeToStream; python/paddle/fluid/io.py
save_persistables/save_inference_model/load_*), so checkpoints move between
the frameworks in both directions.

Format per LoDTensor:
  u32 version(=0)
  u64 lod_level; per level: u64 byte_size, then size_t[] offsets
  u32 tensor version(=0)
  i32 TensorDesc proto size; TensorDesc{data_type, dims} proto bytes
  raw buffer (C order)
"""
from __future__ import annotations

import os
import struct
from typing import List, Optional

import numpy as np

from . import core
from .core import LoDTensor, VarDesc, global_scope
from .framework import Program, Parameter, Variable, default_main_program
from .proto import framework_pb2

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load",
]


def _serialize_lod_tensor(t: LoDTensor, as_fp16: bool = False) -> bytes:
    arr = np.asarray(t.array)
    if as_fp16:
        arr = arr.astype(np.float16)
    parts = [struct.pack("<I", 0)]
    lod = t.lod()
    parts.append(struct.pack("<Q", len(lod)))
    for level in lod:
        parts.append(struct.pack("<Q", len(level) * 8))
        parts.append(np.asarray(level, np.uint64).tobytes())
    parts.append(struct.pack("<I", 0))
    desc = framework_pb2.VarType.TensorDesc()
    desc.data_type = core.np_to_dtype(arr.dtype)
    desc.dims.extend(arr.shape)
    db = desc.SerializeToString()
    parts.append(struct.pack("<i", len(db)))
    parts.append(db)
    parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def _deserialize_lod_tensor(data: bytes, offset: int = 0):
    t, _ = _deserialize_one(data, offset)
    return t


def _deserialize_one(data: bytes, off: int):
    (ver,) = struct.unpack_from("<I", data, off)
    off += 4
    assert ver == 0, f"unsupported tensor version {ver}"
    (lod_level,) = struct.unpack_from("<Q", data, off)
    off += 8
    lod = []
    for _ in range(lod_level):
        (nbytes,) = struct.unpack_from("<Q", data, off)
        off += 8
        level = np.frombuffer(data, np.uint64, nbytes // 8, off).tolist()
        off += nbytes
        lod.append([int(x) for x in level])
    (tver,) = struct.unpack_from("<I", data, off)
    off += 4
    assert tver == 0
    (dsize,) = struct.unpack_from("<i", data, off)
    off += 4
    desc = framework_pb2.VarType.TensorDesc()
    desc.ParseFromString(data[off:off + dsize])
    off += dsize
    np_dtype = np.dtype(core.dtype_to_np(desc.data_type))
    count = int(np.prod(desc.dims)) if desc.dims else 1
    arr = np.frombuffer(data, np_dtype, count, off).reshape(list(desc.dims))
    off += count * np_dtype.itemsize
    t = LoDTensor()
    t.set(arr.copy())
    t.set_lod(lod)
    return t, off


def _deserialize_lod_tensor_stream(data: bytes, n: int) -> List[LoDTensor]:
    res, off = [], 0
    for _ in range(n):
        t, off = _deserialize_one(data, off)
        res.append(t)
    return res


# --------------------------------------------------------------------------
# save/load APIs (reference: python/paddle/fluid/io.py)
# --------------------------------------------------------------------------
def _is_persistable(var: Variable) -> bool:
    return (var.persistable and var.type not in (
        VarDesc.VarType.FEED_MINIBATCH, VarDesc.VarType.FETCH_LIST,
        VarDesc.VarType.READER, VarDesc.VarType.RAW))


def _is_parameter(var) -> bool:
    return isinstance(var, Parameter)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        os.makedirs(dirname or ".", exist_ok=True)
        for v in vars:
            sv = scope.find_var(v.name)
            if sv is None or not sv.is_initialized():
                continue
            with open(os.path.join(dirname, v.name), "wb") as f:
                f.write(_serialize_lod_tensor(sv.get_tensor()))
    else:
        os.makedirs(dirname or ".", exist_ok=True)
        with open(os.path.join(dirname, filename), "wb") as f:
            for v in vars:
                sv = scope.find_var(v.name)
                f.write(_serialize_lod_tensor(sv.get_tensor()))


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        for v in vars:
            path = os.path.join(dirname, v.name)
            if not os.path.exists(path):
                raise RuntimeError(f"missing checkpoint file {path}")
            with open(path, "rb") as f:
                scope.var(v.name).set_value(_deserialize_lod_tensor(f.read()))
    else:
        with open(os.path.join(dirname, filename), "rb") as f:
            data = f.read()
        for v, t in zip(vars, _deserialize_lod_tensor_stream(data, len(vars))):
            scope.var(v.name).set_value(t)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    target_names = [v.name if isinstance(v, Variable) else v
                    for v in target_vars]
    pruned = main_program.clone(for_test=True)._prune(target_names)
    # inject feed/fetch ops so the serialized program records its interface
    # (reference io.py prepend_feed_ops/append_fetch_ops — the wire format
    # AnalysisPredictor and Executor both understand, executor.cc:195-306)
    block = pruned.global_block()
    # declare the feed/fetch holder vars (reference io.py
    # prepend_feed_ops creates the FEED_MINIBATCH/FETCH_LIST VarDescs so
    # the serialized program is structurally complete)
    from .core import VarDesc as _VD
    if not block.has_var("feed"):
        block.create_var(name="feed", type=_VD.VarType.FEED_MINIBATCH,
                         persistable=True)
    if not block.has_var("fetch"):
        block.create_var(name="fetch", type=_VD.VarType.FETCH_LIST,
                         persistable=True)
    feed_ops = []
    for i, name in enumerate(feeded_var_names):
        from .framework import Operator
        feed_ops.append(Operator(block, type="feed",
                                 inputs={"X": ["feed"]},
                                 outputs={"Out": [name]},
                                 attrs={"col": i}))
    block.ops[:0] = feed_ops
    for i, name in enumerate(target_names):
        block.append_op(type="fetch", inputs={"X": [name]},
                        outputs={"Out": ["fetch"]}, attrs={"col": i})
    model_name = model_filename or "__model__"
    with open(os.path.join(dirname, model_name), "wb") as f:
        f.write(pruned.serialize_to_string())
    if not program_only:
        save_persistables(executor, dirname, main_program, params_filename)
    return [v.name if isinstance(v, Variable) else v for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    model_name = model_filename or "__model__"
    with open(os.path.join(dirname, model_name), "rb") as f:
        program = Program.parse_from_string(f.read())
    load_persistables(executor, dirname, program, params_filename)
    feed_names = []
    fetch_names = []
    for op in program.global_block().ops:
        if op.type == "feed":
            feed_names.append(op.output("Out")[0])
        elif op.type == "fetch":
            fetch_names.append(op.input("X")[0])
    if not fetch_names:
        # legacy programs without fetch ops: last op outputs are targets
        if program.global_block().ops:
            fetch_names = program.global_block().ops[-1].output_arg_names
    fetch_targets = [program.global_block().var(n) for n in fetch_names
                     if program.global_block().has_var(n)]
    # strip feed/fetch ops so the program body is runnable directly
    block = program.global_block()
    block.ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
    return program, feed_names, fetch_targets


def save(program: Program, model_path: str):
    """2.0-style single-file save (reference: framework/save_load_util.cc via
    fluid.save) — here: pickle of name→ndarray + program."""
    import pickle
    scope = global_scope()
    params = {}
    opt_vars = {}
    for v in program.list_vars():
        if not _is_persistable(v):
            continue
        sv = scope.find_var(v.name)
        if sv is None or not sv.is_initialized():
            continue
        arr = np.asarray(sv.get_tensor().array)
        if _is_parameter(v):
            params[v.name] = arr
        else:
            opt_vars[v.name] = arr
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(opt_vars, f)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())


def load(program: Program, model_path: str, executor=None, var_list=None):
    import pickle
    scope = global_scope()
    loaded = {}
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if os.path.exists(path):
            with open(path, "rb") as f:
                loaded.update(pickle.load(f))
    for name, arr in loaded.items():
        t = LoDTensor()
        t.set(arr)
        scope.var(name).set_value(t)
