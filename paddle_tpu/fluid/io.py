"""Model save/load — wire-compatible with the reference's tensor stream
format (reference: paddle/fluid/framework/tensor_util.cc:386 TensorToStream,
lod_tensor.cc:220 SerializeToStream; python/paddle/fluid/io.py
save_persistables/save_inference_model/load_*), so checkpoints move between
the frameworks in both directions.

Format per LoDTensor:
  u32 version(=0)
  u64 lod_level; per level: u64 byte_size, then size_t[] offsets
  u32 tensor version(=0)
  i32 TensorDesc proto size; TensorDesc{data_type, dims} proto bytes
  raw buffer (C order)
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import struct
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from . import core
from .core import LoDTensor, VarDesc, global_scope
from .framework import Program, Parameter, Variable, default_main_program
from .proto import framework_pb2

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save", "load",
    "save_checkpoint", "load_checkpoint", "latest_checkpoint",
    "validate_checkpoint", "rollback_to_latest",
    "build_handoff_manifest", "check_handoff_section",
]

_LOG = logging.getLogger("paddle_tpu.io")


def _serialize_lod_tensor(t: LoDTensor, as_fp16: bool = False) -> bytes:
    arr = np.asarray(t.array)
    if as_fp16:
        arr = arr.astype(np.float16)
    parts = [struct.pack("<I", 0)]
    lod = t.lod()
    parts.append(struct.pack("<Q", len(lod)))
    for level in lod:
        parts.append(struct.pack("<Q", len(level) * 8))
        parts.append(np.asarray(level, np.uint64).tobytes())
    parts.append(struct.pack("<I", 0))
    desc = framework_pb2.VarType.TensorDesc()
    desc.data_type = core.np_to_dtype(arr.dtype)
    desc.dims.extend(arr.shape)
    db = desc.SerializeToString()
    parts.append(struct.pack("<i", len(db)))
    parts.append(db)
    parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def _deserialize_lod_tensor(data: bytes, offset: int = 0):
    t, _ = _deserialize_one(data, offset)
    return t


def _deserialize_one(data: bytes, off: int):
    (ver,) = struct.unpack_from("<I", data, off)
    off += 4
    assert ver == 0, f"unsupported tensor version {ver}"
    (lod_level,) = struct.unpack_from("<Q", data, off)
    off += 8
    lod = []
    for _ in range(lod_level):
        (nbytes,) = struct.unpack_from("<Q", data, off)
        off += 8
        level = np.frombuffer(data, np.uint64, nbytes // 8, off).tolist()
        off += nbytes
        lod.append([int(x) for x in level])
    (tver,) = struct.unpack_from("<I", data, off)
    off += 4
    assert tver == 0
    (dsize,) = struct.unpack_from("<i", data, off)
    off += 4
    desc = framework_pb2.VarType.TensorDesc()
    desc.ParseFromString(data[off:off + dsize])
    off += dsize
    np_dtype = np.dtype(core.dtype_to_np(desc.data_type))
    count = int(np.prod(desc.dims)) if desc.dims else 1
    arr = np.frombuffer(data, np_dtype, count, off).reshape(list(desc.dims))
    off += count * np_dtype.itemsize
    t = LoDTensor()
    t.set(arr.copy())
    t.set_lod(lod)
    return t, off


def _deserialize_lod_tensor_stream(data: bytes, n: int) -> List[LoDTensor]:
    res, off = [], 0
    for _ in range(n):
        t, off = _deserialize_one(data, off)
        res.append(t)
    return res


# --------------------------------------------------------------------------
# save/load APIs (reference: python/paddle/fluid/io.py)
# --------------------------------------------------------------------------
def _is_persistable(var: Variable) -> bool:
    return (var.persistable and var.type not in (
        VarDesc.VarType.FEED_MINIBATCH, VarDesc.VarType.FETCH_LIST,
        VarDesc.VarType.READER, VarDesc.VarType.RAW))


def _is_parameter(var) -> bool:
    return isinstance(var, Parameter)


def _write_slab_var(path: str, tbl) -> None:
    """Persist one LazyEmbeddingTable as a slab section stream — spilled
    segments go disk→disk one bounded section at a time, never
    materializing the table in RAM (docs/PS_DATA_PLANE.md "Capacity
    tier")."""
    from . import slab_spill
    with open(path, "wb") as f:
        slab_spill.write_section_stream(
            f, slab_spill.table_sections(tbl, with_crc=False))


def _load_slab_var(path: str):
    """Inverse of ``_write_slab_var``: rebuild the table section-by-
    section (ONE section in RAM at a time — the header scan seeks past
    payloads, and ``build_table_from_sections`` pulls each payload on
    demand; a tiered table re-spills under FLAGS_ps_slab_spill_dir or
    a fresh tempdir)."""
    from . import slab_spill
    with open(path, "rb") as f:
        index = {name: (off, plen) for name, off, plen
                 in slab_spill.scan_section_headers(f)}

        def _sec(n):
            if n not in index:
                raise core.SpillCorruptionError(
                    f"{path}: section {n!r} missing from the stream")
            off, plen = index[n]
            f.seek(off)
            payload = f.read(plen)
            if len(payload) != plen:
                raise core.SpillCorruptionError(
                    f"{path}: section {n!r} truncated")
            return payload

        meta = json.loads(_sec("tier:meta"))
        return slab_spill.build_table_from_sections(meta, _sec)


def _drop_replaced_table(var) -> None:
    """Release the spill log of a tiered table about to be replaced
    wholesale — set_value alone would leak the on-disk log + fd."""
    old = var.value() if var is not None else None
    if isinstance(old, core.LazyEmbeddingTable):
        try:
            old.close_spill(unlink=True)
        except Exception:
            pass


def _is_slab_file(path: str) -> bool:
    from .slab_spill import SLAB_STREAM_MAGIC
    try:
        with open(path, "rb") as f:
            return f.read(len(SLAB_STREAM_MAGIC)) == SLAB_STREAM_MAGIC
    except OSError:
        return False


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        os.makedirs(dirname or ".", exist_ok=True)
        for v in vars:
            sv = scope.find_var(v.name)
            if sv is None or not sv.is_initialized():
                continue
            if isinstance(sv.value(), core.LazyEmbeddingTable):
                # slab table (possibly spill-tiered): streamed section
                # file instead of a RAM-materializing dense export
                _write_slab_var(os.path.join(dirname, v.name),
                                sv.value())
                continue
            with open(os.path.join(dirname, v.name), "wb") as f:
                f.write(_serialize_lod_tensor(sv.get_tensor()))
    else:
        os.makedirs(dirname or ".", exist_ok=True)
        slabs = [v.name for v in vars
                 if (sv := scope.find_var(v.name)) is not None
                 and sv.is_initialized()
                 and isinstance(sv.value(), core.LazyEmbeddingTable)]
        if slabs:
            raise ValueError(
                f"save_vars(filename=...): slab tables "
                f"{', '.join(slabs)} cannot join a combined tensor "
                f"stream — save them per-var (filename=None), where "
                f"they stream section-by-section")
        with open(os.path.join(dirname, filename), "wb") as f:
            for v in vars:
                sv = scope.find_var(v.name)
                f.write(_serialize_lod_tensor(sv.get_tensor()))


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if main_program is None:
        main_program = default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    if filename is None:
        # collect EVERY missing file before failing — a checkpoint with
        # 40 absent slot vars reports all 40 in one error, not a
        # 40-iteration whack-a-mole (CheckpointError IS a RuntimeError,
        # so existing handlers keep working)
        missing = [os.path.join(dirname, v.name) for v in vars
                   if not os.path.exists(os.path.join(dirname, v.name))]
        if missing:
            raise core.CheckpointError(
                f"{len(missing)} checkpoint file(s) missing under "
                f"{dirname}: " + ", ".join(sorted(missing)))
        for v in vars:
            path = os.path.join(dirname, v.name)
            if _is_slab_file(path):
                new_val = _load_slab_var(path)
            else:
                with open(path, "rb") as f:
                    new_val = _deserialize_lod_tensor(f.read())
            # release a live tiered table's spill log only AFTER the
            # replacement loaded — dropping first would brick the
            # still-installed table's cold rows on a torn restore
            _drop_replaced_table(scope.find_var(v.name))
            scope.var(v.name).set_value(new_val)
    else:
        with open(os.path.join(dirname, filename), "rb") as f:
            data = f.read()
        for v, t in zip(vars, _deserialize_lod_tensor_stream(data, len(vars))):
            scope.var(v.name).set_value(t)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)
    target_names = [v.name if isinstance(v, Variable) else v
                    for v in target_vars]
    pruned = main_program.clone(for_test=True)._prune(target_names)
    # inject feed/fetch ops so the serialized program records its interface
    # (reference io.py prepend_feed_ops/append_fetch_ops — the wire format
    # AnalysisPredictor and Executor both understand, executor.cc:195-306)
    block = pruned.global_block()
    # declare the feed/fetch holder vars (reference io.py
    # prepend_feed_ops creates the FEED_MINIBATCH/FETCH_LIST VarDescs so
    # the serialized program is structurally complete)
    from .core import VarDesc as _VD
    if not block.has_var("feed"):
        block.create_var(name="feed", type=_VD.VarType.FEED_MINIBATCH,
                         persistable=True)
    if not block.has_var("fetch"):
        block.create_var(name="fetch", type=_VD.VarType.FETCH_LIST,
                         persistable=True)
    feed_ops = []
    for i, name in enumerate(feeded_var_names):
        from .framework import Operator
        feed_ops.append(Operator(block, type="feed",
                                 inputs={"X": ["feed"]},
                                 outputs={"Out": [name]},
                                 attrs={"col": i}))
    block.ops[:0] = feed_ops
    for i, name in enumerate(target_names):
        block.append_op(type="fetch", inputs={"X": [name]},
                        outputs={"Out": ["fetch"]}, attrs={"col": i})
    # drop vars no surviving op references (optimizer slot vars — adam
    # moments/beta pows — that the backward+optimize prune orphaned):
    # they must neither serialize into the inference ProgramDesc nor be
    # saved below, and load_inference_model's load_persistables reads
    # the program's own var list, so program and params stay consistent.
    # Scan EVERY block, not just the global one: a persistable read only
    # inside a while/conditional_block sub-block must survive the drop
    used = {"feed", "fetch"}
    for blk in pruned.blocks:
        for op in blk.ops:
            used.update(op.input_arg_names)
            used.update(op.output_arg_names)
    for name in [n for n in block.vars if n not in used]:
        del block.vars[name]
    # the PR 7 multi-block var-drop invariant promoted to a verifier
    # rule: the program about to serialize must be structurally complete
    # (every op input in every block resolves to a VarDesc, no
    # def-before-use, distributed tails paired). Unconditional — a save
    # dir that fails level="error" verification would fail the native
    # load validation anyway, just later and without the fix hints.
    from . import analysis
    analysis.enforce(
        analysis.verify_program(
            pruned, feed_names=tuple(feeded_var_names),
            fetch_names=tuple(target_names), where="save"),
        level="error", where="save")
    model_name = model_filename or "__model__"
    with open(os.path.join(dirname, model_name), "wb") as f:
        f.write(pruned.serialize_to_string())
    if not program_only:
        # persistables of the PRUNED program, not the training program:
        # optimizer slot vars (adam moments, ...) are dead weight in a
        # serving dir — for wide_deep they dwarf the model — and a
        # params_filename combined stream saved from the full var list
        # would not line up with the pruned list load_inference_model
        # deserializes against (reference io.py saves the pruned
        # program's vars for the same reason)
        save_persistables(executor, dirname, pruned, params_filename)
    return [v.name if isinstance(v, Variable) else v for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    model_name = model_filename or "__model__"
    with open(os.path.join(dirname, model_name), "rb") as f:
        program = Program.parse_from_string(f.read())
    load_persistables(executor, dirname, program, params_filename)
    feed_names = []
    fetch_names = []
    for op in program.global_block().ops:
        if op.type == "feed":
            feed_names.append(op.output("Out")[0])
        elif op.type == "fetch":
            fetch_names.append(op.input("X")[0])
    if not fetch_names:
        # legacy programs without fetch ops: last op outputs are targets
        if program.global_block().ops:
            fetch_names = program.global_block().ops[-1].output_arg_names
    fetch_targets = [program.global_block().var(n) for n in fetch_names
                     if program.global_block().has_var(n)]
    # strip feed/fetch ops so the program body is runnable directly
    block = program.global_block()
    block.ops = [op for op in block.ops if op.type not in ("feed", "fetch")]
    return program, feed_names, fetch_targets


def save(program: Program, model_path: str):
    """2.0-style single-file save (reference: framework/save_load_util.cc via
    fluid.save) — here: pickle of name→ndarray + program."""
    import pickle
    scope = global_scope()
    params = {}
    opt_vars = {}
    for v in program.list_vars():
        if not _is_persistable(v):
            continue
        sv = scope.find_var(v.name)
        if sv is None or not sv.is_initialized():
            continue
        arr = np.asarray(sv.get_tensor().array)
        if _is_parameter(v):
            params[v.name] = arr
        else:
            opt_vars[v.name] = arr
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(opt_vars, f)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(program.serialize_to_string())


def load(program: Program, model_path: str, executor=None, var_list=None):
    import pickle
    scope = global_scope()
    loaded = {}
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if os.path.exists(path):
            with open(path, "rb") as f:
                loaded.update(pickle.load(f))
    for name, arr in loaded.items():
        t = LoDTensor()
        t.set(arr)
        scope.var(name).set_value(t)


# --------------------------------------------------------------------------
# Atomic checkpoints with bit-exact resume (docs/FAULT_TOLERANCE.md)
#
# Layout: <root>/ckpt-<global_step>/ holding one reference-format tensor
# blob per persistable var plus MANIFEST.json (format_version, global_step,
# rng_counter, per-file crc32+size, dataloader state, extra). A checkpoint
# is written to a temp dir, every file fsynced, then renamed into place —
# a kill mid-save leaves only a .tmp-* dir that validation never selects,
# so the previous intact checkpoint always wins (the reference's
# save_persistables writes in place and a mid-save kill corrupts the only
# copy — checkpoint_notify_op.cc has no atomicity either).
# --------------------------------------------------------------------------
CKPT_PREFIX = "ckpt-"
CKPT_MANIFEST = "MANIFEST.json"
CKPT_FORMAT_VERSION = 1
RNG_COUNTER_VAR = "@RNG_COUNTER@"


def _fsync_path(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _scope_rng_counter(scope) -> int:
    v = scope.find_var(RNG_COUNTER_VAR)
    if v is None or not v.is_initialized():
        return 0
    return int(np.asarray(v.get_tensor().array).reshape(-1)[0])


def save_checkpoint(executor, dirname, main_program=None, scope=None,
                    global_step: int = 0, dataloader_state=None,
                    extra=None, max_to_keep: int = 3) -> str:
    """Write one atomic checkpoint to ``<dirname>/ckpt-<global_step>``.

    Captures every initialized persistable LoDTensor of ``main_program``
    (parameters AND optimizer slot vars — momentum velocities, adam
    moments, LR schedules), the scope's global rng fold counter (what
    makes resumed dropout streams bit-identical), plus opaque
    ``dataloader_state`` (e.g. ``DataLoader.state_dict()``) and ``extra``
    for the manifest. Keeps the newest ``max_to_keep`` checkpoints.
    Returns the final checkpoint directory."""
    if main_program is None:
        main_program = default_main_program()
    if scope is None:
        scope = global_scope()
    step = int(global_step)
    os.makedirs(dirname, exist_ok=True)
    tmp = os.path.join(dirname, f".tmp-{CKPT_PREFIX}{step}-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    files: Dict[str, Dict[str, int]] = {}
    for v in main_program.list_vars():
        if not _is_persistable(v):
            continue
        sv = scope.find_var(v.name)
        if sv is None or not sv.is_initialized():
            continue
        val = sv.value()
        path = os.path.join(tmp, v.name)
        if isinstance(val, core.LazyEmbeddingTable):
            # slab table: STREAM the section file (spilled segments go
            # disk→disk one bounded section at a time — a part-spilled
            # table checkpoints at O(one section) peak RSS) and record
            # the incrementally-computed crc32/size in the manifest
            # like any tensor blob
            from . import slab_spill
            with open(path, "wb") as f:
                crc, size = slab_spill.write_section_stream(
                    f, slab_spill.table_sections(val, with_crc=False))
                f.flush()
                os.fsync(f.fileno())
            files[v.name] = {"crc32": crc, "size": size}
            continue
        if not isinstance(val, LoDTensor):
            _LOG.warning("checkpoint: skipping non-dense persistable "
                         "'%s' (%s)", v.name, type(val).__name__)
            continue
        blob = _serialize_lod_tensor(val)
        with open(path, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        files[v.name] = {"crc32": zlib.crc32(blob) & 0xFFFFFFFF,
                         "size": len(blob)}
    manifest = {
        "format_version": CKPT_FORMAT_VERSION,
        "global_step": step,
        "rng_counter": _scope_rng_counter(scope),
        "files": files,
        "dataloader": dataloader_state,
        "extra": extra,
    }
    mpath = os.path.join(tmp, CKPT_MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(tmp)
    final = os.path.join(dirname, f"{CKPT_PREFIX}{step}")
    aside = None
    if os.path.exists(final):
        # same-step overwrite: move the old dir ASIDE first so a kill
        # between here and the rename below can never destroy the only
        # copy — the aside dir (non-numeric suffix) is never a resume
        # candidate and gets pruned
        aside = f"{final}.old-{os.getpid()}"
        if os.path.exists(aside):
            shutil.rmtree(aside)
        os.rename(final, aside)
    os.rename(tmp, final)
    _fsync_path(dirname)
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)
    _prune_checkpoints(dirname, max_to_keep)
    return final


def _checkpoint_steps(dirname) -> List[int]:
    steps = []
    try:
        entries = os.listdir(dirname)
    except OSError:
        return steps
    for name in entries:
        if not name.startswith(CKPT_PREFIX):
            continue
        try:
            steps.append(int(name[len(CKPT_PREFIX):]))
        except ValueError:
            continue
    return sorted(steps)


def _prune_checkpoints(dirname, max_to_keep: int):
    if not max_to_keep or max_to_keep <= 0:
        return
    steps = _checkpoint_steps(dirname)
    for step in steps[:-max_to_keep]:
        shutil.rmtree(os.path.join(dirname, f"{CKPT_PREFIX}{step}"),
                      ignore_errors=True)
    # stale temp/aside dirs from killed saves are garbage by definition
    for name in os.listdir(dirname):
        if name.startswith(".tmp-" + CKPT_PREFIX) or \
                (name.startswith(CKPT_PREFIX) and ".old-" in name):
            shutil.rmtree(os.path.join(dirname, name), ignore_errors=True)


def validate_checkpoint(ckpt_dir) -> Dict[str, Any]:
    """Validate one checkpoint directory against its manifest. Returns
    the manifest; raises ``core.CheckpointError`` aggregating EVERY
    problem (missing manifest, missing files, size/CRC mismatches) —
    a truncated or bit-flipped checkpoint is rejected wholesale."""
    mpath = os.path.join(ckpt_dir, CKPT_MANIFEST)
    if not os.path.exists(mpath):
        raise core.CheckpointError(
            f"checkpoint {ckpt_dir}: no {CKPT_MANIFEST} — incomplete "
            f"save (killed mid-write) or not a checkpoint directory")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise core.CheckpointError(
            f"checkpoint {ckpt_dir}: unreadable manifest: {e}") from e
    problems = []
    for name, meta in sorted(manifest.get("files", {}).items()):
        path = os.path.join(ckpt_dir, name)
        if not os.path.exists(path):
            problems.append(f"missing file '{name}'")
            continue
        size = os.path.getsize(path)
        if size != int(meta["size"]):
            problems.append(
                f"'{name}' truncated ({size} bytes, manifest says "
                f"{meta['size']})")
            continue
        crc = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = zlib.crc32(chunk, crc)
        if (crc & 0xFFFFFFFF) != int(meta["crc32"]):
            problems.append(f"'{name}' CRC mismatch (corrupted)")
    if problems:
        raise core.CheckpointError(
            f"checkpoint {ckpt_dir} failed validation "
            f"({len(problems)} problem(s)): " + "; ".join(problems))
    return manifest


def _latest_valid(dirname):
    """(dir, manifest) of the newest checkpoint under ``dirname`` that
    PASSES validation, or (None, None). Corrupt/incomplete candidates
    are logged and skipped — a kill mid-save can never shadow the
    previous intact checkpoint."""
    for step in reversed(_checkpoint_steps(dirname)):
        cand = os.path.join(dirname, f"{CKPT_PREFIX}{step}")
        try:
            return cand, validate_checkpoint(cand)
        except core.CheckpointError as e:
            _LOG.warning("skipping invalid checkpoint %s: %s", cand, e)
    return None, None


def latest_checkpoint(dirname) -> Optional[str]:
    return _latest_valid(dirname)[0]


def rollback_to_latest(executor, dirname, main_program=None, scope=None
                       ) -> Optional[Dict[str, Any]]:
    """Numeric-fault rollback entry point (executor.HealthMonitor —
    docs/FAULT_TOLERANCE.md "Numeric faults"): restore the newest VALID
    checkpoint under ``dirname`` — parameters, optimizer slots, and the
    rng fold counter, so the re-run of the faulted window is bit-exact —
    and return its manifest. Returns None when nothing under ``dirname``
    validates (the caller escalates to core.NumericFaultError instead of
    training on from a poisoned state)."""
    if not dirname or not os.path.isdir(dirname):
        return None
    try:
        # ONE pick+validate sweep (load_checkpoint's _latest_valid); a
        # separate latest_checkpoint() probe would CRC every candidate
        # twice and open a TOCTOU window mid-recovery
        return load_checkpoint(executor, dirname,
                               main_program=main_program, scope=scope)
    except core.CheckpointError:
        return None



# --------------------------------------------------------------------------
# Elastic-membership shard handoff manifests (docs/FAULT_TOLERANCE.md
# "Elastic membership"). Same per-blob integrity record as the checkpoint
# MANIFEST above ({"crc32", "size"} per section), but the sections travel
# over the PS binary wire instead of through the filesystem: the draining
# pserver streams each section to the destination, which validates it
# against this manifest BEFORE anything is installed — a corrupted handoff
# is rejected wholesale and the drain aborts with the source still serving.
# --------------------------------------------------------------------------
HANDOFF_FORMAT_VERSION = 1


def build_handoff_manifest(slot: str, epoch_next: int, view_next,
                           sections: Dict[str, Dict[str, Any]],
                           dedup_hwms=None, extra=None) -> Dict[str, Any]:
    """Manifest for one shard handoff. ``sections`` maps section name →
    {"kind": ..., "bytes": <payload>, "meta": {...}}; the payload itself
    is NOT embedded — only its crc32/size, checkpoint-manifest style.
    Streaming sections (the capacity tier's spilled-table legs) carry
    precomputed ``crc32``/``size`` instead of ``bytes`` — the payload
    is regenerated at stream time, never held for the manifest."""
    files = {}
    for name, sec in sections.items():
        if "bytes" in sec:
            blob = sec["bytes"]
            crc, size = zlib.crc32(blob) & 0xFFFFFFFF, len(blob)
        else:
            crc, size = int(sec["crc32"]), int(sec["size"])
        files[name] = {"crc32": crc,
                       "size": size, "kind": sec.get("kind", "raw"),
                       "meta": sec.get("meta") or {}}
    return {
        "format_version": HANDOFF_FORMAT_VERSION,
        "slot": slot,
        "epoch_next": int(epoch_next),
        "view_next": view_next,
        "sections": files,
        "dedup_hwms": dict(dedup_hwms or {}),
        "extra": extra,
    }


def check_handoff_section(manifest: Dict[str, Any], name: str,
                          payload: bytes) -> Dict[str, Any]:
    """Validate one streamed section against the handoff manifest.
    Returns the section's manifest entry; raises ``core.CheckpointError``
    (the same rejection type torn checkpoints use) on an undeclared
    section, size mismatch, or CRC mismatch."""
    entry = (manifest or {}).get("sections", {}).get(name)
    problems = []
    if entry is None:
        raise core.CheckpointError(
            f"handoff section '{name}' not declared in the manifest — "
            f"source/destination desynchronized")
    if len(payload) != int(entry["size"]):
        problems.append(
            f"size {len(payload)} != manifest {entry['size']} (truncated)")
    elif (zlib.crc32(payload) & 0xFFFFFFFF) != int(entry["crc32"]):
        problems.append("CRC mismatch (corrupted in flight)")
    if problems:
        raise core.CheckpointError(
            f"handoff section '{name}' failed validation: "
            + "; ".join(problems))
    return entry


def load_checkpoint(executor, path, main_program=None, scope=None
                    ) -> Dict[str, Any]:
    """Restore a checkpoint saved by ``save_checkpoint``. ``path`` may be
    a specific ``ckpt-<n>`` directory or a root holding several (the
    newest VALID one is picked). Restores every manifest-listed var into
    ``scope`` and the global rng fold counter — the next step after
    resume folds the same per-step keys an uninterrupted run would, so
    dropout streams (and hence losses) are bit-identical. Returns the
    manifest (global_step, dataloader state, extra).

    When ``main_program`` is given, every initialized dense/table
    persistable of the program must appear in the manifest — a var the
    checkpoint doesn't cover would silently keep its startup init after
    "resume", so the mismatch raises ``CheckpointError`` BEFORE the
    scope is touched. The classic way to hit this is rebuilding the net
    without ``fluid.unique_name.guard()``: the rebuilt params are named
    ``fc_1.*`` while the checkpoint holds ``fc_0.*``."""
    if scope is None:
        scope = global_scope()
    if os.path.exists(os.path.join(path, CKPT_MANIFEST)):
        ckpt_dir, manifest = path, validate_checkpoint(path)
    else:  # one validation pass total: pick + validate together
        ckpt_dir, manifest = _latest_valid(path)
        if ckpt_dir is None:
            raise core.CheckpointError(
                f"no valid checkpoint found under {path}")
    if main_program is not None:
        have = set(manifest.get("files", {}))
        missing = []
        for v in main_program.list_vars():
            if not _is_persistable(v) or v.name in have:
                continue
            sv = scope.find_var(v.name)
            if sv is None or not sv.is_initialized():
                continue  # save_checkpoint skips these too
            if not isinstance(sv.value(), (LoDTensor,
                                           core.LazyEmbeddingTable)):
                continue  # non-dense persistables are never captured
            missing.append(v.name)
        if missing:
            raise core.CheckpointError(
                f"checkpoint {ckpt_dir} does not cover program "
                f"persistables {sorted(missing)} — resuming would leave "
                f"them at their startup init (was the net rebuilt "
                f"without fluid.unique_name.guard()?)")
    for name in manifest.get("files", {}):
        fpath = os.path.join(ckpt_dir, name)
        if _is_slab_file(fpath):
            # slab table section stream (validated by the manifest's
            # whole-file CRC above, like every other blob)
            new_val = _load_slab_var(fpath)
        else:
            with open(fpath, "rb") as f:
                new_val = _deserialize_lod_tensor(f.read())
        # release a live tiered table's spill log only AFTER the
        # replacement loaded — dropping first would brick the still-
        # installed table's cold rows on a torn restore
        _drop_replaced_table(scope.find_var(name))
        scope.var(name).set_value(new_val)
    counter = int(manifest.get("rng_counter", 0))
    scope.var(RNG_COUNTER_VAR).set_value(
        LoDTensor(np.asarray([counter], np.int32)))
    # the Executor mirrors the counter in a host-side WeakKeyDictionary —
    # sync it or the next _advance_rng_counter would ignore the scope var
    from .executor import Executor
    Executor._rng_counters[scope] = counter
    return manifest
