"""Graph-builder front end: Program / Block / Operator / Variable.

TPU-native re-design of the reference's Python graph builder
(reference: python/paddle/fluid/framework.py — Program:3843, Block:2386,
Operator:1817, Variable:830). The reference mirrors a C++ ProgramDesc through
pybind; here the Python objects ARE the source of truth and serialize
directly to the wire-compatible protobuf (paddle_tpu/fluid/proto/framework.proto),
so programs saved by the reference load here and vice versa.

The executor does not interpret these ops per step: a Block traces into one
jitted XLA computation (see executor.py). Hence no per-op C++ handles — an
Operator is pure metadata.
"""
from __future__ import annotations

import contextlib
import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from . import core, unique_name
from .core import VarDesc, convert_np_dtype_to_dtype_
from .proto import framework_pb2
from ..ops.registry import OPS

__all__ = [
    "Program", "Block", "Operator", "Variable", "Parameter",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "grad_var_name", "in_dygraph_mode", "cpu_places",
    "cuda_places", "tpu_places", "device_guard",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"
CONTROL_DEP_VAR_PREFIX = "@DEPENDENCY"


def grad_var_name(name: str) -> str:
    return name + GRAD_VAR_SUFFIX


# --------------------------------------------------------------------------
# dygraph mode plumbing (tracer lives in dygraph/; hooks here)
# --------------------------------------------------------------------------
_dygraph_tracer_ = None
_dygraph_current_expected_place_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


def _current_expected_place():
    if _dygraph_current_expected_place_ is not None:
        return _dygraph_current_expected_place_
    return core.TPUPlace(0) if core.is_compiled_with_tpu() else core.CPUPlace()


@contextlib.contextmanager
def _dygraph_guard(tracer):
    global _dygraph_tracer_
    tmp = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    try:
        yield
    finally:
        _dygraph_tracer_ = tmp


@contextlib.contextmanager
def _dygraph_place_guard(place):
    global _dygraph_current_expected_place_
    tmp = _dygraph_current_expected_place_
    _dygraph_current_expected_place_ = place
    try:
        yield
    finally:
        _dygraph_current_expected_place_ = tmp


def cpu_places(device_count: Optional[int] = None):
    import os
    if device_count is None:
        device_count = int(os.environ.get("CPU_NUM", 1))
    return [core.CPUPlace()] * device_count


def tpu_places(device_ids: Optional[Sequence[int]] = None):
    import jax
    if device_ids is None:
        device_ids = range(len(jax.devices()))
    return [core.TPUPlace(i) for i in device_ids]


# reference scripts call cuda_places(); give them the accelerator list.
cuda_places = tpu_places


@contextlib.contextmanager
def name_scope(prefix: Optional[str] = None):
    yield  # cosmetic grouping only; XLA names come from jit


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    yield  # single logical device space under XLA; placement is sharding


# --------------------------------------------------------------------------
# Variable
# --------------------------------------------------------------------------
class Variable:
    """Symbolic graph variable (reference framework.py:830). Holds static
    metadata; runtime values live in a Scope keyed by name."""

    def __init__(self, block: "Block", type=VarDesc.VarType.LOD_TENSOR,
                 name: Optional[str] = None, shape=None, dtype=None,
                 lod_level: Optional[int] = None, capacity=None,
                 persistable: Optional[bool] = None, error_clip=None,
                 stop_gradient: bool = False, is_data: bool = False,
                 need_check_feed: bool = False, belong_to_optimizer: bool = False,
                 **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.type = type
        self.shape = tuple(shape) if shape is not None else ()
        if dtype is not None and not isinstance(dtype, int):
            dtype = convert_np_dtype_to_dtype_(dtype)
        self.dtype = dtype if dtype is not None else VarDesc.VarType.FP32
        self.lod_level = lod_level if lod_level is not None else 0
        self.persistable = bool(persistable) if persistable is not None else False
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.need_check_feed = need_check_feed
        self.belong_to_optimizer = belong_to_optimizer
        self.error_clip = error_clip
        self.op: Optional["Operator"] = None  # producing op (set by append_op)

    # -- metadata ---------------------------------------------------------
    @property
    def desc(self):
        return self

    def element_size(self) -> int:
        return np.dtype(core.dtype_to_np(self.dtype)).itemsize

    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def to_string(self, throw_on_error=False, with_details=False):
        return (f"var {self.name} : {_type_name(self.type)}.shape{list(self.shape)}"
                f".dtype({_dtype_name(self.dtype)}).stop_gradient({self.stop_gradient})")

    __repr__ = __str__ = lambda self: self.to_string()

    def clone(self):
        out = self.block.create_var(
            name=unique_name.generate_with_ignorable_key(self.name + "_clone"),
            dtype=self.dtype, shape=self.shape, lod_level=self.lod_level,
            persistable=self.persistable, stop_gradient=self.stop_gradient)
        self.block.append_op(type="assign", inputs={"X": [self]},
                             outputs={"Out": [out]})
        return out

    def astype(self, dtype):
        if not isinstance(dtype, int):
            dtype = convert_np_dtype_to_dtype_(dtype)
        out = self.block.create_var(
            name=unique_name.generate_with_ignorable_key(self.name + "_cast"),
            dtype=dtype, shape=self.shape, persistable=False,
            stop_gradient=self.stop_gradient)
        self.block.append_op(type="cast", inputs={"X": [self]},
                             outputs={"Out": [out]},
                             attrs={"in_dtype": self.dtype, "out_dtype": dtype})
        return out

    # -- serialization ----------------------------------------------------
    def _to_proto(self) -> framework_pb2.VarDesc:
        vd = framework_pb2.VarDesc()
        vd.name = self.name
        vd.type.type = self.type
        vd.persistable = self.persistable
        vd.need_check_feed = self.need_check_feed
        if self.type == VarDesc.VarType.LOD_TENSOR:
            vd.type.lod_tensor.tensor.data_type = self.dtype
            vd.type.lod_tensor.tensor.dims.extend(self.shape)
            vd.type.lod_tensor.lod_level = self.lod_level
        elif self.type == VarDesc.VarType.SELECTED_ROWS:
            vd.type.selected_rows.data_type = self.dtype
            vd.type.selected_rows.dims.extend(self.shape)
        elif self.type == VarDesc.VarType.LOD_TENSOR_ARRAY:
            vd.type.tensor_array.tensor.data_type = self.dtype
            vd.type.tensor_array.tensor.dims.extend(self.shape)
            vd.type.tensor_array.lod_level = self.lod_level
        return vd

    # operator sugar so ``a + b`` works in static graph (subset)
    def _binary(self, other, op_type, reverse=False):
        from .layers import math_op  # late import to avoid cycle
        if reverse:
            from .layers.tensor import fill_constant
            o = fill_constant([1], self.dtype, float(other))
            return math_op(op_type, o, self)
        return math_op(op_type, self, other)

    __add__ = lambda self, o: self._binary(o, "elementwise_add")
    __radd__ = __add__
    __sub__ = lambda self, o: self._binary(o, "elementwise_sub")
    __rsub__ = lambda self, o: self._binary(o, "elementwise_sub", True)
    __mul__ = lambda self, o: self._binary(o, "elementwise_mul")
    __rmul__ = __mul__
    __truediv__ = lambda self, o: self._binary(o, "elementwise_div")
    __rtruediv__ = lambda self, o: self._binary(o, "elementwise_div", True)
    __pow__ = lambda self, o: self._binary(o, "elementwise_pow")
    __rpow__ = lambda self, o: self._binary(o, "elementwise_pow", True)
    __neg__ = lambda self: self._binary(-1.0, "elementwise_mul")
    __lt__ = lambda self, o: self._binary(o, "less_than")
    __le__ = lambda self, o: self._binary(o, "less_equal")
    __gt__ = lambda self, o: self._binary(o, "greater_than")
    __ge__ = lambda self, o: self._binary(o, "greater_equal")


def _type_name(t):
    for k in dir(VarDesc.VarType):
        if not k.startswith("_") and getattr(VarDesc.VarType, k) == t:
            return k
    return str(t)


def _dtype_name(d):
    try:
        return np.dtype(core.dtype_to_np(d)).name
    except Exception:
        return str(d)


class Parameter(Variable):
    """Trainable persistable variable (reference framework.py:5055)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        super().__init__(block, shape=shape, dtype=dtype,
                         stop_gradient=kwargs.pop("stop_gradient", False),
                         **{k: v for k, v in kwargs.items() if k in (
                             "name", "type", "lod_level", "persistable",
                             "error_clip", "need_check_feed")})
        self.trainable = kwargs.get("trainable", True)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.do_model_average = kwargs.get("do_model_average", None)
        self.is_distributed = kwargs.get("is_distributed", False)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)


# --------------------------------------------------------------------------
# Operator
# --------------------------------------------------------------------------
class Operator:
    """One op instance: type + named var-name slots + attrs (reference
    framework.py:1817). Pure metadata — execution happens when the enclosing
    block is traced/compiled."""

    def __init__(self, block: "Block", type: str,
                 inputs: Optional[Dict[str, Any]] = None,
                 outputs: Optional[Dict[str, Any]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = _normalize_slots(inputs)
        self.outputs: Dict[str, List[str]] = _normalize_slots(outputs)
        self.attrs: Dict[str, Any] = dict(attrs or {})
        if OPS.has(type):
            for k, v in OPS.get(type).attr_defaults.items():
                self.attrs.setdefault(k, v)

    # -- reference OpDesc API --------------------------------------------
    def input(self, slot: str) -> List[str]:
        return list(self.inputs.get(slot, []))

    def output(self, slot: str) -> List[str]:
        return list(self.outputs.get(slot, []))

    @property
    def input_names(self):
        return list(self.inputs.keys())

    @property
    def output_names(self):
        return list(self.outputs.keys())

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name):
        return self.attrs.get(name)

    def has_attr(self, name):
        return name in self.attrs

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def _rename_input(self, old, new):
        for ns in self.inputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def _rename_output(self, old, new):
        for ns in self.outputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def to_string(self, throw_on_error=False):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        attrs = {k: v for k, v in self.attrs.items() if not k.startswith("_")}
        return f"{outs} = {self.type}(inputs={ins}, attrs={attrs})"

    __repr__ = __str__ = lambda self: self.to_string()

    # -- serialization ----------------------------------------------------
    def _to_proto(self) -> framework_pb2.OpDesc:
        od = framework_pb2.OpDesc()
        od.type = self.type
        for slot, names in self.inputs.items():
            v = od.inputs.add()
            v.parameter = slot
            v.arguments.extend(names)
        for slot, names in self.outputs.items():
            v = od.outputs.add()
            v.parameter = slot
            v.arguments.extend(names)
        for name, val in sorted(self.attrs.items()):
            if name.startswith("_"):
                continue  # runtime-internal attrs don't serialize
            a = od.attrs.add()
            a.name = name
            _attr_to_proto(a, val)
        return od


def _normalize_slots(slots) -> Dict[str, List[str]]:
    res: Dict[str, List[str]] = {}
    if not slots:
        return res
    for slot, args in slots.items():
        if args is None:
            res[slot] = []
            continue
        if not isinstance(args, (list, tuple)):
            args = [args]
        # anything with a .name (static Variable, dygraph VarBase during a
        # to-static trace) records by name; bare strings pass through
        res[slot] = [a if isinstance(a, str) else getattr(a, "name", None)
                     or str(a) for a in args]
    return res


def _attr_to_proto(a: framework_pb2.OpDesc.Attr, val):
    AT = framework_pb2
    if isinstance(val, bool):
        a.type = AT.BOOLEAN
        a.b = val
    elif isinstance(val, int) or isinstance(val, np.integer):
        iv = int(val)
        if -(2**31) <= iv < 2**31:
            a.type = AT.INT
            a.i = iv
        else:
            a.type = AT.LONG
            a.l = iv
    elif isinstance(val, float) or isinstance(val, np.floating):
        a.type = AT.FLOAT
        a.f = float(val)
    elif isinstance(val, str):
        a.type = AT.STRING
        a.s = val
    elif isinstance(val, Block):
        a.type = AT.BLOCK
        a.block_idx = val.idx
    elif isinstance(val, (list, tuple)):
        if len(val) == 0:
            a.type = AT.INTS
        elif isinstance(val[0], bool):
            a.type = AT.BOOLEANS
            a.bools.extend(bool(x) for x in val)
        elif isinstance(val[0], (int, np.integer)):
            if all(-(2**31) <= int(x) < 2**31 for x in val):
                a.type = AT.INTS
                a.ints.extend(int(x) for x in val)
            else:
                a.type = AT.LONGS
                a.longs.extend(int(x) for x in val)
        elif isinstance(val[0], (float, np.floating)):
            a.type = AT.FLOATS
            a.floats.extend(float(x) for x in val)
        elif isinstance(val[0], str):
            a.type = AT.STRINGS
            a.strings.extend(val)
        elif isinstance(val[0], Block):
            a.type = AT.BLOCKS
            a.blocks_idx.extend(b.idx for b in val)
        else:
            raise TypeError(f"unsupported list attr {val!r}")
    else:
        raise TypeError(f"unsupported attr {val!r}")


def _attr_from_proto(a: framework_pb2.OpDesc.Attr, program: "Program"):
    AT = framework_pb2
    t = a.type
    if t == AT.INT:
        return a.i
    if t == AT.FLOAT:
        return a.f
    if t == AT.STRING:
        return a.s
    if t == AT.INTS:
        return list(a.ints)
    if t == AT.FLOATS:
        return list(a.floats)
    if t == AT.STRINGS:
        return list(a.strings)
    if t == AT.BOOLEAN:
        return a.b
    if t == AT.BOOLEANS:
        return list(a.bools)
    if t == AT.BLOCK:
        return program.block(a.block_idx)
    if t == AT.BLOCKS:
        return [program.block(i) for i in a.blocks_idx]
    if t == AT.LONG:
        return a.l
    if t == AT.LONGS:
        return list(a.longs)
    raise TypeError(f"unknown attr type {t}")


# --------------------------------------------------------------------------
# Block
# --------------------------------------------------------------------------
class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.forward_block_idx = -1
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- vars -------------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        global_block = self.program.global_block()
        param = Parameter(global_block, **kwargs)
        global_block.vars[param.name] = param
        return param

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"var {name} not in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def _var_recursive(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        raise ValueError(f"var {name} not found from block {self.idx}")

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        try:
            return self._var_recursive(name)
        except ValueError:
            return None

    def has_var_recursive(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def _rename_var(self, old: str, new: str):
        v = self.vars.pop(old)
        v.name = new
        self.vars[new] = v
        for op in self.ops:
            op._rename_input(old, new)
            op._rename_output(old, new)
        return v

    def _remove_var(self, name: str):
        self.vars.pop(name, None)

    # -- ops --------------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None,
                  **kwargs) -> Operator:
        if in_dygraph_mode():
            tracer = _dygraph_tracer()
            return tracer.trace_op(type, inputs or {}, outputs or {},
                                   attrs or {})
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self.program._version += 1
        for names in op.outputs.values():
            for n in names:
                v = self.vars.get(n)
                if v is not None:
                    v.op = op
        info = OPS._map.get(type)
        if info is not None and info.infer_shape is not None:
            info.infer_shape(op, self)
        return op

    def _prepend_op(self, type: str, inputs=None, outputs=None, attrs=None,
                    **kwargs) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        return op

    def _insert_op(self, index: int, type: str, inputs=None, outputs=None,
                   attrs=None, **kwargs) -> Operator:
        op = Operator(self, type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._version += 1
        return op

    def _remove_op(self, index: int, end: Optional[int] = None):
        del self.ops[index:(index + 1) if end is None else end]
        self.program._version += 1

    def _sync_with_cpp(self):
        pass  # no C++ mirror to sync

    def to_string(self, throw_on_error=False, with_details=False):
        lines = [f"block idx={self.idx} parent={self.parent_idx}"]
        for v in self.vars.values():
            lines.append("    " + v.to_string())
        for op in self.ops:
            lines.append("    " + op.to_string())
        return "\n".join(lines)

    __repr__ = __str__ = lambda self: self.to_string()

    def _to_proto(self) -> framework_pb2.BlockDesc:
        bd = framework_pb2.BlockDesc()
        bd.idx = self.idx
        bd.parent_idx = self.parent_idx
        bd.forward_block_idx = self.forward_block_idx
        for v in self.vars.values():
            bd.vars.append(v._to_proto())
        for op in self.ops:
            bd.ops.append(op._to_proto())
        return bd


# --------------------------------------------------------------------------
# Program
# --------------------------------------------------------------------------
class Program:
    """A multi-block program (reference framework.py:3843). Blocks trace to
    XLA computations; block 0 is global."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0, -1)]
        self.current_block_idx = 0
        self._seed = 0
        self._version = 0  # bumped on mutation; part of executor cache key
        self._is_start_up_program = False
        self._op_role_var: List[str] = []
        self._appending_grad_times = 0
        self.lr_sheduler = None

    # -- structure --------------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, new_idx, parent)
        self.blocks.append(b)
        self.current_block_idx = new_idx
        self._version += 1
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = int(seed)

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self):
        return self.global_block().all_parameters()

    # -- clone / prune ----------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            nb.forward_block_idx = b.forward_block_idx
            p.blocks.append(nb)
        for b, nb in zip(self.blocks, p.blocks):
            for name, v in b.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(nb, shape=v.shape, dtype=v.dtype,
                                   name=v.name, trainable=v.trainable,
                                   optimize_attr=v.optimize_attr,
                                   regularizer=v.regularizer)
                    nv.lod_level = v.lod_level
                else:
                    nv = Variable(nb, type=v.type, name=v.name, shape=v.shape,
                                  dtype=v.dtype, lod_level=v.lod_level,
                                  persistable=v.persistable,
                                  stop_gradient=v.stop_gradient,
                                  is_data=v.is_data,
                                  need_check_feed=v.need_check_feed)
                nb.vars[name] = nv
            for op in b.ops:
                attrs = dict(op.attrs)
                for k, val in attrs.items():
                    if isinstance(val, Block):
                        attrs[k] = p.blocks[val.idx]
                    elif isinstance(val, list) and val and isinstance(val[0], Block):
                        attrs[k] = [p.blocks[x.idx] for x in val]
                if for_test and "is_test" in _op_attr_names(op.type):
                    attrs["is_test"] = True
                nop = Operator(nb, op.type,
                               inputs={k: list(v) for k, v in op.inputs.items()},
                               outputs={k: list(v) for k, v in op.outputs.items()},
                               attrs=attrs)
                nb.ops.append(nop)
        p.current_block_idx = self.current_block_idx
        p._seed = self._seed
        p.lr_sheduler = self.lr_sheduler
        # AMP dynamic loss scaling state names/hyperparams ride the
        # program (mixed_precision.decorator); a clone (CompiledProgram
        # build-strategy re-apply, transpiled trainer programs,
        # use_prune=True) must keep them or the cloned program keeps the
        # scaled-loss/unscale ops but silently loses the scale update
        # and the overflow-step discard
        amp = getattr(self, "_amp_dynamic", None)
        if amp is not None:
            p._amp_dynamic = dict(amp)
        return p

    def _prune(self, targets):
        """Backward-slice the global block to the ops needed for targets
        (reference framework.py Program._prune_with_input)."""
        if not isinstance(targets, (list, tuple)):
            targets = [targets]
        p = self.clone()
        block = p.global_block()
        needed = {t.name if isinstance(t, Variable) else str(t)
                  for t in targets}
        keep = []
        for op in reversed(block.ops):
            if any(n in needed for n in op.output_arg_names):
                keep.append(op)
                needed.update(op.input_arg_names)
        block.ops = list(reversed(keep))
        # drop vars no longer referenced (params stay: they're persistable)
        referenced = set(needed)
        for op in block.ops:
            referenced.update(op.output_arg_names)
        block.vars = {n: v for n, v in block.vars.items()
                      if n in referenced or v.persistable}
        p._version += 1
        return p

    def _inference_optimize(self, prune_read_op=True):
        return self.clone(for_test=True)

    # -- serialization ----------------------------------------------------
    def desc_proto(self) -> framework_pb2.ProgramDesc:
        pd = framework_pb2.ProgramDesc()
        for b in self.blocks:
            pd.blocks.append(b._to_proto())
        pd.version.version = 0
        return pd

    @property
    def desc(self):
        return self.desc_proto()

    def serialize_to_string(self) -> bytes:
        return self.desc_proto().SerializeToString()

    @staticmethod
    def parse_from_string(binary: bytes) -> "Program":
        # native wire-format validation first (programdesc.cpp): catches
        # truncation / dangling var refs with a precise report instead of
        # a deep KeyError later (reference: the C++ ProgramDesc layer
        # validates on load)
        try:
            from ..native import inspect_program_bytes
            report = inspect_program_bytes(binary)
        except Exception:
            report = None  # native toolchain unavailable: python path only
        if report and report.get("errors"):
            raise ValueError(
                "invalid ProgramDesc: " + "; ".join(report["errors"][:8]))
        pd = framework_pb2.ProgramDesc()
        pd.ParseFromString(binary)
        return Program._from_proto(pd)

    @staticmethod
    def _from_proto(pd: framework_pb2.ProgramDesc) -> "Program":
        p = Program()
        p.blocks = []
        for bd in pd.blocks:
            b = Block(p, bd.idx, bd.parent_idx)
            b.forward_block_idx = bd.forward_block_idx
            p.blocks.append(b)
        for bd, b in zip(pd.blocks, p.blocks):
            for vd in bd.vars:
                vt = vd.type.type
                shape, dtype, lod_level = (), VarDesc.VarType.FP32, 0
                if vt == VarDesc.VarType.LOD_TENSOR:
                    shape = tuple(vd.type.lod_tensor.tensor.dims)
                    dtype = vd.type.lod_tensor.tensor.data_type
                    lod_level = vd.type.lod_tensor.lod_level
                elif vt == VarDesc.VarType.SELECTED_ROWS:
                    shape = tuple(vd.type.selected_rows.dims)
                    dtype = vd.type.selected_rows.data_type
                elif vt == VarDesc.VarType.LOD_TENSOR_ARRAY:
                    shape = tuple(vd.type.tensor_array.tensor.dims)
                    dtype = vd.type.tensor_array.tensor.data_type
                    lod_level = vd.type.tensor_array.lod_level
                v = Variable(b, type=vt, name=vd.name, shape=shape,
                             dtype=dtype, lod_level=lod_level,
                             persistable=vd.persistable,
                             need_check_feed=vd.need_check_feed)
                b.vars[vd.name] = v
            for od in bd.ops:
                ins = {v.parameter: list(v.arguments) for v in od.inputs}
                outs = {v.parameter: list(v.arguments) for v in od.outputs}
                attrs = {a.name: _attr_from_proto(a, p) for a in od.attrs}
                b.ops.append(Operator(b, od.type, inputs=ins, outputs=outs,
                                      attrs=attrs))
        p.current_block_idx = 0
        return p

    def to_string(self, throw_on_error=False, with_details=False):
        return "\n".join(b.to_string() for b in self.blocks)

    __repr__ = __str__ = lambda self: self.to_string()


def _op_attr_names(op_type: str):
    if OPS.has(op_type):
        return OPS.get(op_type).attr_defaults.keys()
    return ()


# --------------------------------------------------------------------------
# default programs + guards
# --------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()
_startup_program_._is_start_up_program = True


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)
