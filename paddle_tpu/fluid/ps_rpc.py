"""Parameter-server RPC plane — threaded TCP + length-prefixed pickle.

TPU-native stand-in for the reference's gRPC/BRPC variable RPC stack
(reference: paddle/fluid/operators/distributed/send_recv.proto.in —
SendVariable/GetVariable/PrefetchVariable; grpc/grpc_client.h:95,
request_handler_impl.cc). On TPU pods the DENSE data path is ICI
collectives under pjit; this host-side DCN plane exists for the sparse
parameter-server configs (beyond-HBM embedding tables live in host RAM on
pserver processes, like the reference's Wide&Deep path). Python threads are
fine here: the payloads are numpy blobs and the work is IO-bound.

Wire format: 8-byte big-endian length + pickle of a dict
{"method": ..., **kwargs}; response likewise {"ok": bool, ...}.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

_LEN = struct.Struct(">Q")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class VarServer:
    """Serves variables + barriers for one pserver process (reference:
    listen_and_serv_op.cc:333 RunImpl's gRPC server)."""

    def __init__(self, endpoint: str,
                 handlers: Dict[str, Callable[..., Any]]):
        host, port = endpoint.rsplit(":", 1)
        self._handlers = handlers
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        method = msg.pop("method")
                        if method == "stop":
                            _send_msg(self.request, {"ok": True})
                            outer._stop_evt.set()
                            return
                        fn = outer._handlers.get(method)
                        if fn is None:
                            _send_msg(self.request,
                                      {"ok": False,
                                       "error": f"no method {method}"})
                            continue
                        try:
                            res = fn(**msg)
                            _send_msg(self.request, {"ok": True, "result": res})
                        except Exception as e:  # surfaced to the client
                            _send_msg(self.request,
                                      {"ok": False, "error": repr(e)})
                except (ConnectionError, OSError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, int(port)), _Handler)
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self):
        self._thread.start()
        return self

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._stop_evt.wait(timeout)

    def shutdown(self):
        self._stop_evt.set()
        self._srv.shutdown()
        self._srv.server_close()


class VarClient:
    """Per-endpoint client with one persistent connection (reference:
    grpc_client.h AsyncSendVar/AsyncGetVar calling convention)."""

    _pool: Dict[str, "VarClient"] = {}
    _pool_lock = threading.Lock()

    def __init__(self, endpoint: str, connect_timeout: float = 30.0):
        self.endpoint = endpoint
        host, port = endpoint.rsplit(":", 1)
        deadline = time.time() + connect_timeout
        last = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection((host, int(port)),
                                                      timeout=120.0)
                break
            except OSError as e:  # server may not be up yet — retry
                last = e
                time.sleep(0.1)
        else:
            raise ConnectionError(
                f"cannot reach pserver {endpoint}: {last}")
        self._lock = threading.Lock()

    @classmethod
    def of(cls, endpoint: str) -> "VarClient":
        with cls._pool_lock:
            c = cls._pool.get(endpoint)
            if c is None:
                c = cls._pool[endpoint] = VarClient(endpoint)
            return c

    @classmethod
    def reset_pool(cls):
        with cls._pool_lock:
            for c in cls._pool.values():
                try:
                    c._sock.close()
                except OSError:
                    pass
            cls._pool.clear()

    def call(self, method: str, **kwargs):
        with self._lock:
            _send_msg(self._sock, {"method": method, **kwargs})
            resp = _recv_msg(self._sock)
        if not resp.get("ok"):
            raise RuntimeError(
                f"rpc {method} on {self.endpoint} failed: "
                f"{resp.get('error')}")
        return resp.get("result")

    # convenience wrappers mirroring send_recv.proto service methods
    def send_var(self, name: str, value: np.ndarray, trainer_id: int = 0,
                 rows=None, height: int = 0):
        return self.call("send_var", name=name, value=np.asarray(value),
                         trainer_id=trainer_id,
                         rows=None if rows is None else list(map(int, rows)),
                         height=int(height))

    def get_var(self, name: str, trainer_id: int = 0) -> np.ndarray:
        return self.call("get_var", name=name, trainer_id=trainer_id)

    def prefetch_rows(self, name: str, rows) -> np.ndarray:
        return self.call("prefetch_rows", name=name,
                         rows=list(map(int, rows)))

    def barrier(self, kind: str, trainer_id: int = 0):
        return self.call("barrier", kind=kind, trainer_id=trainer_id)

    def stop(self):
        try:
            with self._lock:
                _send_msg(self._sock, {"method": "stop"})
                _recv_msg(self._sock)
        except (ConnectionError, OSError):
            pass


class HeartBeatMonitor:
    """Worker-liveness watchdog on the pserver (reference:
    operators/distributed/heart_beat_monitor.h:54 — every worker RPC
    updates its beat; a monitor thread flags workers whose last beat is
    older than the timeout). Detection only, like the reference: dead
    workers are logged and queryable; tearing the job down is the
    launcher's job (launch.py watch loop)."""

    def __init__(self, worker_num: int, timeout: float = 60.0,
                 check_interval: float = 3.0,
                 on_dead: Optional[Callable[[int], None]] = None):
        self.worker_num = worker_num
        self.timeout = timeout
        self.check_interval = check_interval
        self._on_dead = on_dead
        self._beats: Dict[int, float] = {}
        self._dead: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def update(self, worker_id: int) -> None:
        now = time.time()
        with self._lock:
            self._beats[int(worker_id)] = now
            self._dead.discard(int(worker_id))

    def dead_workers(self):
        with self._lock:
            return sorted(self._dead)

    def alive_workers(self):
        with self._lock:
            return sorted(set(self._beats) - self._dead)

    def _scan(self):
        while not self._stop.wait(self.check_interval):
            now = time.time()
            newly_dead = []
            with self._lock:
                for wid, t in self._beats.items():
                    if wid not in self._dead and now - t > self.timeout:
                        self._dead.add(wid)
                        newly_dead.append(wid)
            for wid in newly_dead:
                import logging
                logging.getLogger("paddle_tpu.ps").warning(
                    "HeartBeatMonitor: worker %d silent for >%.0fs — "
                    "presumed dead", wid, self.timeout)
                if self._on_dead is not None:
                    self._on_dead(wid)

    def start_monitor(self) -> "HeartBeatMonitor":
        self._thread = threading.Thread(target=self._scan, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.check_interval * 2)

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"heartbeat": lambda trainer_id=0: (self.update(trainer_id)
                                                   or True),
                # liveness is queryable over RPC (the reference exposes it
                # via GetWorkerStatus on the monitor thread)
                "dead_workers": lambda trainer_id=0: self.dead_workers(),
                "alive_workers": lambda trainer_id=0: self.alive_workers()}


class WorkerHeartBeat:
    """Worker-side beat thread: pings every pserver endpoint periodically
    (reference workers beat inside their send RPCs; an idle worker still
    beats here so slow data pipelines aren't declared dead)."""

    def __init__(self, endpoints, trainer_id: int, interval: float = 5.0):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self):
        while not self._stop.wait(self.interval):
            for ep in self.endpoints:
                try:
                    VarClient.of(ep).call("heartbeat",
                                          trainer_id=self.trainer_id)
                except Exception:
                    pass  # server gone/restarting; the monitor sees silence

    def start(self) -> "WorkerHeartBeat":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)


class ReduceService:
    """Sum-across-workers service for host-side metric reductions (the
    reference's GlooWrapper::AllReduce role — gloo_wrapper.h:146). Workers
    push a named array; get blocks until all ``world`` contributions of the
    current generation arrived, then every worker reads the sum. The
    generation resets once all workers fetched, so the same name can be
    reduced repeatedly."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._sums: Dict[str, np.ndarray] = {}
        self._contrib: Dict[str, set] = {}
        self._fetched: Dict[str, set] = {}

    def push(self, name: str, value, trainer_id: int):
        arr = np.asarray(value, np.float64)
        with self._cv:
            if trainer_id in self._contrib.setdefault(name, set()):
                raise RuntimeError(
                    f"reduce '{name}': trainer {trainer_id} pushed twice in "
                    f"one generation")
            cur = self._sums.get(name)
            self._sums[name] = arr if cur is None else cur + arr
            self._contrib[name].add(trainer_id)
            self._cv.notify_all()
        return True

    def get(self, name: str, trainer_id: int, world: int,
            timeout: float = 300.0):
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._contrib.get(name, ())) >= world, timeout)
            if not ok:
                raise TimeoutError(
                    f"reduce '{name}': only "
                    f"{len(self._contrib.get(name, ()))}/{world} workers "
                    f"contributed within {timeout}s")
            result = self._sums[name]
            fetched = self._fetched.setdefault(name, set())
            fetched.add(trainer_id)
            if len(fetched) >= world:  # everyone has it → reset generation
                self._sums.pop(name, None)
                self._contrib.pop(name, None)
                self._fetched.pop(name, None)
                self._cv.notify_all()
            return result

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"reduce_push": self.push, "reduce_get": self.get}
