"""Parameter-server RPC plane — threaded TCP + length-prefixed pickle.

TPU-native stand-in for the reference's gRPC/BRPC variable RPC stack
(reference: paddle/fluid/operators/distributed/send_recv.proto.in —
SendVariable/GetVariable/PrefetchVariable; grpc/grpc_client.h:95,
request_handler_impl.cc). On TPU pods the DENSE data path is ICI
collectives under pjit; this host-side DCN plane exists for the sparse
parameter-server configs (beyond-HBM embedding tables live in host RAM on
pserver processes, like the reference's Wide&Deep path). Python threads are
fine here: the payloads are numpy blobs and the work is IO-bound.

Wire format: 8-byte big-endian length + pickle of a dict
{"method": ..., **kwargs}; response likewise {"ok": bool, ...}.

Fault tolerance (docs/FAULT_TOLERANCE.md):
  * ``VarClient.call`` retries transient ``ConnectionError``/``OSError``
    with exponential backoff and reconnect, up to FLAGS_rpc_retry_times
    attempts, each bounded by FLAGS_rpc_deadline ms (reference
    grpc_client.cc FLAGS_rpc_deadline/FLAGS_rpc_retry_times). Idempotent
    methods are re-sent verbatim; every other method carries a send-dedup
    token the server replays from a bounded cache, so a retry after a
    lost response cannot double-apply a gradient.
  * ``_recv_msg`` rejects length prefixes beyond
    FLAGS_rpc_max_message_size with ``RpcProtocolError`` (never retried).
  * ``BarrierManager`` + ``HeartBeatMonitor``: barriers release with
    ``WorkerDeadError`` as soon as a participant is declared dead instead
    of blocking for the full FLAGS_barrier_deadline.
"""
from __future__ import annotations

import itertools
import logging
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import core

_LEN = struct.Struct(">Q")

_LOG = logging.getLogger("paddle_tpu.ps")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    limit = int(core.globals_["FLAGS_rpc_max_message_size"])
    if n > limit:
        # a garbage/malicious prefix must fail as a PROTOCOL error, not
        # as a MemoryError from trying to buffer it
        raise core.RpcProtocolError(
            f"rpc message length prefix {n} exceeds "
            f"FLAGS_rpc_max_message_size={limit} — corrupted or "
            f"malicious peer stream")
    return pickle.loads(_recv_exact(sock, n))


class VarServer:
    """Serves variables + barriers for one pserver process (reference:
    listen_and_serv_op.cc:333 RunImpl's gRPC server).

    Requests carrying a ``_dedup`` token (non-idempotent methods from a
    retrying VarClient) execute AT MOST ONCE per server lifetime: the
    token is reserved the moment the request is read, a retry arriving
    while the original is still executing (client timed out mid-call)
    WAITS for that execution's outcome, and a retry arriving after
    completion replays the cached response — at-least-once delivery,
    exactly-once application. The cache does not survive a server
    restart."""

    _DEDUP_CAP = 4096

    def __init__(self, endpoint: str,
                 handlers: Dict[str, Callable[..., Any]]):
        host, port = endpoint.rsplit(":", 1)
        self._handlers = handlers
        self._dedup: "OrderedDict[tuple, dict]" = OrderedDict()
        self._dedup_lock = threading.Lock()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        method = msg.pop("method")
                        if method == "stop":
                            _send_msg(self.request, {"ok": True})
                            outer._stop_evt.set()
                            return
                        token = msg.pop("_dedup", None)
                        if token is not None:
                            kind, val = outer._dedup_begin(token)
                            if kind == "done":
                                _send_msg(self.request, val)
                                continue
                            if kind == "pending":
                                # the original execution (from a timed-
                                # out connection) is still running —
                                # wait for ITS outcome, never re-execute
                                _send_msg(self.request,
                                          outer._dedup_wait(token, val))
                                continue
                        fn = outer._handlers.get(method)
                        if fn is None:
                            _send_msg(self.request,
                                      {"ok": False,
                                       "error": f"no method {method}"})
                            continue
                        try:
                            res = fn(**msg)
                            resp = {"ok": True, "result": res}
                        except Exception as e:  # surfaced to the client
                            # error_type lets the client re-raise the
                            # TYPED exception (WorkerDeadError survives
                            # the wire — tests/launchers dispatch on it)
                            resp = {"ok": False, "error": repr(e),
                                    "error_type": type(e).__name__}
                        if token is not None:
                            outer._dedup_put(token, resp)
                        _send_msg(self.request, resp)
                except core.RpcProtocolError:
                    _LOG.warning("VarServer: dropping connection with "
                                 "invalid framing", exc_info=True)
                    return
                except (ConnectionError, OSError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, int(port)), _Handler)
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def _dedup_begin(self, token):
        """Reserve a token. Returns ("new", event) when this call owns
        execution, ("pending", event) when another connection is
        executing it right now, ("done", response) when it completed."""
        t = tuple(token)
        with self._dedup_lock:
            entry = self._dedup.get(t)
            if entry is not None:
                return entry
            ev = threading.Event()
            entry = self._dedup[t] = ("pending", ev)
            return ("new", ev)

    def _dedup_wait(self, token, event):
        t = tuple(token)
        while not event.wait(1.0):
            if self._stop_evt.is_set():
                return {"ok": False,
                        "error": "server stopping before the original "
                                 "execution of this request completed"}
        with self._dedup_lock:
            entry = self._dedup.get(t)
        if entry is not None and entry[0] == "done":
            return entry[1]
        return {"ok": False, "error": "dedup entry lost mid-wait"}

    def _dedup_put(self, token, resp):
        t = tuple(token)
        with self._dedup_lock:
            prev = self._dedup.get(t)
            self._dedup[t] = ("done", resp)
            self._dedup.move_to_end(t)
            if len(self._dedup) > self._DEDUP_CAP:
                # evict oldest COMPLETED entries; pending ones belong to
                # live executions and their waiters
                for k in list(self._dedup):
                    if len(self._dedup) <= self._DEDUP_CAP:
                        break
                    if self._dedup[k][0] == "done" and k != t:
                        del self._dedup[k]
        if prev is not None and prev[0] == "pending":
            prev[1].set()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self):
        self._thread.start()
        return self

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._stop_evt.wait(timeout)

    def shutdown(self):
        self._stop_evt.set()
        self._srv.shutdown()
        self._srv.server_close()
        # sever live connections like a process death would — peers see
        # ConnectionError immediately (and their retry plane kicks in)
        # instead of blocked reads on a half-dead server
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# errors a pserver handler may legitimately raise that the client should
# re-raise TYPED instead of as a generic RuntimeError
_WIRE_ERRORS: Dict[str, type] = {
    "WorkerDeadError": core.WorkerDeadError,
    "TimeoutError": TimeoutError,
    "KeyError": KeyError,
}


class VarClient:
    """Per-endpoint client with one persistent connection (reference:
    grpc_client.h AsyncSendVar/AsyncGetVar calling convention).

    ``call`` survives transient transport failures: the socket is closed,
    re-connected, and the request re-sent with exponential backoff up to
    FLAGS_rpc_retry_times attempts. Methods in ``_IDEMPOTENT`` are safe
    verbatim; every other method is stamped with a per-client dedup token
    the server replays instead of re-executing."""

    _pool: Dict[str, "VarClient"] = {}
    _pool_lock = threading.Lock()

    # read-only methods: re-sending after a lost response cannot change
    # server state. NOTE barrier/reduce_get are deliberately NOT here:
    # a barrier retry that lands AFTER its round released would enroll
    # as a phantom arrival in the NEXT round (and a reduce_get retry
    # after a generation reset would re-join a fresh generation) — they
    # ride the dedup-token path instead, replaying the completed
    # response; in-round duplicates are additionally absorbed by the
    # trainer-id keying.
    _IDEMPOTENT = frozenset({
        "get_var", "prefetch_rows", "heartbeat",
        "dead_workers", "alive_workers", "table_stats",
    })

    def __init__(self, endpoint: str, connect_timeout: float = 30.0):
        self.endpoint = endpoint
        self._host, port = endpoint.rsplit(":", 1)
        self._port = int(port)
        self._connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._token_prefix = f"{os.getpid()}:{id(self):x}"
        self._seq = itertools.count()
        with self._lock:
            self._connect_locked(connect_timeout)

    # ------------------------------------------------------------ plumbing
    @property
    def _deadline_s(self) -> float:
        return float(core.globals_["FLAGS_rpc_deadline"]) / 1000.0

    def _connect_locked(self, connect_timeout: float):
        """(Re)establish the connection; the server may be down/restarting
        — poll until ``connect_timeout`` elapses."""
        deadline = time.time() + connect_timeout
        last = None
        while time.time() < deadline:
            try:
                self._sock = socket.create_connection(
                    (self._host, self._port), timeout=self._deadline_s)
                return
            except OSError as e:  # server not up (yet) — retry
                last = e
                time.sleep(0.1)
        self._sock = None
        raise ConnectionError(
            f"cannot reach pserver {self.endpoint}: {last}")

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    @classmethod
    def of(cls, endpoint: str) -> "VarClient":
        with cls._pool_lock:
            c = cls._pool.get(endpoint)
            if c is None:
                c = cls._pool[endpoint] = VarClient(endpoint)
            return c

    @classmethod
    def reset_pool(cls):
        with cls._pool_lock:
            for c in cls._pool.values():
                with c._lock:
                    c._close_locked()
            cls._pool.clear()

    # ---------------------------------------------------------------- call
    def call(self, method: str, _rpc_timeout: Optional[float] = None,
             _rpc_retries: Optional[int] = None, **kwargs):
        """One RPC with retry/backoff/reconnect for transient transport
        errors. Protocol errors (bad framing) and application errors
        (ok=False responses) are never retried. ``_rpc_timeout`` (s) /
        ``_rpc_retries`` override the FLAGS for this call only (the
        heartbeat thread uses short ones so a dead server can't pin it)."""
        deadline_s = (self._deadline_s if _rpc_timeout is None
                      else float(_rpc_timeout))
        retries = (max(0, int(core.globals_["FLAGS_rpc_retry_times"]))
                   if _rpc_retries is None else max(0, int(_rpc_retries)))
        msg = {"method": method, **kwargs}
        if method not in self._IDEMPOTENT:
            msg["_dedup"] = (self._token_prefix, next(self._seq))
        attempt = 0
        while True:
            try:
                with self._lock:
                    if self._sock is None:
                        self._connect_locked(self._connect_timeout)
                    self._sock.settimeout(deadline_s)
                    _send_msg(self._sock, msg)
                    resp = _recv_msg(self._sock)
                break
            except core.RpcProtocolError:
                with self._lock:
                    self._close_locked()
                raise
            except (ConnectionError, OSError) as e:
                with self._lock:
                    self._close_locked()
                attempt += 1
                if attempt > retries:
                    raise ConnectionError(
                        f"rpc {method} on {self.endpoint} failed after "
                        f"{retries} retries: {e!r}") from e
                backoff = min(2.0, 0.05 * (2 ** (attempt - 1)))
                _LOG.warning(
                    "rpc %s on %s hit %r — retry %d/%d in %.2fs",
                    method, self.endpoint, e, attempt, retries, backoff)
                time.sleep(backoff)
        if not resp.get("ok"):
            err = resp.get("error")
            etype = _WIRE_ERRORS.get(resp.get("error_type"))
            if etype is not None:
                raise etype(
                    f"rpc {method} on {self.endpoint} failed: {err}")
            raise RuntimeError(
                f"rpc {method} on {self.endpoint} failed: {err}")
        return resp.get("result")

    # convenience wrappers mirroring send_recv.proto service methods
    def send_var(self, name: str, value: np.ndarray, trainer_id: int = 0,
                 rows=None, height: int = 0):
        return self.call("send_var", name=name, value=np.asarray(value),
                         trainer_id=trainer_id,
                         rows=None if rows is None else list(map(int, rows)),
                         height=int(height))

    def get_var(self, name: str, trainer_id: int = 0) -> np.ndarray:
        return self.call("get_var", name=name, trainer_id=trainer_id)

    def prefetch_rows(self, name: str, rows) -> np.ndarray:
        return self.call("prefetch_rows", name=name,
                         rows=list(map(int, rows)))

    def barrier(self, kind: str, trainer_id: int = 0):
        return self.call("barrier", kind=kind, trainer_id=trainer_id)

    def stop(self):
        try:
            with self._lock:
                if self._sock is None:
                    return
                _send_msg(self._sock, {"method": "stop"})
                _recv_msg(self._sock)
        except (ConnectionError, OSError):
            pass


class HeartBeatMonitor:
    """Worker-liveness watchdog on the pserver (reference:
    operators/distributed/heart_beat_monitor.h:54 — every worker RPC
    updates its beat; a monitor thread flags workers whose last beat is
    older than the timeout). Dead workers are logged and queryable, AND
    death listeners fire so collectives (BarrierManager, ReduceService)
    release their waiters promptly with WorkerDeadError; tearing the
    whole job down remains the launcher's call (launch.py watch loop)."""

    def __init__(self, worker_num: int, timeout: float = 60.0,
                 check_interval: float = 3.0,
                 on_dead: Optional[Callable[[int], None]] = None):
        self.worker_num = worker_num
        self.timeout = timeout
        self.check_interval = check_interval
        self._listeners: List[Callable[[int], None]] = []
        if on_dead is not None:
            self._listeners.append(on_dead)
        self._beats: Dict[int, float] = {}
        self._dead: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_dead_listener(self, cb: Callable[[int], None]) -> None:
        """Register an extra callback fired (off-lock) for every newly
        declared-dead worker id."""
        self._listeners.append(cb)

    def update(self, worker_id: int) -> None:
        now = time.time()
        with self._lock:
            self._beats[int(worker_id)] = now
            self._dead.discard(int(worker_id))

    def dead_workers(self):
        with self._lock:
            return sorted(self._dead)

    def alive_workers(self):
        with self._lock:
            return sorted(set(self._beats) - self._dead)

    def is_dead(self, worker_id: int) -> bool:
        with self._lock:
            return int(worker_id) in self._dead

    def _scan(self):
        while not self._stop.wait(self.check_interval):
            now = time.time()
            newly_dead = []
            with self._lock:
                for wid, t in self._beats.items():
                    if wid not in self._dead and now - t > self.timeout:
                        self._dead.add(wid)
                        newly_dead.append(wid)
            for wid in newly_dead:
                _LOG.warning(
                    "HeartBeatMonitor: worker %d silent for >%.0fs — "
                    "presumed dead", wid, self.timeout)
                for cb in self._listeners:
                    try:
                        cb(wid)
                    except Exception:
                        _LOG.exception("dead-worker listener failed")

    def start_monitor(self) -> "HeartBeatMonitor":
        self._thread = threading.Thread(target=self._scan, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.check_interval * 2)

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"heartbeat": lambda trainer_id=0: (self.update(trainer_id)
                                                   or True),
                # liveness is queryable over RPC (the reference exposes it
                # via GetWorkerStatus on the monitor thread)
                "dead_workers": lambda trainer_id=0: self.dead_workers(),
                "alive_workers": lambda trainer_id=0: self.alive_workers()}


class BarrierManager:
    """Dead-worker-aware rendezvous for ``world`` trainers (replaces the
    reference's RPCServer barrier counters — rpc_server.cc
    IncreaseBatchBarrier/WaitBarrier, which block until a condition or
    forever).

    Arrival is keyed by trainer id, so duplicate arrivals WITHIN a round
    (e.g. a retry racing its still-executing original) are absorbed with
    no double-count; retries landing after the round released are handled
    one layer down by the VarServer dedup cache (barrier RPCs carry
    ``_dedup`` tokens), so they replay the completed response instead of
    phantom-arriving in the next round. When every
    participant arrived, the releasing arrival runs ``on_release`` (the
    pserver's aggregate+optimize action) under the lock, bumps the round
    and wakes everyone. If the HeartBeatMonitor declares a participant
    dead, ALL current and future waiters of the in-flight round raise
    ``WorkerDeadError`` naming the dead worker(s) — within roughly one
    monitor check interval, never the full deadline. Stragglers without
    a death verdict time out after ``deadline`` (FLAGS_barrier_deadline)
    with a TimeoutError naming the missing count."""

    def __init__(self, world: int, monitor: Optional[HeartBeatMonitor]
                 = None, deadline: Optional[float] = None, lock=None):
        self._world = int(world)
        self._monitor = monitor
        self._deadline = (float(core.globals_["FLAGS_barrier_deadline"])
                          if deadline is None else float(deadline))
        self._cv = threading.Condition(lock)
        self._state: Dict[str, Dict[str, Any]] = {}
        if monitor is not None:
            monitor.add_dead_listener(self._on_dead)

    def _on_dead(self, wid: int):
        with self._cv:
            self._cv.notify_all()

    def _check_dead_locked(self, kind: str, st: Dict[str, Any],
                           trainer_id: int):
        if self._monitor is None:
            return
        dead = [d for d in self._monitor.dead_workers()
                if d != int(trainer_id)]
        if dead:
            # abort the in-flight round: every waiter re-checks this on
            # wake and raises too; arrivals reset so a later round (after
            # revival or relaunch) starts clean
            st["arrived"] = set()
            raise core.WorkerDeadError(
                f"barrier '{kind}': worker(s) {dead} declared dead by the "
                f"heartbeat monitor while {self._world} participants were "
                f"expected")

    def arrive(self, kind: str, trainer_id: int,
               on_release: Optional[Callable[[], None]] = None,
               deadline: Optional[float] = None) -> int:
        """Block until all ``world`` participants arrived at ``kind``.
        Returns the completed round number."""
        deadline = self._deadline if deadline is None else float(deadline)
        with self._cv:
            st = self._state.setdefault(kind,
                                        {"arrived": set(), "round": 0})
            self._check_dead_locked(kind, st, trainer_id)
            st["arrived"].add(int(trainer_id))
            if len(st["arrived"]) >= self._world:
                if on_release is not None:
                    on_release()
                st["arrived"] = set()
                st["round"] += 1
                self._cv.notify_all()
                return st["round"]
            rnd = st["round"]
            end = time.time() + deadline
            while st["round"] == rnd:
                remaining = end - time.time()
                if remaining <= 0:
                    missing = self._world - len(st["arrived"])
                    st["arrived"].discard(int(trainer_id))
                    raise TimeoutError(
                        f"barrier '{kind}': {missing} of {self._world} "
                        f"participants missing after {deadline:.0f}s")
                self._cv.wait(min(1.0, remaining))
                self._check_dead_locked(kind, st, trainer_id)
            return st["round"]


class WorkerHeartBeat:
    """Worker-side beat thread: pings every pserver endpoint periodically
    (reference workers beat inside their send RPCs; an idle worker still
    beats here so slow data pipelines aren't declared dead).

    Beats ride PRIVATE connections, not the pooled VarClient: the pooled
    client serializes calls on one socket, so a data RPC blocked in a
    long server-side barrier would stall the beats and get this very
    worker declared dead. Each beat is one short-timeout, zero-retry
    attempt — a missed beat is information, the monitor sees silence."""

    def __init__(self, endpoints, trainer_id: int, interval: float = 5.0):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self.interval = interval
        self._clients: Dict[str, VarClient] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self):
        while not self._stop.wait(self.interval):
            for ep in self.endpoints:
                try:
                    cli = self._clients.get(ep)
                    if cli is None:
                        cli = self._clients[ep] = VarClient(
                            ep, connect_timeout=max(1.0, self.interval))
                    cli.call("heartbeat", trainer_id=self.trainer_id,
                             _rpc_timeout=max(1.0, self.interval * 2),
                             _rpc_retries=0)
                except Exception:
                    # server gone/restarting; the monitor sees silence.
                    # drop the client so the next beat reconnects fresh
                    self._clients.pop(ep, None)

    def start(self) -> "WorkerHeartBeat":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)
        # snapshot: the beat thread may outlive the bounded join and
        # still be mutating the dict
        for cli in list(self._clients.values()):
            with cli._lock:
                cli._close_locked()
        self._clients.clear()


class ReduceService:
    """Sum-across-workers service for host-side metric reductions (the
    reference's GlooWrapper::AllReduce role — gloo_wrapper.h:146). Workers
    push a named array; get blocks until all ``world`` contributions of the
    current generation arrived, then every worker reads the sum. The
    generation resets once all workers fetched, so the same name can be
    reduced repeatedly. With a ``monitor``, a dead worker that has not yet
    contributed releases every waiter with WorkerDeadError instead of
    letting them run out the full timeout."""

    def __init__(self, monitor: Optional[HeartBeatMonitor] = None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._monitor = monitor
        self._sums: Dict[str, np.ndarray] = {}
        self._contrib: Dict[str, set] = {}
        self._fetched: Dict[str, set] = {}
        if monitor is not None:
            monitor.add_dead_listener(
                lambda wid: self._notify_all())

    def _notify_all(self):
        with self._cv:
            self._cv.notify_all()

    def push(self, name: str, value, trainer_id: int):
        arr = np.asarray(value, np.float64)
        with self._cv:
            if trainer_id in self._contrib.setdefault(name, set()):
                raise RuntimeError(
                    f"reduce '{name}': trainer {trainer_id} pushed twice in "
                    f"one generation")
            cur = self._sums.get(name)
            self._sums[name] = arr if cur is None else cur + arr
            self._contrib[name].add(trainer_id)
            self._cv.notify_all()
        return True

    def get(self, name: str, trainer_id: int, world: int,
            timeout: float = 300.0):
        end = time.time() + timeout
        with self._cv:
            while len(self._contrib.get(name, ())) < world:
                if self._monitor is not None:
                    dead = [d for d in self._monitor.dead_workers()
                            if d != int(trainer_id)
                            and d not in self._contrib.get(name, ())]
                    if dead:
                        raise core.WorkerDeadError(
                            f"reduce '{name}': worker(s) {dead} declared "
                            f"dead before contributing "
                            f"({len(self._contrib.get(name, ()))}/{world} "
                            f"arrived)")
                remaining = end - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"reduce '{name}': only "
                        f"{len(self._contrib.get(name, ()))}/{world} "
                        f"workers contributed within {timeout}s")
                self._cv.wait(min(1.0, remaining))
            result = self._sums[name]
            fetched = self._fetched.setdefault(name, set())
            fetched.add(trainer_id)
            if len(fetched) >= world:  # everyone has it → reset generation
                self._sums.pop(name, None)
                self._contrib.pop(name, None)
                self._fetched.pop(name, None)
                self._cv.notify_all()
            return result

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"reduce_push": self.push, "reduce_get": self.get}
