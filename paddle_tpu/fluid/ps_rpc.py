"""Parameter-server RPC plane — threaded TCP + length-prefixed pickle.

TPU-native stand-in for the reference's gRPC/BRPC variable RPC stack
(reference: paddle/fluid/operators/distributed/send_recv.proto.in —
SendVariable/GetVariable/PrefetchVariable; grpc/grpc_client.h:95,
request_handler_impl.cc). On TPU pods the DENSE data path is ICI
collectives under pjit; this host-side DCN plane exists for the sparse
parameter-server configs (beyond-HBM embedding tables live in host RAM on
pserver processes, like the reference's Wide&Deep path). Python threads are
fine here: the payloads are numpy blobs and the work is IO-bound.

Wire format — two generations, negotiated per connection:
  * legacy (v1): 8-byte big-endian length + pickle of a dict
    {"method": ..., **kwargs}; response likewise {"ok": bool, ...}.
  * binary (v2, docs/PS_DATA_PLANE.md): tensor bytes never enter pickle.
    Each frame is a SMALL pickled header (op, name, dtype/shape specs,
    dedup token) followed by the raw contiguous buffers, sent with
    ``sendall(memoryview)`` and received with ``recv_into`` directly
    into preallocated arrays — the reference's gRPC
    ``SerializeToByteBuffer`` zero-copy framing
    (grpc_serde.cc GetTensorPayload / grpc_bytebuffer_stream.h).
    A new client opens every connection with a legacy-framed ``_hello``
    probe; a new server upgrades the connection, an old server answers
    "no method" and the client stays on v1 — old-frame peers keep
    working in both directions. ``PADDLE_TPU_PS_PICKLE_WIRE=1`` pins a
    client to v1 (the paired-bench legacy lane).

Fault tolerance (docs/FAULT_TOLERANCE.md):
  * ``VarClient.call`` retries transient ``ConnectionError``/``OSError``
    with exponential backoff and reconnect, up to FLAGS_rpc_retry_times
    attempts, each bounded by FLAGS_rpc_deadline ms (reference
    grpc_client.cc FLAGS_rpc_deadline/FLAGS_rpc_retry_times). Idempotent
    methods are re-sent verbatim; every other method carries a send-dedup
    token the server replays from a bounded cache, so a retry after a
    lost response cannot double-apply a gradient.
  * ``_recv_msg`` rejects length prefixes beyond
    FLAGS_rpc_max_message_size with ``RpcProtocolError`` (never retried).
  * ``BarrierManager`` + ``HeartBeatMonitor``: barriers release with
    ``WorkerDeadError`` as soon as a participant is declared dead instead
    of blocking for the full FLAGS_barrier_deadline.

Elastic membership (docs/FAULT_TOLERANCE.md "Elastic membership"):
  * Programs bake SLOT endpoints into their op attrs; ``VarClient``
    resolves a slot to the endpoint currently serving it through the
    process-global ``ps_membership`` view on every (re)connect, stamps
    data RPCs with the client's view epoch, and — on a typed
    ``StaleClusterViewError`` response — installs the newer view the
    server shipped back and replays the SAME cached frame (same dedup
    token) against the new owner. Exactly-once survives both re-routes
    and replica failovers: a drained server transfers its dedup
    high-water marks to the destination, which answers replayed tokens
    below the mark without re-executing.
"""
from __future__ import annotations

import contextlib
import itertools
import logging
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import core
from . import ps_membership
from . import telemetry

_LEN = struct.Struct(">Q")

_LOG = logging.getLogger("paddle_tpu.ps")

# wire protocol generations (negotiated per connection via "_hello")
PROTO_PICKLE = 1    # legacy: one pickle blob carries tensors too
PROTO_BINARY = 2    # v2: pickled header + raw zero-copy tensor buffers
PROTO_BINARY_Q = 3  # v3: v2 + quantized buffer specs (fp16 / int8+scale)
WIRE_VERSION = 3

# ---------------------------------------------------------------------------
# wire v3 — quantized tensor frames (docs/PS_DATA_PLANE.md "Compression").
# FLAGS_ps_wire_quant ("" | "fp16" | "int8") turns float32 payload buffers
# of DATA-PLANE methods into lossy wire encodings: fp16 is a plain
# downcast; int8 ships per-row absmax scales (row = leading axis; a 1-D
# array is one row) as an extra f32 buffer right after the int8 buffer.
# Gated three ways so it can never corrupt a peer or a control frame:
#   * negotiation — only connections that agreed on wire v3 in the
#     _hello handshake carry quantized specs (a v2/v1 peer keeps
#     receiving exact frames, both directions);
#   * method allowlist — only the tensor data plane quantizes; control,
#     membership, handoff, and replica-forward frames stay exact (the
#     replica chain MUST forward the decoded apply, not the compressed
#     frame, or the standby diverges from the primary bit-for-bit);
#   * dtype/finiteness — only finite float32 arrays quantize; a
#     non-finite int8 candidate ships RAW so the pserver's
#     FLAGS_ps_reject_nonfinite guard sees the poison exactly (fp16
#     keeps NaN/Inf representable, and an fp16 OVERFLOW becomes Inf —
#     also caught by the guard at dequant-on-receive).
_QUANT_MODES = ("", "fp16", "int8")
# derived from the canonical tensor-plane set (ps_membership) minus
# dgc_send: DGC's compression IS the sparsity, its values are ~0.1% of
# the payload already, and quantizing them would inject error AFTER the
# compressor zeroed the residual by the exact values — a systematic
# per-push bias that error feedback never corrects (the geo flat-delta
# path tolerates quantization because its pull-telescoped shifts feed
# the wire error back into the baseline; the direct grad path has no
# such loop).
_QUANT_METHODS = ps_membership.TENSOR_DATA_METHODS - {"dgc_send"}


def _quant_mode() -> str:
    mode = str(core.globals_["FLAGS_ps_wire_quant"] or "")
    if mode not in _QUANT_MODES:
        raise ValueError(
            f"FLAGS_ps_wire_quant={mode!r} — expected one of "
            f"{_QUANT_MODES}")
    return mode


# bytes-saved evidence (docs/OBSERVABILITY.md): raw = the quantized
# arrays' pre-quant payload bytes, sent = their on-wire bytes (incl.
# int8 scale vectors). Registered lazily as the "ps_wire" metrics view
# so ps_wire_bytes_{raw,sent}_total land on GET /metrics the moment the
# first quantized frame is encoded.
_QUANT_STATS = {"bytes_raw_total": 0, "bytes_sent_total": 0,
                "frames_quantized_total": 0}
_QUANT_STATS_LOCK = threading.Lock()
_QUANT_VIEW = None


def quant_wire_stats() -> Dict[str, int]:
    with _QUANT_STATS_LOCK:
        return dict(_QUANT_STATS)


def reset_quant_wire_stats() -> None:
    with _QUANT_STATS_LOCK:
        for k in _QUANT_STATS:
            _QUANT_STATS[k] = 0


def _bump_quant_stats(raw: int, sent: int) -> None:
    global _QUANT_VIEW
    with _QUANT_STATS_LOCK:
        _QUANT_STATS["bytes_raw_total"] += int(raw)
        _QUANT_STATS["bytes_sent_total"] += int(sent)
        _QUANT_STATS["frames_quantized_total"] += 1
        need_view = _QUANT_VIEW is None
        if need_view:
            _QUANT_VIEW = True  # claim before dropping the lock
    if need_view:
        _QUANT_VIEW = telemetry.REGISTRY.register_view(
            "ps_wire", quant_wire_stats)


def _quant_int8(arr: np.ndarray):
    """Per-row symmetric int8: scale[r] = absmax(row r)/127 (1.0 for
    all-zero rows so dequant stays exact zeros). Returns (q, scale).
    Multiplies by the reciprocal scale with out= reuse — the encode is
    the hot half of the codec (decode is one cast + one multiply)."""
    n = arr.shape[0] if arr.ndim > 1 else 1
    a2 = arr.reshape(n, -1)
    absmax = np.abs(a2).max(axis=1).astype(np.float32)
    scale = absmax / np.float32(127.0)
    scale[scale == 0] = 1.0
    tmp = a2 * (np.float32(1.0) / scale)[:, None]
    np.rint(tmp, out=tmp)
    np.clip(tmp, -127, 127, out=tmp)
    return tmp.astype(np.int8).reshape(arr.shape), scale


def _dequant_int8(q: np.ndarray, scale: np.ndarray,
                  dtype: np.dtype) -> np.ndarray:
    n = q.shape[0] if q.ndim > 1 else 1
    q2 = q.reshape(n, -1)
    out = (q2.astype(np.float32) * scale.reshape(-1, 1)).astype(
        dtype, copy=False)
    return out.reshape(q.shape)

# ---------------------------------------------------------------------------
# serving-time embedding row cache hook (docs/SERVING.md). When a cache is
# installed, distributed_lookup_table FORWARD pulls consult it before
# fanning out to the pservers — a fully-hit lookup issues zero RPCs.
# Gradient pushes never touch it, and nothing installs one in training
# processes; the ServingEngine installs its EmbeddingCache for its
# lifetime. Process-global by design (the op kernels have no serving
# context): the last installed cache wins, installers restore the
# previous one on teardown.
_ROW_CACHE = None


def install_row_cache(cache):
    """Install ``cache`` (EmbeddingCache-shaped: ``lookup(table, ids,
    fetch_fn)``) as the process row cache; returns the previously
    installed cache (or None) so callers can restore it."""
    global _ROW_CACHE
    prev = _ROW_CACHE
    _ROW_CACHE = cache
    return prev


def current_row_cache():
    return _ROW_CACHE


# Cross-process half of the same contract (docs/SERVING.md "Fleet"): a
# TRAINER process installs an invalidation publisher
# (serving.fleet.InvalidationPublisher-shaped: ``publish(table, ids)``)
# and the grad-push site fans the pushed row ids to every remote serving
# EmbeddingCache over the wire — never installed as a row cache (a
# publisher must not be consulted on forward lookups).
_INV_PUBLISHER = None


def install_invalidation_publisher(pub):
    """Install ``pub`` as the process invalidation publisher; returns
    the previously installed one (or None) so callers can restore it."""
    global _INV_PUBLISHER
    prev = _INV_PUBLISHER
    _INV_PUBLISHER = pub
    return prev


def current_invalidation_publisher():
    return _INV_PUBLISHER


# ---------------------------------------------------------------------------
# deadline-aware call budget (docs/SERVING.md "Ingress & overload"). The
# serving ingress stamps each request with a deadline; the engine installs
# the batch's remaining budget on the dispatching thread and every
# VarClient.call under it caps its socket/connect timeouts at the
# remainder — an expired budget raises core.DeadlineExceededError instead
# of starting (or retrying) an RPC the caller can no longer use. Thread-
# local because concurrent requests carry independent budgets; the
# sharded-pull fan-out re-installs the submitting thread's budget on its
# pool threads (_fanout in ops/distributed_ops.py).
_CALL_BUDGET = threading.local()


def current_call_budget():
    """Absolute time.monotonic deadline of the budget installed on THIS
    thread, or None when unbudgeted."""
    return getattr(_CALL_BUDGET, "deadline", None)


def budget_remaining():
    """Seconds left in this thread's call budget (None = unbudgeted;
    can be <= 0 when already expired)."""
    d = current_call_budget()
    return None if d is None else d - time.monotonic()


class call_budget:
    """Context manager installing an absolute time.monotonic ``deadline``
    as this thread's RPC budget (None = no-op). Nested budgets take the
    MINIMUM — an inner scope can only tighten the outer one."""

    def __init__(self, deadline):
        self._deadline = deadline

    def __enter__(self):
        self._prev = current_call_budget()
        if self._deadline is not None:
            d = self._deadline
            if self._prev is not None:
                d = min(d, self._prev)
            _CALL_BUDGET.deadline = d
        return self

    def __exit__(self, *exc):
        _CALL_BUDGET.deadline = self._prev
        return False


def _check_budget(method: str, endpoint: str):
    """Raise typed when this thread's budget is already spent; returns
    the remaining seconds (None = unbudgeted)."""
    rem = budget_remaining()
    if rem is not None and rem <= 0:
        raise core.DeadlineExceededError(
            f"rpc {method} on {endpoint}: request deadline expired "
            f"before the call could start")
    return rem


# ---------------------------------------------------------------------------
# per-endpoint circuit breaker (docs/SERVING.md "Ingress & overload").
# State machine: CLOSED —(FLAGS_rpc_breaker_failures consecutive
# transport/worker-dead failures)→ OPEN —(FLAGS_rpc_breaker_reset_s
# cooldown)→ HALF-OPEN (exactly one probe call passes) —success→ CLOSED
# / —failure→ OPEN. Recording happens whenever the flag is on; fast-fail
# (CircuitOpenError) only on data-plane calls, never heartbeats — the
# monitor must keep seeing real silence, not synthesized failures.
class CircuitBreaker:
    """One endpoint's breaker. Thread-safe; keyed by the SLOT endpoint
    (what programs bake in), so a PR 6 failover's half-open probe lands
    on the promoted replica and closes the breaker — the automatic
    un-degrade path."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.trips = 0  # cumulative CLOSED→OPEN transitions

    def _threshold(self) -> int:
        return max(1, int(core.globals_["FLAGS_rpc_breaker_failures"]))

    def _reset_s(self) -> float:
        return float(core.globals_["FLAGS_rpc_breaker_reset_s"])

    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self._reset_s():
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """True when a call may proceed. While OPEN only the first
        caller past the cooldown gets through (the half-open probe);
        everyone else keeps failing fast until its outcome lands."""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self._reset_s():
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_neutral(self) -> None:
        """Resolve an allow()'d call without judging the endpoint —
        the CALLER's deadline expired (its budget, not the server's
        fault) or an unexpected non-transport error aborted the call.
        Only releases a reserved half-open probe so the next caller
        can retry it; failure counts and the open clock are
        untouched — tight-deadline traffic against a slow-but-healthy
        endpoint must neither trip the breaker nor hold it open."""
        with self._lock:
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._opened_at is not None:
                # half-open probe failed (or late failures while open):
                # restart the cooldown
                self._opened_at = time.monotonic()
                self._probing = False
            elif self._failures >= self._threshold():
                self._opened_at = time.monotonic()
                self._probing = False
                self.trips += 1
                _LOG.warning(
                    "circuit breaker OPEN for pserver %s after %d "
                    "consecutive failures (reset in %.1fs)",
                    self.endpoint, self._failures, self._reset_s())


_BREAKERS: Dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker_for(endpoint: str) -> CircuitBreaker:
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(endpoint)
        if b is None:
            b = _BREAKERS[endpoint] = CircuitBreaker(endpoint)
        return b


def breaker_states() -> Dict[str, Dict[str, Any]]:
    """endpoint -> {state, trips} snapshot — the serving stats()
    ``breaker_open`` evidence surface."""
    with _BREAKERS_LOCK:
        bs = list(_BREAKERS.items())
    return {ep: {"state": b.state(), "trips": b.trips} for ep, b in bs}


def reset_breakers() -> None:
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


def _breaker_enabled() -> bool:
    return bool(core.globals_["FLAGS_rpc_circuit_breaker"])


class AckWindow:
    """Ack plumbing for the bounded-staleness async plane
    (docs/PS_DATA_PLANE.md "Async overlap"). Counts submitted vs
    acknowledged rounds under one condition variable: ``acquire_slot``
    blocks while ``max_inflight`` rounds are submitted-but-unacked (a
    full pipe blocks the trainer's step), ``ack`` releases a slot and
    records the round's error if it failed. A recorded error surfaces
    TYPED on the main thread at the next ``acquire_slot``/``wait_all``
    — a background round failure (WorkerDeadError, NumericFaultError
    from a rejecting pserver) must stop the training loop, not vanish
    into a daemon thread."""

    def __init__(self):
        self._cv = threading.Condition()
        self._submitted = 0
        self._acked = 0
        self._error: Optional[BaseException] = None

    def inflight(self) -> int:
        with self._cv:
            return self._submitted - self._acked

    def counts(self):
        """(submitted, acked) — the round pipeline's stats() surface."""
        with self._cv:
            return self._submitted, self._acked

    def _raise_pending_locked(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def record_error(self, err: BaseException) -> None:
        """Record a failure from a non-round pipeline task (e.g. an
        async sparse push) without touching the slot accounting."""
        with self._cv:
            if self._error is None:
                self._error = err
            self._cv.notify_all()

    def acquire_slot(self, max_inflight: int,
                     timeout: Optional[float] = None) -> int:
        """Block until a slot frees, then count one submission and
        return its 0-based round index. Raises the first deferred round
        error instead of submitting (the error is consumed)."""
        max_inflight = max(1, int(max_inflight))
        end = None if timeout is None else time.time() + timeout
        with self._cv:
            while True:
                self._raise_pending_locked()
                if self._submitted - self._acked < max_inflight:
                    rid = self._submitted
                    self._submitted += 1
                    return rid
                wait = None if end is None else end - time.time()
                if wait is not None and wait <= 0:
                    raise TimeoutError(
                        f"AckWindow: pipe full ({max_inflight} rounds "
                        f"in flight) past the deadline")
                self._cv.wait(wait if wait is None else min(wait, 1.0))

    def ack(self, error: Optional[BaseException] = None) -> None:
        with self._cv:
            self._acked += 1
            if error is not None and self._error is None:
                self._error = error
            self._cv.notify_all()

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted round acked. Returns False on
        timeout; re-raises the first deferred error (consumed)."""
        end = None if timeout is None else time.time() + timeout
        with self._cv:
            while self._submitted > self._acked:
                wait = None if end is None else end - time.time()
                if wait is not None and wait <= 0:
                    return False
                self._cv.wait(wait if wait is None else min(wait, 1.0))
            self._raise_pending_locked()
            return True


# fault injection (tests/faultinject.py rpc_delay): a pserver sleeps
# PADDLE_TPU_PS_RPC_DELAY_MS before dispatching each data-plane call —
# models a slow wire/congested server so the async-overlap and WAN
# tests can prove the staleness/geo pipes decouple the step from the
# RPCs. Two refinements for honest WAN emulation
# (docs/PS_DATA_PLANE.md "Compression"):
#   * PADDLE_TPU_PS_RPC_DELAY_RESP_MS delays the RESPONSE direction
#     independently (asymmetric up/down links);
#   * PADDLE_TPU_PS_RPC_DELAY_JITTER_MS adds a uniform [0, j) extra to
#     every injected delay (real WAN RTTs are never constant) — it
#     rides on top of a configured base and does nothing alone.
# Heartbeat / membership traffic stays exempt by default (delaying
# beats would declare live workers dead).
# the tensor plane plus barriers: the round's rendezvous RPCs pay the
# emulated RTT like any data call (heartbeats/membership stay exempt)
_DELAY_DEFAULT_METHODS = ps_membership.TENSOR_DATA_METHODS | {"barrier"}


def _maybe_inject_rpc_delay(method: str, response: bool = False) -> None:
    ms = os.environ.get("PADDLE_TPU_PS_RPC_DELAY_RESP_MS" if response
                        else "PADDLE_TPU_PS_RPC_DELAY_MS")
    if not ms:
        return
    allowed = os.environ.get("PADDLE_TPU_PS_RPC_DELAY_METHODS")
    methods = (frozenset(allowed.split(",")) if allowed
               else _DELAY_DEFAULT_METHODS)
    if method not in methods:
        return
    try:
        delay = float(ms)
        jitter = float(
            os.environ.get("PADDLE_TPU_PS_RPC_DELAY_JITTER_MS") or 0.0)
        if jitter > 0:
            import random
            delay += random.uniform(0.0, jitter)
        time.sleep(delay / 1000.0)
    except ValueError:
        pass


def _pickle_wire_forced() -> bool:
    """PADDLE_TPU_PS_PICKLE_WIRE=1 is the LEGACY DATA-PLANE mode: the
    pre-throughput-overhaul behavior end to end — v1 pickle frames, one
    connection per endpoint, serial shard walks, no duplicate-id dedup,
    no coalesced flushes (docs/PS_DATA_PLANE.md; the paired lane of
    `bench.py wide_deep_1b`). Checked dynamically so tests can flip it
    per client."""
    return os.environ.get("PADDLE_TPU_PS_PICKLE_WIRE", "") == "1"


class _NDRef:
    """Placeholder left in the pickled header where an ndarray was
    extracted into the frame's raw-buffer section (index into it)."""

    __slots__ = ("i",)

    def __init__(self, i):
        self.i = i

    def __reduce__(self):
        return (_NDRef, (self.i,))


def _strip_arrays(obj, bufs: list, specs: list, quant: str = "",
                  qinfo: Optional[dict] = None):
    """Replace every ndarray in ``obj`` (recursively through
    dicts/lists/tuples) with an _NDRef and append the WIRE arrays to
    ``bufs`` with their spec entries in ``specs``. 0-d, zero-SIZE, and
    object-dtype arrays stay inline — they are header-sized and
    sidestep buffer-protocol edge cases (memoryview cannot cast a view
    with zeros in its shape, so an empty sparse update would kill the
    frame encoder).

    Specs (wire v2): ``(dtype.str, shape)`` per buffer. Wire v3 adds
    quantized entries when ``quant`` is set: an fp16 downcast is
    ``(wire_dtype, shape, ["f", orig_dtype])``; an int8 buffer is
    ``(wire_dtype, shape, ["i", orig_dtype])`` followed IMMEDIATELY by
    its per-row scale buffer ``("<f4", (rows,), ["s"])`` — two wire
    buffers, ONE logical _NDRef slot. The decoder rebuilds logical
    arrays in spec order, so _NDRef indices stay dense."""
    if qinfo is None:
        qinfo = {}
    if isinstance(obj, np.ndarray) and obj.ndim >= 1 and obj.size \
            and obj.dtype != object:
        arr = np.ascontiguousarray(obj)
        # logical index: an int8 buffer + its scale fill ONE logical
        # slot, so the running counter (not len(bufs)) is the ref
        ref = _NDRef(qinfo.get("slots", 0))
        qinfo["slots"] = qinfo.get("slots", 0) + 1
        # int8 profitability gate: the per-row f32 scale costs 4 bytes,
        # so a buffer with fewer than ~1.34 elements per row would
        # EXPAND on the wire (a 1-element top-k delta: 5B vs 4B raw) —
        # ship such slivers raw
        n_rows = arr.shape[0] if arr.ndim > 1 else 1
        if quant == "int8" and 4 * n_rows >= 3 * arr.size:
            quant_this = ""
        else:
            quant_this = quant
        if quant_this and arr.dtype == np.float32 \
                and (quant_this == "fp16" or np.isfinite(arr).all()):
            if quant_this == "fp16":
                wire = arr.astype(np.float16)
                bufs.append(wire)
                specs.append((wire.dtype.str, wire.shape,
                              ["f", arr.dtype.str]))
                sent = wire.nbytes
            else:
                q, scale = _quant_int8(arr)
                bufs.append(q)
                specs.append((q.dtype.str, q.shape,
                              ["i", arr.dtype.str]))
                bufs.append(scale)
                specs.append((scale.dtype.str, scale.shape, ["s"]))
                sent = q.nbytes + scale.nbytes
            if qinfo is not None:
                qinfo["raw"] = qinfo.get("raw", 0) + arr.nbytes
                qinfo["sent"] = qinfo.get("sent", 0) + sent
                qinfo["n"] = qinfo.get("n", 0) + 1
        else:
            bufs.append(arr)
            specs.append((arr.dtype.str, arr.shape))
        return ref
    if isinstance(obj, dict):
        return {k: _strip_arrays(v, bufs, specs, quant, qinfo)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        walked = [_strip_arrays(v, bufs, specs, quant, qinfo)
                  for v in obj]
        return walked if isinstance(obj, list) else tuple(walked)
    return obj


def _plant_arrays(obj, bufs: list):
    if isinstance(obj, _NDRef):
        return bufs[obj.i]
    if isinstance(obj, dict):
        return {k: _plant_arrays(v, bufs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        walked = [_plant_arrays(v, bufs) for v in obj]
        return walked if isinstance(obj, list) else tuple(walked)
    return obj


def _encode_frame(obj, proto: int, quant: str = "",
                  info: Optional[dict] = None):
    """Serialize ``obj`` into wire parts. Returns (parts, nbytes); parts
    are bytes/memoryview objects sent back-to-back — retry/replay paths
    re-send them VERBATIM, no re-serialization (a dedup-tokened retry
    of a QUANTIZED frame replays the exact quantized bytes). ``quant``
    only takes effect on a v3 connection — v2/v1 peers always get
    exact frames. ``info`` (optional dict) receives the quantization
    evidence for the caller's rpc span args."""
    if proto == PROTO_PICKLE:
        payload = pickle.dumps(obj, protocol=4)
        return [_LEN.pack(len(payload)) + payload], _LEN.size + len(payload)
    if proto < PROTO_BINARY_Q:
        quant = ""
    bufs: list = []
    specs: list = []
    qinfo: dict = {}
    stripped = _strip_arrays(obj, bufs, specs, quant, qinfo)
    if qinfo.get("n"):
        _bump_quant_stats(qinfo["raw"], qinfo["sent"])
        if info is not None:
            info.update(quant=quant, bytes_raw=qinfo["raw"],
                        bytes_quant=qinfo["sent"])
    header = pickle.dumps({"h": stripped, "b": specs}, protocol=4)
    parts = [_LEN.pack(len(header)) + header]
    nbytes = _LEN.size + len(header)
    for b in bufs:
        mv = memoryview(b).cast("B")
        parts.append(mv)
        nbytes += mv.nbytes
    if nbytes <= (1 << 16) and len(parts) > 1:
        # small frame: one syscall beats zero-copy — join the parts
        # (the copy is cheaper than extra sendall round-trips)
        parts = [b"".join(parts)]
    return parts, nbytes


# thin-pipe emulation (docs/PS_DATA_PLANE.md "Compression"):
# PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS rate-limits every frame SEND by
# sleeping nbytes/bandwidth after the write — models a bandwidth-bound
# WAN/DCN link the way PADDLE_TPU_PS_RPC_DELAY_MS models its latency.
# Loopback itself is CPU-bound, so compression claims are measured
# against this emulated pipe (tools/rpc_microbench.py --quant). Applies
# to both directions (each side pays for what IT sends). Heartbeats
# ride it too, but at ~100 B a beat the cost is microseconds.
def _maybe_throttle_send(nbytes: int) -> None:
    bw = os.environ.get("PADDLE_TPU_PS_RPC_BANDWIDTH_MBPS")
    if not bw:
        return
    try:
        mbps = float(bw)
        if mbps > 0:
            time.sleep(nbytes / (mbps * 1e6))
    except ValueError:
        pass


def _send_parts(sock: socket.socket, parts) -> None:
    n = 0
    for p in parts:
        sock.sendall(p)
        n += p.nbytes if isinstance(p, memoryview) else len(p)
    _maybe_throttle_send(n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_into_exact(sock: socket.socket, mv: memoryview) -> None:
    while len(mv):
        n = sock.recv_into(mv)
        if n == 0:
            raise ConnectionError("peer closed")
        mv = mv[n:]


def _recv_frame(sock: socket.socket, proto: int):
    """Read one frame. Returns (obj, nbytes). The
    FLAGS_rpc_max_message_size guard applies to BOTH parts: the pickled
    header's length prefix and the declared raw-buffer total."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    limit = int(core.globals_["FLAGS_rpc_max_message_size"])
    if n > limit:
        # a garbage/malicious prefix must fail as a PROTOCOL error, not
        # as a MemoryError from trying to buffer it
        raise core.RpcProtocolError(
            f"rpc message length prefix {n} exceeds "
            f"FLAGS_rpc_max_message_size={limit} — corrupted or "
            f"malicious peer stream")
    obj = pickle.loads(_recv_exact(sock, n))
    nbytes = _LEN.size + n
    if proto == PROTO_PICKLE:
        return obj, nbytes
    if not (isinstance(obj, dict) and "h" in obj and "b" in obj):
        raise core.RpcProtocolError(
            "binary-wire frame without header/buffer sections — peer "
            "framing desynchronized")
    specs = obj["b"]
    raw_total = 0
    try:
        for spec in specs:
            dt, shape = spec[0], spec[1]
            if any(int(d) < 0 for d in shape):
                raise core.RpcProtocolError(
                    f"rpc raw-buffer spec with negative dim {shape} — "
                    f"corrupted or malicious peer stream")
            # python-int product: an attacker-chosen shape must not
            # int64-overflow past the size guard below
            n_elems = 1
            for d in shape:
                n_elems *= int(d)
            raw_total += int(np.dtype(dt).itemsize) * n_elems
    except core.RpcProtocolError:
        raise
    except Exception as e:  # bad dtype string / malformed spec entry
        raise core.RpcProtocolError(
            f"rpc raw-buffer spec malformed ({e!r}) — corrupted or "
            f"malicious peer stream") from e
    if raw_total > limit:
        raise core.RpcProtocolError(
            f"rpc raw-buffer total {raw_total} exceeds "
            f"FLAGS_rpc_max_message_size={limit} — corrupted or "
            f"malicious peer stream")
    # wire v3 quantized entries dequantize HERE — handlers and callers
    # only ever see full-precision arrays, so the pserver's
    # FLAGS_ps_reject_nonfinite guard runs over exactly what will be
    # applied (an fp16 overflow arrives as Inf and trips it)
    bufs = []
    pending_int8 = None  # (q_array, orig_dtype) awaiting its scale
    try:
        for spec in specs:
            arr = np.empty(spec[1], np.dtype(spec[0]))
            _recv_into_exact(sock, memoryview(arr).cast("B"))
            if len(spec) == 2:
                if pending_int8 is not None:
                    raise core.RpcProtocolError(
                        "rpc int8 buffer without its scale entry")
                bufs.append(arr)
                continue
            tag = spec[2][0]
            if tag == "f":
                bufs.append(arr.astype(np.dtype(spec[2][1])))
            elif tag == "i":
                if pending_int8 is not None:
                    raise core.RpcProtocolError(
                        "rpc int8 buffer without its scale entry")
                pending_int8 = (arr, np.dtype(spec[2][1]))
            elif tag == "s":
                if pending_int8 is None:
                    raise core.RpcProtocolError(
                        "rpc scale entry without an int8 buffer")
                q, odt = pending_int8
                pending_int8 = None
                bufs.append(_dequant_int8(q, arr, odt))
            else:
                raise core.RpcProtocolError(
                    f"rpc buffer spec with unknown quant tag {tag!r}")
        if pending_int8 is not None:
            raise core.RpcProtocolError(
                "rpc int8 buffer without its scale entry")
    except (core.RpcProtocolError, ConnectionError, OSError):
        raise
    except Exception as e:  # malformed quant metadata
        raise core.RpcProtocolError(
            f"rpc quantized-buffer spec malformed ({e!r}) — corrupted "
            f"or malicious peer stream") from e
    return _plant_arrays(obj["h"], bufs), nbytes + raw_total


def _send_msg(sock: socket.socket, obj) -> None:
    """Legacy-framed send (v1). Kept as the negotiation substrate and
    for raw-socket tests."""
    _send_parts(sock, _encode_frame(obj, PROTO_PICKLE)[0])


# per-request context: the dedup token and serving VarServer of the call
# the CURRENT handler thread is executing — lets deep handler code
# (listen_and_serv's apply path) mark a token as applied for the dedup
# high-water mark without threading it through every signature
_REQUEST = threading.local()


def request_dedup_token():
    """Dedup token of the in-flight request on THIS handler thread
    (None outside a VarServer dispatch or for idempotent calls)."""
    return getattr(_REQUEST, "token", None)


def note_request_token_applied() -> None:
    """Record that the current request's state mutation has been applied
    — bumps the serving VarServer's per-prefix dedup high-water mark.
    Called by write handlers UNDER the grad lock, so a shard handoff
    (which snapshots the marks under the same lock) sees exactly the
    applies that are part of the transferred state: a replayed token at
    or below the transferred mark is answered without re-executing,
    one above it executes fresh — exactly-once across the re-route."""
    srv = getattr(_REQUEST, "server", None)
    token = getattr(_REQUEST, "token", None)
    if srv is not None and token is not None:
        srv._note_token_applied(token)


def _recv_msg(sock: socket.socket):
    """Legacy-framed receive (v1) — see _recv_frame for the guard."""
    return _recv_frame(sock, PROTO_PICKLE)[0]


class VarServer:
    """Serves variables + barriers for one pserver process (reference:
    listen_and_serv_op.cc:333 RunImpl's gRPC server).

    Requests carrying a ``_dedup`` token (non-idempotent methods from a
    retrying VarClient) execute AT MOST ONCE per server lifetime: the
    token is reserved the moment the request is read, a retry arriving
    while the original is still executing (client timed out mid-call)
    WAITS for that execution's outcome, and a retry arriving after
    completion replays the cached response — at-least-once delivery,
    exactly-once application. The cache does not survive a server
    restart."""

    _DEDUP_CAP = 4096

    def __init__(self, endpoint: str,
                 handlers: Dict[str, Callable[..., Any]],
                 legacy_wire: bool = False, membership=None,
                 wire_version: int = WIRE_VERSION):
        host, port = endpoint.rsplit(":", 1)
        self._endpoint = endpoint
        self._handlers = handlers
        # negotiation cap (tests pin 2 to simulate a pre-quant server;
        # the hello answers min(cap, client version) so a v3 client
        # against a v2 server settles on v2 — exact frames only)
        self._wire_version = max(PROTO_BINARY,
                                 min(int(wire_version), WIRE_VERSION))
        # elastic-membership hook (ps_membership.MembershipPlane):
        # consulted before dispatching data-plane methods so a server
        # that handed its shard off answers StaleClusterViewError
        # (carrying the new view) instead of serving stale state
        self._membership = membership
        # legacy_wire simulates an old-frame-only peer: _hello is
        # rejected like any unknown method, every connection stays v1
        # (wire-compat tests exercise new-client↔old-server)
        self._legacy_wire = bool(legacy_wire)
        self._dedup: "OrderedDict[tuple, dict]" = OrderedDict()
        # per-token-prefix EXACT applied-seq tracking of non-idempotent
        # calls (note_request_token_applied): [floor, extra] where every
        # seq <= floor applied, plus a sparse set of applied seqs above
        # it (concurrent in-flight calls apply out of order). A retry
        # whose seq is tracked applied but whose cache entry is gone —
        # evicted, or the apply happened on the pserver this server took
        # a handoff from — replays a generic success instead of
        # double-applying. A seq in a GAP (lost frame racing a
        # later-seq sibling, or a failed call) is NOT tracked and
        # re-executes — a max-only high-water mark would falsely replay
        # it as success and silently drop the update.
        self._dedup_applied: Dict[Any, list] = {}
        self._dedup_lock = threading.Lock()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # per-op observability counters, served by the built-in "stats"
        # RPC (calls/bytes_in/bytes_out/dedup_replays per method)
        self._op_stats: Dict[str, Dict[str, int]] = {}
        self._stats_lock = threading.Lock()
        # extra stats() sections contributed by the hosting op (e.g.
        # listen_and_serv's FLAGS_ps_reject_nonfinite trip counters ride
        # under a "health" key) — each source returns a dict merged into
        # the stats() payload
        self._stats_sources: List[Callable[[], Dict[str, Any]]] = []
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                proto = PROTO_PICKLE  # every connection starts legacy

                def send(resp, quant: str = "") -> int:
                    parts, n = _encode_frame(resp, proto, quant=quant)
                    _send_parts(self.request, parts)
                    return n

                try:
                    while True:
                        msg, nin = _recv_frame(self.request, proto)
                        method = msg.pop("method")
                        if method == "_hello":
                            # wire negotiation: acknowledge and upgrade
                            # THIS connection; an old server (or a
                            # legacy_wire one) never reaches here and
                            # answers "no method" below instead.
                            # "mono" is the clock-offset half of the
                            # handshake (docs/OBSERVABILITY.md): this
                            # process's time.perf_counter() at reply
                            # time — the client turns it into an
                            # NTP-style offset estimate the timeline
                            # merger uses to align trace shards. Old
                            # clients ignore the extra key; old servers
                            # never send it — compatible both ways.
                            if not outer._legacy_wire and \
                                    int(msg.get("version", 0)) >= 2:
                                # both ends speak the LOWER of their
                                # generations: a v2 client on a v3
                                # server (and the reverse) stays on
                                # exact v2 frames — quantized specs
                                # only ever cross a both-ends-v3 link
                                negotiated = min(
                                    outer._wire_version,
                                    int(msg.get("version", 0)))
                                send({"ok": True,
                                      "result": {
                                          "version": negotiated,
                                          "mono": time.perf_counter()}})
                                proto = negotiated
                            else:
                                send({"ok": False,
                                      "error": "no method _hello"})
                            continue
                        if method == "stop":
                            send({"ok": True})
                            outer._stop_evt.set()
                            return
                        _maybe_inject_rpc_delay(method)
                        nout = 0
                        token = msg.pop("_dedup", None)
                        epoch = msg.pop("_view_epoch", None)
                        gview = msg.pop("_view", None)
                        # distributed trace correlation
                        # (docs/OBSERVABILITY.md): the caller's
                        # (trace_id, span_id) header — installed around
                        # handler execution so every span the handler
                        # records carries the CALLER's trace id with a
                        # server-minted span id parented on the
                        # caller's rpc span
                        trace = msg.pop("_trace", None)
                        # calls/bytes_in count BEFORE the handler runs
                        # and the response ships: the old finally-bump
                        # landed AFTER send(), so a client reading
                        # stats() on a second pooled channel the moment
                        # its data call returned could miss the call it
                        # just made (observed as a load-dependent
                        # KeyError flake in the per-op counter tests)
                        outer._bump(method, calls=1, bytes_in=nin)
                        try:
                            if method == "stats":
                                nout = send({"ok": True,
                                             "result": outer.stats()})
                                continue
                            if token is not None:
                                # dedup BEFORE the membership guard: a
                                # retry of an already-applied call must
                                # replay its cached response even after
                                # this server drained its shard
                                kind, val = outer._dedup_begin(token)
                                if kind == "done":
                                    outer._bump(method, replays=1)
                                    outer._trace_replay(method, trace)
                                    nout = send(val)
                                    continue
                                if kind == "pending":
                                    # the original execution (from a
                                    # timed-out connection) is still
                                    # running — wait for ITS outcome,
                                    # never re-execute
                                    outer._bump(method, replays=1)
                                    outer._trace_replay(method, trace)
                                    nout = send(
                                        outer._dedup_wait(token, val))
                                    continue
                            fn = outer._handlers.get(method)
                            if fn is None:
                                resp = {"ok": False,
                                        "error": f"no method {method}"}
                                if token is not None:
                                    # resolve the reservation _dedup_begin
                                    # made, or a retry of this token
                                    # would wait forever on a pending
                                    # entry nothing will complete
                                    outer._dedup_put(token, resp)
                                nout = send(resp)
                                continue
                            _REQUEST.token = token
                            _REQUEST.server = outer
                            tcm = (telemetry.trace_scope(
                                       trace_id=trace[0],
                                       parent_span_id=trace[1])
                                   if trace else
                                   contextlib.nullcontext())
                            with tcm:
                                t_handler = time.perf_counter()
                                try:
                                    if outer._membership is not None:
                                        outer._membership.pre_dispatch(
                                            method, epoch, gview)
                                    res = fn(**msg)
                                    resp = {"ok": True, "result": res}
                                except Exception as e:  # to client
                                    # error_type lets the client
                                    # re-raise the TYPED exception
                                    # (WorkerDeadError survives the
                                    # wire — tests/launchers dispatch
                                    # on it)
                                    resp = {"ok": False,
                                            "error": repr(e),
                                            "error_type":
                                                type(e).__name__}
                                    if isinstance(
                                            e,
                                            core.StaleClusterViewError):
                                        # ship the server's newer view
                                        # so the client can re-route +
                                        # replay
                                        resp["error_data"] = {
                                            "view": e.view_dict}
                                finally:
                                    _REQUEST.token = None
                                    _REQUEST.server = None
                                # handler span recorded INSIDE the
                                # trace scope: it carries the caller's
                                # trace id (the propagation tests pin
                                # trainer rpc span → pserver handler
                                # span linkage on this)
                                from . import profiler as _profiler
                                if _profiler.is_profiling():
                                    _profiler.record_span(
                                        f"rpc_handler:{method}",
                                        t_handler, time.perf_counter(),
                                        cat="rpc",
                                        args={"ok": bool(
                                            resp.get("ok"))})
                            if token is not None:
                                outer._dedup_put(token, resp)
                            # response-direction WAN emulation (the
                            # asymmetric half of the rpc_delay hook)
                            _maybe_inject_rpc_delay(method,
                                                    response=True)
                            # row pulls / dense batches quantize on the
                            # way OUT too when this server's flag is on
                            # and the connection negotiated v3 —
                            # "quantized rows on the wire" covers both
                            # directions of the data plane
                            nout = send(
                                resp,
                                quant=(_quant_mode()
                                       if proto >= PROTO_BINARY_Q
                                       and resp.get("ok")
                                       and method in _QUANT_METHODS
                                       else ""))
                        finally:
                            outer._bump(method, bytes_out=nout)
                except core.RpcProtocolError:
                    _LOG.warning("VarServer: dropping connection with "
                                 "invalid framing", exc_info=True)
                    return
                except (ConnectionError, OSError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, int(port)), _Handler)
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    # bound on the sparse applied-seq set per prefix: a permanent gap
    # (a call that failed and never re-applied) would otherwise pin the
    # floor and grow the set for the client's lifetime. On overflow the
    # set collapses to its max (the old high-water-mark semantics) —
    # a seq that stale retrying is beyond any real retry window.
    _APPLIED_GAP_CAP = 1024

    def _dedup_begin(self, token):
        """Reserve a token. Returns ("new", event) when this call owns
        execution, ("pending", event) when another connection is
        executing it right now, ("done", response) when it completed —
        either from the bounded response cache or, for (prefix, seq)
        tokens tracked APPLIED (cache evicted, or the apply happened
        pre-handoff on the server this one inherited the shard from),
        as a generic success."""
        t = tuple(token)
        with self._dedup_lock:
            applied = False
            if len(t) == 2 and isinstance(t[1], int):
                st = self._dedup_applied.get(t[0])
                applied = st is not None and (t[1] <= st[0]
                                              or t[1] in st[1])
            entry = self._dedup.get(t)
            if entry is not None and entry[0] == "done" \
                    and not entry[1].get("ok", False) \
                    and entry[1].get("error_type") == \
                    "StaleClusterViewError":
                # a membership REFUSAL mutated nothing, so it must not
                # pin the token's outcome: after a rejoin this server
                # owns the shard again and the replay must EXECUTE —
                # and while still drained, re-evaluating issues a fresh
                # refusal carrying the newest view instead of a stale
                # one (a drain+rejoin pair 50ms apart poisoned tokens
                # this way — every trainer looped on the cached epoch-1
                # refusal from a server already serving at epoch 2)
                del self._dedup[t]
                entry = None
            if entry is not None:
                if applied and entry[0] == "done" \
                        and not entry[1].get("ok", False):
                    # the cached outcome is a REFUSAL (e.g. a stale-view
                    # error from before this server handed its shard
                    # off) but the applied-seq tracking — possibly
                    # imported back via a handoff manifest — says the
                    # call's mutation DID land on the then-owner: the
                    # truthful replay is the success, not the stale
                    # refusal
                    return ("done", {"ok": True, "result": True})
                return entry
            if applied:
                # the write-method contract is a bare True result,
                # which is what the evicted/transferred response
                # carried
                return ("done", {"ok": True, "result": True})
            ev = threading.Event()
            entry = self._dedup[t] = ("pending", ev)
            return ("new", ev)

    def _applied_add(self, prefix, seq: int) -> None:
        # caller holds _dedup_lock
        st = self._dedup_applied.get(prefix)
        if st is None:
            st = self._dedup_applied[prefix] = [-1, set()]
        floor, extra = st
        if seq <= floor or seq in extra:
            return
        extra.add(seq)
        while floor + 1 in extra:
            floor += 1
            extra.discard(floor)
        st[0] = floor
        if len(extra) > self._APPLIED_GAP_CAP:
            st[0] = max(extra)
            extra.clear()

    def _note_token_applied(self, token) -> None:
        t = tuple(token)
        if len(t) != 2 or not isinstance(t[1], int):
            return
        with self._dedup_lock:
            self._applied_add(t[0], t[1])

    def dedup_hwms(self) -> Dict[Any, tuple]:
        """Snapshot of the applied-seq tracking (shard handoff):
        prefix -> (floor, sorted extra seqs above it)."""
        with self._dedup_lock:
            return {p: (st[0], sorted(st[1]))
                    for p, st in self._dedup_applied.items()}

    def install_dedup_hwms(self, hwms) -> None:
        """Merge transferred applied-seq tracking. Accepts the
        (floor, extra) pairs ``dedup_hwms`` exports, or a bare int
        floor; floors take the max, extras union and re-compact."""
        with self._dedup_lock:
            for prefix, val in (hwms or {}).items():
                if isinstance(val, (list, tuple)):
                    fl, ex = int(val[0]), {int(s) for s in val[1]}
                else:
                    fl, ex = int(val), set()
                st = self._dedup_applied.setdefault(prefix, [-1, set()])
                if fl > st[0]:
                    st[0] = fl
                st[1] = {s for s in (st[1] | ex) if s > st[0]}
                while st[0] + 1 in st[1]:
                    st[0] += 1
                    st[1].discard(st[0])

    def _dedup_wait(self, token, event):
        t = tuple(token)
        while not event.wait(1.0):
            if self._stop_evt.is_set():
                return {"ok": False,
                        "error": "server stopping before the original "
                                 "execution of this request completed"}
        with self._dedup_lock:
            entry = self._dedup.get(t)
        if entry is not None and entry[0] == "done":
            return entry[1]
        return {"ok": False, "error": "dedup entry lost mid-wait"}

    def _dedup_put(self, token, resp):
        t = tuple(token)
        with self._dedup_lock:
            prev = self._dedup.get(t)
            self._dedup[t] = ("done", resp)
            self._dedup.move_to_end(t)
            if len(self._dedup) > self._DEDUP_CAP:
                # evict oldest COMPLETED entries; pending ones belong to
                # live executions and their waiters
                for k in list(self._dedup):
                    if len(self._dedup) <= self._DEDUP_CAP:
                        break
                    if self._dedup[k][0] == "done" and k != t:
                        del self._dedup[k]
        if prev is not None and prev[0] == "pending":
            prev[1].set()

    def _trace_replay(self, method: str, trace) -> None:
        """A dedup replay answered without re-executing: record a
        zero-duration marker carrying the caller's trace id so the
        retry is FOLLOWABLE in the merged timeline — the trace shows
        the same trace id landing twice with the second occurrence
        marked as a replay (same trace id, new server-side span id)."""
        from . import profiler as _profiler
        if trace is None or not _profiler.is_profiling():
            return
        with telemetry.trace_scope(trace_id=trace[0],
                                   parent_span_id=trace[1]):
            _profiler.record_instant(
                f"rpc_handler:{method}", cat="rpc",
                args={"dedup_replay": True})

    def _bump(self, method: str, calls: int = 0, bytes_in: int = 0,
              bytes_out: int = 0, replays: int = 0) -> None:
        with self._stats_lock:
            st = self._op_stats.setdefault(
                method, {"calls": 0, "bytes_in": 0, "bytes_out": 0,
                         "dedup_replays": 0})
            st["calls"] += calls
            st["bytes_in"] += bytes_in
            st["bytes_out"] += bytes_out
            st["dedup_replays"] += replays

    def add_stats_source(self, fn: Callable[[], Dict[str, Any]]) -> None:
        """Register an extra section for stats() (and so for the "stats"
        RPC). The numeric fault plane's pserver trip counters surface
        this way (docs/FAULT_TOLERANCE.md "Numeric faults")."""
        self._stats_sources.append(fn)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-op counters (calls, bytes in/out, dedup replays) plus any
        add_stats_source sections — also served over the wire by the
        built-in idempotent "stats" RPC."""
        with self._stats_lock:
            base: Dict[str, Any] = {k: dict(v)
                                    for k, v in self._op_stats.items()}
        for fn in self._stats_sources:
            try:
                base.update(fn() or {})
            except Exception:  # a broken source must not break stats
                _LOG.exception("VarServer stats source failed")
        return base

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self):
        # metrics-registry view over stats() — the per-op counters,
        # health trips, membership and prefetch sections all become
        # scrape-able as ps_server_*{endpoint=...} gauges; the opt-in
        # FLAGS_metrics_port sidecar makes them HTTP-reachable without
        # the stats RPC (docs/OBSERVABILITY.md)
        # label with the BOUND endpoint (an ephemeral ":0" construction
        # endpoint resolves to the real port only after bind)
        label_ep = (self._endpoint if not self._endpoint.endswith(":0")
                    else f"{self._endpoint.rsplit(':', 1)[0]}:"
                         f"{self.port}")
        self._metrics_view = telemetry.REGISTRY.register_view(
            "ps_server", self.stats, labels={"endpoint": label_ep})
        telemetry.maybe_start_metrics_server()
        self._thread.start()
        return self

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._stop_evt.wait(timeout)

    def shutdown(self):
        view = getattr(self, "_metrics_view", None)
        if view is not None:
            telemetry.REGISTRY.unregister_view(view)
            self._metrics_view = None
        self._stop_evt.set()
        self._srv.shutdown()
        self._srv.server_close()
        # sever live connections like a process death would — peers see
        # ConnectionError immediately (and their retry plane kicks in)
        # instead of blocked reads on a half-dead server
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# errors a pserver handler may legitimately raise that the client should
# re-raise TYPED instead of as a generic RuntimeError
_WIRE_ERRORS: Dict[str, type] = {
    "WorkerDeadError": core.WorkerDeadError,
    "TimeoutError": TimeoutError,
    "KeyError": KeyError,
    # FLAGS_ps_reject_nonfinite=reject: the pserver refuses a poisoned
    # grad and the SENDING trainer gets the typed numeric fault back
    "NumericFaultError": core.NumericFaultError,
    # elastic membership: surfaced only after the client exhausted its
    # stale-view replays (VarClient.call re-routes transparently first)
    "StaleClusterViewError": core.StaleClusterViewError,
    # capacity tier: a pull touching a torn/bit-flipped spill segment
    # is REFUSED typed (docs/PS_DATA_PLANE.md "Capacity tier") — the
    # trainer sees the integrity fault, never silently-corrupt rows
    "SpillCorruptionError": core.SpillCorruptionError,
    "CheckpointError": core.CheckpointError,
}


# process-lifetime client serial for dedup token prefixes (never reused,
# unlike id())
_CLIENT_SERIAL = itertools.count()


class _Channel:
    """One pooled connection: socket + its negotiated wire protocol."""

    __slots__ = ("sock", "proto")

    def __init__(self):
        self.sock: Optional[socket.socket] = None
        self.proto = PROTO_PICKLE

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        self.proto = PROTO_PICKLE


class VarClient:
    """Per-endpoint client over a small connection pool (reference:
    grpc_client.h AsyncSendVar/AsyncGetVar calling convention; the pool
    plays the role of gRPC channel multiplexing so the parameter_prefetch
    fan-out's concurrent section RPCs don't serialize on one socket).

    ``call`` survives transient transport failures: the channel is
    closed, re-connected, and the ENCODED frame re-sent verbatim with
    exponential backoff up to FLAGS_rpc_retry_times attempts. Methods in
    ``_IDEMPOTENT`` are safe as-is; every other method is stamped with a
    per-client dedup token the server replays instead of re-executing.
    Each connection negotiates the wire protocol at connect time
    (binary v2 with a new server, legacy pickle with an old one)."""

    _pool: Dict[str, "VarClient"] = {}
    _pool_lock = threading.Lock()

    # read-only methods: re-sending after a lost response cannot change
    # server state. NOTE barrier/reduce_get are deliberately NOT here:
    # a barrier retry that lands AFTER its round released would enroll
    # as a phantom arrival in the NEXT round (and a reduce_get retry
    # after a generation reset would re-join a fresh generation) — they
    # ride the dedup-token path instead, replaying the completed
    # response; in-round duplicates are additionally absorbed by the
    # trainer-id keying.
    _IDEMPOTENT = frozenset({
        "get_var", "get_vars_batch", "prefetch_rows", "heartbeat",
        "dead_workers", "alive_workers", "table_stats", "stats",
        "get_view", "participant_states",
    })

    # how many StaleClusterViewError re-routes one call tolerates before
    # surfacing (each installs a newer view, so 3 covers a drain racing
    # a failover racing a rejoin)
    _STALE_RETRIES = 3

    def __init__(self, endpoint: str, connect_timeout: float = 30.0,
                 channels: Optional[int] = None, resolve: bool = True,
                 wire_version: int = WIRE_VERSION):
        # ``endpoint`` is the SLOT name (what the transpiler baked into
        # the program). With ``resolve`` (the default), every
        # (re)connect maps it through the installed ClusterView to the
        # endpoint currently serving the slot — membership-plane
        # internals (handoff streams, replica forwards, view probes)
        # pass resolve=False to reach a PHYSICAL endpoint.
        self.endpoint = endpoint
        self._resolve = bool(resolve)
        if ":" not in endpoint:
            raise ValueError(f"endpoint {endpoint!r} is not host:port")
        self._connect_timeout = connect_timeout
        # negotiation cap, mirroring VarServer's (tests pin 2 to model
        # a pre-quant client against a new server)
        self._wire_version = max(PROTO_BINARY,
                                 min(int(wire_version), WIRE_VERSION))
        if channels is None:
            # legacy mode pins the pool to the pre-overhaul single
            # connection per endpoint
            n = (1 if _pickle_wire_forced() else
                 int(core.globals_["FLAGS_rpc_channels_per_endpoint"]))
        else:
            n = int(channels)
        self._channels = [_Channel() for _ in range(max(1, n))]
        self._free = deque(self._channels)
        self._cv = threading.Condition()
        # token prefix must be unique per client LIFETIME, not per live
        # object: id() recycles after gc, and a recycled prefix whose
        # predecessor raised the server's dedup high-water mark would
        # get this client's fresh calls falsely replayed
        self._token_prefix = f"{os.getpid()}:{next(_CLIENT_SERIAL)}"
        self._seq = itertools.count()
        # methods this endpoint's server answered "no method" to — the
        # batch helpers probe once, then fall back without the wasted
        # round trip (server lifetime assumption: capabilities don't
        # shrink; a restart with fewer methods re-probes only after a
        # new VarClient)
        self._missing_methods: set = set()
        # did the last _hello carry the telemetry fields (clock offset)?
        # Gates the _trace header: a peer that never answered the
        # telemetry hello would pass _trace straight into its handler
        # as an unexpected kwarg — same wire-compat rule as _view_epoch
        self._telemetry_ok = False
        # connect ONE channel eagerly: an unreachable pserver surfaces
        # now, and negotiation happens off the data path. The remaining
        # channels connect lazily on first concurrent use. Data-plane
        # clients (resolve=True) participate in the endpoint's circuit
        # breaker: an open breaker fails construction fast, and the
        # eager connect is the half-open probe when one is due.
        brk = (breaker_for(endpoint)
               if _breaker_enabled() and self._resolve else None)
        if brk is not None and not brk.allow():
            raise core.CircuitOpenError(
                f"pserver {endpoint}: circuit breaker open — failing "
                f"fast instead of a connect poll")
        ch = self._acquire()
        try:
            self._connect_channel(ch, connect_timeout)
        except core.DeadlineExceededError:
            # the caller's budget, not the endpoint's fault: release a
            # reserved probe but record no failure
            if brk is not None:
                brk.record_neutral()
            raise
        except (ConnectionError, OSError):
            if brk is not None:
                brk.record_failure()
            raise
        except BaseException:
            if brk is not None:  # never leak a reserved probe
                brk.record_neutral()
            raise
        else:
            if brk is not None:
                brk.record_success()
        finally:
            self._release(ch)

    # ------------------------------------------------------------ plumbing
    @property
    def _deadline_s(self) -> float:
        return float(core.globals_["FLAGS_rpc_deadline"]) / 1000.0

    def _acquire(self) -> _Channel:
        # bounded wait (lockcheck cv-wait-no-timeout): releases are
        # finally-guaranteed in-process, so a starved pool means a leaked
        # channel (a bug) or pathological contention — surface a typed
        # deadline like every other stalled wait in the RPC plane instead
        # of hanging the trainer forever on a lost notify
        deadline = time.time() + self._deadline_s
        with self._cv:
            while not self._free:
                if not self._cv.wait(timeout=min(
                        1.0, max(0.0, deadline - time.time()))) \
                        and time.time() >= deadline:
                    raise core.DeadlineExceededError(
                        f"no free RPC channel to {self.endpoint} within "
                        f"FLAGS_rpc_deadline — "
                        f"{len(self._channels)} channel(s) all busy")
            return self._free.popleft()

    def _release(self, ch: _Channel) -> None:
        with self._cv:
            self._free.append(ch)
            self._cv.notify()

    def _connect_channel(self, ch: _Channel, connect_timeout: float):
        """(Re)establish one connection; the server may be down or
        restarting — poll until ``connect_timeout`` elapses. Each poll
        re-resolves the slot through the installed ClusterView, and a
        failed attempt probes the slot's replicas for a NEWER view
        (ps_membership.refresh_view_for) — this poll loop IS the
        trainer's failover path: once the dead primary's replica
        promotes itself, resolution flips and the connect lands there.
        Negotiates the wire protocol: a legacy-framed ``_hello`` probe
        upgrades the connection to binary v2 when the server supports
        it; an old server answers "no method" and the channel stays
        legacy."""
        deadline = time.time() + connect_timeout
        last = None
        while time.time() < deadline:
            rem = budget_remaining()
            if rem is not None and rem <= 0:
                # the caller's request deadline expired mid-poll: a
                # connection it can no longer use is not worth making
                ch.close()
                raise core.DeadlineExceededError(
                    f"pserver {self.endpoint}: request deadline expired "
                    f"while polling for a connection ({last!r})")
            target = (ps_membership.resolve(self.endpoint)
                      if self._resolve else self.endpoint)
            host, port = target.rsplit(":", 1)
            try:
                sock = socket.create_connection(
                    (host, int(port)), timeout=self._deadline_s)
            except OSError as e:  # server not up (yet) — retry
                last = e
                if self._resolve:
                    ps_membership.refresh_view_for(self.endpoint)
                time.sleep(0.1)
                continue
            ch.sock, ch.proto = sock, PROTO_PICKLE
            if _pickle_wire_forced():
                return
            try:
                t_hello = time.perf_counter()
                _send_msg(sock, {"method": "_hello",
                                 "version": self._wire_version})
                resp = _recv_msg(sock)
                t_reply = time.perf_counter()
            except core.RpcProtocolError:
                # a poisoned stream is NOT a transient connect failure —
                # surface it typed, never retry into it
                ch.close()
                raise
            except (ConnectionError, OSError) as e:
                ch.close()
                last = e
                time.sleep(0.1)
                continue
            srv_version = int((resp.get("result") or {})
                              .get("version", 0)) if resp.get("ok") else 0
            if srv_version >= 2:
                # settle on the LOWER generation (an old v2 server
                # answers 2 → this channel never carries quantized
                # specs; a v3 server answering a capped client already
                # clamped to our hello version)
                ch.proto = min(self._wire_version, srv_version)
                mono = (resp.get("result") or {}).get("mono")
                self._telemetry_ok = mono is not None
                if mono is not None:
                    # NTP-style single-sample offset: the server read
                    # its perf_counter ~rtt/2 after we sent — offset =
                    # peer clock minus ours at the same instant. Keyed
                    # by the PHYSICAL endpoint (what the server's trace
                    # shard is labeled with); timeline merge consumes
                    # it via the shard metadata.
                    rtt = t_reply - t_hello
                    telemetry.note_clock_offset(
                        target,
                        float(mono) - (t_hello + rtt / 2.0), rtt)
            else:
                self._telemetry_ok = False
            return
        ch.close()
        raise ConnectionError(
            f"cannot reach pserver {self.endpoint}: {last}")

    def close(self):
        """Close every channel (in-flight calls on other threads surface
        a transport error and take the retry plane)."""
        with self._cv:
            for ch in self._channels:
                ch.close()

    @classmethod
    def of(cls, endpoint: str) -> "VarClient":
        with cls._pool_lock:
            c = cls._pool.get(endpoint)
            if c is None:
                c = cls._pool[endpoint] = VarClient(endpoint)
            return c

    @classmethod
    def reset_pool(cls):
        with cls._pool_lock:
            for c in cls._pool.values():
                c.close()
            cls._pool.clear()

    # ---------------------------------------------------------------- call
    def call(self, method: str, _rpc_timeout: Optional[float] = None,
             _rpc_retries: Optional[int] = None, **kwargs):
        """One RPC with retry/backoff/reconnect for transient transport
        errors. Protocol errors (bad framing) and application errors
        (ok=False responses) are never retried. ``_rpc_timeout`` (s) /
        ``_rpc_retries`` override the FLAGS for this call only (the
        heartbeat thread uses short ones so a dead server can't pin it).
        Frames are encoded ONCE per wire protocol and retries re-send
        the cached parts verbatim. A typed ``StaleClusterViewError``
        response installs the newer view the server shipped and replays
        the SAME frame (same dedup token) against the new shard owner —
        a re-route is not a new logical call, so exactly-once holds
        across it. When the profiler is on, every call emits a
        cat="rpc" span carrying byte and retry counts."""
        deadline_s = (self._deadline_s if _rpc_timeout is None
                      else float(_rpc_timeout))
        retries = (max(0, int(core.globals_["FLAGS_rpc_retry_times"]))
                   if _rpc_retries is None else max(0, int(_rpc_retries)))
        # serving robustness plane (docs/SERVING.md "Ingress &
        # overload"): an already-spent request budget never starts an
        # RPC, and an OPEN endpoint breaker fails fast — both typed, so
        # the serving layers map them to 504/degraded instead of a
        # generic transport error. Data-plane clients only
        # (resolve=True); heartbeats are exempt so the monitor keeps
        # seeing real silence.
        _check_budget(method, self.endpoint)
        brk = (breaker_for(self.endpoint)
               if _breaker_enabled() and self._resolve
               and method != "heartbeat" else None)
        if brk is not None:
            probing = brk.state() != "closed"
            if not brk.allow():
                raise core.CircuitOpenError(
                    f"rpc {method} on {self.endpoint}: circuit breaker "
                    f"open — failing fast")
            if probing:
                # the half-open probe decides recovery: start it from
                # fresh connections — pooled channels that were live
                # when the endpoint died hold severed sockets whose
                # first use answers "peer closed", which would fail the
                # probe against a server (or promoted replica) that is
                # actually healthy
                self.close()
        msg = {"method": method, **kwargs}
        if self._resolve and method in ps_membership.DATA_METHODS:
            cur_view = ps_membership.current_view()
            if cur_view is not None and cur_view.epoch > 0:
                # gossip the epoch + FULL view once membership has
                # CHANGED: servers that missed an epoch — a replica's
                # primary, a server about to mint the NEXT epoch —
                # learn it from the clients that already hold it.
                # Epoch-0 clusters stamp NOTHING: they are exactly the
                # clusters that may still contain pre-elastic servers
                # whose dispatch would pass an unexpected _view_epoch
                # kwarg straight into the handler (TypeError).
                # Known cost: the full view rides EVERY post-epoch-0
                # data call (~100 B/slot in the pickled header). Fine at
                # the few-slot scale this repo runs; a 50+-slot cluster
                # should dedup it (ship the view once per epoch per
                # connection — note_gossip only needs each server to
                # hear each epoch once), which must be re-validated
                # against the chaos loop's promotion-floor races before
                # it lands.
                msg["_view_epoch"] = cur_view.epoch
                msg["_view"] = cur_view.to_dict()
        # trace correlation: each call is its own child span of the
        # caller's context; the (trace_id, span_id) header rides the
        # ENCODED frame, so a dedup retry or stale-view re-route
        # replays the SAME trace/span ids — the server mints fresh
        # handler span ids per execution. Gated on the hello-probed
        # capability (old peers would choke on the kwarg) exactly like
        # the _view_epoch stamp.
        tscope = None
        if self._telemetry_ok and telemetry.current_trace() is not None:
            tscope = telemetry.trace_scope()
            tctx = tscope.__enter__()
            msg["_trace"] = (tctx.trace_id, tctx.span_id)
        if method not in self._IDEMPOTENT:
            msg["_dedup"] = (self._token_prefix, next(self._seq))
        # wire v3 quantization: data-plane payloads only, and only on
        # channels that negotiated v3 (encode applies it per proto —
        # a mid-call failover to a v2 peer re-encodes exact frames).
        # The dedup token rides the header, so a retry of a quantized
        # frame replays the exact same quantized bytes.
        qmode = _quant_mode() if method in _QUANT_METHODS else ""
        enc_info: dict = {}
        frames: Dict[int, tuple] = {}  # proto -> (parts, nbytes)
        attempt = 0
        stale = 0
        stale_wait_end = None
        bytes_out = bytes_in = 0
        # breaker outcome: "fail" unless the call completes ("ok") or
        # dies of the CALLER's own expired budget ("neutral" — resolves
        # a reserved half-open probe without judging the endpoint)
        brk_outcome = "fail"
        t_start = time.perf_counter()
        try:
            while True:
                rem = budget_remaining()
                if rem is not None and rem <= 0:
                    raise core.DeadlineExceededError(
                        f"rpc {method} on {self.endpoint}: request "
                        f"deadline expired"
                        + (f" after {attempt} transport retries"
                           if attempt else ""))
                backoff = 0.0
                got = False
                ch = self._acquire()
                try:
                    if ch.sock is None:
                        self._connect_channel(
                            ch, self._connect_timeout if rem is None
                            else max(0.05, min(self._connect_timeout,
                                               rem)))
                        if "_trace" in msg and not self._telemetry_ok:
                            # mid-call failover/re-route landed on a
                            # peer that never advertised telemetry in
                            # its hello: strip the header and re-encode
                            # or fn(**msg) dies on the unexpected kwarg
                            # (the _view_epoch wire-compat rule). The
                            # dedup token is untouched — exactly-once
                            # is unaffected by the re-encode.
                            msg.pop("_trace")
                            frames.clear()
                    ch.sock.settimeout(
                        deadline_s if rem is None
                        else max(0.05, min(deadline_s, rem)))
                    if ch.proto not in frames:
                        frames[ch.proto] = _encode_frame(
                            msg, ch.proto, quant=qmode, info=enc_info)
                    parts, nb = frames[ch.proto]
                    _send_parts(ch.sock, parts)
                    bytes_out += nb
                    resp, nin = _recv_frame(ch.sock, ch.proto)
                    bytes_in += nin
                    got = True
                except core.RpcProtocolError:
                    ch.close()
                    raise
                except core.DeadlineExceededError:
                    # DeadlineExceededError ⊂ TimeoutError ⊂ OSError:
                    # without this arm the transient-transport handler
                    # below would swallow and retry a spent budget
                    ch.close()
                    raise
                except (ConnectionError, OSError) as e:
                    ch.close()
                    rem_now = budget_remaining()
                    if rem_now is not None and rem_now <= 0:
                        # the budget-capped socket timeout just fired
                        # (or the failure consumed the remainder): the
                        # caller's deadline is the real story — typed,
                        # and NOT an endpoint-failure breaker signal
                        raise core.DeadlineExceededError(
                            f"rpc {method} on {self.endpoint}: request "
                            f"deadline expired during the call "
                            f"({e!r})") from e
                    attempt += 1
                    if attempt > retries:
                        raise ConnectionError(
                            f"rpc {method} on {self.endpoint} failed "
                            f"after {retries} retries: {e!r}") from e
                    backoff = min(2.0, 0.05 * (2 ** (attempt - 1)))
                    _LOG.warning(
                        "rpc %s on %s hit %r — retry %d/%d in %.2fs",
                        method, self.endpoint, e, attempt, retries,
                        backoff)
                finally:
                    self._release(ch)
                if got:
                    if (self._resolve and not resp.get("ok")
                            and resp.get("error_type") ==
                            "StaleClusterViewError"):
                        # shard moved: install the server's newer view,
                        # sever the now-misrouted pool, and replay the
                        # cached frame against the new owner
                        prev_owner = ps_membership.resolve(self.endpoint)
                        view = (resp.get("error_data") or {}).get("view")
                        if view is not None:
                            ps_membership.install_view(view)
                        else:  # unpromoted standby: poll for promotion
                            ps_membership.refresh_view_for(self.endpoint)
                        moved = (ps_membership.resolve(self.endpoint)
                                 != prev_owner)
                        if moved:
                            # progress: counts against the re-route
                            # budget (3 covers a drain racing a failover
                            # racing a rejoin)
                            stale += 1
                        if stale_wait_end is None:
                            stale_wait_end = time.time() + float(
                                core.globals_[
                                    "FLAGS_ps_failover_deadline"])
                        if moved and stale <= self._STALE_RETRIES:
                            _LOG.info(
                                "rpc %s on %s: stale cluster view — "
                                "re-routing to %s (replay %d/%d)",
                                method, self.endpoint,
                                ps_membership.resolve(self.endpoint),
                                stale, self._STALE_RETRIES)
                            self.close()
                            time.sleep(0.05)
                            continue
                        if not moved and time.time() < stale_wait_end:
                            # mid-handoff convergence window: the
                            # answering server's view could not advance
                            # ours (monotonic install refuses older
                            # epochs — e.g. a rejoin destination that
                            # has not committed yet), so an immediate
                            # replay hits the same refusal. Wait for
                            # the commit/promotion to land, probing the
                            # slot's replicas, bounded by
                            # FLAGS_ps_failover_deadline.
                            self.close()
                            time.sleep(0.3)
                            ps_membership.refresh_view_for(self.endpoint)
                            continue
                    # breaker classification: a served response means
                    # the endpoint is alive UNLESS it is the typed
                    # worker-dead/timeout family the breaker exists to
                    # consume (PR 3 errors crossing the wire)
                    brk_outcome = ("ok" if resp.get("error_type")
                                   not in ("WorkerDeadError",
                                           "TimeoutError") else "fail")
                    break
                time.sleep(backoff)
        except core.DeadlineExceededError:
            brk_outcome = "neutral"
            raise
        finally:
            if brk is not None:
                {"ok": brk.record_success, "fail": brk.record_failure,
                 "neutral": brk.record_neutral}[brk_outcome]()
            # recorded INSIDE the call's trace scope so the client rpc
            # span carries the span id the server parented on
            _record_rpc_span(method, kwargs.get("name"), self.endpoint,
                             t_start, bytes_out, bytes_in, attempt,
                             quant_info=enc_info)
            if tscope is not None:
                tscope.__exit__(None, None, None)
        if not resp.get("ok"):
            err = resp.get("error")
            etype = _WIRE_ERRORS.get(resp.get("error_type"))
            if etype is not None:
                exc = etype(
                    f"rpc {method} on {self.endpoint} failed: {err}")
                if isinstance(exc, core.StaleClusterViewError):
                    exc.view_dict = (resp.get("error_data")
                                     or {}).get("view")
                raise exc
            raise RuntimeError(
                f"rpc {method} on {self.endpoint} failed: {err}")
        return resp.get("result")

    # convenience wrappers mirroring send_recv.proto service methods
    def send_var(self, name: str, value: np.ndarray, trainer_id: int = 0,
                 rows=None, height: int = 0):
        # rows ride as an int64 ndarray: a raw buffer on the binary wire
        # instead of a pickled python list of boxed ints
        return self.call("send_var", name=name, value=np.asarray(value),
                         trainer_id=trainer_id,
                         rows=None if rows is None
                         else np.asarray(rows, np.int64).reshape(-1),
                         height=int(height))

    def get_var(self, name: str, trainer_id: int = 0) -> np.ndarray:
        return self.call("get_var", name=name, trainer_id=trainer_id)

    def prefetch_rows(self, name: str, rows,
                      prefetch: bool = False) -> np.ndarray:
        """Row pull. ``prefetch=True`` tags the call as an async-overlap
        early fetch so the server's stats() can count prefetch traffic
        separately; an old server without the kwarg gets the untagged
        call (memoized fallback — the method is idempotent, so the
        retry is safe)."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if prefetch and "prefetch_rows#tag" not in self._missing_methods:
            try:
                return self.call("prefetch_rows", name=name, rows=rows,
                                 prefetch=True)
            except (RuntimeError, TypeError) as e:
                if "unexpected keyword" not in str(e):
                    raise
                self._missing_methods.add("prefetch_rows#tag")
        return self.call("prefetch_rows", name=name, rows=rows)

    def barrier(self, kind: str, trainer_id: int = 0):
        return self.call("barrier", kind=kind, trainer_id=trainer_id)

    def stop(self):
        try:
            ch = self._acquire()
            try:
                if ch.sock is None:
                    # prefer an idle channel that is already connected
                    with self._cv:
                        for other in list(self._free):
                            if other.sock is not None:
                                self._free.remove(other)
                                self._free.append(ch)
                                ch = other
                                break
                if ch.sock is None:
                    # no live connection anywhere — a dead/never-reached
                    # server has nothing to stop; don't burn a connect
                    # poll on teardown
                    return
                ch.sock.settimeout(self._deadline_s)
                _send_parts(ch.sock,
                            _encode_frame({"method": "stop"},
                                          ch.proto)[0])
                _recv_frame(ch.sock, ch.proto)
            finally:
                self._release(ch)
        except (ConnectionError, OSError):
            pass


def send_vars_batch(client: "VarClient", items, trainer_id: int = 0):
    """One coalesced multi-var send (items: [(name, value), ...]). Falls
    back to per-var ``send_var`` ONLY when the server predates the batch
    method ("no method" — nothing was applied); any other failure
    propagates, because a partially-applied batch must NOT be re-sent
    per-var under fresh dedup tokens (that would double-apply its
    already-applied prefix). The missing method is memoized on the
    client so only the FIRST call against an old server pays the probe
    round trip."""
    if "send_vars_batch" not in client._missing_methods:
        try:
            client.call("send_vars_batch",
                        vars=[{"name": n, "value": np.asarray(v)}
                              for n, v in items],
                        trainer_id=trainer_id)
            return
        except RuntimeError as e:
            if "no method send_vars_batch" not in str(e):
                raise
            client._missing_methods.add("send_vars_batch")
    for n, v in items:
        client.send_var(n, v, trainer_id=trainer_id)


def _record_rpc_span(method, var, endpoint, t_start, bytes_out, bytes_in,
                     retries, quant_info=None):
    """cat="rpc" profiler span per client call (name ``op:var@ep``) so
    chrome traces show RPC time next to cat="segment"/"window" spans.
    Quantized frames additionally carry quant/bytes_raw args — the
    per-call compression evidence beside the registry counters."""
    from . import profiler
    if not profiler.is_profiling():
        return
    args = {"bytes_out": int(bytes_out), "bytes_in": int(bytes_in),
            "retries": int(retries)}
    if quant_info:
        args["quant"] = quant_info.get("quant", "")
        args["bytes_raw"] = int(quant_info.get("bytes_raw", 0))
        args["bytes_quant"] = int(quant_info.get("bytes_quant", 0))
    profiler.record_span(
        f"{method}:{var or '-'}@{endpoint}", t_start,
        time.perf_counter(), cat="rpc", args=args)


class HeartBeatMonitor:
    """Worker-liveness watchdog on the pserver (reference:
    operators/distributed/heart_beat_monitor.h:54 — every worker RPC
    updates its beat; a monitor thread flags workers whose last beat is
    older than the timeout). Dead workers are logged and queryable, AND
    death listeners fire so collectives (BarrierManager, ReduceService)
    release their waiters promptly with WorkerDeadError; tearing the
    whole job down remains the launcher's call (launch.py watch loop)."""

    def __init__(self, worker_num: int, timeout: float = 60.0,
                 check_interval: float = 3.0,
                 on_dead: Optional[Callable[[int], None]] = None):
        self.worker_num = worker_num
        self.timeout = timeout
        self.check_interval = check_interval
        self._listeners: List[Callable[[int], None]] = []
        if on_dead is not None:
            self._listeners.append(on_dead)
        self._beats: Dict[int, float] = {}
        self._dead: set = set()
        # participants in an INTENTIONAL drain: silence past the timeout
        # is expected (state streaming, planned leave) and must NOT fire
        # the dead-listeners — which would abort every in-flight barrier
        # with WorkerDeadError for a worker that is fine
        # (docs/FAULT_TOLERANCE.md "Elastic membership")
        self._draining: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_dead_listener(self, cb: Callable[[int], None]) -> None:
        """Register an extra callback fired (off-lock) for every newly
        declared-dead worker id."""
        self._listeners.append(cb)

    def update(self, worker_id: int) -> None:
        now = time.time()
        with self._lock:
            self._beats[int(worker_id)] = now
            self._dead.discard(int(worker_id))

    def mark_draining(self, worker_id: int) -> None:
        """Flag an intentional drain: the participant may go silent past
        the timeout without being declared dead. Sticky until
        ``clear_draining`` — a beat alone does not clear it (a draining
        participant keeps beating while it streams its state, and its
        eventual silence is still not a death)."""
        with self._lock:
            self._draining.add(int(worker_id))
            self._dead.discard(int(worker_id))
            # restart the silence clock so a pre-drain beat gap can't
            # flip it to dead the instant draining is cleared
            self._beats[int(worker_id)] = time.time()

    def clear_draining(self, worker_id: int) -> None:
        with self._lock:
            self._draining.discard(int(worker_id))
            self._beats[int(worker_id)] = time.time()

    def dead_workers(self):
        with self._lock:
            return sorted(self._dead)

    def alive_workers(self):
        with self._lock:
            return sorted(set(self._beats) - self._dead)

    def is_dead(self, worker_id: int) -> bool:
        with self._lock:
            return int(worker_id) in self._dead

    def participant_states(self) -> Dict[int, str]:
        """wid → "dead" | "draining" | "alive" for every participant
        that ever beat (drain tooling polls this over the wire)."""
        with self._lock:
            out = {}
            for wid in set(self._beats) | self._dead | self._draining:
                out[wid] = ("draining" if wid in self._draining else
                            "dead" if wid in self._dead else "alive")
            return out

    def _scan(self):
        while not self._stop.wait(self.check_interval):
            now = time.time()
            newly_dead = []
            with self._lock:
                for wid, t in self._beats.items():
                    if wid in self._dead or wid in self._draining:
                        continue
                    if now - t > self.timeout:
                        self._dead.add(wid)
                        newly_dead.append(wid)
            for wid in newly_dead:
                _LOG.warning(
                    "HeartBeatMonitor: worker %d silent for >%.0fs — "
                    "presumed dead", wid, self.timeout)
                for cb in self._listeners:
                    try:
                        cb(wid)
                    except Exception:
                        _LOG.exception("dead-worker listener failed")

    def start_monitor(self) -> "HeartBeatMonitor":
        self._thread = threading.Thread(target=self._scan, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.check_interval * 2)

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"heartbeat": lambda trainer_id=0: (self.update(trainer_id)
                                                   or True),
                # liveness is queryable over RPC (the reference exposes it
                # via GetWorkerStatus on the monitor thread)
                "dead_workers": lambda trainer_id=0: self.dead_workers(),
                "alive_workers": lambda trainer_id=0: self.alive_workers(),
                "participant_states": lambda trainer_id=0:
                    self.participant_states(),
                # intentional-leave plumbing: a draining participant (or
                # the admin driving its drain) flags itself so silence
                # is not death
                "mark_draining": lambda trainer_id=0:
                    (self.mark_draining(trainer_id) or True),
                "clear_draining": lambda trainer_id=0:
                    (self.clear_draining(trainer_id) or True)}


class BarrierManager:
    """Dead-worker-aware rendezvous for ``world`` trainers (replaces the
    reference's RPCServer barrier counters — rpc_server.cc
    IncreaseBatchBarrier/WaitBarrier, which block until a condition or
    forever).

    Arrival is keyed by trainer id, so duplicate arrivals WITHIN a round
    (e.g. a retry racing its still-executing original) are absorbed with
    no double-count; retries landing after the round released are handled
    one layer down by the VarServer dedup cache (barrier RPCs carry
    ``_dedup`` tokens), so they replay the completed response instead of
    phantom-arriving in the next round. When every
    participant arrived, the releasing arrival runs ``on_release`` (the
    pserver's aggregate+optimize action) under the lock, bumps the round
    and wakes everyone. If the HeartBeatMonitor declares a participant
    dead, ALL current and future waiters of the in-flight round raise
    ``WorkerDeadError`` naming the dead worker(s) — within roughly one
    monitor check interval, never the full deadline. Stragglers without
    a death verdict time out after ``deadline`` (FLAGS_barrier_deadline)
    with a TimeoutError naming the missing count."""

    def __init__(self, world: int, monitor: Optional[HeartBeatMonitor]
                 = None, deadline: Optional[float] = None, lock=None):
        self._world = int(world)
        self._monitor = monitor
        self._deadline = (float(core.globals_["FLAGS_barrier_deadline"])
                          if deadline is None else float(deadline))
        self._cv = threading.Condition(lock)
        self._state: Dict[str, Dict[str, Any]] = {}
        if monitor is not None:
            monitor.add_dead_listener(self._on_dead)

    def _on_dead(self, wid: int):
        with self._cv:
            self._cv.notify_all()

    def idle(self, kind: str) -> bool:
        """True when no participant is parked at ``kind`` — the
        between-rounds window a shard drain quiesces into. Safe to call
        while already holding the shared lock (the Condition wraps an
        RLock in the listen_and_serv wiring)."""
        with self._cv:
            st = self._state.get(kind)
            return st is None or not st["arrived"]

    def _check_dead_locked(self, kind: str, st: Dict[str, Any],
                           trainer_id: int):
        if self._monitor is None:
            return
        dead = [d for d in self._monitor.dead_workers()
                if d != int(trainer_id)]
        if dead:
            # abort the in-flight round: every waiter re-checks this on
            # wake and raises too; arrivals reset so a later round (after
            # revival or relaunch) starts clean
            st["arrived"] = set()
            raise core.WorkerDeadError(
                f"barrier '{kind}': worker(s) {dead} declared dead by the "
                f"heartbeat monitor while {self._world} participants were "
                f"expected")

    def arrive(self, kind: str, trainer_id: int,
               on_release: Optional[Callable[[], None]] = None,
               deadline: Optional[float] = None) -> int:
        """Block until all ``world`` participants arrived at ``kind``.
        Returns the completed round number."""
        deadline = self._deadline if deadline is None else float(deadline)
        with self._cv:
            st = self._state.setdefault(kind,
                                        {"arrived": set(), "round": 0})
            self._check_dead_locked(kind, st, trainer_id)
            st["arrived"].add(int(trainer_id))
            if len(st["arrived"]) >= self._world:
                if on_release is not None:
                    on_release()
                st["arrived"] = set()
                st["round"] += 1
                self._cv.notify_all()
                return st["round"]
            rnd = st["round"]
            end = time.time() + deadline
            while st["round"] == rnd:
                remaining = end - time.time()
                if remaining <= 0:
                    missing = self._world - len(st["arrived"])
                    st["arrived"].discard(int(trainer_id))
                    raise TimeoutError(
                        f"barrier '{kind}': {missing} of {self._world} "
                        f"participants missing after {deadline:.0f}s")
                self._cv.wait(min(1.0, remaining))
                self._check_dead_locked(kind, st, trainer_id)
            return st["round"]


class WorkerHeartBeat:
    """Worker-side beat thread: pings every pserver endpoint periodically
    (reference workers beat inside their send RPCs; an idle worker still
    beats here so slow data pipelines aren't declared dead).

    Beats ride PRIVATE connections, not the pooled VarClient: the pooled
    client serializes calls on one socket, so a data RPC blocked in a
    long server-side barrier would stall the beats and get this very
    worker declared dead. Each beat is one short-timeout, zero-retry
    attempt — a missed beat is information, the monitor sees silence."""

    def __init__(self, endpoints, trainer_id: int, interval: float = 5.0):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self.interval = interval
        self._clients: Dict[str, VarClient] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _targets(self):
        """Physical endpoints to beat THIS round: each configured slot's
        current primary (the view re-points beats after a drain or
        failover) plus its warm replicas — a standby must see trainer
        beats BEFORE promotion or its own trainer-liveness monitor
        would start from silence the moment it takes over."""
        view = ps_membership.current_view()
        out = []
        for ep in self.endpoints:
            cur = ep if view is None else view.resolve(ep)
            if cur not in out:
                out.append(cur)
            if view is not None:
                for r in view.replicas(ep):
                    if r not in out:
                        out.append(r)
        return out

    def _loop(self):
        while not self._stop.wait(self.interval):
            # beats carry the trainer's view gossip: a standby whose
            # primary dies the instant after an epoch was minted
            # elsewhere (drain/rejoin) would otherwise promote BELOW
            # the epoch the trainers already hold — monotonic installs
            # refuse the promotion view and no one ever re-routes. The
            # resolve=False beat clients skip the data-path stamping,
            # so stamp explicitly (epoch-0 clusters stamp nothing —
            # wire compat with pre-elastic servers, same rule as call).
            gossip = {}
            view = ps_membership.current_view()
            if view is not None and view.epoch > 0:
                gossip["_view_epoch"] = view.epoch
                gossip["_view"] = view.to_dict()
            for ep in self._targets():
                try:
                    cli = self._clients.get(ep)
                    if cli is None:
                        # one private channel is enough: beats are tiny
                        # and strictly sequential on this thread;
                        # targets are already physical — no resolution
                        cli = self._clients[ep] = VarClient(
                            ep, connect_timeout=max(1.0, self.interval),
                            channels=1, resolve=False)
                    cli.call("heartbeat", trainer_id=self.trainer_id,
                             _rpc_timeout=max(1.0, self.interval * 2),
                             _rpc_retries=0, **gossip)
                except Exception:
                    # server gone/restarting; the monitor sees silence.
                    # drop the client so the next beat reconnects fresh
                    self._clients.pop(ep, None)

    def start(self) -> "WorkerHeartBeat":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 2)
        # snapshot: the beat thread may outlive the bounded join and
        # still be mutating the dict
        for cli in list(self._clients.values()):
            cli.close()
        self._clients.clear()


class ReduceService:
    """Sum-across-workers service for host-side metric reductions (the
    reference's GlooWrapper::AllReduce role — gloo_wrapper.h:146). Workers
    push a named array; get blocks until all ``world`` contributions of the
    current generation arrived, then every worker reads the sum. The
    generation resets once all workers fetched, so the same name can be
    reduced repeatedly. With a ``monitor``, a dead worker that has not yet
    contributed releases every waiter with WorkerDeadError instead of
    letting them run out the full timeout."""

    def __init__(self, monitor: Optional[HeartBeatMonitor] = None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._monitor = monitor
        self._sums: Dict[str, np.ndarray] = {}
        self._contrib: Dict[str, set] = {}
        self._fetched: Dict[str, set] = {}
        if monitor is not None:
            monitor.add_dead_listener(
                lambda wid: self._notify_all())

    def _notify_all(self):
        with self._cv:
            self._cv.notify_all()

    def push(self, name: str, value, trainer_id: int):
        arr = np.asarray(value, np.float64)
        with self._cv:
            if trainer_id in self._contrib.setdefault(name, set()):
                raise RuntimeError(
                    f"reduce '{name}': trainer {trainer_id} pushed twice in "
                    f"one generation")
            cur = self._sums.get(name)
            self._sums[name] = arr if cur is None else cur + arr
            self._contrib[name].add(trainer_id)
            self._cv.notify_all()
        return True

    def get(self, name: str, trainer_id: int, world: int,
            timeout: float = 300.0):
        end = time.time() + timeout
        with self._cv:
            while len(self._contrib.get(name, ())) < world:
                if self._monitor is not None:
                    dead = [d for d in self._monitor.dead_workers()
                            if d != int(trainer_id)
                            and d not in self._contrib.get(name, ())]
                    if dead:
                        raise core.WorkerDeadError(
                            f"reduce '{name}': worker(s) {dead} declared "
                            f"dead before contributing "
                            f"({len(self._contrib.get(name, ()))}/{world} "
                            f"arrived)")
                remaining = end - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"reduce '{name}': only "
                        f"{len(self._contrib.get(name, ()))}/{world} "
                        f"workers contributed within {timeout}s")
                self._cv.wait(min(1.0, remaining))
            result = self._sums[name]
            fetched = self._fetched.setdefault(name, set())
            fetched.add(trainer_id)
            if len(fetched) >= world:  # everyone has it → reset generation
                self._sums.pop(name, None)
                self._contrib.pop(name, None)
                self._fetched.pop(name, None)
                self._cv.notify_all()
            return result

    def handlers(self) -> Dict[str, Callable[..., Any]]:
        return {"reduce_push": self.push, "reduce_get": self.get}
