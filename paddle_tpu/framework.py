"""paddle.framework 2.0-preview (reference: python/paddle/framework/ —
random.py manual_seed, framework.py get/set_default_dtype + re-exports of
the core graph types)."""
from __future__ import annotations

import numpy as np

from .fluid import core
from .fluid.framework import (Program, Block, Operator, Variable,  # noqa
                              Parameter, program_guard,
                              default_main_program,
                              default_startup_program, in_dygraph_mode)
from .fluid.core import CPUPlace, TPUPlace, CUDAPlace  # noqa: F401

__all__ = ["manual_seed", "seed", "get_default_dtype", "set_default_dtype",
           "Program", "Block", "Operator", "Variable", "Parameter",
           "program_guard", "default_main_program",
           "default_startup_program", "in_dygraph_mode", "CPUPlace",
           "TPUPlace", "CUDAPlace"]

_default_dtype = "float32"


def manual_seed(seed: int):
    """reference framework/random.py manual_seed — seeds program RNG."""
    core.globals_["FLAGS_seed"] = int(seed)
    default_main_program().random_seed = int(seed)
    default_startup_program().random_seed = int(seed)
    return seed


seed = manual_seed


def set_default_dtype(d):
    global _default_dtype
    d = np.dtype(d).name if not isinstance(d, str) else d
    if d not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(f"default dtype must be a float type, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype
