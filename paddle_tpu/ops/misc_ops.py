"""Miscellaneous host/feature ops: hash (reference: hash_op.cc — xxhash
bucketing of int id sequences for sparse features; here a deterministic
32-bit avalanche mix per hash seed — same bucketing semantics though not
bit-identical values, and ids are mixed modulo 2^32 since this build runs
with jax x64 disabled)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, seq, out


@register_op("hash", inputs=("X",), no_grad=True,
             attr_defaults={"num_hash": 1, "mod_by": 100000})
def _hash(ins, attrs):
    x = first(ins, "X")
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 100000))
    ids = x.reshape(x.shape[0], -1).astype(jnp.uint32)
    # combine the row's ids into one key (polynomial roll), then num_hash
    # independent avalanche mixes
    key = jnp.zeros((x.shape[0],), jnp.uint32)
    for j in range(ids.shape[1]):
        key = key * jnp.uint32(1000003) + ids[:, j]
    outs = []
    for h in range(num_hash):
        v = key ^ jnp.uint32((0x9E3779B9 + 0x61C88647 * h) & 0xFFFFFFFF)
        v = (v ^ (v >> 16)) * jnp.uint32(0x85EBCA6B)
        v = (v ^ (v >> 13)) * jnp.uint32(0xC2B2AE35)
        v = v ^ (v >> 16)
        outs.append((v % jnp.uint32(mod_by)).astype(jnp.int32))
    return out(Out=jnp.stack(outs, axis=1)[:, :, None])
