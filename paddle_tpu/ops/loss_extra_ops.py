"""Specialty losses & remaining nn ops — CTC, NCE, hierarchical sigmoid,
linear-chain CRF, sampled softmax, center loss, grid sampler, spectral
norm, random crop, edit distance (reference: operators/warpctc_op.cc,
ctc_align_op.cc, edit_distance_op.cc, nce_op.cc, hierarchical_sigmoid_op.cc,
linear_chain_crf_op.cc, crf_decoding_op.cc, sample_logits_op.cc,
center_loss_op.cc, grid_sampler_op.cc (cudnn), spectral_norm_op.cc,
random_crop_op.cc, teacher_student_sigmoid_loss_op.cc).

TPU notes: CTC replaces the vendored warp-ctc library with a log-domain
dynamic program under lax.scan (padded per LoD bucket, masked); CRF
forward/viterbi likewise. NCE/sampled-softmax draw negatives with the
op-seeded PRNG. Host-only ops (edit_distance, ctc_align) are stateful."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, register_grad_maker, first, out

NEG_INF = -1e30


def _offs(attrs, slot):
    lods = attrs.get("_lod") or {}
    vals = lods.get(slot)
    if not vals or vals[0] is None:
        return None
    return np.asarray(vals[0][-1], np.int64)


def _pad_seqs(x, offs, maxlen=None, fill=0.0):
    lens = offs[1:] - offs[:-1]
    n = len(lens)
    T = int(maxlen or (lens.max() if n else 0))
    pos = np.arange(T)[None, :] + offs[:-1, None]
    valid = np.arange(T)[None, :] < lens[:, None]
    idx = np.where(valid, pos, 0)
    p = jnp.take(jnp.asarray(x), jnp.asarray(idx), axis=0)
    p = jnp.where(jnp.asarray(valid).reshape(valid.shape + (1,) *
                                             (p.ndim - 2)), p, fill)
    return p, jnp.asarray(lens), valid


# --------------------------------------------------------------------------
# CTC (reference: warpctc_op.cc — vendored warp-ctc → log-domain scan)
# --------------------------------------------------------------------------
@register_op("warpctc", needs_lod=True, diff_inputs=["Logits"],
             host_inputs=("Label",),
             attr_defaults={"blank": 0, "norm_by_times": False})
def _warpctc(ins, attrs):
    logits = first(ins, "Logits")      # LoD [T, C] or padded [Tm, N, C]
    label = first(ins, "Label")        # LoD [L, 1] int32
    blank = int(attrs.get("blank", 0))
    l_offs = _offs(attrs, "Logits")
    lab_offs = _offs(attrs, "Label")
    if l_offs is None or lab_offs is None:
        raise ValueError("warpctc: Logits and Label must carry LoD")
    logp_all = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    lp, t_lens, _ = _pad_seqs(logp_all, l_offs, fill=0.0)  # [N, Tm, C]
    labels_np = np.asarray(label).reshape(-1)
    lab_lens = lab_offs[1:] - lab_offs[:-1]
    N = len(lab_lens)
    Lm = int(lab_lens.max()) if N else 0
    lab_pad = np.zeros((N, Lm), np.int32)
    for i in range(N):
        lab_pad[i, :lab_lens[i]] = labels_np[lab_offs[i]:lab_offs[i + 1]]
    # extended label sequence with blanks: S = 2*Lm + 1
    S = 2 * Lm + 1
    ext = np.full((N, S), blank, np.int32)
    ext[:, 1::2] = lab_pad
    ext_j = jnp.asarray(ext)
    lab_lens_j = jnp.asarray(lab_lens)
    s_lens = 2 * lab_lens_j + 1
    # allowed skip transition: ext[s] != blank and ext[s] != ext[s-2]
    skip_ok = np.zeros((N, S), bool)
    skip_ok[:, 2:] = (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])
    skip_ok = jnp.asarray(skip_ok)
    Tm = lp.shape[1]

    def lse(a, b):
        m = jnp.maximum(a, b)
        m_safe = jnp.where(m <= NEG_INF, 0.0, m)
        r = m_safe + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe))
        return jnp.where(m <= NEG_INF, NEG_INF, r)

    emit0 = jnp.take_along_axis(lp[:, 0], ext_j, axis=1)  # [N, S]
    alpha0 = jnp.full((N, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lab_lens_j > 0, emit0[:, 1], NEG_INF))

    def step(alpha, t):
        emit = jnp.take_along_axis(lp[:, t], ext_j, axis=1)
        prev1 = jnp.concatenate(
            [jnp.full((N, 1), NEG_INF), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate(
            [jnp.full((N, 2), NEG_INF), alpha[:, :-2]], 1)
        a = lse(alpha, prev1)
        a = jnp.where(skip_ok, lse(a, prev2), a)
        new = a + emit
        # freeze past each sequence's length
        active = (t < t_lens)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, Tm))
    idx_last = (s_lens - 1).astype(jnp.int32)
    idx_prev = jnp.maximum(idx_last - 1, 0)
    ll = lse(jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0],
             jnp.take_along_axis(alpha, idx_prev[:, None], 1)[:, 0])
    loss = -ll
    if attrs.get("norm_by_times", False):
        loss = loss / t_lens.astype(loss.dtype)
    return {"Loss": [loss.reshape(-1, 1)], "_lod": {"Loss": [None]}}


def _merge_drop(seq, blank, merge):
    kept = []
    prev = None
    for v in seq:
        if merge and prev is not None and v == prev:
            prev = v
            continue
        prev = v
        if v != blank:
            kept.append(int(v))
    return kept


@register_op("ctc_align", needs_lod=True, no_grad=True, stateful=True,
             host_inputs=("InputLength",),
             attr_defaults={"blank": 0, "merge_repeated": True,
                            "padding_value": 0})
def _ctc_align(ins, attrs):
    """Merge repeats + drop blanks (reference ctc_align_op.cc). Two
    modes like the reference: LoD ([T, 1] + lod), or padded ([N, T] +
    InputLength → padded Output + OutputLength)."""
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    in_len = first(ins, "InputLength")
    if in_len is not None:  # padding mode
        x = np.asarray(first(ins, "Input"))
        lens = np.asarray(in_len).reshape(-1).astype(np.int64)
        pad = int(attrs.get("padding_value", 0))
        N, T = x.shape[0], x.shape[-1]
        x2 = x.reshape(N, T)
        out = np.full((N, T), pad, np.int32)
        out_lens = np.zeros((N, 1), np.int64)
        for i in range(N):
            kept = _merge_drop(x2[i, :int(lens[i])], blank, merge)
            out[i, :len(kept)] = kept
            out_lens[i, 0] = len(kept)
        return {"Output": [jnp.asarray(out)],
                "OutputLength": [jnp.asarray(out_lens)],
                "_lod": {"Output": [None], "OutputLength": [None]}}
    x = np.asarray(first(ins, "Input")).reshape(-1)
    offs = _offs(attrs, "Input")
    rows, lens = [], []
    for i in range(len(offs) - 1):
        kept = _merge_drop(x[offs[i]:offs[i + 1]], blank, merge)
        if not kept:
            kept = [-1]  # reference emits -1 row for empty result
        rows.extend(kept)
        lens.append(len(kept))
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Output": [jnp.asarray(np.asarray(rows, np.int32)
                                   .reshape(-1, 1))],
            "_lod": {"Output": [(lod0,)]}}


@register_op("edit_distance", needs_lod=True, no_grad=True, stateful=True,
             attr_defaults={"normalized": False})
def _edit_distance(ins, attrs):
    """Levenshtein distance per sequence pair (reference
    edit_distance_op.cc)."""
    hyp = np.asarray(first(ins, "Hyps")).reshape(-1)
    ref = np.asarray(first(ins, "Refs")).reshape(-1)
    h_offs = _offs(attrs, "Hyps")
    r_offs = _offs(attrs, "Refs")
    n = len(h_offs) - 1
    dists = np.zeros((n, 1), np.float32)
    for i in range(n):
        a = hyp[h_offs[i]:h_offs[i + 1]]
        b = ref[r_offs[i]:r_offs[i + 1]]
        dp = np.arange(len(b) + 1, dtype=np.int64)
        for x_ in a:
            prev = dp.copy()
            dp[0] = prev[0] + 1
            for j in range(1, len(b) + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (x_ != b[j - 1]))
        d = float(dp[-1])
        if attrs.get("normalized", False) and len(b):
            d /= len(b)
        dists[i, 0] = d
    return out(Out=jnp.asarray(dists),
               SequenceNum=jnp.asarray(np.asarray([n], np.int64)))


# --------------------------------------------------------------------------
# NCE / sampled softmax / hierarchical sigmoid
# --------------------------------------------------------------------------
@register_op("nce", needs_rng=True,
             diff_inputs=["Input", "Weight", "Bias"],
             attr_defaults={"num_total_classes": 2, "num_neg_samples": 10,
                            "sampler": 0, "seed": 0, "is_sparse": False})
def _nce(ins, attrs):
    """Noise-contrastive estimation (reference nce_op.cc): binary
    logistic on the true class + k uniform noise classes."""
    x = first(ins, "Input")            # [N, D]
    label = first(ins, "Label")        # [N, 1]
    w = first(ins, "Weight")           # [V, D]
    b = first(ins, "Bias")             # [V]
    V = int(attrs["num_total_classes"])
    k = int(attrs["num_neg_samples"])
    N = x.shape[0]
    rng = attrs["_rng"]
    neg = jax.random.randint(rng, (N, k), 0, V)      # uniform sampler
    lab = label.reshape(N).astype(jnp.int32)
    pos_logit = jnp.sum(x * w[lab], -1)
    if b is not None:
        pos_logit = pos_logit + b.reshape(-1)[lab]
    neg_logit = jnp.einsum("nd,nkd->nk", x, w[neg])
    if b is not None:
        neg_logit = neg_logit + b.reshape(-1)[neg]
    # NCE with uniform noise: q = k/V constant, folded into the sigmoid
    logq = jnp.log(jnp.asarray(k / V, x.dtype))
    pos_loss = jax.nn.softplus(-(pos_logit - logq))
    neg_loss = jax.nn.softplus(neg_logit - logq).sum(-1)
    cost = (pos_loss + neg_loss).reshape(N, 1)
    return out(Cost=cost,
               SampleLogits=neg_logit,
               SampleLabels=neg.astype(jnp.int32))


@register_op("sampled_softmax_with_cross_entropy", needs_rng=True,
             diff_inputs=["Logits"],
             attr_defaults={"num_samples": 5, "seed": 0,
                            "use_customized_samples": False})
def _sampled_softmax(ins, attrs):
    """Softmax CE over {true, sampled} classes (reference
    sample_logits_op.cc + tests)."""
    logits = first(ins, "Logits")      # [N, V]
    label = first(ins, "Label")        # [N, 1]
    S = int(attrs["num_samples"])
    N, V = logits.shape
    rng = attrs["_rng"]
    samples = jax.random.randint(rng, (N, S), 0, V)
    lab = label.reshape(N, 1).astype(jnp.int32)
    cols = jnp.concatenate([lab, samples], 1)        # [N, 1+S]
    sub = jnp.take_along_axis(logits, cols, axis=1)
    ce = -jax.nn.log_softmax(sub, -1)[:, 0]
    return out(Loss=ce.reshape(N, 1))


@register_op("hierarchical_sigmoid",
             diff_inputs=["X", "W", "Bias"],
             attr_defaults={"num_classes": 2, "is_sparse": False})
def _hierarchical_sigmoid(ins, attrs):
    """Complete-binary-tree hierarchical sigmoid (reference
    hierarchical_sigmoid_op.cc; SimpleCode in matrix_bit_code.h: for label
    l the path code is c = l + num_classes, node at depth j is
    (c >> (j+1)) - 1, bit j is (c >> j) & 1)."""
    x = first(ins, "X")                # [N, D]
    w = first(ins, "W")                # [num_classes-1, D]
    label = first(ins, "Label")        # [N, 1]
    bias = first(ins, "Bias")
    V = int(attrs["num_classes"])
    N = x.shape[0]
    c = label.reshape(N).astype(jnp.int32) + V
    depth = int(np.ceil(np.log2(max(V, 2)))) + 1
    loss = jnp.zeros((N,), x.dtype)
    for j in range(depth):
        node = (c >> (j + 1)) - 1
        bit = (c >> j) & 1
        active = node >= 0
        node_c = jnp.clip(node, 0, w.shape[0] - 1)
        logit = jnp.sum(x * w[node_c], -1)
        if bias is not None:
            logit = logit + bias.reshape(-1)[node_c]
        # bit==1 → sigmoid(logit), bit==0 → 1-sigmoid
        l = jax.nn.softplus(jnp.where(bit == 1, -logit, logit))
        loss = loss + jnp.where(active, l, 0.0)
    pre = jnp.zeros((N, w.shape[0]), x.dtype)  # PreOut parity slot
    return out(Out=loss.reshape(N, 1), PreOut=pre)


# --------------------------------------------------------------------------
# linear-chain CRF + viterbi decode
# --------------------------------------------------------------------------
@register_op("linear_chain_crf", needs_lod=True,
             diff_inputs=["Emission", "Transition"])
def _linear_chain_crf(ins, attrs):
    """Negative log-likelihood of a linear-chain CRF (reference
    linear_chain_crf_op.cc). Transition layout: row 0 start weights,
    row 1 end weights, rows 2.. the [tags, tags] transition matrix."""
    emission = first(ins, "Emission")  # LoD [T, K]
    transition = first(ins, "Transition")  # [K+2, K]
    label = first(ins, "Label")        # LoD [T, 1]
    offs = _offs(attrs, "Emission")
    K = emission.shape[-1]
    start_w, end_w = transition[0], transition[1]
    trans = transition[2:]             # [K, K] from->to
    em_p, lens, _ = _pad_seqs(emission, offs, fill=0.0)   # [N, Tm, K]
    N, Tm = em_p.shape[0], em_p.shape[1]
    # label padding must stay traceable: the values may be jit tracers
    # (only the LoD offsets are host-static)
    lab_pad, _, _ = _pad_seqs(label.reshape(-1, 1), offs, fill=0)
    lab_p = lab_pad[..., 0].astype(jnp.int32)

    # log partition via forward recursion
    alpha0 = start_w[None, :] + em_p[:, 0]

    def fstep(alpha, t):
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) \
            + em_p[:, t]
        active = (t < lens)[:, None]
        return jnp.where(active, nxt, alpha), None

    alpha, _ = jax.lax.scan(fstep, alpha0, jnp.arange(1, Tm))
    last_lab = jnp.take_along_axis(
        lab_p, jnp.maximum(lens - 1, 0)[:, None].astype(jnp.int32), 1)[:, 0]
    logZ = jax.nn.logsumexp(alpha + end_w[None], -1)

    # gold path score
    em_gold = jnp.take_along_axis(em_p, lab_p[..., None], -1)[..., 0]
    tmask = jnp.asarray(np.arange(Tm))[None, :] < lens[:, None]
    gold = (em_gold * tmask).sum(-1)
    tr_gold = trans[lab_p[:, :-1], lab_p[:, 1:]]
    tr_mask = jnp.asarray(np.arange(1, Tm))[None, :] < lens[:, None]
    gold = gold + (tr_gold * tr_mask).sum(-1)
    gold = gold + start_w[lab_p[:, 0]] + end_w[last_lab]
    ll = gold - logZ
    return {"LogLikelihood": [(-ll).reshape(-1, 1)],
            "Alpha": [alpha], "EmissionExps": [jnp.exp(em_p[:, 0])],
            "TransitionExps": [jnp.exp(transition)],
            "_lod": {"LogLikelihood": [None]}}


@register_op("crf_decoding", needs_lod=True, no_grad=True)
def _crf_decoding(ins, attrs):
    """Viterbi decode (reference crf_decoding_op.cc). Traceable: padded
    batch viterbi via lax.scan with backpointers; LoD offsets are static,
    emission values may be jit tracers."""
    emission = first(ins, "Emission")
    transition = first(ins, "Transition")
    label = first(ins, "Label")
    offs = _offs(attrs, "Emission")
    start_w, end_w = transition[0], transition[1]
    trans = transition[2:]                               # [K, K]
    em_p, lens, _ = _pad_seqs(emission, offs, fill=0.0)  # [N, Tm, K]
    N, Tm = em_p.shape[0], em_p.shape[1]
    lens_np = np.asarray(offs[1:] - offs[:-1])

    if Tm == 0 or N == 0:
        o = jnp.zeros((0, 1), jnp.int32)
    else:
        score0 = start_w[None, :] + em_p[:, 0]

        def step(score, t):
            cand = score[:, :, None] + trans[None]       # [N, from, to]
            bp = jnp.argmax(cand, axis=1).astype(jnp.int32)
            nxt = jnp.max(cand, axis=1) + em_p[:, t]
            active = (t < lens)[:, None]
            return jnp.where(active, nxt, score), bp

        score, bps = jax.lax.scan(step, score0, jnp.arange(1, Tm))
        # bps[t-1]: backpointer INTO position t-1 from tags at position t
        last_tag = jnp.argmax(score + end_w[None], -1).astype(jnp.int32)
        rows = jnp.arange(N)
        tags = [None] * Tm
        cur = last_tag
        for t in range(Tm - 1, -1, -1):
            # (re)anchor each sequence's backtrace at its own end position
            cur = jnp.where(jnp.asarray(lens_np - 1 == t), last_tag, cur)
            tags[t] = cur
            if t > 0:
                cur = bps[t - 1][rows, cur]
        tags = jnp.stack(tags, axis=1)                   # [N, Tm]
        # unpad with static offsets
        o = jnp.concatenate(
            [tags[i, :int(lens_np[i])] for i in range(N)]
        ).reshape(-1, 1).astype(jnp.int32)
    if label is not None:
        o = (o == label.reshape(-1, 1)).astype(jnp.int32)
    lod = (attrs.get("_lod") or {}).get("Emission")[0]
    return {"ViterbiPath": [o], "_lod": {"ViterbiPath": [lod]}}


# --------------------------------------------------------------------------
# misc nn ops
# --------------------------------------------------------------------------
@register_op("center_loss", diff_inputs=["X"],
             attr_defaults={"cluster_num": 2, "alpha": 0.1,
                            "need_update": True})
def _center_loss(ins, attrs):
    """Center loss + center update (reference center_loss_op.cc)."""
    x = first(ins, "X")                # [N, D]
    label = first(ins, "Label").reshape(-1).astype(jnp.int32)
    centers = first(ins, "Centers")    # [C, D]
    lr = first(ins, "CenterUpdateRate")
    alpha = (lr.reshape(-1)[0] if lr is not None
             else jnp.asarray(attrs.get("alpha", 0.1), x.dtype))
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(diff * diff, -1, keepdims=True)
    new_centers = centers
    if attrs.get("need_update", True):
        counts = jnp.zeros((centers.shape[0],), x.dtype) \
            .at[label].add(1.0) + 1.0
        delta = jnp.zeros_like(centers).at[label].add(diff)
        new_centers = centers + alpha * delta / counts[:, None]
    return out(Loss=loss, SampleCenterDiff=diff, CentersOut=new_centers)


@register_op("grid_sampler", diff_inputs=["X", "Grid"],
             attr_defaults={"align_corners": True, "mode": "bilinear",
                            "padding_mode": "zeros"})
def _grid_sampler(ins, attrs):
    """Bilinear grid sampling, grid in [-1, 1] (reference
    grid_sampler_op.cc / cudnn)."""
    x = first(ins, "X")        # [N, C, H, W]
    grid = first(ins, "Grid")  # [N, Ho, Wo, 2] (x, y)
    N, C, H, W = x.shape
    gx = (grid[..., 0] + 1) * (W - 1) / 2
    gy = (grid[..., 1] + 1) * (H - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    lx, ly = gx - x0, gy - y0

    def gather(yy, xx):
        inside = ((yy >= 0) & (yy < H) & (xx >= 0) & (xx < W))
        yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        v = x[jnp.arange(N)[:, None, None], :, yc, xc]   # [N, Ho, Wo, C]
        return v * inside[..., None]

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    o = (v00 * ((1 - ly) * (1 - lx))[..., None]
         + v01 * ((1 - ly) * lx)[..., None]
         + v10 * (ly * (1 - lx))[..., None]
         + v11 * (ly * lx)[..., None])
    return out(Output=jnp.moveaxis(o, -1, 1))


@register_op("spectral_norm", diff_inputs=["Weight"],
             attr_defaults={"dim": 0, "power_iters": 1, "eps": 1e-12})
def _spectral_norm(ins, attrs):
    """Weight / sigma_max via power iteration (reference
    spectral_norm_op.cc)."""
    w = first(ins, "Weight")
    u = first(ins, "U").reshape(-1)
    v = first(ins, "V").reshape(-1)
    dim = int(attrs.get("dim", 0))
    eps = float(attrs.get("eps", 1e-12))
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(int(attrs.get("power_iters", 1))):
        v = mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ mat @ v
    return out(Out=w / sigma)


@register_op("random_crop", needs_rng=True, no_grad=True,
             attr_defaults={"shape": [], "startup_seed": 0})
def _random_crop(ins, attrs):
    x = first(ins, "X")
    shape = [int(s) for s in attrs["shape"]]
    rng = attrs["_rng"]
    nd = len(shape)
    starts = []
    for i, s in enumerate(shape):
        dim = x.shape[x.ndim - nd + i]
        rng, sub = jax.random.split(rng)
        starts.append(jax.random.randint(sub, (), 0, dim - s + 1))
    o = jax.lax.dynamic_slice(
        x, [0] * (x.ndim - nd) + [s for s in starts],
        list(x.shape[:x.ndim - nd]) + shape)
    return out(Out=o)


@register_op("teacher_student_sigmoid_loss",
             diff_inputs=["X"],
             attr_defaults={"soft_max_up_bound": 15.0,
                            "soft_max_lower_bound": -15.0})
def _teacher_student_sigmoid_loss(ins, attrs):
    """reference teacher_student_sigmoid_loss_op.cc: CE where label < 0
    marks teacher soft score encoded as label = -score - 1."""
    x = first(ins, "X").reshape(-1)
    label = first(ins, "Label").reshape(-1)
    x = jnp.clip(x, attrs["soft_max_lower_bound"],
                 attrs["soft_max_up_bound"])
    hard = jax.nn.softplus(x) - x * (label > 0)
    soft_t = -(label + 1.0)
    soft = jax.nn.softplus(x) - x * soft_t
    loss = jnp.where(label < 0, soft, hard)
    return out(Y=loss.reshape(-1, 1))
