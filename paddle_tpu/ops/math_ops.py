"""Dense math op kernels (TPU-native re-implementations of the reference
operator set under paddle/fluid/operators/ — elementwise/, reduce_ops/,
matmul/mul, activations). Each kernel is a pure JAX function; gradients come
from the generic vjp path (registry.run_generic_grad) unless noted.

Semantics follow the reference op contracts:
  * elementwise_* broadcast: Y aligns to X at ``axis`` (default -1 = trailing
    alignment), trailing size-1 dims of Y trimmed
    (reference: operators/elementwise/elementwise_op_function.h).
  * mul: flatten X by num_col_dims into 2-D (reference: operators/mul_op.cc).
  * matmul: optional transpose + alpha, batched with broadcast
    (reference: operators/matmul_op.cc).
  * reduce_*: dim list + keep_dim + reduce_all (reference: operators/reduce_ops/).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, register_grad_maker, first, seq, out


# --------------------------------------------------------------------------
# elementwise binary family
# --------------------------------------------------------------------------
def _align_y(x, y, axis):
    """Paddle elementwise broadcast: reshape Y so it aligns to X at axis.
    Shapes that already broadcast numpy-style (the axis=-1 rightmost
    alignment) pass through unchanged."""
    if x.shape == y.shape:
        return y
    if int(axis) == -1:
        try:
            np.broadcast_shapes(x.shape, y.shape)
            return y
        except ValueError:
            pass
    axis = int(axis)
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1:
        yshape.pop()
    if axis == -1:
        axis = x.ndim - len(yshape)
    new_shape = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return y.reshape(new_shape)


def _register_elementwise(name, fn):
    @register_op(name, inputs=("X", "Y"), attr_defaults={"axis": -1})
    def _kernel(ins, attrs, _fn=fn):
        x, y = first(ins, "X"), first(ins, "Y")
        return out(Out=_fn(x, _align_y(x, y, attrs.get("axis", -1))))
    return _kernel


_register_elementwise("elementwise_add", lambda x, y: x + y)
_register_elementwise("elementwise_sub", lambda x, y: x - y)
_register_elementwise("elementwise_mul", lambda x, y: x * y)
_register_elementwise("elementwise_div", lambda x, y: x / y)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_pow", lambda x, y: x ** y)
_register_elementwise("elementwise_mod", lambda x, y: x % y)
_register_elementwise("elementwise_floordiv", lambda x, y: x // y)


# --------------------------------------------------------------------------
# mul / matmul / bmm / dot  (MXU-bound ops — keep as single dot_generals)
# --------------------------------------------------------------------------
def mxu_available():
    """ONE bf16-matmul gate for every FLAGS_use_bf16_matmul consumer
    (mul/matmul here, conv in nn_ops, fused attention): bf16 only pays
    off where there IS an MXU — on CPU the emulation is a ~2.5x
    pessimization (measured on the bert smoke bench)."""
    from .pallas.flash_attention import _on_tpu
    return _on_tpu()


def _mm(a, b):
    """MXU matmul honoring FLAGS_use_bf16_matmul (bf16 inputs, f32 accum)."""
    from ..fluid import core as _core
    if _core.globals_["FLAGS_use_bf16_matmul"] and a.dtype == jnp.float32 \
            and mxu_available():
        return jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.matmul(a, b)


@register_op("mul", inputs=("X", "Y"),
             attr_defaults={"x_num_col_dims": 1, "y_num_col_dims": 1})
def _mul(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), -1))
    y2 = y.reshape((int(np.prod(ys[:yn])), -1))
    o = _mm(x2, y2)
    return out(Out=o.reshape(xs[:xn] + ys[yn:]))


@register_op("matmul", inputs=("X", "Y"),
             attr_defaults={"transpose_X": False, "transpose_Y": False,
                            "alpha": 1.0})
def _matmul(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    # 1-D operands follow reference rules: vec@vec -> [1], promote otherwise.
    squeeze_front = squeeze_back = False
    if x.ndim == 1:
        x = x[None, :]
        squeeze_front = True
    if y.ndim == 1:
        y = y[:, None]
        squeeze_back = True
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    o = _mm(x, y)
    if squeeze_front:
        o = jnp.squeeze(o, -2)
    if squeeze_back:
        o = jnp.squeeze(o, -1)
    if squeeze_front and squeeze_back:
        o = o.reshape((1,))
    if alpha != 1.0:
        o = o * jnp.asarray(alpha, o.dtype)
    return out(Out=o)


@register_op("matmul_v2", inputs=("X", "Y"),
             attr_defaults={"trans_x": False, "trans_y": False})
def _matmul_v2(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return out(Out=_mm(x, y))


@register_op("bmm", inputs=("X", "Y"))
def _bmm(ins, attrs):
    return out(Out=_mm(first(ins, "X"), first(ins, "Y")))


@register_op("dot", inputs=("X", "Y"))
def _dot(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    return out(Out=jnp.sum(x * y, axis=-1, keepdims=x.ndim == 1))


@register_op("mv", inputs=("X", "Vec"))
def _mv(ins, attrs):
    return out(Out=jnp.matmul(first(ins, "X"), first(ins, "Vec")))


# --------------------------------------------------------------------------
# reductions
# --------------------------------------------------------------------------
def _reduce_axes(x, attrs):
    if attrs.get("reduce_all", False):
        return None
    dims = attrs.get("dim", [0])
    if isinstance(dims, int):
        dims = [dims]
    if not dims:
        return None
    return tuple(int(d) % x.ndim for d in dims)


def _register_reduce(name, fn):
    @register_op(name, inputs=("X",),
                 attr_defaults={"dim": [0], "keep_dim": False,
                                "reduce_all": False})
    def _kernel(ins, attrs, _fn=fn):
        x = first(ins, "X")
        axes = _reduce_axes(x, attrs)
        o = _fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if o.ndim == 0:
            o = o.reshape((1,))
        return out(Out=o)
    return _kernel


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)
_register_reduce("reduce_all", lambda x, axis, keepdims: jnp.all(x, axis=axis, keepdims=keepdims))
_register_reduce("reduce_any", lambda x, axis, keepdims: jnp.any(x, axis=axis, keepdims=keepdims))


@register_op("mean", inputs=("X",))
def _mean(ins, attrs):
    return out(Out=jnp.mean(first(ins, "X")).reshape((1,)))


@register_op("sum", inputs=("X",))
def _sum(ins, attrs):
    xs = seq(ins, "X")
    acc = xs[0]
    for x in xs[1:]:
        acc = acc + x
    return out(Out=acc)


@register_op("logsumexp", inputs=("X",),
             attr_defaults={"axis": [0], "keepdim": False, "reduce_all": False})
def _logsumexp(ins, attrs):
    x = first(ins, "X")
    axes = None if attrs.get("reduce_all") else tuple(
        int(d) % x.ndim for d in (attrs.get("axis") or [0]))
    o = jax.scipy.special.logsumexp(x, axis=axes,
                                    keepdims=attrs.get("keepdim", False))
    if o.ndim == 0:
        o = o.reshape((1,))
    return out(Out=o)


# --------------------------------------------------------------------------
# activations (reference: operators/activation_op.cc REGISTER_ACTIVATION_OP)
# --------------------------------------------------------------------------
def _register_act(name, fn, **kw):
    @register_op(name, inputs=("X",), **kw)
    def _kernel(ins, attrs, _fn=fn):
        return out(Out=_fn(first(ins, "X"), attrs))
    return _kernel


_register_act("relu", lambda x, a: jnp.maximum(x, 0))
_register_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_register_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_register_act("tanh", lambda x, a: jnp.tanh(x))
_register_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_register_act("sqrt", lambda x, a: jnp.sqrt(x))
_register_act("rsqrt", lambda x, a: lax.rsqrt(x))
_register_act("abs", lambda x, a: jnp.abs(x))
_register_act("ceil", lambda x, a: jnp.ceil(x), no_grad=True)
_register_act("floor", lambda x, a: jnp.floor(x), no_grad=True)
_register_act("round", lambda x, a: jnp.round(x), no_grad=True)
_register_act("cos", lambda x, a: jnp.cos(x))
_register_act("sin", lambda x, a: jnp.sin(x))
_register_act("acos", lambda x, a: jnp.arccos(x))
_register_act("asin", lambda x, a: jnp.arcsin(x))
_register_act("atan", lambda x, a: jnp.arctan(x))
_register_act("sinh", lambda x, a: jnp.sinh(x))
_register_act("cosh", lambda x, a: jnp.cosh(x))
_register_act("reciprocal", lambda x, a: 1.0 / x)
_register_act("log", lambda x, a: jnp.log(x))
_register_act("log1p", lambda x, a: jnp.log1p(x))
_register_act("square", lambda x, a: jnp.square(x))
_register_act("exp", lambda x, a: jnp.exp(x))
_register_act("softplus", lambda x, a: jax.nn.softplus(x))
_register_act("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_register_act("erf", lambda x, a: jax.scipy.special.erf(x))
_register_act("sign", lambda x, a: jnp.sign(x), no_grad=True)

_register_act("leaky_relu", lambda x, a: jnp.where(x >= 0, x, x * a.get("alpha", 0.02)),
              attr_defaults={"alpha": 0.02})
_register_act("elu", lambda x, a: jnp.where(x > 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)),
              attr_defaults={"alpha": 1.0})
_register_act("selu",
              lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
                  x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1)),
              attr_defaults={"scale": 1.0507009873554805,
                             "alpha": 1.6732632423543772})
_register_act("relu6", lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)),
              attr_defaults={"threshold": 6.0})
_register_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
              attr_defaults={"t_min": 0.0, "t_max": 24.0})
_register_act("soft_relu",
              lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                                                      a.get("threshold", 40.0)))),
              attr_defaults={"threshold": 40.0})
_register_act("gelu",
              lambda x, a: (0.5 * x * (1.0 + jnp.tanh(
                  np.sqrt(2.0 / np.pi) * (x + 0.044715 * x ** 3)))
                  ) if a.get("approximate", False) else jax.nn.gelu(x, approximate=False),
              attr_defaults={"approximate": False})
_register_act("hard_sigmoid",
              lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
              attr_defaults={"slope": 0.2, "offset": 0.5})
_register_act("hard_swish",
              lambda x, a: x * jnp.clip(x + a.get("offset", 3.0), 0,
                                        a.get("threshold", 6.0)) / a.get("scale", 6.0),
              attr_defaults={"threshold": 6.0, "scale": 6.0, "offset": 3.0})
_register_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
              attr_defaults={"beta": 1.0})
_register_act("stanh",
              lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x),
              attr_defaults={"scale_a": 0.67, "scale_b": 1.7159})
_register_act("softshrink",
              lambda x, a: jnp.where(x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
                                     jnp.where(x < -a.get("lambda", 0.5),
                                               x + a.get("lambda", 0.5), 0.0)),
              attr_defaults={"lambda": 0.5})
_register_act("hard_shrink",
              lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
              attr_defaults={"threshold": 0.5})
_register_act("thresholded_relu",
              lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
              attr_defaults={"threshold": 1.0})
_register_act("pow", lambda x, a: x ** a.get("factor", 1.0),
              attr_defaults={"factor": 1.0})


@register_op("prelu", inputs=("X", "Alpha"), attr_defaults={"mode": "all"})
def _prelu(ins, attrs):
    x, alpha = first(ins, "X"), first(ins, "Alpha")
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    return out(Out=jnp.where(x > 0, x, alpha * x))


# --------------------------------------------------------------------------
# scale / clip / misc unary
# --------------------------------------------------------------------------
@register_op("scale", inputs=("X", "ScaleTensor"),
             attr_defaults={"scale": 1.0, "bias": 0.0, "bias_after_scale": True})
def _scale(ins, attrs):
    x = first(ins, "X")
    s = first(ins, "ScaleTensor")
    s = jnp.asarray(attrs.get("scale", 1.0), x.dtype) if s is None else s.astype(x.dtype)
    b = jnp.asarray(attrs.get("bias", 0.0), x.dtype)
    if attrs.get("bias_after_scale", True):
        return out(Out=x * s + b)
    return out(Out=(x + b) * s)


@register_op("clip", inputs=("X",), attr_defaults={"min": 0.0, "max": 0.0})
def _clip(ins, attrs):
    return out(Out=jnp.clip(first(ins, "X"), attrs.get("min"), attrs.get("max")))


@register_op("clip_by_norm", inputs=("X",), attr_defaults={"max_norm": 1.0})
def _clip_by_norm(ins, attrs):
    x = first(ins, "X")
    mn = attrs.get("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return out(Out=jnp.where(norm > mn, x * (mn / norm), x))


@register_op("squared_l2_norm", inputs=("X",))
def _squared_l2_norm(ins, attrs):
    return out(Out=jnp.sum(jnp.square(first(ins, "X"))).reshape((1,)))


@register_op("l1_norm", inputs=("X",))
def _l1_norm(ins, attrs):
    return out(Out=jnp.sum(jnp.abs(first(ins, "X"))).reshape((1,)))


@register_op("frobenius_norm", inputs=("X",),
             attr_defaults={"dim": [0], "keep_dim": False, "reduce_all": False})
def _frobenius_norm(ins, attrs):
    x = first(ins, "X")
    axes = _reduce_axes(x, attrs)
    o = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes,
                         keepdims=attrs.get("keep_dim", False)))
    if o.ndim == 0:
        o = o.reshape((1,))
    return out(Out=o)


@register_op("p_norm", inputs=("X",),
             attr_defaults={"porder": 2.0, "axis": -1, "epsilon": 1e-12,
                            "keepdim": False})
def _p_norm(ins, attrs):
    x = first(ins, "X")
    p = attrs.get("porder", 2.0)
    ax = int(attrs.get("axis", -1))
    o = jnp.sum(jnp.abs(x) ** p, axis=ax,
                keepdims=attrs.get("keepdim", False)) ** (1.0 / p)
    return out(Out=o)


@register_op("cumsum", inputs=("X",),
             attr_defaults={"axis": -1, "flatten": False, "exclusive": False,
                            "reverse": False})
def _cumsum(ins, attrs):
    x = first(ins, "X")
    if attrs.get("flatten", False):
        x = x.reshape(-1)
    ax = int(attrs.get("axis", -1))
    if attrs.get("reverse", False):
        x = jnp.flip(x, ax)
    o = jnp.cumsum(x, axis=ax)
    if attrs.get("exclusive", False):
        o = o - x
    if attrs.get("reverse", False):
        o = jnp.flip(o, ax)
    return out(Out=o)


@register_op("kron", inputs=("X", "Y"))
def _kron(ins, attrs):
    return out(Out=jnp.kron(first(ins, "X"), first(ins, "Y")))


@register_op("trace", inputs=("Input",),
             attr_defaults={"offset": 0, "axis1": 0, "axis2": 1})
def _trace(ins, attrs):
    return out(Out=jnp.trace(first(ins, "Input"), offset=attrs.get("offset", 0),
                             axis1=attrs.get("axis1", 0),
                             axis2=attrs.get("axis2", 1)))


@register_op("addmm", inputs=("Input", "X", "Y"),
             attr_defaults={"Alpha": 1.0, "Beta": 1.0})
def _addmm(ins, attrs):
    inp, x, y = first(ins, "Input"), first(ins, "X"), first(ins, "Y")
    return out(Out=attrs.get("Beta", 1.0) * inp + attrs.get("Alpha", 1.0) * (x @ y))


@register_op("increment", inputs=("X",), attr_defaults={"step": 1.0})
def _increment(ins, attrs):
    x = first(ins, "X")
    return out(Out=x + jnp.asarray(attrs.get("step", 1.0), x.dtype))


@register_op("minus", inputs=("X", "Y"))
def _minus(ins, attrs):
    return out(Out=first(ins, "X") - first(ins, "Y"))


@register_op("cos_sim", inputs=("X", "Y"))
def _cos_sim(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    xy = jnp.sum(x * y, -1, keepdims=True)
    return out(Out=xy / (xn * yn), XNorm=xn, YNorm=yn)


@register_op("isfinite", inputs=("X",), no_grad=True)
def _isfinite(ins, attrs):
    return out(Out=jnp.all(jnp.isfinite(first(ins, "X"))).reshape((1,)))


# isnan/isinf are DISTINCT reductions (reference: isfinite_op.cc
# registers all three over the Any/All functors): isnan answers "any
# NaN?", isinf "any Inf?" — an Inf-only tensor has has_nan()==False and
# a NaN-only tensor has has_inf()==False (layers.tensor.has_nan/has_inf
# build on these; the old port aliased both to NOT isfinite).
@register_op("isnan", inputs=("X",), no_grad=True)
def _isnan(ins, attrs):
    return out(Out=jnp.any(jnp.isnan(first(ins, "X"))).reshape((1,)))


@register_op("isinf", inputs=("X",), no_grad=True)
def _isinf(ins, attrs):
    return out(Out=jnp.any(jnp.isinf(first(ins, "X"))).reshape((1,)))


@register_op("allclose", inputs=("Input", "Other"), no_grad=True,
             attr_defaults={"rtol": 1e-5, "atol": 1e-8, "equal_nan": False})
def _allclose(ins, attrs):
    return out(Out=jnp.allclose(first(ins, "Input"), first(ins, "Other"),
                                rtol=attrs.get("rtol", 1e-5),
                                atol=attrs.get("atol", 1e-8),
                                equal_nan=attrs.get("equal_nan", False)).reshape((1,)))


# comparison / logical (no grad)
def _register_cmp(name, fn):
    @register_op(name, inputs=("X", "Y"), no_grad=True,
                 attr_defaults={"axis": -1})
    def _kernel(ins, attrs, _fn=fn):
        x, y = first(ins, "X"), first(ins, "Y")
        return out(Out=_fn(x, _align_y(x, y, attrs.get("axis", -1))))
    return _kernel


_register_cmp("less_than", lambda x, y: x < y)
_register_cmp("less_equal", lambda x, y: x <= y)
_register_cmp("greater_than", lambda x, y: x > y)
_register_cmp("greater_equal", lambda x, y: x >= y)
_register_cmp("equal", lambda x, y: x == y)
_register_cmp("not_equal", lambda x, y: x != y)
_register_cmp("logical_and", jnp.logical_and)
_register_cmp("logical_or", jnp.logical_or)
_register_cmp("logical_xor", jnp.logical_xor)


@register_op("logical_not", inputs=("X",), no_grad=True)
def _logical_not(ins, attrs):
    return out(Out=jnp.logical_not(first(ins, "X")))


@register_op("maximum", inputs=("X", "Y"))
def _maximum(ins, attrs):
    return out(Out=jnp.maximum(first(ins, "X"), first(ins, "Y")))


@register_op("inverse", inputs=("Input",))
def _inverse(ins, attrs):
    return out(Output=jnp.linalg.inv(first(ins, "Input")))


@register_op("cholesky", inputs=("X",), attr_defaults={"upper": False})
def _cholesky(ins, attrs):
    l = jnp.linalg.cholesky(first(ins, "X"))
    if attrs.get("upper", False):
        l = jnp.swapaxes(l, -1, -2)
    return out(Out=l)


@register_op("dist", inputs=("X", "Y"), attr_defaults={"p": 2.0})
def _dist(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    p = attrs.get("p", 2.0)
    d = jnp.abs(x - y).reshape(-1)
    if p == 0:
        o = jnp.sum(d != 0).astype(x.dtype)
    elif np.isinf(p):
        o = jnp.max(d)
    else:
        o = jnp.sum(d ** p) ** (1.0 / p)
    return out(Out=o.reshape((1,)))
