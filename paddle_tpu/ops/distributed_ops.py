"""Parameter-server ops — send / recv / barriers / listen_and_serv /
distributed_lookup_table (reference: paddle/fluid/operators/distributed_ops/
send_op.cc, recv_op.cc, send_barrier_op.cc, fetch_barrier_op.cc,
listen_and_serv_op.cc:333,110,226, distributed_lookup_table_op.cc,
checkpoint_notify_op.cc; RPC plane in ../fluid/ps_rpc.py).

All stateful host ops: the PS plane lives on TPU-VM hosts over DCN; the
dense data path on TPU uses ICI collectives instead (parallel/). Sync-mode
server semantics follow RunSyncLoop (listen_and_serv_op.cc:110): collect
each trainer's grads + a send barrier, SUM per grad name, run the optimize
blocks, then serve gets until the next round. Async follows RunAsyncLoop
(:226): apply a grad's optimize block on arrival.
"""
from __future__ import annotations

import itertools
import os
import socket
import threading
import time

import numpy as np
import jax.numpy as jnp

from .registry import register_op, register_grad_maker, first, seq, out
from ..fluid import core


def _client(ep):
    from ..fluid.ps_rpc import VarClient
    return VarClient.of(ep)


# shared fan-out pool for per-pserver RPC overlap (reference:
# parameter_prefetch.cc issues every section's RPC before waiting on any
# of them). Threads are IO-bound socket waiters, so a small shared pool
# is plenty; VarClient's per-endpoint channel pool keeps the concurrent
# calls from serializing on one socket.
_FANOUT_POOL = None
_FANOUT_LOCK = threading.Lock()


def _legacy_dataplane() -> bool:
    """PADDLE_TPU_PS_PICKLE_WIRE=1 = the full legacy data plane (serial
    shard walks, no dedup, no batched RPCs) — one source of truth in
    ps_rpc so the bench lanes can't drift."""
    from ..fluid.ps_rpc import _pickle_wire_forced
    return _pickle_wire_forced()


def _fanout(tasks):
    """Run callables concurrently; return their results in order. The
    FIRST error wins — the rest are drained (awaited) first so no RPC is
    left in flight against a half-torn-down scope. The submitting
    thread's RPC call budget (serving deadline propagation,
    ps_rpc.call_budget) is re-installed on the pool threads — without
    it every sharded section RPC of a deadline-stamped request would
    run unbudgeted."""
    if len(tasks) == 1 or _legacy_dataplane():
        return [t() for t in tasks]
    global _FANOUT_POOL
    with _FANOUT_LOCK:
        if _FANOUT_POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _FANOUT_POOL = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="ps-fanout")
    from ..fluid import ps_rpc as _ps_rpc
    from ..fluid import telemetry as _telemetry
    budget = _ps_rpc.current_call_budget()
    # the submitting thread's TRACE context rides along with its budget:
    # every sharded section RPC of one lookup must carry the same trace
    # id or the pserver-side handler spans fall out of the request's
    # timeline (docs/OBSERVABILITY.md)
    tctx = _telemetry.current_trace()
    if budget is not None or tctx is not None:
        tasks = [(lambda t=t: _run_budgeted(t, budget, tctx))
                 for t in tasks]
    futs = [_FANOUT_POOL.submit(t) for t in tasks]
    results, first_err = [], None
    for f in futs:
        try:
            results.append(f.result())
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if first_err is None:
                first_err = e
            results.append(None)
    if first_err is not None:
        raise first_err
    return results


def _run_budgeted(task, budget, tctx=None):
    from ..fluid import ps_rpc as _ps_rpc
    from ..fluid import telemetry as _telemetry
    import contextlib
    tcm = (_telemetry.trace_scope(adopt=tctx) if tctx is not None
           else contextlib.nullcontext())
    with tcm, _ps_rpc.call_budget(budget):
        return task()


def _np_of(scope, name):
    v = scope.find_var(name)
    if v is None or not v.is_initialized():
        return None
    val = v.value()
    if isinstance(val, core.SelectedRows):
        return val
    return np.asarray(val.array)


# --------------------------------------------------------------------------
# trainer-side ops
# --------------------------------------------------------------------------
# send op: vars whose scope slot was never initialized (a conditional
# branch that never ran, an optimizer slot created late) are SKIPPED with
# a one-time warning instead of shipping None into send_var and crashing
# the pserver handler
_warned_uninit_sends = set()


def _push_dense_batch(ep, items, tid, legacy=False):
    """Ship one endpoint's dense grads: with FLAGS_dgc on, eligible
    grads go out as top-k (indices, values) ``dgc_send`` frames with
    the unsent mass staying in the trainer's error-feedback residual
    (docs/PS_DATA_PLANE.md "Compression"); everything else takes the
    PR 4 coalesced ``send_vars_batch`` path. An old server without
    ``dgc_send`` ("no method" — nothing applied) gets the FULL
    accumulated grad dense instead, residual cleared, so the fallback
    neither loses nor double-sends mass; the miss is memoized."""
    from ..fluid import communicator as _comm
    from ..fluid.ps_rpc import send_vars_batch
    cli = _client(ep)
    rest = []
    if _comm.dgc_enabled() and not legacy:
        comp = _comm.dgc_compressor()
        for name, val in items:
            val = np.asarray(val)
            enc = (comp.compress(name, val)
                   if "dgc_send" not in cli._missing_methods else None)
            if enc is None:
                rest.append((name, val))
                continue
            idx, vals = enc
            try:
                cli.call("dgc_send", name=name, values=vals,
                         indices=idx, shape=list(val.shape),
                         trainer_id=tid)
            except RuntimeError as e:
                if "no method dgc_send" not in str(e):
                    raise
                cli._missing_methods.add("dgc_send")
                full = comp.restore_dense(name, idx, vals)
                rest.append((name, full.reshape(val.shape)))
    else:
        rest = [(n, v) for n, v in items]
    if not rest:
        return
    if len(rest) > 1 and not legacy:
        send_vars_batch(cli, rest, trainer_id=tid)
    else:
        for name, val in rest:
            cli.send_var(name, val, trainer_id=tid)


@register_op("send", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "trainer_id": 0})
def _send(ins, attrs):
    import logging
    from ..fluid.communicator import Communicator
    ctx = attrs["_ctx"]
    names = ctx.op.input("X")
    epmap = attrs.get("epmap") or []
    tid = int(attrs.get("trainer_id", 0))
    comm = Communicator.global_instance()
    dense_by_ep: dict = {}
    for i, name in enumerate(names):
        ep = epmap[i if i < len(epmap) else -1]
        val = _np_of(ctx.scope, name)
        if val is None:
            if name not in _warned_uninit_sends:
                _warned_uninit_sends.add(name)
                logging.getLogger("paddle_tpu.ps").warning(
                    "send op: var '%s' is uninitialized in this scope — "
                    "skipping its RPC to %s (warned once)", name, ep)
            continue
        if isinstance(val, core.SelectedRows):
            _client(ep).send_var(name, np.asarray(val.get_tensor().array),
                                 trainer_id=tid, rows=val.rows(),
                                 height=val.height())
        elif comm is not None:
            # async mode with a running Communicator: enqueue for the
            # merge thread (reference AsyncCommunicator::Send)
            comm.push(name, val, ep, trainer_id=tid)
        else:
            dense_by_ep.setdefault(ep, []).append((name, val))
    # dense grads coalesce into ONE batched RPC per endpoint (the dedup
    # token covers the batch, old servers get the per-var fallback —
    # ps_rpc.send_vars_batch; the legacy lane keeps one RPC per var);
    # FLAGS_dgc routes eligible grads through top-k compression first
    for ep, items in dense_by_ep.items():
        _push_dense_batch(ep, items, tid, legacy=_legacy_dataplane())
    return {}


# --------------------------------------------------------------------------
# geo async WAN lane (docs/PS_DATA_PLANE.md "Compression"): when
# FLAGS_async_staleness > 0, geo_sgd_send submits each DENSE delta-merge
# round (push delta → pull merged param) to the communicator's geo
# RoundPipeline instead of blocking the local step on the WAN RTT. The
# pipeline worker computes each round's REMOTE increment ("shift") by
# telescoping against the previous round's pull — shift_j = F_j -
# (F_{j-1} + sent_j) — and queues it FIFO; the op installs every queued
# shift at the next step boundary onto BOTH the param and its @GEO_OLD
# baseline, so local progress and the un-pushed residual survive the
# merge. One state per process, like the round pipeline (one trainer
# per process); the step-1 anchor resets it for a fresh job.
_GEO_ASYNC = {"last_f": {}, "shifts": None, "push_step": 0}
_GEO_ASYNC_LOCK = threading.Lock()


def _geo_async_reset():
    from collections import deque
    with _GEO_ASYNC_LOCK:
        _GEO_ASYNC["last_f"] = {}
        _GEO_ASYNC["last_f_sparse"] = {}
        _GEO_ASYNC["shifts"] = deque()
        _GEO_ASYNC["push_step"] = 0


def _geo_install_shifts(scope):
    """Apply every completed round's queued remote increment, FIFO.
    Shifts translate the param AND its @GEO_OLD baseline by the same
    amount, so the pending local delta (cur - old) is untouched.
    Sparse-table entries are row-keyed — ("rows", row_ids, shift_rows)
    — and translate only the touched rows of both tensors."""
    q = _GEO_ASYNC["shifts"]
    if not q:
        return
    while True:
        try:
            shift_map = q.popleft()
        except IndexError:
            break
        for name, shift in shift_map.items():
            var = scope.find_var(name)
            if var is None or not var.is_initialized():
                continue
            if isinstance(shift, tuple):
                rows, sh = shift[1], shift[2]
                if not np.any(sh):
                    continue
                cur = np.asarray(var.value().array).copy()
                cur[rows] += sh
                var.set_value(core.LoDTensor(jnp.asarray(cur)))
                old_var = scope.var(name + "@GEO_OLD")
                if old_var.is_initialized():
                    old = np.asarray(
                        old_var.get_tensor().array).copy()
                    old[rows] += sh
                    old_var.set_value(core.LoDTensor(old))
                continue
            if not np.any(shift):
                continue
            cur = np.asarray(var.value().array)
            var.set_value(core.LoDTensor(jnp.asarray(cur + shift)))
            old_var = scope.var(name + "@GEO_OLD")
            if old_var.is_initialized():
                old = np.asarray(old_var.get_tensor().array)
                old_var.set_value(core.LoDTensor(old + shift))


def _geo_dense_round_async(ctx, scope, names, epmap, tid, staleness):
    """Submit one dense delta-merge round to the geo RoundPipeline.

    Error feedback happens HERE, synchronously: ``old`` advances by
    exactly what this round will push (under FLAGS_dgc, only the top-k
    selection — the residual stays in cur-old and ships next round).
    The background closure pushes the captured payloads, pulls each
    merged param, and queues shift = fresh - (last_f + sent): with no
    remote regions both terms are the same fp add, so the shift is
    exactly zero and a single-region async run tracks the inline one."""
    from ..fluid import communicator as _comm
    pushes = []
    dgc = _comm.dgc_enabled()
    min_el = int(core.globals_["FLAGS_dgc_min_elements"])
    push_step = _GEO_ASYNC["push_step"]
    _GEO_ASYNC["push_step"] = push_step + 1
    for i, name in enumerate(names):
        ep = epmap[i if i < len(epmap) else -1]
        var = scope.find_var(name)
        if var is None or not var.is_initialized():
            continue
        cur = np.asarray(var.value().array)
        old_var = scope.var(name + "@GEO_OLD")
        old = np.asarray(old_var.get_tensor().array)
        delta = np.ascontiguousarray(cur - old)
        if dgc and delta.dtype == np.float32 and delta.size >= min_el:
            sparsity = _comm.DGCCompressor._sparsity_at(push_step)
            idx, vals = _comm.topk_sparsify(delta.reshape(-1), sparsity)
            sent = np.zeros(delta.size, delta.dtype)
            sent[idx] = vals
            sent = sent.reshape(delta.shape)
            _comm.dgc_compressor().note_external(
                delta.size, idx.size, delta.nbytes,
                idx.nbytes + vals.nbytes)
            pushes.append((name, ep, idx, vals, sent))
        else:
            sent = delta
            pushes.append((name, ep, None, None, sent))
        # error feedback: the baseline advances by the SENT part only
        old_var.set_value(core.LoDTensor(old + sent))
    if not pushes:
        return

    def do_geo_round():
        cli_of = _client
        shift_map = {}
        for name, ep, idx, vals, sent in pushes:
            cli = cli_of(ep)
            if idx is not None \
                    and "geo_delta#flat" not in cli._missing_methods:
                try:
                    cli.call("geo_delta", name=name, value=vals,
                             rows=idx, flat=True, trainer_id=tid)
                except (RuntimeError, TypeError) as e:
                    if "unexpected keyword" not in str(e) \
                            and "no method" not in str(e):
                        raise
                    # pre-compression server: ship the dense sent mass
                    # (same applied values — idx/vals scattered)
                    cli._missing_methods.add("geo_delta#flat")
                    cli.call("geo_delta", name=name, value=sent,
                             trainer_id=tid)
            else:
                cli.call("geo_delta", name=name, value=sent,
                         trainer_id=tid)
            fresh = np.asarray(cli.get_var(name, trainer_id=tid))
            last_f = _GEO_ASYNC["last_f"].get(name)
            if last_f is None or last_f.shape != fresh.shape:
                shift = np.zeros_like(fresh)
            else:
                shift = fresh - (last_f + sent)
            _GEO_ASYNC["last_f"][name] = fresh
            shift_map[name] = shift
        _GEO_ASYNC["shifts"].append(shift_map)

    _comm.geo_round_pipeline().submit(do_geo_round, staleness,
                                      label="geo_round")


def _geo_sparse_round_async(ctx, scope, sparse_names, epmap, n_dense,
                            tid, staleness):
    """Submit one sparse row-delta round to the geo RoundPipeline (the
    PR 11 remainder: these used to sync inline at every push point,
    stalling the local step on the WAN RTT even at staleness > 0).

    Same contract as the dense lane, row-keyed: error feedback happens
    HERE synchronously (@GEO_OLD's touched rows advance by exactly the
    pushed delta), the background closure pushes the row deltas, pulls
    the merged rows, and queues a per-row telescoped shift —
    shift_j[r] = F_j[r] - (F_{j-1}[r] + sent_j[r]) — installed FIFO
    onto the param AND the baseline at the next step boundary. A row's
    first-ever pull uses its baseline value at push time as the
    F_{j-1} estimate (the baseline tracks anchor + sent + installed
    shifts = our best estimate of the server row), so a single-region
    run's shifts are exactly zero and it tracks the inline path."""
    pushes = []
    for j, name in enumerate(sparse_names):
        ep_idx = n_dense + j
        ep = epmap[ep_idx if ep_idx < len(epmap) else -1]
        var = scope.find_var(name)
        if var is None or not var.is_initialized():
            continue
        cur = np.asarray(var.value().array)
        old_var = scope.var(name + "@GEO_OLD")
        if not old_var.is_initialized():
            old_var.set_value(core.LoDTensor(cur.copy()))
            continue
        old = np.asarray(old_var.get_tensor().array)
        delta = cur - old
        touched = np.where(np.abs(delta).reshape(len(delta), -1)
                           .max(axis=1) > 0)[0]
        if not len(touched):
            continue
        payload = np.ascontiguousarray(delta[touched])
        prev_est = old[touched].copy()
        pushes.append((name, ep, touched, payload, prev_est))
        # error feedback: baseline rows advance by the SENT delta only
        old = old.copy()
        old[touched] = cur[touched]
        old_var.set_value(core.LoDTensor(old))
    if not pushes:
        return
    from ..fluid import communicator as _comm

    def do_geo_sparse_round():
        shift_map = {}
        for name, ep, touched, payload, prev_est in pushes:
            cli = _client(ep)
            cli.call("geo_delta", name=name, value=payload,
                     rows=touched, trainer_id=tid)
            fresh_rows = np.asarray(cli.prefetch_rows(name, touched))
            lf = _GEO_ASYNC["last_f_sparse"].setdefault(name, {})
            shift = np.zeros_like(fresh_rows)
            for i, r in enumerate(touched):
                r = int(r)
                prev = lf.get(r)
                if prev is None or prev.shape != fresh_rows[i].shape:
                    prev = prev_est[i]
                shift[i] = fresh_rows[i] - (prev + payload[i])
                lf[r] = fresh_rows[i].copy()
            shift_map[name] = ("rows", touched, shift)
        _GEO_ASYNC["shifts"].append(shift_map)

    _comm.geo_round_pipeline().submit(do_geo_sparse_round, staleness,
                                      label="geo_sparse_round")


@register_op("geo_sgd_send", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "push_nums": 100, "trainer_id": 0,
                            "trainers": 1})
def _geo_sgd_send(ins, attrs):
    """GEO-SGD delta sync (reference: GeoSgdCommunicator,
    communicator.h:383): every ``push_nums`` local steps push
    (param - snapshot) to the param's pserver, pull the merged global
    param back, and reset the snapshot. Between syncs training is fully
    local, so the step stays on-device.

    With FLAGS_async_staleness > 0 the dense sync rides the geo
    RoundPipeline (see _GEO_ASYNC above): the push/pull round drains in
    the background while local steps continue, bounded at k rounds in
    flight, and FLAGS_dgc additionally top-k-sparsifies each delta with
    the residual kept in the @GEO_OLD baseline (old advances only by
    what was SENT — exact error feedback). Sparse tables ride the same
    pipeline with row-keyed deltas and per-row telescoped shifts
    (_geo_sparse_round_async, r20). At staleness 0 the path below is
    byte-for-byte the pre-compression inline code — bit-identical."""
    ctx = attrs["_ctx"]
    scope = ctx.scope
    names = ctx.op.input("Params")
    epmap = attrs.get("epmap") or []
    tid = int(attrs.get("trainer_id", 0))
    push_nums = max(1, int(attrs.get("push_nums", 100)))
    staleness = int(core.globals_["FLAGS_async_staleness"])

    if staleness > 0:
        # step boundary: land every completed background round first
        _geo_install_shifts(scope)

    cvar = scope.var("@GEO_STEP@")
    step = 0
    if cvar.is_initialized():
        step = int(np.asarray(cvar.get_tensor().array).reshape(-1)[0])
    step += 1
    cvar.set_value(core.LoDTensor(np.asarray([step], np.int64)))

    if step == 1:
        # anchor: snapshot the server's params (dense AND sparse tables)
        # as the delta baseline (reference GeoSgdCommunicator pulls at
        # init_worker; trainers and server share the startup init, so
        # this is the common start)
        if staleness > 0:
            _geo_async_reset()
        all_names = list(names) + list(ctx.op.input("SparseParams") or [])
        for i, name in enumerate(all_names):
            ep = epmap[i if i < len(epmap) else -1]
            fresh = np.asarray(_client(ep).get_var(name, trainer_id=tid))
            scope.var(name + "@GEO_OLD").set_value(
                core.LoDTensor(fresh.copy()))
            if staleness > 0 and name in names:
                _GEO_ASYNC["last_f"][name] = fresh.copy()
        return {}
    if step % push_nums != 0:
        return {}

    if staleness > 0:
        _geo_dense_round_async(ctx, scope, names, epmap, tid, staleness)
        # sparse tables ride the SAME pipeline now (r20; formerly they
        # synced inline even at staleness > 0 — the PR 11 remainder):
        # row-keyed deltas push/pull in the background and install as
        # per-row shifts at the next step boundary
        _geo_sparse_round_async(
            ctx, scope, list(ctx.op.input("SparseParams") or []),
            epmap, len(names), tid, staleness)
        return {}
    for i, name in enumerate(names):
        ep = epmap[i if i < len(epmap) else -1]
        cur = np.asarray(scope.find_var(name).value().array)
        old_var = scope.var(name + "@GEO_OLD")
        old = np.asarray(old_var.get_tensor().array)
        _client(ep).call("geo_delta", name=name,
                         value=np.ascontiguousarray(cur - old),
                         trainer_id=tid)
        fresh = np.asarray(_client(ep).get_var(name, trainer_id=tid))
        scope.find_var(name).set_value(
            core.LoDTensor(jnp.asarray(fresh)))
        old_var.set_value(core.LoDTensor(fresh.copy()))

    # sparse tables: push only the TOUCHED row deltas, pull those rows'
    # merged values back (reference GeoSgdCommunicator
    # SendUpdateSparseVars / RecvUpdateSparseVars)
    n_dense = len(names)
    for j, name in enumerate(ctx.op.input("SparseParams") or []):
        ep_idx = n_dense + j
        ep = epmap[ep_idx if ep_idx < len(epmap) else -1]
        cur = np.asarray(scope.find_var(name).value().array)
        old_var = scope.var(name + "@GEO_OLD")
        if not old_var.is_initialized():
            old_var.set_value(core.LoDTensor(cur.copy()))
            continue
        old = np.asarray(old_var.get_tensor().array)
        delta = cur - old
        touched = np.where(np.abs(delta).reshape(len(delta), -1)
                           .max(axis=1) > 0)[0]
        if len(touched):
            _client(ep).call("geo_delta", name=name,
                             value=np.ascontiguousarray(delta[touched]),
                             rows=touched, trainer_id=tid)
            fresh_rows = np.asarray(
                _client(ep).prefetch_rows(name, touched))
            cur = cur.copy()
            cur[touched] = fresh_rows
            scope.find_var(name).set_value(
                core.LoDTensor(jnp.asarray(cur)))
        old_var.set_value(core.LoDTensor(cur.copy()))
    return {}


@register_op("recv", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "trainer_id": 0})
def _recv(ins, attrs):
    ctx = attrs["_ctx"]
    names = ctx.op.output("Out")
    epmap = attrs.get("epmap") or []
    tid = int(attrs.get("trainer_id", 0))
    from ..fluid.communicator import Communicator
    comm = Communicator.global_instance()
    if comm is not None:
        # fully-async mode (reference AsyncCommunicator::RecvThread):
        # never block the step on a pull — register the set once, let
        # the communicator's background thread refresh a double buffer
        # at its recv interval, and install only the newest completed
        # buffer here at the step boundary. The FIRST call primes the
        # buffer synchronously so params exist before step 1 computes.
        pairs = [(n, epmap[i if i < len(epmap) else -1])
                 for i, n in enumerate(names)]
        comm.register_recv(pairs, trainer_id=tid)
        buf = comm.take_fresh_recv()
        if buf is None and not getattr(comm, "_recv_primed", False):
            buf = comm.recv()
            comm._recv_primed = True
        if buf:
            for name, arr in buf.items():
                if name in names:
                    ctx.scope.var(name).set_value(
                        core.LoDTensor(jnp.asarray(arr)))
        # async mode has no fetch_barrier, so the save/shrink cron
        # (FLAGS_ps_shrink_every_steps) ticks here — the recv op is
        # the one per-step boundary the async trainer still crosses
        _shrink_cron_tick(list(dict.fromkeys(epmap)), tid)
        return {}
    by_ep: dict = {}
    for i, name in enumerate(names):
        ep = epmap[i if i < len(epmap) else -1]
        by_ep.setdefault(ep, []).append(name)
    for ep, ep_names in by_ep.items():
        cli = _client(ep)
        if len(ep_names) == 1 or _legacy_dataplane() \
                or "get_vars_batch" in cli._missing_methods:
            got = [cli.get_var(n, trainer_id=tid) for n in ep_names]
        else:
            # one batched fetch per endpoint (get_vars_batch; falls back
            # per-var when an old server doesn't know the method — any
            # other failure propagates; the miss is memoized so only
            # the first call pays the probe)
            try:
                got = cli.call("get_vars_batch", names=ep_names,
                               trainer_id=tid)
            except RuntimeError as e:
                if "no method get_vars_batch" not in str(e):
                    raise
                cli._missing_methods.add("get_vars_batch")
                got = [cli.get_var(n, trainer_id=tid) for n in ep_names]
        for name, arr in zip(ep_names, got):
            ctx.scope.var(name).set_value(
                core.LoDTensor(jnp.asarray(arr)))
    return {}


_SHRINK_CRON_STEPS: dict = {}   # (endpoints) -> trainer-0 round count
_shrink_cron_warned: set = set()


def reset_shrink_cron() -> None:
    """Forget cron round counts (tests / a new transpiled job)."""
    _SHRINK_CRON_STEPS.clear()


def _shrink_cron_tick(endpoints, tid) -> None:
    """Trainer-driven shrink schedule (FLAGS_ps_shrink_every_steps — the
    PSLib save/shrink cron analogue, docs/PS_DATA_PLANE.md): trainer 0
    counts its completed sync rounds per endpoint set and, every N-th,
    fires ONE `table_shrink` admin RPC at each pserver (decay/threshold
    from FLAGS_ps_shrink_decay/_threshold). The RPC lands between
    rounds — the server runs it under the grad lock — so training never
    observes a half-shrunk table. Best-effort like the reference cron:
    a failed shrink warns (once per endpoint) and training continues;
    evidence is the server-side slab "shrink_runs"/"shrunk_rows"
    counters."""
    every = int(core.globals_["FLAGS_ps_shrink_every_steps"] or 0)
    if every <= 0 or tid != 0 or not endpoints:
        return
    key = tuple(endpoints)
    n = _SHRINK_CRON_STEPS.get(key, 0) + 1
    _SHRINK_CRON_STEPS[key] = n
    if n % every:
        return
    import logging
    decay = float(core.globals_["FLAGS_ps_shrink_decay"])
    threshold = float(core.globals_["FLAGS_ps_shrink_threshold"])
    for ep in dict.fromkeys(endpoints):
        try:
            _client(ep).call("table_shrink", decay=decay,
                             threshold=threshold)
        except Exception as e:  # noqa: BLE001 — cron is best-effort
            if ep not in _shrink_cron_warned:
                _shrink_cron_warned.add(ep)
                logging.getLogger("paddle_tpu.ps").warning(
                    "shrink cron: table_shrink on %s failed (%r) — "
                    "continuing (warned once)", ep, e)


def _barrier_op(kind):
    def _kernel(ins, attrs):
        ctx = attrs["_ctx"]
        tid = int(attrs.get("trainer_id", 0))
        eps = list(dict.fromkeys(attrs.get("endpoints") or []))
        for ep in eps:
            _client(ep).barrier(kind, trainer_id=tid)
        if kind == "fetch":
            # the fetch barrier closes trainer 0's sync round — the
            # between-rounds window the shrink cron fires in
            _shrink_cron_tick(eps, tid)
        return {}
    return _kernel


register_op("send_barrier", stateful=True, no_grad=True,
            attr_defaults={"endpoints": [], "trainer_id": 0})(
    _barrier_op("send"))
register_op("fetch_barrier", stateful=True, no_grad=True,
            attr_defaults={"endpoints": [], "trainer_id": 0})(
    _barrier_op("fetch"))


@register_op("ps_round", stateful=True, no_grad=True,
             attr_defaults={"grad_epmap": [], "param_epmap": [],
                            "endpoints": [], "trainer_id": 0})
def _ps_round(ins, attrs):
    """The whole sync comm tail — push grads → send barrier → pull
    params → fetch barrier — as ONE op, emitted by the transpiler's
    async-mode rewrite (docs/PS_DATA_PLANE.md "Async overlap").

    ``FLAGS_async_staleness = 0``: the round runs INLINE, replaying the
    exact RPC sequence of the pre-overlap send/send_barrier/recv/
    fetch_barrier tail — the trajectory is bit-identical to sync mode
    (the golden-oracle contract; tested on the 3-trainer wide_deep
    agreement run).

    ``FLAGS_async_staleness = k > 0``: the round is SUBMITTED to the
    communicator's RoundPipeline and the op returns immediately, so the
    executor launches window i+1 while round i's wire work drains in
    the background; at most k submitted-but-unacked rounds may be in
    flight (a full pipe blocks here — backpressure, not divergence).
    Each round's pulled params land in the pipeline's latest-pull
    buffer; the newest completed buffer is installed into the scope at
    this (step-boundary) call — the double-buffered dense pull. A
    background round failure re-raises TYPED at the next submit."""
    import logging
    ctx = attrs["_ctx"]
    scope = ctx.scope
    grad_names = list(ctx.op.input("X") or [])
    param_names = list(ctx.op.output("Out") or [])
    gmap = [str(e) for e in (attrs.get("grad_epmap") or [])]
    pmap = [str(e) for e in (attrs.get("param_epmap") or [])]
    beps = list(dict.fromkeys(
        str(e) for e in (attrs.get("endpoints") or [])))
    tid = int(attrs.get("trainer_id", 0))
    legacy = _legacy_dataplane()

    # snapshot grads NOW (jax arrays are immutable, so holding the refs
    # is safe while the next step replaces the scope slots); host
    # conversion happens inside the round so the D2H wait overlaps too
    send_groups: dict = {}
    for i, name in enumerate(grad_names):
        ep = gmap[i if i < len(gmap) else -1]
        val = _np_of(scope, name)
        if val is None:
            if name not in _warned_uninit_sends:
                _warned_uninit_sends.add(name)
                logging.getLogger("paddle_tpu.ps").warning(
                    "ps_round: var '%s' is uninitialized in this scope "
                    "— skipping its push to %s (warned once)", name, ep)
            continue
        send_groups.setdefault(ep, []).append((name, val))
    recv_groups: dict = {}
    for i, name in enumerate(param_names):
        ep = pmap[i if i < len(pmap) else -1]
        recv_groups.setdefault(ep, []).append(name)

    def do_round():
        for ep, items in send_groups.items():
            dense = []
            for n, v in items:
                if isinstance(v, core.SelectedRows):
                    _client(ep).send_var(
                        n, np.asarray(v.get_tensor().array),
                        trainer_id=tid, rows=v.rows(),
                        height=v.height())
                else:
                    dense.append((n, np.asarray(v)))
            if dense:
                _push_dense_batch(ep, dense, tid, legacy=legacy)
        for ep in beps:
            _client(ep).barrier("send", trainer_id=tid)
        pulled = {}
        for ep, names in recv_groups.items():
            cli = _client(ep)
            if len(names) == 1 or legacy \
                    or "get_vars_batch" in cli._missing_methods:
                got = [cli.get_var(n, trainer_id=tid) for n in names]
            else:
                try:
                    got = cli.call("get_vars_batch", names=names,
                                   trainer_id=tid)
                except RuntimeError as e:
                    if "no method get_vars_batch" not in str(e):
                        raise
                    cli._missing_methods.add("get_vars_batch")
                    got = [cli.get_var(n, trainer_id=tid)
                           for n in names]
            pulled.update(zip(names, got))
        for ep in beps:
            _client(ep).barrier("fetch", trainer_id=tid)
        return pulled

    def install(pulled):
        for name, arr in pulled.items():
            scope.var(name).set_value(core.LoDTensor(jnp.asarray(arr)))

    staleness = int(core.globals_["FLAGS_async_staleness"])
    if staleness <= 0:
        install(do_round())
        # round complete — same cron point as the sync fetch_barrier
        _shrink_cron_tick(beps, tid)
        return {}
    from ..fluid import communicator as _comm
    pipe = _comm.round_pipeline()
    pipe.submit(do_round, staleness, label="ps_round")
    fresh = pipe.take_fresh_pulls()
    if fresh:
        install(fresh)
    # async rounds: count at submit — the shrink RPC itself serializes
    # on the server's grad lock, so landing mid-drain is still safe
    _shrink_cron_tick(beps, tid)
    return {}


@register_op("checkpoint_notify", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "dir": ""})
def _checkpoint_notify(ins, attrs):
    for ep in dict.fromkeys(attrs.get("epmap") or []):
        _client(ep).call("checkpoint", dir=attrs.get("dir", ""))
    return {}


def _table_dim(ctx, w_name):
    """Embedding dim of the (possibly remote-only) table, from the block
    var desc; last resort 1 when the program never declared the var."""
    try:
        v = ctx.op.block.var(w_name)
        shape = list(getattr(v, "shape", None) or [])
        if shape and int(shape[-1]) > 0:
            return int(shape[-1])
    except Exception:
        pass
    return 1


def _table_dtype(ctx, w_name):
    """The table's declared dtype from the block var desc — the empty-ids
    fast path must carry it (an fp16/bf16 table must not silently upcast
    its zero-row result to float32)."""
    try:
        v = ctx.op.block.var(w_name)
        return jnp.dtype(core.dtype_to_np(v.dtype))
    except Exception:
        return jnp.float32


def _pull_rows_sharded(eps, w_name, uniq, prefetch=False):
    """One deduped row pull, row-sharded across ``eps`` by
    ``id %% n_pservers`` with every per-pserver section RPC issued
    concurrently (reference parameter_prefetch overlap). ``uniq`` must
    hold distinct ids; returns [len(uniq), dim] in input order.
    ``prefetch=True`` tags the RPCs as async-overlap early fetches for
    the server-side stats counter."""
    uniq = np.asarray(uniq)
    if len(eps) == 1:
        return np.asarray(_client(eps[0]).prefetch_rows(
            w_name, uniq, prefetch=prefetch))
    shard = uniq % len(eps)
    sels = [np.where(shard == k)[0] for k in range(len(eps))]
    live = [(ep, sel) for ep, sel in zip(eps, sels) if len(sel)]

    def _pull(ep, sel):
        return np.asarray(_client(ep).prefetch_rows(
            w_name, uniq[sel], prefetch=prefetch))

    parts = _fanout([(lambda ep=ep, sel=sel: _pull(ep, sel))
                     for ep, sel in live])
    rows_u = np.empty((len(uniq), parts[0].shape[-1]), parts[0].dtype)
    for (_ep, sel), part in zip(live, parts):
        rows_u[sel] = part
    return rows_u


@register_op("distributed_lookup_table", stateful=True,
             attr_defaults={"epmap": [], "table_names": [], "padding_idx": -1,
                            "is_distributed": True, "trainer_id": 0})
def _distributed_lookup_table(ins, attrs):
    """Pulls embedding rows from the pserver-resident table, row-sharded
    across ALL endpoints in epmap by ``id %% n_pservers`` (reference:
    distributed_lookup_table_op.cc over parameter_prefetch.cc, which
    splits ids per-section the same way).

    Serving mode (docs/SERVING.md): when a row cache is installed
    (``ps_rpc.install_row_cache`` — the ServingEngine's EmbeddingCache),
    the deduped id set consults it first and only the misses fan out;
    a fully-hit lookup issues ZERO RPCs. Training paths never install a
    cache, so this is dead code there."""
    from ..fluid import ps_rpc as _ps_rpc
    ctx = attrs["_ctx"]
    id_names = ctx.op.input("Ids")
    w_name = (attrs.get("table_names") or ctx.op.input("W"))[0]
    eps = [e for e in (attrs.get("epmap") or []) if e] or [None]
    outs = []
    for nm in id_names:
        ids = np.asarray(ctx.scope.find_var(nm).value().array).reshape(-1)
        if len(ids) == 0:
            # legitimately empty id batch: no RPC; the result must still
            # carry the table's embedding dim AND dtype or downstream
            # ops reject the shape / silently upcast (ADVICE r2)
            outs.append(jnp.zeros((0, _table_dim(ctx, w_name)),
                                  _table_dtype(ctx, w_name)))
            continue
        # duplicate-id dedup: a CTR batch repeats hot ids heavily — pull
        # each distinct row ONCE and scatter back via the inverse map
        # (reference parameter_prefetch merges ids per section the same
        # way); cuts the payload by the batch's duplication factor
        if _legacy_dataplane():
            uniq, inv = ids, np.arange(len(ids))
        else:
            uniq, inv = np.unique(ids, return_inverse=True)
        cache = _ps_rpc.current_row_cache()
        if cache is not None:
            rows_u = cache.lookup(
                w_name, uniq,
                lambda miss: _pull_rows_sharded(eps, w_name, miss))
        else:
            rows_u = _pull_rows_sharded(eps, w_name, uniq)
        outs.append(jnp.asarray(rows_u[inv]))
    return {"Outputs": outs}


def _program_has_ps_round(program) -> bool:
    """Whether the trainer program was async-rewritten (ps_round tail);
    cached per program version."""
    cached = program.__dict__.get("_has_ps_round")
    if cached is None or cached[0] != program._version:
        has = any(op.type == "ps_round"
                  for op in program.global_block().ops)
        program.__dict__["_has_ps_round"] = cached = \
            (program._version, has)
    return cached[1]


@register_grad_maker("distributed_lookup_table")
def _dist_lookup_grad_maker(op, grad_map):
    return [{
        "type": "distributed_lookup_table_grad",
        "inputs": {"Ids": op.input("Ids"), "W": op.input("W"),
                   "Outputs@GRAD": [grad_map[n]
                                    for n in op.output("Outputs")]},
        "outputs": {},
        "attrs": {k: v for k, v in op.attrs.items()
                  if not k.startswith("_")},
    }]


@register_op("distributed_lookup_table_grad", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "table_names": [], "trainer_id": 0})
def _distributed_lookup_table_grad(ins, attrs):
    """Pushes SelectedRows gradients back, row-sharded across epmap the
    same way the forward pull routes ids."""
    from ..fluid import ps_rpc as _ps_rpc
    ctx = attrs["_ctx"]
    id_names = ctx.op.input("Ids")
    w_name = (attrs.get("table_names") or ctx.op.input("W"))[0]
    eps = [e for e in (attrs.get("epmap") or []) if e] or [None]
    tid = int(attrs.get("trainer_id", 0))
    g_names = ctx.op.input("Outputs@GRAD")
    # async pushes require the ps_round tail, not just the flag: in a
    # program still carrying the plain send_barrier tail (flag flipped
    # after transpile) a backgrounded push could land AFTER the
    # main-thread barrier released its round — a phantom next-round
    # arrival — and with no ps_round submit()/drain() on this program
    # a failed push's deferred error would never re-raise
    overlap = int(core.globals_["FLAGS_async_staleness"]) > 0 \
        and _program_has_ps_round(ctx.op.block.program)
    for nm, gn in zip(id_names, g_names):
        ids = np.asarray(ctx.scope.find_var(nm).value().array).reshape(-1)
        if len(ids) == 0:
            continue  # nothing to push, no RPC
        g = np.asarray(ctx.scope.find_var(gn).value().array)
        g = g.reshape(len(ids), -1)
        # pre-merge duplicate rows client-side: the server applies ONE
        # row per distinct id (sum of the duplicates), the payload
        # shrinks by the duplication factor. NOT gated by the legacy
        # lane: merging changes fp accumulation ORDER, and the paired
        # bench rows assert bit-exact loss parity across lanes — every
        # legacy-gated difference must be numerics-exact
        # (wire/fan-out/pool/coalescing/lookup-dedup all are)
        uniq, inv = np.unique(ids, return_inverse=True)
        if len(uniq) < len(ids):
            merged = np.zeros((len(uniq), g.shape[1]), g.dtype)
            np.add.at(merged, inv, g)
            ids, g = uniq, merged
        # async overlap: the prefetch buffer must drop its copies of
        # the rows this push dirties BEFORE the push even enqueues —
        # inline on the main thread, so no later lookup can race a
        # known-dirty row (docs/PS_DATA_PLANE.md "Async overlap")
        cache = _ps_rpc.current_row_cache()
        if cache is not None and hasattr(cache, "invalidate_rows"):
            try:
                # same-process train+serve: the push instant IS the
                # event time for the freshness histogram
                cache.invalidate_rows(w_name, ids, t_event=time.time())
            except TypeError:
                cache.invalidate_rows(w_name, ids)
        # cross-process half (docs/SERVING.md "Fleet"): fan the same
        # pushed-row invalidation to every REMOTE serving cache via the
        # fleet publisher — enqueue-only here (subscribers long-poll),
        # so the push path never blocks on a slow serving box
        pub = _ps_rpc.current_invalidation_publisher()
        if pub is not None:
            pub.publish(w_name, ids)

        def _push_all(ids=ids, g=g):
            if len(eps) == 1:
                _client(eps[0]).send_var(w_name + "@GRAD", g,
                                         trainer_id=tid, rows=ids,
                                         height=0)
                return
            # concurrent per-pserver sends, first error wins (fan-out
            # like the forward pull)
            shard = ids % len(eps)
            sels = [np.where(shard == k)[0] for k in range(len(eps))]
            live = [(ep, sel) for ep, sel in zip(eps, sels) if len(sel)]

            def _push(ep, sel):
                _client(ep).send_var(w_name + "@GRAD", g[sel],
                                     trainer_id=tid, rows=ids[sel],
                                     height=0)

            _fanout([(lambda ep=ep, sel=sel: _push(ep, sel))
                     for ep, sel in live])

        if overlap:
            # ride the round pipeline's FIFO: the push lands after the
            # previous round's release and before this round's sends —
            # exactly where the inline path would have put it — while
            # the main thread keeps computing. Errors surface typed at
            # the next ps_round submit.
            from ..fluid import communicator as _comm
            _comm.round_pipeline().submit_task(
                _push_all, label=f"sparse_push:{w_name}")
        else:
            _push_all()
    return {}


_SPILL_PATH_SEQ = itertools.count()


def _safe_name(name: str) -> str:
    """Filesystem-safe var/section name — ONE collision-sensitive rule
    shared by every spill/staging path builder (always paired with a
    uniquifying sequence, since the mapping is lossy)."""
    return "".join(c if c.isalnum() else "_" for c in name)


@register_op("lazy_table_init", stateful=True, no_grad=True,
             attr_defaults={"height": 0, "dim": 0, "seed": 0,
                            "scale": 0.0, "max_rows": 0})
def _lazy_table_init(ins, attrs):
    """Initializes a pserver var as a LazyEmbeddingTable: rows materialize
    on first touch, so the logical [height, dim] never allocates
    (reference: fleet_wrapper.h DownpourSparseTable pull-creates).

    Capacity tier (docs/PS_DATA_PLANE.md "Capacity tier"): the spill/
    gating FLAGS are read HERE, at pserver startup — env-settable, so
    subprocess pservers of one bench/test lane configure the tier
    without new program attrs (the async-overlap flag precedent). With
    the flags at their defaults the table is the exact pre-tier slab."""
    ctx = attrs["_ctx"]
    name = ctx.op.output("Out")[0]
    scale = float(attrs.get("scale") or 0.0)
    tier_kw = {}
    spill_dir = str(core.globals_["FLAGS_ps_slab_spill_dir"] or "")
    if spill_dir:
        hot = int(core.globals_["FLAGS_ps_slab_hot_rows"])
        if hot <= 0:
            raise ValueError(
                "FLAGS_ps_slab_spill_dir is set but "
                "FLAGS_ps_slab_hot_rows is 0 — the spill tier needs a "
                "hot-set bound (silently ignoring the spill dir would "
                "run the table unbounded in RAM)")
        # per-process sequence: two table names that sanitize to the
        # same string (or a handoff-rebuilt replacement) must never
        # open — and truncate — each other's live log
        tier_kw = dict(
            spill_path=os.path.join(
                spill_dir,
                f"{_safe_name(name)}-{os.getpid()}"
                f"-i{next(_SPILL_PATH_SEQ)}.slab"),
            hot_rows=hot,
            at_rest_quant=str(
                core.globals_["FLAGS_ps_at_rest_quant"] or ""),
            spill_seg_rows=int(core.globals_["FLAGS_ps_slab_seg_rows"]),
            track_scores=(True if core.globals_[
                "FLAGS_ps_slab_track_scores"] else None))
    thr = int(core.globals_["FLAGS_ps_entry_threshold"])
    if thr > 1:
        tier_kw["entry_threshold"] = thr
    # score tracking without the spill tier: FLAGS_ps_slab_track_scores
    # alone makes the table shrinkable (the cron's table_shrink needs
    # per-row touch scores; an online-learning pserver wants idle rows
    # decaying out whether or not it also spills). max_rows-bounded
    # tables keep their LRU semantics — the tier would reject the combo.
    if core.globals_["FLAGS_ps_slab_track_scores"] \
            and "track_scores" not in tier_kw \
            and not int(attrs.get("max_rows") or 0):
        tier_kw["track_scores"] = True
    tbl = core.LazyEmbeddingTable(
        height=int(attrs["height"]), dim=int(attrs["dim"]),
        seed=int(attrs.get("seed", 0)),
        scale=scale if scale > 0 else None,
        max_rows=int(attrs.get("max_rows") or 0) or None, **tier_kw)
    # a startup re-run over a scope already holding a tiered table
    # must release the old spill log (every replacement path does)
    from ..fluid import io as fio
    fio._drop_replaced_table(ctx.scope.find_var(name))
    ctx.scope.var(name).set_value(tbl)
    return {}


# --------------------------------------------------------------------------
# split/merge helpers for sharded sparse ids (reference: split_ids_op.cc,
# merge_ids_op.cc — used when a table spans several pservers)
# --------------------------------------------------------------------------
@register_op("split_ids", stateful=True, no_grad=True)
def _split_ids(ins, attrs):
    ctx = attrs["_ctx"]
    ids = np.asarray(
        ctx.scope.find_var(ctx.op.input("Ids")[0]).value().array).reshape(-1)
    n = len(ctx.op.output("Out"))
    return {"Out": [jnp.asarray(ids[ids % n == k]) for k in range(n)]}


@register_op("merge_ids", stateful=True, no_grad=True)
def _merge_ids(ins, attrs):
    ctx = attrs["_ctx"]
    ids = np.asarray(
        ctx.scope.find_var(ctx.op.input("Ids")[0]).value().array).reshape(-1)
    n = len(ctx.op.input("X"))
    parts = [np.asarray(ctx.scope.find_var(nm).value().array)
             for nm in ctx.op.input("X")]
    dim = parts[0].shape[-1]
    merged = np.zeros((len(ids), dim), parts[0].dtype)
    counters = [0] * n
    for i, idv in enumerate(ids):
        k = int(idv) % n
        merged[i] = parts[k][counters[k]]
        counters[k] += 1
    return {"Out": [jnp.asarray(merged)]}


# --------------------------------------------------------------------------
# listen_and_serv (reference: listen_and_serv_op.cc)
# --------------------------------------------------------------------------
@register_op("listen_and_serv", stateful=True, no_grad=True,
             attr_defaults={"endpoint": "", "sync_mode": True, "Fanin": 1,
                            "grad_to_block_id": [], "sparse_lr": 0.01,
                            "distributed_mode": 0,
                            # elastic membership (docs/FAULT_TOLERANCE.md
                            # "Elastic membership"): the full slot list,
                            # whether this process starts as a warm
                            # standby (drain destination / replica), the
                            # slot it replicates, and the PHYSICAL
                            # endpoint to bind when serving a slot
                            # program at another address
                            "pserver_endpoints": [], "standby": False,
                            "replica_of": "", "bind_endpoint": ""})
def _listen_and_serv(ins, attrs):
    """Server loop: blocks until a stop RPC (parity with RunImpl's
    server_thread join, listen_and_serv_op.cc:382)."""
    from ..fluid import io as fio
    from ..fluid import ps_membership
    from ..fluid.ps_rpc import (BarrierManager, HeartBeatMonitor,
                                VarClient, VarServer,
                                note_request_token_applied)
    ctx = attrs["_ctx"]
    scope, executor = ctx.scope, ctx.executor
    endpoint = attrs["endpoint"]
    sync = bool(attrs.get("sync_mode", True))
    fanin = int(attrs.get("Fanin", 1))
    optimize_blocks = attrs.get("optimize_blocks") or []
    grad_to_block = dict(
        kv.split(":") for kv in attrs.get("grad_to_block_id") or [])
    sparse_lr = float(attrs.get("sparse_lr", 0.01))

    # ---- elastic membership plane -------------------------------------
    # ``endpoint`` is the SLOT name (what the transpiler baked into every
    # program); ``bind`` is where THIS process actually listens — they
    # differ for standbys/replicas serving a slot program elsewhere.
    bind = str(attrs.get("bind_endpoint") or "") or endpoint
    slot_eps = [str(e) for e in (attrs.get("pserver_endpoints") or [])] \
        or [endpoint]
    replica_of = str(attrs.get("replica_of") or "")
    standby = bool(attrs.get("standby", False)) or bool(replica_of)
    membership = ps_membership.MembershipPlane(
        slot=endpoint, bind=bind,
        view=ps_membership.ClusterView.initial(slot_eps),
        state=(ps_membership.STANDBY if standby
               else ps_membership.ACTIVE),
        replica_of=replica_of)

    # ONE lock guards grad state for send/geo handlers AND backs the
    # BarrierManager's condition — the release action (aggregate +
    # optimize) runs holding it, so it can't race a straggler send.
    # pending: dense grads per name; pending_sparse: row grads as
    # (trainer_id, seq, name, value, rows) — in SYNC mode sparse applies
    # are DEFERRED to the barrier release (reference RunSyncLoop applies
    # everything after the send barrier), so every trainer's pulls
    # within a round see the same pre-round table, and the release
    # applies entries in a deterministic (trainer, seq) order.
    lock = threading.RLock()
    state = {"pending": {}, "pending_sparse": [], "sparse_seq": 0}

    # numeric fault plane, pserver side (FLAGS_ps_reject_nonfinite —
    # docs/FAULT_TOLERANCE.md "Numeric faults"): trip counters surface
    # through the built-in "stats" RPC under the "health" key. They get
    # their OWN innermost lock (like VarServer's _stats_lock) so a
    # monitoring stats RPC never blocks behind an in-flight sync
    # optimize round holding the grad lock.
    health = {"dropped_sparse_rows": 0, "dropped_dense_updates": 0,
              "rejected_calls": 0, "per_var": {}}
    health_lock = threading.Lock()
    # async-overlap observability: row pulls tagged prefetch=True (the
    # trainer-side prefetch thread's early fetches) — shares the
    # innermost counter lock with the health counters
    prefetch_stats = {"calls": 0, "rows": 0}

    def _bump_health(key, name, n):
        with health_lock:
            health[key] += n
            health["per_var"][name] = health["per_var"].get(name, 0) + n

    def _guard_nonfinite(name, value, rows, trainer_id):
        """Apply FLAGS_ps_reject_nonfinite to one incoming update.
        Returns (value, rows, apply?) — sparse updates drop only their
        non-finite rows, a non-finite dense update drops wholesale;
        "reject" raises NumericFaultError back to the SENDING trainer
        (typed across the wire), leaving server state untouched. The
        checks run on host numpy — the grads already live there."""
        mode = str(core.globals_["FLAGS_ps_reject_nonfinite"] or "")
        if not mode:
            return value, rows, True
        value = np.asarray(value)
        if not np.issubdtype(value.dtype, np.floating):
            return value, rows, True
        if rows is not None and len(rows) == 0:
            # benign no-op update (public send_var allows it): nothing
            # to check, and reshape(0, -1) cannot infer a dimension
            return value, rows, False
        if rows is not None:
            n = len(rows)
            if value.shape[0] != n:
                # flat payload: row-major it so per-row masking works
                value = value.reshape(n, -1)
            # check on a 2-D VIEW; the clean pass-through and the
            # filtered value keep the sender's original shape (a 1-D
            # payload must not come back (n, 1) just because the guard
            # flag is on)
            per_row = np.isfinite(value.reshape(n, -1)).all(axis=1)
            if per_row.all():
                return value, rows, True
            n_bad = int((~per_row).sum())
            if mode == "reject":
                _bump_health("rejected_calls", name, 1)
                raise core.NumericFaultError(
                    f"pserver rejected sparse grad '{name}' from trainer "
                    f"{trainer_id}: {n_bad}/{len(rows)} non-finite rows "
                    f"(FLAGS_ps_reject_nonfinite=reject)")
            _bump_health("dropped_sparse_rows", name, n_bad)
            return (value[per_row],
                    np.asarray(rows).reshape(-1)[per_row], True)
        if np.isfinite(value).all():
            return value, rows, True
        if mode == "reject":
            _bump_health("rejected_calls", name, 1)
            raise core.NumericFaultError(
                f"pserver rejected dense update '{name}' from trainer "
                f"{trainer_id}: non-finite values "
                f"(FLAGS_ps_reject_nonfinite=reject)")
        _bump_health("dropped_dense_updates", name, 1)
        return value, rows, False

    # failure-detection cadence is deploy-tunable (tests shrink it to
    # seconds; reference FLAGS_worker_update_interval_secs plays this role)
    hb_timeout = float(os.environ.get("PADDLE_PS_HEARTBEAT_TIMEOUT", 60.0))
    monitor = HeartBeatMonitor(
        fanin, timeout=hb_timeout,
        check_interval=min(3.0, max(0.2, hb_timeout / 4)))
    barriers = BarrierManager(fanin, monitor=monitor, lock=lock)

    def _apply_sparse(name, value, rows):
        # row-wise SGD on the host-resident table (reference async sparse
        # update path; communicator.h AsyncCommunicator). In sync mode
        # each trainer's grad is the mean over ITS shard of the global
        # batch, so 1/fanin makes the applied sum the full-batch mean —
        # the reference transpiler's scale(1/trainers) on the server.
        scale = 1.0 / fanin if sync else 1.0
        pname = name[:-5] if name.endswith("@GRAD") else name
        var = scope.find_var(pname)
        val = var.value()
        if isinstance(val, core.LazyEmbeddingTable):
            val.apply_grad(rows, np.asarray(value) * scale, sparse_lr)
            return
        tbl = np.array(val.array)  # jax-array views are read-only
        np.subtract.at(tbl, np.asarray(rows, np.int64),
                       sparse_lr * scale * np.asarray(value))
        var.set_value(core.LoDTensor(jnp.asarray(tbl)))

    def _run_block_for(grad_name):
        blk_id = grad_to_block.get(grad_name)
        # the pserver optimize block runs OUTSIDE any Executor.run step
        # epilogue, so the per-op localizer is its ONLY numeric guard —
        # force it whenever the check flag is on, regardless of the
        # (trainer-oriented) action: a NaN minted here raises back to
        # the trainer typed instead of landing in the served params
        check = bool(core.globals_["FLAGS_check_nan_inf"]) or None
        for i, blk in enumerate(optimize_blocks):
            if blk_id is None or str(i) == str(blk_id):
                executor._run_block_eager(blk, scope, ctx.rng_base,
                                          check_nan=check)
                if blk_id is not None:
                    break

    def _apply_checked_locked(name, value, rows, trainer_id=0):
        """Apply one already-guarded update (rows pre-filtered)."""
        if rows is not None:
            if sync:
                state["sparse_seq"] += 1
                state["pending_sparse"].append(
                    (int(trainer_id), state["sparse_seq"], name,
                     np.asarray(value), np.asarray(rows, np.int64)))
            else:
                _apply_sparse(name, value, rows)
            return
        if sync:
            # tagged (trainer, seq) like the sparse entries: the release
            # SORTS before summing, so the fp accumulation order is
            # deterministic regardless of arrival interleaving — what
            # makes a 3-trainer round bit-identical run-to-run (2-way
            # sums are commutative, 3-way sums are not) and across a
            # replica failover's re-ordered replays
            state["sparse_seq"] += 1
            state["pending"].setdefault(name, []).append(
                (int(trainer_id), state["sparse_seq"],
                 np.asarray(value)))
        else:
            scope.var(name).set_value(
                core.LoDTensor(jnp.asarray(value)))
            _run_block_for(name)

    def _apply_one_locked(name, value, rows, trainer_id=0):
        value, rows, apply_ = _guard_nonfinite(name, value, rows,
                                               trainer_id)
        if not apply_ or (rows is not None and len(rows) == 0):
            return
        _apply_checked_locked(name, value, rows, trainer_id)

    def _apply_batch_locked(vars, trainer_id=0):
        """The numeric guard runs over the WHOLE batch before anything
        applies (one scan per array, not two): under
        FLAGS_ps_reject_nonfinite=reject a half-applied batch would be
        unrecoverable — the dedup cache replays the error on retry and
        nothing re-sends the tail — so reject must leave server state
        untouched."""
        checked = [(v["name"],) + _guard_nonfinite(
            v["name"], v["value"], v.get("rows"), trainer_id)
            for v in vars]
        for name, value, rows, apply_ in checked:
            if apply_ and not (rows is not None and len(rows) == 0):
                _apply_checked_locked(name, value, rows, trainer_id)

    def h_send_var(name, value, trainer_id=0, rows=None, height=0):
        monitor.update(trainer_id)
        with lock:
            # race-free drain guard: the handoff commit flips the
            # membership state while holding this same lock, so a send
            # that slipped past the server-level pre_dispatch is
            # refused HERE — never applied to a shard that moved
            membership.check_serving()
            _apply_one_locked(name, value, rows, trainer_id)
            # forward BEFORE noting the token applied: the forward is
            # where a false promotion surfaces (typed stale refusal),
            # and a token noted first would let a lost-response retry
            # replay a cached success for an apply that only ever
            # mutated this server's fenced-out state
            _forward("send_var", {"name": name,
                                  "value": np.asarray(value),
                                  "trainer_id": int(trainer_id),
                                  "rows": rows, "height": int(height)})
            note_request_token_applied()
        return True

    def h_send_vars_batch(vars, trainer_id=0):
        """Coalesced multi-var send (Communicator flush): every entry
        applies under ONE grad-lock acquisition; the caller's dedup
        token covers the whole batch, so a replayed retry re-applies
        none of it."""
        monitor.update(trainer_id)
        with lock:
            membership.check_serving()
            _apply_batch_locked(vars, trainer_id)
            # forward-then-note, same fencing rationale as h_send_var
            _forward("send_vars_batch", {"vars": vars,
                                         "trainer_id": int(trainer_id)})
            note_request_token_applied()
        return True

    def _release_send_round():
        # aggregate: average each grad across trainers (the reference
        # transpiler's sum + scale(1/trainers) on the server optimize
        # path), then run optimize. Runs under the shared lock, invoked
        # by the LAST arrival inside BarrierManager.arrive. Sparse row
        # grads deferred by _apply_one_locked apply FIRST, in
        # (trainer, seq) order — deterministic regardless of arrival
        # interleaving, so lock-stepped trainers reproduce bit-for-bit.
        for tid, seq, name, value, rows in sorted(
                state["pending_sparse"], key=lambda e: (e[0], e[1])):
            _apply_sparse(name, value, rows)
        state["pending_sparse"].clear()
        state["sparse_seq"] = 0
        for name, parts in state["pending"].items():
            entries = sorted(parts, key=lambda e: (e[0], e[1]))
            total = entries[0][2]
            for _tid, _seq, p in entries[1:]:
                total = total + p
            scope.var(name).set_value(
                core.LoDTensor(jnp.asarray(total / len(entries))))
        for name in list(state["pending"]):
            _run_block_for(name)
        state["pending"].clear()
        # chain replication: the standby buffered this round's forwarded
        # sends in ITS pending state; releasing its round from here (in
        # primary order, under the primary's lock) keeps the replica's
        # optimize trajectory bit-identical to the primary's
        _forward("round_release", {})

    def h_barrier(kind, trainer_id=0):
        monitor.update(trainer_id)
        if not sync or kind != "send":
            return True
        # the whole rendezvous runs under the shared grad RLock (the
        # BarrierManager Condition wraps it and fully releases it in
        # wait()), so the drain guard, the arrival, and the
        # applied-token note are one atomic step against a concurrent
        # handoff commit
        with lock:
            membership.check_serving()
            try:
                barriers.arrive("send", trainer_id,
                                on_release=_release_send_round)
            except core.WorkerDeadError:
                # drop the dead trainer's (and the whole aborted
                # round's) pending grads so the next round starts clean
                # instead of double-counting a partial batch — and the
                # standby's forwarded copy of them too: the survivors'
                # retried round would otherwise average in the aborted
                # entries on the replica only, so a later promotion
                # would serve a silently diverged trajectory
                state["pending"].clear()
                state["pending_sparse"].clear()
                state["sparse_seq"] = 0
                _forward("round_abort", {})
                raise
            # a completed barrier must replay (not re-arrive) if its
            # lost response is retried against the post-drain owner — a
            # fresh arrival there would phantom-join the next round
            note_request_token_applied()
            # and the same for the FAILOVER owner: register this
            # completed barrier's token on the replica so a lost-ack
            # retry replays there too instead of phantom-arriving
            _forward("barrier_done", {})
        return True

    def h_dgc_send(name, values, indices, shape, trainer_id=0):
        """DGC top-k dense-grad push (docs/PS_DATA_PLANE.md
        "Compression"): scatter the (indices, values) selection into a
        dense zeros grad and apply it EXACTLY like send_var would —
        sync mode defers it into the round's pending set, async runs
        the optimize block. The values arrive already dequantized
        (wire v3 decodes at receive), so the FLAGS_ps_reject_nonfinite
        guard inside _apply_one_locked sees the real numbers. The
        replica chain forwards the DECODED dense apply, never the
        compressed frame — a warm standby must stay bit-identical to
        the primary through a quantized/DGC push."""
        monitor.update(trainer_id)
        vals = np.asarray(values).reshape(-1)
        dims = [int(d) for d in shape]
        n_elems = 1
        for d in dims:
            n_elems *= d
        dense = np.zeros(n_elems, vals.dtype)
        dense[np.asarray(indices, np.int64).reshape(-1)] = vals
        dense = dense.reshape(dims)
        with lock:
            membership.check_serving()
            _apply_one_locked(name, dense, None, trainer_id)
            # forward-then-note, same fencing rationale as h_send_var
            _forward("send_var", {"name": name, "value": dense,
                                  "trainer_id": int(trainer_id),
                                  "rows": None, "height": 0})
            note_request_token_applied()
        return True

    def h_get_var(name, trainer_id=0):
        arr = _np_of(scope, name)
        if arr is None:
            raise KeyError(f"pserver has no var '{name}'")
        return np.asarray(arr)

    def h_get_vars_batch(names, trainer_id=0):
        """Batched fetch: the recv op pulls all of an endpoint's params
        in ONE RPC (read-only, idempotent like get_var)."""
        return [h_get_var(n, trainer_id) for n in names]

    def h_prefetch_rows(name, rows, prefetch=False):
        # ``prefetch=True`` marks an async-overlap early fetch (the
        # trainer pulled window i+1's rows while window i computed) —
        # counted separately under stats()["prefetch"] so operators can
        # see how much of the row traffic moved off the step's critical
        # path (docs/PS_DATA_PLANE.md "Async overlap")
        if prefetch:
            with health_lock:
                prefetch_stats["calls"] += 1
                prefetch_stats["rows"] += len(rows)
        # under the grad lock: get_rows materializes rows (slab growth,
        # index/LRU mutation) and must not interleave with a concurrent
        # apply_grad — the channel pool + fan-out make overlapping RPCs
        # from one trainer routine now
        with lock:
            val = scope.find_var(name).value()
            if isinstance(val, core.LazyEmbeddingTable):
                return val.get_rows(rows)
            tbl = np.asarray(val.array)
            return tbl[np.asarray(rows, np.int64)]

    def h_table_stats(name):
        """Introspection for tests/monitoring: touched rows + evictions
        (+ capacity-tier gauges for tiered tables)."""
        val = scope.find_var(name).value()
        if isinstance(val, core.LazyEmbeddingTable):
            out = {"touched": val.touched_rows(),
                   "evictions": val.evictions,
                   "nbytes": val.nbytes(),
                   "logical_params": val.logical_params()}
            # bounded acquire like _slab_stats_snapshot: a drain or a
            # wedged optimize round holds the grad lock for seconds and
            # this poll must not stall behind it (it just omits the
            # tier section then)
            if val._tier is not None and lock.acquire(timeout=1.0):
                try:
                    tier = val.tier_stats()
                finally:
                    lock.release()
                if tier:
                    out["tier"] = tier
            return out
        arr = np.asarray(val.array)
        return {"touched": int(arr.shape[0]), "evictions": 0,
                "nbytes": int(arr.nbytes),
                "logical_params": int(arr.size)}

    def h_table_shrink(name="", decay=0.5, threshold=0.5):
        """Decay-based shrink of one named (or every) tiered/gated
        table — the reference PSLib shrink() admin RPC. Runs under the
        grad lock so it can't interleave with an apply."""
        out = {}
        with lock:
            names = [name] if name else list(scope.local_var_names())
            for n in names:
                var = scope.find_var(n)
                if var is None or not var.is_initialized():
                    continue
                val = var.value()
                if isinstance(val, core.LazyEmbeddingTable) \
                        and val._tier is not None \
                        and val._tier.track_scores:
                    out[n] = val.shrink(decay=float(decay),
                                        threshold=float(threshold))
        return out

    def h_checkpoint(dir=""):
        return True

    def _geo_apply_locked(name, value, rows, flat=False):
        var = scope.find_var(name)
        if var is None:
            raise KeyError(f"geo pserver has no param '{name}'")
        cur = np.asarray(var.value().array)
        if rows is not None and flat:
            # DGC'd delta: ``rows`` are FLAT element indices of the
            # top-k selection, not leading-axis row ids
            cur = np.array(cur)
            flat_view = cur.reshape(-1)
            np.add.at(flat_view, np.asarray(rows, np.int64).reshape(-1),
                      np.asarray(value).reshape(-1))
            var.set_value(core.LoDTensor(jnp.asarray(cur)))
        elif rows is not None:
            cur = np.array(cur)  # jax-array views are read-only
            np.add.at(cur, np.asarray(rows, np.int64),
                      np.asarray(value))
            var.set_value(core.LoDTensor(jnp.asarray(cur)))
        else:
            var.set_value(core.LoDTensor(
                jnp.asarray(cur + np.asarray(value))))

    def h_geo_delta(name, value, trainer_id=0, rows=None, flat=False):
        """GEO-SGD delta apply: param += delta on arrival; with ``rows``
        only those table rows are touched (reference GeoSgdCommunicator
        sparse-id sync, communicator.h:383 SendUpdateSparseVars);
        ``flat=True`` marks a DGC top-k delta whose ``rows`` are flat
        element indices (docs/PS_DATA_PLANE.md "Compression")."""
        monitor.update(trainer_id)
        with lock:
            membership.check_serving()
            _geo_apply_locked(name, value, rows, flat=bool(flat))
            # forward-then-note, same fencing rationale as h_send_var.
            # The forwarded values are the DECODED delta (post-dequant)
            # so the standby applies bit-identically to this primary.
            _forward("geo_delta", {"name": name,
                                   "value": np.asarray(value),
                                   "rows": rows, "flat": bool(flat)})
            note_request_token_applied()
        return True

    # ---- replication: chain-forward applied updates to a warm standby
    # (FLAGS_ps_replicas=2 — docs/FAULT_TOLERANCE.md "Elastic
    # membership"). Forwards run UNDER the grad lock in receipt order on
    # one private single-channel client, so the replica sees the exact
    # apply sequence the primary ran — bit-identical state. A forward
    # failure marks replication broken (warn once, stop forwarding):
    # promoting a replica that missed updates would diverge, which the
    # docs call out as the replica-consistency caveat.
    fwd = {"client": None, "broken": False, "warned": False}

    def _replica_target(for_beat=False):
        # DRAINING still accepts writes (the quiesce window), so the
        # chain must keep forwarding through it — a gap here would
        # silently diverge the warm standby without marking it BROKEN.
        # A BROKEN chain stops data forwards but NOT liveness beats:
        # beats keep flowing with chain_broken=True so the stale
        # standby disables its own promotion — without them the break
        # itself looks like primary death and the standby promotes
        # over a live primary with state missing every update since
        # the break (split views, silent rollback at the real death).
        if int(core.globals_["FLAGS_ps_replicas"]) < 2 \
                or (fwd["broken"] and not for_beat) \
                or membership.state not in (ps_membership.ACTIVE,
                                            ps_membership.DRAINING):
            return None
        reps = [r for r in membership.view.replicas(endpoint)
                if r != bind]
        return reps[0] if reps else None

    def _forward(method, kw):
        target = _replica_target()
        if target is None:
            return
        from ..fluid.ps_rpc import request_dedup_token
        token = request_dedup_token()
        try:
            cli = fwd.get("client")
            if cli is None or cli.endpoint != target:
                cli = fwd["client"] = VarClient(
                    target, connect_timeout=5.0, channels=1,
                    resolve=False)
            # the view rides every forward: the replica's minting floor
            # must track epochs OTHER slots' drains created, or its
            # promotion would mint an epoch trainers already hold
            # bounded schedule: this runs holding the grad lock, so the
            # full FLAGS_rpc_deadline×retries ladder against a hung
            # replica would stall every data handler on this pserver —
            # one dedup-tokened retry inside ~2×hb, then BROKEN
            cli.call("replica_apply", fwd_method=method, kw=kw,
                     token=token, from_ep=bind,
                     view=membership.view.to_dict(),
                     _rpc_timeout=max(1.0, hb_timeout), _rpc_retries=1)
            membership.replication["forwarded_calls"] += 1
        except core.StaleClusterViewError as e:
            # the replica refused the forward: it PROMOTED while this
            # server was presumed dead (GC pause / healed partition).
            # Absorb its newer view — note_gossip demotes this server
            # out of ACTIVE so it stops serving a shard that moved —
            # and stop forwarding (the chain inverted).
            membership.replication["forward_failures"] += 1
            fwd["broken"] = True
            membership.note_gossip(view=getattr(e, "view_dict", None))
            if not fwd["warned"]:
                fwd["warned"] = True
                import logging
                logging.getLogger("paddle_tpu.ps").warning(
                    "replica forward refused by %s (%r) — the replica "
                    "promoted; this server has been replaced as the "
                    "owner of slot %s", target, e, endpoint)
            if method in ("send_var", "send_vars_batch", "geo_delta"):
                # surface the refusal to the CLIENT of the data call:
                # its re-route replays the same token on the true owner
                # (this server's local apply is on fenced-out state).
                # Barrier-internal forwards (round_release/barrier_done)
                # swallow instead — clients learn at their next data RPC
                raise membership.stale_error()
        except Exception as e:  # noqa: BLE001 — degraded, not fatal
            membership.replication["forward_failures"] += 1
            fwd["broken"] = True
            if not fwd["warned"]:
                fwd["warned"] = True
                import logging
                logging.getLogger("paddle_tpu.ps").warning(
                    "replica forward to %s failed (%r) — replication "
                    "for slot %s is BROKEN from here on; a later "
                    "promotion of that replica would serve stale state",
                    target, e, endpoint)

    # ---- replica side: primary-liveness monitor + forwarded applies.
    # The primary is participant 0 of a dedicated monitor; its forwards
    # and replica_beat pings are the beats. On silence past the timeout
    # the dead-listener PROMOTES this standby: it mints view epoch+1
    # with itself as the slot's primary, and trainers pick it up through
    # the get_view probes their reconnect loops run.
    upstream = {"ep": None, "stale": False}
    pmon = None
    if replica_of:
        pmon = HeartBeatMonitor(
            1, timeout=hb_timeout,
            check_interval=min(1.0, max(0.1, hb_timeout / 4)))

        def _on_primary_dead(_wid):
            if upstream["stale"]:
                # the primary told us the replication chain is BROKEN
                # (we missed forwards): promoting would serve state
                # missing those updates. Failover is disabled for this
                # slot — the next primary death is a WorkerDeadError
                # abort, exactly the documented broken-chain caveat.
                import logging
                logging.getLogger("paddle_tpu.ps").warning(
                    "standby %s: primary %s silent but this standby is "
                    "STALE (replication chain broke earlier) — refusing "
                    "promotion; failover for this slot is disabled",
                    bind, upstream["ep"] or replica_of)
                return
            if upstream["ep"] is None:
                # never heard a single forward/beat: the primary may
                # still be BOOTING (process spawned, socket not serving
                # yet). Probe its liveness before a first-contact
                # promotion; a connectable primary just hasn't found us
                # — re-arm and keep waiting.
                target = membership.view.resolve(replica_of) \
                    if membership.view is not None else replica_of
                host, port = target.rsplit(":", 1)
                try:
                    socket.create_connection(
                        (host, int(port)), timeout=1.0).close()
                    pmon.update(0)
                    return
                except OSError:
                    pass
            membership.promote()

        pmon.add_dead_listener(_on_primary_dead)
        pmon.start_monitor()
        # seed the silence clock: without a first beat the monitor's
        # table is empty and a primary that dies BEFORE its first
        # forward/beat (or was already down when this replica started
        # to restore redundancy) would never be declared dead
        pmon.update(0)

    def _on_upstream(from_ep):
        if pmon is None:
            return
        if from_ep and upstream["ep"] != from_ep:
            # a NEW upstream (the post-drain owner) took over forwarding
            # — an intentional-drain mark left by the old one no longer
            # applies to it
            upstream["ep"] = from_ep
            pmon.clear_draining(0)
        pmon.update(0)

    def h_replica_apply(fwd_method, kw, token=None, from_ep="",
                        view=None):
        """Apply one forwarded primary update on the standby. The
        ORIGINAL caller's dedup token is registered as completed here,
        so a trainer replaying that very call after failing over to
        this (promoted) replica gets the cached response instead of a
        double apply — exactly-once across the failover. The primary's
        view piggybacks so a later promotion mints ABOVE every epoch
        the cluster has seen and maps the OTHER slots correctly."""
        membership.note_gossip(view=view)
        if membership.state != ps_membership.STANDBY:
            # ownership fence: this replica PROMOTED (its primary was
            # presumed dead) — the forwarder is a demoted-but-alive
            # primary whose updates must not double-apply on top of the
            # re-routed trainers' direct sends. The typed refusal
            # carries our newer view; the primary absorbs it and steps
            # down (note_gossip demotion).
            raise membership.stale_error()
        _on_upstream(from_ep)
        with lock:
            if fwd_method == "send_var":
                _apply_one_locked(kw["name"], kw["value"],
                                  kw.get("rows"),
                                  kw.get("trainer_id", 0))
            elif fwd_method == "send_vars_batch":
                _apply_batch_locked(kw["vars"], kw.get("trainer_id", 0))
            elif fwd_method == "round_release":
                _release_send_round()
            elif fwd_method == "round_abort":
                # the primary aborted the round (WorkerDeadError): wipe
                # the forwarded pending grads so the survivors' retried
                # round isn't double-counted on this standby
                state["pending"].clear()
                state["pending_sparse"].clear()
                state["sparse_seq"] = 0
            elif fwd_method == "barrier_done":
                pass  # only the token registration below matters
            elif fwd_method == "geo_delta":
                _geo_apply_locked(kw["name"], kw["value"],
                                  kw.get("rows"),
                                  flat=bool(kw.get("flat", False)))
            else:
                raise KeyError(
                    f"replica_apply: unknown forwarded method "
                    f"{fwd_method!r}")
            if token is not None:
                srv_box[0]._dedup_put(tuple(token),
                                      {"ok": True, "result": True})
                srv_box[0]._note_token_applied(tuple(token))
        return True

    def h_replica_beat(from_ep="", view=None, chain_broken=False):
        membership.note_gossip(view=view)
        if chain_broken and pmon is not None and not upstream["stale"]:
            # permanent for this process lifetime: the missed forwards
            # are unrecoverable short of a full handoff, which installs
            # state wholesale and flips this server out of STANDBY
            upstream["stale"] = True
            membership.replication["stale_standby"] = 1
            import logging
            logging.getLogger("paddle_tpu.ps").warning(
                "standby %s: primary %s reports the replication chain "
                "BROKEN — this standby missed updates and will refuse "
                "promotion", bind, from_ep)
        _on_upstream(from_ep)
        return True

    def h_peer_draining(from_ep=""):
        """The primary announces an INTENTIONAL drain before it goes
        silent: its silence afterwards must not trigger a promotion —
        the new owner's first forward re-arms monitoring."""
        if pmon is not None:
            pmon.mark_draining(0)
        return True

    def h_get_view():
        return membership.view.to_dict()

    # ---- drain / handoff (the elastic resharding protocol) ------------
    # destination-side staging: sections validate against the manifest's
    # crc32/size as they stream in; nothing touches the scope until
    # handoff_commit has the complete, validated set
    staging = {}
    staging_lock = threading.Lock()

    def _clear_staging_locked():
        sdir = staging.pop("dir", None)
        staging.clear()
        if sdir:
            import shutil
            shutil.rmtree(sdir, ignore_errors=True)

    hand_seq = itertools.count()

    def _dest_spill_path(var_name):
        """Where a handed-off table's spill log lands on THIS server:
        the configured spill dir, else a fresh tempdir (never the
        source's path — both processes may share the box; the sequence
        keeps a rebuilt table from truncating the log of the still-
        installed table it replaces)."""
        import tempfile
        sdir = str(core.globals_["FLAGS_ps_slab_spill_dir"] or "")
        if not sdir:
            sdir = tempfile.mkdtemp(prefix="pt-slab-handoff-")
        return os.path.join(
            sdir, f"{_safe_name(var_name)}-{os.getpid()}"
            f"-h{next(hand_seq)}.slab")

    def h_handoff_begin(manifest):
        # STANDBY is the normal destination; DRAINED covers the REJOIN
        # without a restart — drain A→B, later drain B→A re-uses the
        # still-running drained A as the destination
        if membership.state not in (ps_membership.STANDBY,
                                    ps_membership.DRAINED):
            raise RuntimeError(
                f"handoff destination must be a standby or drained "
                f"server (state={membership.state})")
        if str(manifest.get("slot", "")) != endpoint:
            # a drain aimed at the wrong standby (swapped endpoints in
            # an operator script) would otherwise CRC-validate and
            # commit another slot's shard onto this server
            raise RuntimeError(
                f"handoff manifest is for slot "
                f"{manifest.get('slot')!r} but this server hosts slot "
                f"{endpoint!r}")
        if int(manifest.get("format_version", 0)) != \
                fio.HANDOFF_FORMAT_VERSION:
            raise core.CheckpointError(
                f"handoff manifest format "
                f"{manifest.get('format_version')!r} not supported")
        with staging_lock:
            _clear_staging_locked()
            staging["manifest"] = manifest
            staging["payloads"] = {}
            staging["files"] = {}
        return True

    def h_handoff_section(name, payload):
        blob = np.asarray(payload, np.uint8).tobytes()
        with staging_lock:
            man = staging.get("manifest")
            if man is None:
                raise RuntimeError("handoff_section before handoff_begin")
            entry = fio.check_handoff_section(man, name, blob)
            if str(entry.get("kind", "")).startswith("tier"):
                # capacity-tier sections STAGE ON DISK: the sum of a
                # spilled table's sections is the whole table, and the
                # destination's RSS must stay bounded by one section
                # (docs/PS_DATA_PLANE.md "Capacity tier")
                sdir = staging.get("dir")
                if sdir is None:
                    import tempfile
                    sdir = staging["dir"] = tempfile.mkdtemp(
                        prefix="pt-handoff-stage-")
                # index prefix: two section names that sanitize to the
                # same string must not clobber each other's staged
                # bytes (the map below is keyed by the TRUE name)
                path = os.path.join(
                    sdir,
                    f"{len(staging['files'])}-{_safe_name(name)}")
                with open(path, "wb") as f:
                    f.write(blob)
                staging["files"][name] = path
            else:
                staging["payloads"][name] = blob
        return True

    def h_handoff_commit():
        with staging_lock:
            man = staging.get("manifest")
            if man is None:
                raise RuntimeError("handoff_commit before handoff_begin")
            missing = sorted(set(man["sections"])
                             - set(staging["payloads"])
                             - set(staging["files"]))
            if missing:
                raise core.CheckpointError(
                    f"handoff incomplete: {len(missing)} section(s) "
                    f"never arrived: {', '.join(missing)}")
            lazy_meta = (man.get("extra") or {}).get("lazy_meta") or {}

            def _staged_bytes(name):
                path = staging["files"].get(name)
                if path is not None:
                    with open(path, "rb") as f:
                        return f.read()
                return staging["payloads"][name]

            with lock:
                slabs = {}
                tier_vars = set()
                for name, entry in man["sections"].items():
                    if str(entry.get("kind", "")).startswith("tier"):
                        tier_vars.add(entry["meta"]["var"])
                        continue
                    blob = staging["payloads"][name]
                    if entry["kind"] == "dense":
                        scope.var(entry["meta"]["var"]).set_value(
                            fio._deserialize_lod_tensor(blob))
                    elif entry["kind"] in ("slab_ids", "slab_rows"):
                        slabs.setdefault(entry["meta"]["var"],
                                         {})[entry["kind"]] = blob
                for var_name, parts in slabs.items():
                    meta = lazy_meta[var_name]
                    ids = np.frombuffer(parts["slab_ids"], np.int64)
                    rows = np.frombuffer(
                        parts["slab_rows"],
                        np.dtype(meta["dtype"])).reshape(
                            len(ids), int(meta["dim"]))
                    new_tbl = core.LazyEmbeddingTable.from_state(
                        meta, ids, rows)
                    # drop the replaced table only AFTER the new one
                    # built — a failed rebuild must not brick the
                    # still-installed table's cold rows
                    fio._drop_replaced_table(scope.find_var(var_name))
                    scope.var(var_name).set_value(new_tbl)
                for var_name in sorted(tier_vars):
                    # tiered rebuild: sections feed in one at a time
                    # from the staged files — peak RSS is one section
                    # plus the hot slab, never the spilled payload
                    from ..fluid import slab_spill
                    import json as _json
                    prefix = f"tier:{var_name}:"

                    def _sec(rel, prefix=prefix):
                        return _staged_bytes(
                            prefix + rel[len("tier:"):])

                    t_meta = _json.loads(_sec("tier:meta"))
                    spilled = bool(
                        (t_meta.get("tier") or {}).get("spilled"))
                    new_tbl = slab_spill.build_table_from_sections(
                        t_meta, _sec,
                        spill_path=(_dest_spill_path(var_name)
                                    if spilled else None))
                    # drop-after-build, same rationale as from_state
                    fio._drop_replaced_table(scope.find_var(var_name))
                    scope.var(var_name).set_value(new_tbl)
                srv_box[0].install_dedup_hwms(man.get("dedup_hwms"))
                membership.state = ps_membership.ACTIVE
                membership.install(man["view_next"])
            _clear_staging_locked()
        return True

    def h_handoff_abort():
        with staging_lock:
            _clear_staging_locked()
        return True

    def _handoff_sections_locked():
        """Snapshot every scope-resident piece of shard state as
        CRC-manifested sections (called under the grad lock, round
        quiesced): dense vars AND optimizer slots as reference-format
        tensor blobs, LazyEmbeddingTable sparse shards as slab
        (ids, rows) pairs with their meta riding the manifest."""
        sections, lazy_meta = {}, {}
        for name in scope.local_var_names():
            var = scope.find_var(name)
            if var is None or not var.is_initialized():
                continue
            val = var.value()
            if isinstance(val, core.LazyEmbeddingTable):
                if val._tier is not None:
                    # capacity tier: STREAM the table section-by-section
                    # (hot chunks + verbatim spill-log records) instead
                    # of a RAM-materializing export — source RSS stays
                    # O(one section) no matter how much is spilled, and
                    # quantized segments move bit-identically
                    # (docs/PS_DATA_PLANE.md "Capacity tier")
                    from ..fluid import slab_spill
                    for rel, sec in slab_spill.table_sections(
                            val).items():
                        full = f"tier:{name}:{rel[len('tier:'):]}"
                        sections[full] = {
                            "kind": sec["kind"], "meta": {"var": name},
                            "size": sec["size"], "crc32": sec["crc32"],
                            "read": sec["read"]}
                    lazy_meta[name] = {"tiered": True}
                    continue
                meta, ids, rows = val.export_state()
                lazy_meta[name] = meta
                sections[f"slab:{name}:ids"] = {
                    "kind": "slab_ids", "bytes": ids.tobytes(),
                    "meta": {"var": name}}
                sections[f"slab:{name}:rows"] = {
                    "kind": "slab_rows",
                    "bytes": np.ascontiguousarray(rows).tobytes(),
                    "meta": {"var": name}}
            elif isinstance(val, core.LoDTensor):
                sections[f"var:{name}"] = {
                    "kind": "dense",
                    "bytes": fio._serialize_lod_tensor(val),
                    "meta": {"var": name}}
        return sections, lazy_meta

    def h_drain(dest):
        """Admin RPC on the current owner: quiesce, stream this slot's
        state to ``dest`` in CRC-manifested sections, and commit the
        epoch bump — the between-rounds view flip that keeps
        lock-stepped sync training bit-identical across the move. Any
        failure aborts with the source still serving. A REJOIN is the
        same call with ``dest`` = the restarted original endpoint
        (running as a standby) — the protocol in reverse."""
        import logging
        log = logging.getLogger("paddle_tpu.ps")
        dest = str(dest)
        # check-and-set under the grad lock: two concurrent drain RPCs
        # (e.g. an operator retry from a fresh client — different dedup
        # token) must not both pass the ACTIVE gate and hand the shard
        # to two destinations
        with lock:
            if membership.state != ps_membership.ACTIVE:
                raise RuntimeError(
                    f"drain: server for slot {endpoint!r} is "
                    f"{membership.state}, not active")
            membership.state = ps_membership.DRAINING
        membership.handoff.update(in_progress=True, bytes=0,
                                  sections_done=0, total_sections=0)
        committed = False
        dest_cli = None
        try:
            dest_cli = VarClient(dest, connect_timeout=10.0, channels=1,
                                 resolve=False)
            quiesce_end = time.time() + float(
                core.globals_["FLAGS_ps_drain_quiesce_deadline"])
            while True:
                with lock:
                    if not state["pending"] and \
                            not state["pending_sparse"] and \
                            barriers.idle("send"):
                        summary = _do_handoff_locked(dest_cli, dest)
                        committed = True
                        break
                if time.time() > quiesce_end:
                    raise TimeoutError(
                        f"drain: slot {endpoint!r} could not quiesce "
                        f"within FLAGS_ps_drain_quiesce_deadline — a "
                        f"sync round never reached a between-rounds "
                        f"window")
                time.sleep(0.02)
            log.warning("membership: slot %s DRAINED %d bytes in %d "
                        "sections to %s (view epoch %d)", endpoint,
                        summary["bytes"], summary["sections"], dest,
                        summary["epoch"])
            return summary
        except BaseException as e:
            membership.handoff["aborts"] += 1
            if not committed:
                # clean abort: the source keeps serving, the destination
                # discards whatever it staged
                if dest_cli is not None:
                    try:
                        dest_cli.call("handoff_abort", _rpc_retries=0)
                    except Exception:
                        pass
                membership.state = ps_membership.ACTIVE
                log.warning("membership: drain of slot %s to %s "
                            "ABORTED (%r) — source still serving",
                            endpoint, dest, e)
            raise
        finally:
            membership.handoff["in_progress"] = False
            if dest_cli is not None:
                dest_cli.close()

    def _do_handoff_locked(dest_cli, dest):
        """Runs holding the grad lock with the round quiesced: snapshot,
        stream, commit, flip. Everything before handoff_commit is
        staged destination-side only, so an error anywhere leaves the
        source authoritative."""
        sections, lazy_meta = _handoff_sections_locked()
        new_view = membership.mint_moved(endpoint, dest)
        manifest = fio.build_handoff_manifest(
            endpoint, new_view.epoch, new_view.to_dict(), sections,
            dedup_hwms=srv_box[0].dedup_hwms(),
            extra={"lazy_meta": lazy_meta, "source": bind})
        membership.handoff["total_sections"] = len(sections)
        dest_cli.call("handoff_begin", manifest=manifest)
        for name, sec in sections.items():
            # tier sections regenerate on demand (read()) so the whole
            # spilled table is never resident; plain sections carry
            # their bytes inline as before
            payload = sec["bytes"] if "bytes" in sec else sec["read"]()
            if ps_membership._corrupt_section_hook is not None:
                payload = ps_membership._corrupt_section_hook(
                    name, payload)
            dest_cli.call("handoff_section", name=name,
                          payload=np.frombuffer(payload, np.uint8))
            membership.handoff["bytes"] += len(payload)
            membership.handoff["sections_done"] += 1
        try:
            dest_cli.call("handoff_commit")
        except Exception:
            # lost-ack hazard: the destination may have committed and
            # become the epoch+1 owner before the ack died in transit.
            # Reverting this source to ACTIVE then would fork the shard
            # (both ends serving), so probe the destination's view on a
            # fresh connection before deciding the commit failed. An
            # unreachable destination can't serve either side of a
            # split, so aborting is safe there (in-memory staging dies
            # with it); see the residual-partition caveat in
            # docs/FAULT_TOLERANCE.md.
            committed_remote = False
            try:
                probe = VarClient(dest, connect_timeout=5.0, channels=1,
                                  resolve=False)
                try:
                    v = probe.call("get_view", _rpc_retries=1)
                    committed_remote = bool(v) and \
                        int(v.get("epoch", -1)) >= new_view.epoch
                finally:
                    probe.close()
            except Exception:
                pass
            if not committed_remote:
                raise
        # tell our replica (if any) the coming silence is intentional —
        # the new owner's first forward re-arms its monitoring
        for rep in membership.view.replicas(endpoint):
            if rep == dest:
                continue
            try:
                rc = VarClient(rep, connect_timeout=2.0, channels=1,
                               resolve=False)
                try:
                    rc.call("peer_draining", from_ep=bind,
                            _rpc_retries=0)
                finally:
                    rc.close()
            except Exception:
                pass
        membership.state = ps_membership.DRAINED
        membership.install(new_view)
        membership.handoff["completed"] += 1
        return {"bytes": membership.handoff["bytes"],
                "sections": len(sections), "dest": dest,
                "epoch": new_view.epoch}

    monitor.start_monitor()
    # cluster-timeline identity (docs/OBSERVABILITY.md): label this
    # process's trace shard with its pserver bind so the timeline
    # merger can match it against the clock offsets trainers measured
    # in the _hello handshake (PADDLE_TPU_TRACE_ROLE env still wins)
    from ..fluid import telemetry as _telemetry
    _telemetry.set_process_role(f"pserver-{bind}", endpoint=bind)
    srv_box = []
    srv = VarServer(bind, {
        "send_var": h_send_var, "send_vars_batch": h_send_vars_batch,
        "dgc_send": h_dgc_send,
        "barrier": h_barrier, "get_var": h_get_var,
        "get_vars_batch": h_get_vars_batch,
        "prefetch_rows": h_prefetch_rows, "checkpoint": h_checkpoint,
        "table_stats": h_table_stats, "table_shrink": h_table_shrink,
        "geo_delta": h_geo_delta,
        # elastic membership plane
        "drain": h_drain, "get_view": h_get_view,
        "handoff_begin": h_handoff_begin,
        "handoff_section": h_handoff_section,
        "handoff_commit": h_handoff_commit,
        "handoff_abort": h_handoff_abort,
        "replica_apply": h_replica_apply,
        "replica_beat": h_replica_beat,
        "peer_draining": h_peer_draining,
        **monitor.handlers(),
    }, membership=membership)
    srv_box.append(srv)
    def _health_stats_snapshot():
        # the dedicated counter lock, NOT the grad lock: an unlocked
        # dict() copy can die mid-iteration against a _bump_health
        # writer, and the grad lock would stall this observability RPC
        # behind a whole sync optimize round
        with health_lock:
            return {"health": {
                "dropped_sparse_rows": health["dropped_sparse_rows"],
                "dropped_dense_updates": health["dropped_dense_updates"],
                "rejected_calls": health["rejected_calls"],
                "per_var": dict(health["per_var"]),
            }, "prefetch": dict(prefetch_stats)}

    srv.add_stats_source(_health_stats_snapshot)
    # drain tooling / tests poll epoch, state, handoff progress, and
    # failover promotions through the same stats RPC the health and
    # per-op counters ride (docs/FAULT_TOLERANCE.md "Elastic membership")
    srv.add_stats_source(membership.stats_section)

    def _slab_stats_snapshot():
        """Capacity-tier gauges aggregated over every tiered table —
        resident/spilled rows+bytes, hit rate, spill/promote counters,
        at-rest density (docs/PS_DATA_PLANE.md "Capacity tier"). Rides
        the stats RPC whose numeric leaves the PR 10 registry view
        scrapes as ps_server_slab_* gauges. Takes the grad lock with a
        bounded wait: a wedged optimize round costs the scrape its
        slab section, never a stall."""
        if not lock.acquire(timeout=1.0):
            return {}
        try:
            from ..fluid import slab_spill
            per_table = []
            for n in scope.local_var_names():
                var = scope.find_var(n)
                if var is None or not var.is_initialized():
                    continue
                val = var.value()
                if isinstance(val, core.LazyEmbeddingTable) \
                        and val._tier is not None:
                    per_table.append(val.tier_stats())
            agg = slab_spill.merge_tier_stats(per_table)
            return {"slab": agg} if agg else {}
        finally:
            lock.release()

    srv.add_stats_source(_slab_stats_snapshot)

    # primary → replica liveness pings: forwards already beat, but an
    # IDLE primary (no traffic) must still prove liveness or the replica
    # would promote over a quiet cluster
    beat_stop = threading.Event()

    def _replica_beat_loop():
        beat_cli = {}
        interval = min(2.0, max(0.2, hb_timeout / 4))
        while not beat_stop.wait(interval):
            target = _replica_target(for_beat=True)
            if target is None:
                continue
            try:
                cli = beat_cli.get(target)
                if cli is None:
                    cli = beat_cli[target] = VarClient(
                        target, connect_timeout=max(1.0, interval),
                        channels=1, resolve=False)
                cli.call("replica_beat", from_ep=bind,
                         view=membership.view.to_dict(),
                         chain_broken=bool(fwd["broken"]),
                         _rpc_timeout=max(1.0, interval * 2),
                         _rpc_retries=0)
            except Exception:
                beat_cli.pop(target, None)

    beat_thread = threading.Thread(target=_replica_beat_loop,
                                   name=f"ps-replica-beat-{bind}",
                                   daemon=True)
    beat_thread.start()
    srv.start()
    try:
        srv.wait_stopped()
    finally:
        beat_stop.set()
        monitor.stop()
        if pmon is not None:
            pmon.stop()
        srv.shutdown()
    return {}


# ---------------------------------------------------------------- pslib ops
@register_op("pslib_pull_sparse", stateful=True, no_grad=True,
             attr_defaults={"TableId": 0, "EmbeddingDim": 8,
                            "padding_idx": -1})
def _pslib_pull_sparse(ins, attrs):
    """Pull rows from a downpour sparse table (TPU-native replacement for
    the reference PSLib pull path — fleet_wrapper.h:86 PullSparseVarsSync).
    Emitted by DownpourOptimizer's rewrite of is_distributed lookups."""
    from ..fluid.incubate.fleet.parameter_server.pslib import _runtime
    ctx = attrs["_ctx"]
    name = ctx.op.input("Ids")[0]
    ids = np.asarray(ctx.scope.find_var(name).value().array)
    flat = ids.reshape(-1)
    pad = int(attrs.get("padding_idx", -1))
    dim = int(attrs["EmbeddingDim"])
    # padding ids never touch the table (no lazy row creation, no
    # last-seen refresh) — reference lookup_table padding semantics
    live = flat != pad if pad >= 0 else np.ones(flat.shape, bool)
    rows = np.zeros((flat.size, dim), np.float32)
    if live.any():
        rows[live] = _runtime.pull(int(attrs["TableId"]), flat[live])
    lead = ids.shape[:-1] if ids.ndim > 1 and ids.shape[-1] == 1 \
        else ids.shape
    out = jnp.asarray(rows).reshape(tuple(lead) + (dim,))
    return {"Out": [out]}


@register_op("pslib_push_sparse", stateful=True, no_grad=True,
             attr_defaults={"TableId": 0, "EmbeddingDim": 8,
                            "padding_idx": -1})
def _pslib_push_sparse(ins, attrs):
    """Push row gradients to a downpour sparse table (reference
    fleet_wrapper.h:130 PushSparseVarsWithLabelAsync). padding_idx rows get
    no gradient, matching lookup_table."""
    from ..fluid.incubate.fleet.parameter_server.pslib import _runtime
    ctx = attrs["_ctx"]
    ids = np.asarray(
        ctx.scope.find_var(ctx.op.input("Ids")[0]).value().array)
    gname = ctx.op.input("Grads")[0]
    gvar = ctx.scope.find_var(gname)
    if gvar is None or not gvar.is_initialized():
        return {}
    dim = int(attrs["EmbeddingDim"])
    flat = ids.reshape(-1)
    grads = np.asarray(gvar.value().array).reshape(-1, dim)
    pad = int(attrs.get("padding_idx", -1))
    if pad >= 0:
        live = flat != pad
        flat, grads = flat[live], grads[live]
    if flat.size:
        _runtime.push(int(attrs["TableId"]), flat, grads)
    return {}
