"""Detection operators (reference: paddle/fluid/operators/detection/ —
prior_box_op.cc, density_prior_box_op.cc, anchor_generator_op.cc,
box_coder_op.cc, bipartite_match_op.cc, target_assign_op.cc,
multiclass_nms_op.cc, yolo_box_op.cc, yolov3_loss_op.cc, roi_align_op.cc,
roi_pool_op.cc, box_clip_op.cc, generate_proposals_op.cc,
distribute_fpn_proposals_op.cc, collect_fpn_proposals_op.cc).

TPU split: geometry generators and decoders (priors/anchors/box_coder/
yolo_box/roi_align/roi_pool) are pure jnp — static shapes, fused by XLA.
Selection ops with data-dependent output counts (NMS family, proposal
generation, matching) are host ops (``stateful``) exactly like the
reference's CPU-only kernels for the same ops; their outputs carry LoD."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, seq, out, mark_no_grad


# --------------------------------------------------------------------------
# prior / anchor generators (pure)
# --------------------------------------------------------------------------
@register_op("prior_box", no_grad=True,
             attr_defaults={"min_sizes": [], "max_sizes": [],
                            "aspect_ratios": [1.0], "variances":
                            [0.1, 0.1, 0.2, 0.2], "flip": False,
                            "clip": False, "step_w": 0.0, "step_h": 0.0,
                            "offset": 0.5, "min_max_aspect_ratios_order":
                            False})
def _prior_box(ins, attrs):
    """SSD prior boxes per feature-map cell (reference prior_box_op.cc)."""
    feat = first(ins, "Input")    # [N, C, H, W]
    image = first(ins, "Image")   # [N, C, IH, IW]
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes") or []]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if attrs.get("flip", False):
                ars.append(1.0 / ar)
    step_w = attrs.get("step_w") or IW / W
    step_h = attrs.get("step_h") or IH / H
    offset = attrs.get("offset", 0.5)

    boxes = []
    for ms in min_sizes:
        for ar in ars:
            boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    num_priors = len(boxes)
    bw = np.asarray([b[0] for b in boxes], np.float32) / 2.0
    bh = np.asarray([b[1] for b in boxes], np.float32) / 2.0

    cx = (np.arange(W, dtype=np.float32) + offset) * step_w
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)                        # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    out_boxes = np.stack([
        (cxg - bw) / IW, (cyg - bh) / IH,
        (cxg + bw) / IW, (cyg + bh) / IH], axis=-1)       # [H, W, P, 4]
    if attrs.get("clip", False):
        out_boxes = np.clip(out_boxes, 0.0, 1.0)
    var = np.broadcast_to(
        np.asarray(attrs["variances"], np.float32),
        out_boxes.shape).copy()
    return out(Boxes=jnp.asarray(out_boxes.astype(np.float32)),
               Variances=jnp.asarray(var))


@register_op("density_prior_box", no_grad=True,
             attr_defaults={"variances": [0.1, 0.1, 0.2, 0.2], "clip": False,
                            "step_w": 0.0, "step_h": 0.0, "offset": 0.5,
                            "fixed_sizes": [], "fixed_ratios": [],
                            "densities": [], "flatten_to_2d": False})
def _density_prior_box(ins, attrs):
    """Densified priors (reference density_prior_box_op.cc)."""
    feat = first(ins, "Input")
    image = first(ins, "Image")
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = image.shape[2], image.shape[3]
    step_w = attrs.get("step_w") or IW / W
    step_h = attrs.get("step_h") or IH / H
    offset = attrs.get("offset", 0.5)
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    all_boxes = []
    for y in range(H):
        for x in range(W):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            for size, dens in zip(fixed_sizes, densities):
                for ratio in fixed_ratios:
                    bw = size * np.sqrt(ratio)
                    bh = size / np.sqrt(ratio)
                    shift = size / dens
                    for di in range(dens):
                        for dj in range(dens):
                            ccx = cx - size / 2.0 + shift / 2.0 + dj * shift
                            ccy = cy - size / 2.0 + shift / 2.0 + di * shift
                            all_boxes.append([
                                (ccx - bw / 2.0) / IW, (ccy - bh / 2.0) / IH,
                                (ccx + bw / 2.0) / IW, (ccy + bh / 2.0) / IH])
    boxes = np.asarray(all_boxes, np.float32)
    if attrs.get("clip", False):
        boxes = np.clip(boxes, 0.0, 1.0)
    P = len(boxes) // (H * W)
    boxes = boxes.reshape(H, W, P, 4)
    var = np.broadcast_to(np.asarray(attrs["variances"], np.float32),
                          boxes.shape).copy()
    if attrs.get("flatten_to_2d", False):
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return out(Boxes=jnp.asarray(boxes), Variances=jnp.asarray(var))


@register_op("anchor_generator", no_grad=True,
             attr_defaults={"anchor_sizes": [64.0, 128.0, 256.0, 512.0],
                            "aspect_ratios": [0.5, 1.0, 2.0],
                            "variances": [0.1, 0.1, 0.2, 0.2],
                            "stride": [16.0, 16.0], "offset": 0.5})
def _anchor_generator(ins, attrs):
    """RPN anchors (reference anchor_generator_op.cc)."""
    feat = first(ins, "Input")
    H, W = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    sw, sh = [float(s) for s in attrs["stride"]]
    offset = attrs.get("offset", 0.5)
    base = []
    for r in ratios:
        for s in sizes:
            area = sw * sh
            area_ratio = area / r
            bw = np.sqrt(area_ratio)
            bh = bw * r
            sc_w = s / sw * bw / 2.0
            sc_h = s / sh * bh / 2.0
            base.append([-sc_w, -sc_h, sc_w, sc_h])
    base = np.asarray(base, np.float32)              # [A, 4]
    cx = (np.arange(W, dtype=np.float32) + offset) * sw
    cy = (np.arange(H, dtype=np.float32) + offset) * sh
    cxg, cyg = np.meshgrid(cx, cy)
    shift = np.stack([cxg, cyg, cxg, cyg], -1)[..., None, :]  # [H, W, 1, 4]
    anchors = shift + base[None, None]
    var = np.broadcast_to(np.asarray(attrs["variances"], np.float32),
                          anchors.shape).copy()
    return out(Anchors=jnp.asarray(anchors.astype(np.float32)),
               Variances=jnp.asarray(var))


# --------------------------------------------------------------------------
# box_coder / box_clip (pure)
# --------------------------------------------------------------------------
@register_op("box_coder", diff_inputs=["TargetBox"],
             attr_defaults={"code_type": "encode_center_size",
                            "box_normalized": True, "axis": 0,
                            "variance": []})
def _box_coder(ins, attrs):
    """Encode/decode boxes against priors (reference box_coder_op.cc)."""
    prior = jnp.asarray(first(ins, "PriorBox"))       # [M, 4]
    pvar = first(ins, "PriorBoxVar")
    target = jnp.asarray(first(ins, "TargetBox"))
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    axis = int(attrs.get("axis", 0))
    avar = attrs.get("variance") or []
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if pvar is not None:
        pvar = jnp.asarray(pvar)
    if code_type.lower() == "encode_center_size":
        # target [N, 4] vs prior [M, 4] -> out [N, M, 4]
        tw = (target[:, 2] - target[:, 0] + off)[:, None]
        th = (target[:, 3] - target[:, 1] + off)[:, None]
        tcx = (target[:, 0:1] + target[:, 2:3]) * 0.5 + (0 if norm else 0.5)
        tcy = (target[:, 1:2] + target[:, 3:4]) * 0.5 + (0 if norm else 0.5)
        ex = (tcx - pcx[None, :]) / pw[None, :]
        ey = (tcy - pcy[None, :]) / ph[None, :]
        ew = jnp.log(jnp.abs(tw / pw[None, :]))
        eh = jnp.log(jnp.abs(th / ph[None, :]))
        o = jnp.stack([ex, ey, ew, eh], -1)
        if pvar is not None:
            o = o / pvar[None, :, :]
        elif avar:
            o = o / jnp.asarray(avar, o.dtype)
    else:  # decode_center_size
        # target [N, M, 4] (axis selects prior broadcast dim)
        if target.ndim == 2:
            target = target[:, None, :]
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :, None], ph[None, :, None],
                                    pcx[None, :, None], pcy[None, :, None])
            if pvar is not None:
                pvar_b = pvar[None, :, :]
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None, None], ph[:, None, None],
                                    pcx[:, None, None], pcy[:, None, None])
            if pvar is not None:
                pvar_b = pvar[:, None, :]
        t = target
        if pvar is not None:
            t = t * pvar_b
        elif avar:
            t = t * jnp.asarray(avar, t.dtype)
        dcx = t[..., 0:1] * pw_ + pcx_
        dcy = t[..., 1:2] * ph_ + pcy_
        dw = jnp.exp(t[..., 2:3]) * pw_
        dh = jnp.exp(t[..., 3:4]) * ph_
        o = jnp.concatenate([dcx - dw * 0.5, dcy - dh * 0.5,
                             dcx + dw * 0.5 - off, dcy + dh * 0.5 - off], -1)
        if o.shape[1] == 1 and target.shape[1] == 1:
            o = o[:, 0, :]
    return out(OutputBox=o)


@register_op("box_clip", needs_lod=True, diff_inputs=["Input"])
def _box_clip(ins, attrs):
    """Clip boxes to image bounds (reference box_clip_op.cc); ImInfo rows
    are [h, w, scale]."""
    boxes = jnp.asarray(first(ins, "Input"))     # LoD [T, 4] or [N, B, 4]
    im_info = jnp.asarray(first(ins, "ImInfo"))  # [N, 3]
    lods = (attrs.get("_lod") or {}).get("Input")
    if lods and lods[0]:
        offs = np.asarray(lods[0][-1], np.int64)
        segs = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
        h = im_info[jnp.asarray(segs), 0] / im_info[jnp.asarray(segs), 2]
        w = im_info[jnp.asarray(segs), 1] / im_info[jnp.asarray(segs), 2]
        h, w = h[:, None] - 1, w[:, None] - 1
    else:
        h = (im_info[:, 0] / im_info[:, 2] - 1).reshape(-1, 1, 1)
        w = (im_info[:, 1] / im_info[:, 2] - 1).reshape(-1, 1, 1)
    x1 = jnp.clip(boxes[..., 0::2], 0, None)
    y1 = jnp.clip(boxes[..., 1::2], 0, None)
    x1 = jnp.minimum(x1, w[..., None] if x1.ndim > w.ndim else w)
    y1 = jnp.minimum(y1, h[..., None] if y1.ndim > h.ndim else h)
    o = jnp.stack([x1[..., 0], y1[..., 0], x1[..., 1], y1[..., 1]], -1)
    return {"Output": [o]}


# --------------------------------------------------------------------------
# matching / assignment (host)
# --------------------------------------------------------------------------
@register_op("bipartite_match", stateful=True, no_grad=True, needs_lod=True,
             attr_defaults={"match_type": "bipartite",
                            "dist_threshold": 0.5})
def _bipartite_match(ins, attrs):
    """Greedy bipartite matching on a distance matrix (reference
    bipartite_match_op.cc). DistMat LoD groups rows per image."""
    dist = np.asarray(first(ins, "DistMat"))     # [T, M] (T = sum rows)
    lods = (attrs.get("_lod") or {}).get("DistMat")
    if lods and lods[0]:
        offs = np.asarray(lods[0][-1], np.int64)
    else:
        offs = np.asarray([0, dist.shape[0]], np.int64)
    M = dist.shape[1]
    n_img = len(offs) - 1
    match_idx = np.full((n_img, M), -1, np.int32)
    match_dist = np.zeros((n_img, M), np.float32)
    for i in range(n_img):
        sub = dist[offs[i]:offs[i + 1]].copy()    # [rows, M]
        rows = sub.shape[0]
        used_r, used_c = set(), set()
        # greedy global-max matching
        while len(used_r) < rows and len(used_c) < M:
            flat = np.argmax(np.where(
                np.isin(np.arange(rows), list(used_r))[:, None] |
                np.isin(np.arange(M), list(used_c))[None, :],
                -np.inf, sub))
            r, c = divmod(int(flat), M)
            if sub[r, c] <= 0:
                break
            match_idx[i, c] = r
            match_dist[i, c] = sub[r, c]
            used_r.add(r)
            used_c.add(c)
        if attrs.get("match_type") == "per_prediction":
            thr = float(attrs.get("dist_threshold", 0.5))
            for c in range(M):
                if match_idx[i, c] == -1:
                    r = int(np.argmax(sub[:, c]))
                    if sub[r, c] >= thr:
                        match_idx[i, c] = r
                        match_dist[i, c] = sub[r, c]
    return out(ColToRowMatchIndices=jnp.asarray(match_idx),
               ColToRowMatchDist=jnp.asarray(match_dist))


@register_op("target_assign", stateful=True, no_grad=True, needs_lod=True,
             attr_defaults={"mismatch_value": 0})
def _target_assign(ins, attrs):
    """Gather per-prior targets by match indices (reference
    target_assign_op.cc). X is LoD [T, K]; MatchIndices [N, M]."""
    x = np.asarray(first(ins, "X"))
    mi = np.asarray(first(ins, "MatchIndices"))
    lods = (attrs.get("_lod") or {}).get("X")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, x.shape[0]], np.int64))
    mismatch = attrs.get("mismatch_value", 0)
    N, M = mi.shape
    K = x.shape[-1] if x.ndim > 1 else 1
    o = np.full((N, M, K), mismatch, x.dtype)
    w = np.zeros((N, M, 1), np.float32)
    for i in range(N):
        for c in range(M):
            r = mi[i, c]
            if r >= 0:
                if x.ndim == 3:
                    # per-prior codes: X [T, M, K] (ssd_loss box encodings)
                    o[i, c] = x[offs[i] + r, c]
                else:
                    o[i, c] = x.reshape(-1, K)[offs[i] + r]
                w[i, c] = 1.0
    return out(Out=jnp.asarray(o), OutWeight=jnp.asarray(w))


# --------------------------------------------------------------------------
# NMS family (host)
# --------------------------------------------------------------------------
def _iou_xyxy(a, b, norm=True):
    off = 0.0 if norm else 1.0
    ix1 = np.maximum(a[0], b[0])
    iy1 = np.maximum(a[1], b[1])
    ix2 = np.minimum(a[2], b[2])
    iy2 = np.minimum(a[3], b[3])
    iw = np.maximum(ix2 - ix1 + off, 0)
    ih = np.maximum(iy2 - iy1 + off, 0)
    inter = iw * ih
    ua = ((a[2] - a[0] + off) * (a[3] - a[1] + off)
          + (b[2] - b[0] + off) * (b[3] - b[1] + off) - inter)
    return inter / ua if ua > 0 else 0.0


def _nms(boxes, scores, thresh, top_k, norm=True, eta=1.0):
    order = np.argsort(-scores)
    if top_k > 0:
        order = order[:top_k]
    keep = []
    adaptive = thresh
    while len(order):
        i = order[0]
        keep.append(int(i))
        rest = []
        for j in order[1:]:
            if _iou_xyxy(boxes[i], boxes[j], norm) <= adaptive:
                rest.append(j)
        order = np.asarray(rest, np.int64)
        if eta < 1.0 and adaptive > 0.5:
            adaptive *= eta
    return keep


@register_op("multiclass_nms", stateful=True, no_grad=True, needs_lod=True,
             attr_defaults={"score_threshold": 0.05, "nms_top_k": 400,
                            "keep_top_k": 200, "nms_threshold": 0.3,
                            "nms_eta": 1.0, "background_label": 0,
                            "normalized": True})
def _multiclass_nms(ins, attrs):
    """Per-class NMS then cross-class top-k (reference
    multiclass_nms_op.cc). BBoxes [N, M, 4], Scores [N, C, M]; output LoD
    [T, 6] rows [label, score, x1, y1, x2, y2]."""
    bboxes = np.asarray(first(ins, "BBoxes"))
    scores = np.asarray(first(ins, "Scores"))
    st = float(attrs["score_threshold"])
    nt = float(attrs["nms_threshold"])
    ntk = int(attrs["nms_top_k"])
    ktk = int(attrs["keep_top_k"])
    bg = int(attrs.get("background_label", 0))
    norm = bool(attrs.get("normalized", True))
    eta = float(attrs.get("nms_eta", 1.0))
    N, C, M = scores.shape
    all_rows, lens = [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == bg:
                continue
            mask = scores[n, c] > st
            idx = np.where(mask)[0]
            if not len(idx):
                continue
            keep = _nms(bboxes[n][idx], scores[n, c][idx], nt, ntk, norm,
                        eta)
            for k in keep:
                i = idx[k]
                dets.append([float(c), float(scores[n, c, i]),
                             *map(float, bboxes[n, i])])
        dets.sort(key=lambda d: -d[1])
        if ktk > 0:
            dets = dets[:ktk]
        all_rows.extend(dets)
        lens.append(len(dets))
    if not all_rows:
        o = np.full((1, 1), -1.0, np.float32)  # reference empty marker
        lod = (tuple([0, 1] + [1] * (N - 1)),) if N else ((0, 1),)
        return {"Out": [jnp.asarray(o)],
                "_lod": {"Out": [(tuple(np.concatenate(
                    [[0], np.cumsum([1] + [0] * (N - 1))]).tolist()),)]}}
    o = np.asarray(all_rows, np.float32)
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Out": [jnp.asarray(o)], "_lod": {"Out": [(lod0,)]}}


register_op("multiclass_nms2", stateful=True, no_grad=True, needs_lod=True,
            attr_defaults={"score_threshold": 0.05, "nms_top_k": 400,
                           "keep_top_k": 200, "nms_threshold": 0.3,
                           "nms_eta": 1.0, "background_label": 0,
                           "normalized": True})(_multiclass_nms)


# --------------------------------------------------------------------------
# YOLO (pure decode, host-free loss)
# --------------------------------------------------------------------------
@register_op("yolo_box", no_grad=True,
             attr_defaults={"anchors": [], "class_num": 1,
                            "conf_thresh": 0.01, "downsample_ratio": 32,
                            "clip_bbox": True})
def _yolo_box(ins, attrs):
    """Decode a YOLOv3 head to boxes+scores (reference yolo_box_op.cc)."""
    x = jnp.asarray(first(ins, "X"))          # [N, A*(5+C), H, W]
    img_size = jnp.asarray(first(ins, "ImgSize"))  # [N, 2] (h, w)
    anchors = [int(a) for a in attrs["anchors"]]
    A = len(anchors) // 2
    C = int(attrs["class_num"])
    ds = int(attrs["downsample_ratio"])
    conf = float(attrs["conf_thresh"])
    N, _, H, W = x.shape
    x = x.reshape(N, A, 5 + C, H, W)
    gx = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]
    cx = (jax.nn.sigmoid(x[:, :, 0]) + gx) / W
    cy = (jax.nn.sigmoid(x[:, :, 1]) + gy) / H
    bw = jnp.exp(x[:, :, 2]) * aw / (ds * W)
    bh = jnp.exp(x[:, :, 3]) * ah / (ds * H)
    obj = jax.nn.sigmoid(x[:, :, 4])
    cls = jax.nn.sigmoid(x[:, :, 5:])
    obj = jnp.where(obj < conf, 0.0, obj)
    imh = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    imw = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    x1 = (cx - bw / 2) * imw
    y1 = (cy - bh / 2) * imh
    x2 = (cx + bw / 2) * imw
    y2 = (cy + bh / 2) * imh
    if attrs.get("clip_bbox", True):
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, A * H * W, 4)
    scores = (obj[..., None] * jnp.moveaxis(cls, 2, -1)).reshape(
        N, A * H * W, C)
    return out(Boxes=boxes, Scores=scores)


@register_op("yolov3_loss", diff_inputs=["X"],
             attr_defaults={"anchors": [], "anchor_mask": [], "class_num": 1,
                            "ignore_thresh": 0.7, "downsample_ratio": 32,
                            "use_label_smooth": True})
def _yolov3_loss(ins, attrs):
    """YOLOv3 training loss (reference yolov3_loss_op.cc): coordinate
    losses on responsible anchors, objectness BCE with ignore region,
    class BCE. GTBox [N, B, 4] (cx, cy, w, h relative), GTLabel [N, B]."""
    x = jnp.asarray(first(ins, "X"))
    gt_box = jnp.asarray(first(ins, "GTBox"))
    gt_label = jnp.asarray(first(ins, "GTLabel"))
    anchors = [float(a) for a in attrs["anchors"]]
    mask = [int(m) for m in attrs["anchor_mask"]]
    C = int(attrs["class_num"])
    ds = int(attrs["downsample_ratio"])
    ignore = float(attrs["ignore_thresh"])
    N, _, H, W = x.shape
    A = len(mask)
    x = x.reshape(N, A, 5 + C, H, W)
    input_size = ds * H

    def bce(p, t):
        p = jax.nn.sigmoid(p)
        p = jnp.clip(p, 1e-7, 1 - 1e-7)
        return -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))

    total = jnp.zeros((N,), x.dtype)
    # responsible cell/anchor per gt (host-static loop over B boxes)
    B = gt_box.shape[1]
    obj_target = jnp.zeros((N, A, H, W), x.dtype)
    obj_mask = jnp.ones((N, A, H, W), x.dtype)
    for b in range(B):
        gx, gy = gt_box[:, b, 0] * W, gt_box[:, b, 1] * H
        gw, gh = gt_box[:, b, 2], gt_box[:, b, 3]
        valid = (gw > 0) & (gh > 0)
        gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)
        # best anchor by wh IoU against ALL anchors
        gw_pix = gw * input_size
        gh_pix = gh * input_size
        best_iou = None
        best_a = jnp.zeros((N,), jnp.int32)
        for ai in range(len(anchors) // 2):
            aw, ah = anchors[2 * ai], anchors[2 * ai + 1]
            inter = (jnp.minimum(gw_pix, aw) * jnp.minimum(gh_pix, ah))
            iou = inter / (gw_pix * gh_pix + aw * ah - inter + 1e-9)
            if best_iou is None:
                best_iou = iou
            else:
                best_a = jnp.where(iou > best_iou, ai, best_a)
                best_iou = jnp.maximum(iou, best_iou)
        # only anchors in this head's mask contribute
        for mi, ai in enumerate(mask):
            sel = valid & (best_a == ai)
            scale = 2.0 - gw * gh
            nrange = jnp.arange(N)
            tx = gx - jnp.floor(gx)
            ty = gy - jnp.floor(gy)
            tw = jnp.log(gw_pix / anchors[2 * ai] + 1e-9)
            th = jnp.log(gh_pix / anchors[2 * ai + 1] + 1e-9)
            px = x[nrange, mi, 0, gj, gi]
            py = x[nrange, mi, 1, gj, gi]
            pw = x[nrange, mi, 2, gj, gi]
            ph = x[nrange, mi, 3, gj, gi]
            coord = (bce(px, tx) + bce(py, ty)
                     + scale * (jnp.abs(pw - tw) + jnp.abs(ph - th)))
            pcls = x[nrange, mi, 5:, gj, gi]
            tcls = jax.nn.one_hot(gt_label[:, b], C, dtype=x.dtype)
            cls_loss = bce(pcls, tcls).sum(-1)
            total = total + jnp.where(sel, scale * coord + cls_loss, 0.0)
            obj_target = obj_target.at[nrange, mi, gj, gi].max(
                jnp.where(sel, 1.0, 0.0))
    obj_loss = bce(x[:, :, 4], obj_target) * obj_mask
    total = total + obj_loss.sum((1, 2, 3))
    return out(Loss=total)


# --------------------------------------------------------------------------
# RoI ops (pure)
# --------------------------------------------------------------------------
@register_op("roi_align", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"pooled_height": 1, "pooled_width": 1,
                            "spatial_scale": 1.0, "sampling_ratio": -1})
def _roi_align(ins, attrs):
    """RoIAlign with bilinear sampling (reference roi_align_op.cc)."""
    x = jnp.asarray(first(ins, "X"))         # [N, C, H, W]
    rois = jnp.asarray(first(ins, "ROIs"))   # LoD [R, 4]
    lods = (attrs.get("_lod") or {}).get("ROIs")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, rois.shape[0]], np.int64))
    batch_of = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    scale = float(attrs["spatial_scale"])
    sratio = int(attrs.get("sampling_ratio", -1))
    N, C, H, W = x.shape

    def one_roi(roi, bidx):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        s = sratio if sratio > 0 else 2
        # per-bin sample coords: s×s samples per pooled cell
        iy = jnp.arange(s) + 0.5
        ix = jnp.arange(s) + 0.5
        py = y1 + (jnp.arange(ph)[:, None] + iy[None, :] / s) * bin_h
        px = x1 + (jnp.arange(pw)[:, None] + ix[None, :] / s) * bin_w

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1_ = jnp.clip(y0 + 1, 0, H - 1)
            x1_ = jnp.clip(x0 + 1, 0, W - 1)
            ly = jnp.clip(yy - y0, 0, 1)
            lx = jnp.clip(xx - x0, 0, 1)
            img = x[bidx]                     # [C, H, W]
            v00 = img[:, y0.astype(int), x0.astype(int)]
            v01 = img[:, y0.astype(int), x1_.astype(int)]
            v10 = img[:, y1_.astype(int), x0.astype(int)]
            v11 = img[:, y1_.astype(int), x1_.astype(int)]
            return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                    + v10 * ly * (1 - lx) + v11 * ly * lx)

        # accumulate over s*s samples per bin
        acc = jnp.zeros((C, ph, pw), x.dtype)
        for i in range(s):
            for j in range(s):
                yy = py[:, i][:, None] * jnp.ones((1, pw))   # [ph, pw]
                xx = px[:, j][None, :] * jnp.ones((ph, 1))
                acc = acc + bilinear(yy, xx)
        return acc / (s * s)

    outs = [one_roi(rois[r], int(batch_of[r]))
            for r in range(rois.shape[0])]
    o = (jnp.stack(outs) if outs
         else jnp.zeros((0, C, ph, pw), x.dtype))
    return {"Out": [o]}


@register_op("roi_pool", stateful=True, needs_lod=True, diff_inputs=["X"],
             attr_defaults={"pooled_height": 1, "pooled_width": 1,
                            "spatial_scale": 1.0})
def _roi_pool(ins, attrs):
    """Max RoI pooling (reference roi_pool_op.cc)."""
    x = np.asarray(first(ins, "X"))
    rois = np.asarray(first(ins, "ROIs"))
    lods = (attrs.get("_lod") or {}).get("ROIs")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, rois.shape[0]], np.int64))
    batch_of = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    scale = float(attrs["spatial_scale"])
    N, C, H, W = x.shape
    R = rois.shape[0]
    o = np.zeros((R, C, ph, pw), x.dtype)
    argmax = np.zeros((R, C, ph, pw), np.int64)
    for r in range(R):
        b = batch_of[r]
        x1, y1, x2, y2 = np.round(rois[r] * scale).astype(np.int64)
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for i in range(ph):
            hs = y1 + (i * rh) // ph
            he = y1 + ((i + 1) * rh + ph - 1) // ph
            hs, he = np.clip([hs, he], 0, H)
            for j in range(pw):
                ws = x1 + (j * rw) // pw
                we = x1 + ((j + 1) * rw + pw - 1) // pw
                ws, we = np.clip([ws, we], 0, W)
                if he > hs and we > ws:
                    patch = x[b, :, hs:he, ws:we].reshape(C, -1)
                    o[r, :, i, j] = patch.max(-1)
                    argmax[r, :, i, j] = patch.argmax(-1)
    return out(Out=jnp.asarray(o), Argmax=jnp.asarray(argmax))


# --------------------------------------------------------------------------
# proposal generation (host)
# --------------------------------------------------------------------------
@register_op("generate_proposals", stateful=True, no_grad=True,
             attr_defaults={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                            "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0})
def _generate_proposals(ins, attrs):
    """RPN proposal generation: decode deltas on anchors, clip, filter
    small, NMS (reference generate_proposals_op.cc)."""
    scores = np.asarray(first(ins, "Scores"))      # [N, A, H, W]
    deltas = np.asarray(first(ins, "BboxDeltas"))  # [N, A*4, H, W]
    im_info = np.asarray(first(ins, "ImInfo"))     # [N, 3]
    anchors = np.asarray(first(ins, "Anchors")).reshape(-1, 4)
    variances = np.asarray(first(ins, "Variances")).reshape(-1, 4)
    pre_n = int(attrs["pre_nms_topN"])
    post_n = int(attrs["post_nms_topN"])
    nt = float(attrs["nms_thresh"])
    min_size = float(attrs["min_size"])
    N = scores.shape[0]
    all_rois, all_scores, lens = [], [], []
    for n in range(N):
        sc = scores[n].transpose(1, 2, 0).reshape(-1)
        dl = deltas[n].reshape(-1, 4, *deltas.shape[2:]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc)[:pre_n]
        sc, dl = sc[order], dl[order]
        an, va = anchors[order], variances[order]
        # decode (anchor-center form with variances)
        aw = an[:, 2] - an[:, 0] + 1
        ah = an[:, 3] - an[:, 1] + 1
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = va[:, 0] * dl[:, 0] * aw + acx
        cy = va[:, 1] * dl[:, 1] * ah + acy
        w = np.exp(np.minimum(va[:, 2] * dl[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(va[:, 3] * dl[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - 1, cy + h / 2 - 1], 1)
        ih, iw = im_info[n, 0], im_info[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - 1)
        ms = min_size * im_info[n, 2]
        keep = np.where((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
                        & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))[0]
        boxes, sc = boxes[keep], sc[keep]
        keep = _nms(boxes, sc, nt, post_n, norm=False)
        boxes, sc = boxes[keep], sc[keep]
        all_rois.append(boxes)
        all_scores.append(sc)
        lens.append(len(boxes))
    rois = (np.concatenate(all_rois) if all_rois
            else np.zeros((0, 4), np.float32))
    scs = (np.concatenate(all_scores) if all_scores
           else np.zeros((0,), np.float32))
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"RpnRois": [jnp.asarray(rois.astype(np.float32))],
            "RpnRoiProbs": [jnp.asarray(scs.astype(np.float32)
                                        .reshape(-1, 1))],
            "RpnRoisNum": [jnp.asarray(np.asarray(lens, np.int32))],
            "_lod": {"RpnRois": [(lod0,)], "RpnRoiProbs": [(lod0,)]}}


@register_op("distribute_fpn_proposals", stateful=True, no_grad=True,
             needs_lod=True,
             attr_defaults={"min_level": 2, "max_level": 5,
                            "refer_level": 4, "refer_scale": 224})
def _distribute_fpn_proposals(ins, attrs):
    """Route RoIs to FPN levels by scale (reference
    distribute_fpn_proposals_op.cc)."""
    rois = np.asarray(first(ins, "FpnRois"))
    lods = (attrs.get("_lod") or {}).get("FpnRois")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, rois.shape[0]], np.int64))
    lo, hi = int(attrs["min_level"]), int(attrs["max_level"])
    rl, rs = int(attrs["refer_level"]), int(attrs["refer_scale"])
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(w * h, 1e-6))
    lvl = np.floor(np.log2(scale / rs + 1e-6) + rl).astype(np.int64)
    lvl = np.clip(lvl, lo, hi)
    n_lvl = hi - lo + 1
    outs, out_lods, restore = [], [], np.zeros(len(rois), np.int64)
    pos = 0
    for L in range(lo, hi + 1):
        idx = np.where(lvl == L)[0]
        outs.append(jnp.asarray(rois[idx]))
        lens = [int(((lvl[offs[i]:offs[i + 1]] == L)).sum())
                for i in range(len(offs) - 1)]
        out_lods.append((tuple(int(v) for v in
                               np.concatenate([[0], np.cumsum(lens)])),))
        restore[idx] = np.arange(pos, pos + len(idx))
        pos += len(idx)
    return {"MultiFpnRois": outs,
            "RestoreIndex": [jnp.asarray(restore.reshape(-1, 1))],
            "_lod": {"MultiFpnRois": out_lods}}


@register_op("collect_fpn_proposals", stateful=True, no_grad=True,
             needs_lod=True, attr_defaults={"post_nms_topN": 100})
def _collect_fpn_proposals(ins, attrs):
    """Merge per-level RoIs back, keep top-N by score (reference
    collect_fpn_proposals_op.cc)."""
    roi_list = [np.asarray(r) for r in seq(ins, "MultiLevelRois")]
    score_list = [np.asarray(s).reshape(-1) for s in
                  seq(ins, "MultiLevelScores")]
    rois = np.concatenate(roi_list) if roi_list else np.zeros((0, 4))
    scores = np.concatenate(score_list) if score_list else np.zeros((0,))
    topn = int(attrs["post_nms_topN"])
    order = np.argsort(-scores)[:topn]
    return {"FpnRois": [jnp.asarray(rois[order].astype(np.float32))],
            "_lod": {"FpnRois": [((0, len(order)),)]}}
