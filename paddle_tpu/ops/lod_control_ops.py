"""DynamicRNN-era LoD control ops (reference:
operators/lod_rank_table_op.cc, max_sequence_len_op.cc,
lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc (RankTable path),
shrink_rnn_memory_op.cc, reorder_lod_tensor_by_rank_op.cc,
controlflow/split_lod_tensor_op.cc, merge_lod_tensor_op.cc,
recurrent_op.cc, conditional_block_infer).

These drive the reference's dynamic (variable-length) RNN machinery: the
rank table sorts sequences by length so each time step processes the
still-alive prefix. All are scope-level host ops (``stateful``); the math
inside the per-step sub-blocks still runs as JAX ops."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .registry import register_op, first, seq, out
from ..fluid import core


def _lod_of_var(var):
    t = var.get_tensor()
    return t.lod()


@register_op("lod_rank_table", stateful=True, no_grad=True,
             attr_defaults={"level": 0})
def _lod_rank_table(ins, attrs):
    ctx = attrs["_ctx"]
    xvar = ctx.scope.find_var(ctx.op.input("X")[0])
    lod = _lod_of_var(xvar)
    level = int(attrs.get("level", 0))
    if lod and len(lod) > level:
        offs = lod[level]
        lens = [(i, int(offs[i + 1] - offs[i]))
                for i in range(len(offs) - 1)]
    else:  # no LoD: every row is a length-1 sequence
        n = xvar.get_tensor().array.shape[0]
        lens = [(i, 1) for i in range(n)]
    # stable sort by length descending (reference lod_rank_table.cc)
    lens.sort(key=lambda t: -t[1])
    table = core.LoDRankTable(lens)
    table.level = level
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(table)
    return {}


@register_op("max_sequence_len", stateful=True, no_grad=True)
def _max_sequence_len(ins, attrs):
    ctx = attrs["_ctx"]
    table = ctx.scope.find_var(
        ctx.op.input("RankTable")[0]).get_lod_rank_table()
    m = table.items[0][1] if table.items else 0
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(jnp.asarray([m], jnp.int32)))
    return {}


@register_op("lod_tensor_to_array", stateful=True, no_grad=True)
def _lod_tensor_to_array(ins, attrs):
    """Split X into per-timestep batches ordered by the rank table: array[t]
    holds row t of every sequence still alive at step t, in rank order."""
    ctx = attrs["_ctx"]
    xvar = ctx.scope.find_var(ctx.op.input("X")[0])
    x = xvar.get_tensor().array
    lod = _lod_of_var(xvar)
    table = ctx.scope.find_var(
        ctx.op.input("RankTable")[0]).get_lod_rank_table()
    level = getattr(table, "level", 0)
    if lod and level != len(lod) - 1:
        raise NotImplementedError(
            "lod_tensor_to_array: splitting at a non-innermost LoD level "
            f"(level={level} of {len(lod)}) — each step would itself be a "
            "ragged sub-sequence; flatten the inner level first")
    offs = (np.asarray(lod[level], np.int64) if lod
            else np.arange(x.shape[0] + 1, dtype=np.int64))
    arr = ctx.scope.var(ctx.op.output("Out")[0]).get_lod_tensor_array()
    arr.clear()
    max_len = table.items[0][1] if table.items else 0
    for t in range(max_len):
        rows = [int(offs[i] + t) for i, l in table.items if t < l]
        arr.append(core.LoDTensor(x[jnp.asarray(rows, jnp.int32)]))
    return {}


@register_op("shrink_rnn_memory", stateful=True,
             attr_defaults={})
def _shrink_rnn_memory(ins, attrs):
    """At step I, keep only the first K rows of X where K = number of
    sequences whose length > I per the rank table (rows are rank-ordered,
    so survivors are a prefix — reference shrink_rnn_memory_op.cc)."""
    ctx = attrs["_ctx"]
    x = ctx.scope.find_var(ctx.op.input("X")[0]).get_tensor().array
    i = int(np.asarray(ctx.scope.find_var(
        ctx.op.input("I")[0]).get_tensor().array).reshape(-1)[0])
    table = ctx.scope.find_var(
        ctx.op.input("RankTable")[0]).get_lod_rank_table()
    k = sum(1 for _, l in table.items if l > i)
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(x[:k]))
    return {}


@register_op("reorder_lod_tensor_by_rank", stateful=True)
def _reorder_lod_tensor_by_rank(ins, attrs):
    """Reorder X's sequences into rank-table order (reference
    reorder_lod_tensor_by_rank_op.cc)."""
    ctx = attrs["_ctx"]
    xvar = ctx.scope.find_var(ctx.op.input("X")[0])
    x = xvar.get_tensor().array
    lod = _lod_of_var(xvar)
    table = ctx.scope.find_var(
        ctx.op.input("RankTable")[0]).get_lod_rank_table()
    if lod:
        offs = np.asarray(lod[0], np.int64)
        rows, new_lens = [], []
        for i, l in table.items:
            rows.extend(range(int(offs[i]), int(offs[i + 1])))
            new_lens.append(int(offs[i + 1] - offs[i]))
        o = x[jnp.asarray(rows, jnp.int32)]
        new_offs = tuple(int(v)
                         for v in np.concatenate([[0], np.cumsum(new_lens)]))
        t = core.LoDTensor(o, (new_offs,))
    else:
        rows = [i for i, _ in table.items]
        t = core.LoDTensor(x[jnp.asarray(rows, jnp.int32)])
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(t)
    return {}


@register_op("split_lod_tensor", stateful=True, no_grad=True,
             attr_defaults={"level": 0})
def _split_lod_tensor(ins, attrs):
    """Rows where Mask is false go to OutFalse, true to OutTrue
    (reference controlflow/split_lod_tensor_op.cc; used by IfElse)."""
    ctx = attrs["_ctx"]
    x = ctx.scope.find_var(ctx.op.input("X")[0]).get_tensor().array
    mask = np.asarray(ctx.scope.find_var(
        ctx.op.input("Mask")[0]).get_tensor().array).reshape(-1).astype(bool)
    t_rows = np.where(mask)[0]
    f_rows = np.where(~mask)[0]
    ctx.scope.var(ctx.op.output("OutTrue")[0]).set_value(
        core.LoDTensor(x[jnp.asarray(t_rows, jnp.int32)]))
    ctx.scope.var(ctx.op.output("OutFalse")[0]).set_value(
        core.LoDTensor(x[jnp.asarray(f_rows, jnp.int32)]))
    return {}


def _merge_lod_tensor_impl(ins, attrs):
    ctx = attrs["_ctx"]
    mask = np.asarray(ctx.scope.find_var(
        ctx.op.input("Mask")[0]).get_tensor().array).reshape(-1).astype(bool)
    in_true = ctx.scope.find_var(ctx.op.input("InTrue")[0]).get_tensor().array
    in_false = ctx.scope.find_var(
        ctx.op.input("InFalse")[0]).get_tensor().array
    width = in_true.shape[1:] if in_true.size else in_false.shape[1:]
    o = np.zeros((len(mask),) + tuple(width),
                 np.asarray(in_true if in_true.size else in_false).dtype)
    o[mask] = np.asarray(in_true)
    o[~mask] = np.asarray(in_false)
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(jnp.asarray(o)))
    return {}


@register_op("merge_lod_tensor", stateful=True, no_grad=True,
             attr_defaults={"level": 0})
def _merge_lod_tensor(ins, attrs):
    return _merge_lod_tensor_impl(ins, attrs)


@register_op("merge_lod_tensor_infer", stateful=True, no_grad=True,
             attr_defaults={"level": 0})
def _merge_lod_tensor_infer(ins, attrs):
    return _merge_lod_tensor_impl(ins, attrs)


@register_op("conditional_block_infer", stateful=True, no_grad=True,
             attr_defaults={"is_scalar_condition": False})
def _conditional_block_infer(ins, attrs):
    from .framework_ops import _conditional_block
    return _conditional_block(ins, attrs)


@register_op("recurrent", stateful=True, no_grad=True,
             attr_defaults={"has_states": True, "ex_states": [],
                            "states": [], "reverse": False,
                            "is_train": True})
def _recurrent(ins, attrs):
    """StaticRNN step-block runner (reference recurrent_op.cc): each time
    step runs the sub-block in a fresh step scope where every sequence
    input var (same name, time-major [T, ...]) holds its row t, each
    ex-state var holds the previous step's state (seeded from
    initial_states, matched by position), and per-step outputs are stacked
    into [T, ...] results in the outer scope."""
    ctx = attrs["_ctx"]
    block = attrs["sub_block"]
    xs = ctx.op.input("inputs")
    init_states = ctx.op.input("initial_states")
    outs = ctx.op.output("outputs")
    ex_states = list(attrs.get("ex_states", []))
    states = list(attrs.get("states", []))
    T = ctx.scope.find_var(xs[0]).get_tensor().array.shape[0]
    rev = attrs.get("reverse", False)
    prev = {ex: ctx.scope.find_var(init).get_tensor().array
            for ex, init in zip(ex_states, init_states)}
    collected = {o: [] for o in outs}
    seqs = {name: ctx.scope.find_var(name).get_tensor().array
            for name in xs}
    for t in (range(T - 1, -1, -1) if rev else range(T)):
        step_scope = ctx.scope.new_scope()
        for name, x in seqs.items():
            step_scope.var(name).set_value(core.LoDTensor(x[t]))
        for ex in ex_states:
            step_scope.var(ex).set_value(core.LoDTensor(prev[ex]))
        ctx.executor._run_block_eager(block, step_scope, ctx.rng_base)
        for ex, st in zip(ex_states, states):
            prev[ex] = step_scope.find_var(st).get_tensor().array
        for o in collected:
            v = step_scope.find_var(o)
            if v is not None and v.is_initialized():
                collected[o].append(v.get_tensor().array)
    for o, vals in collected.items():
        if vals:
            if rev:
                vals = vals[::-1]
            ctx.scope.var(o).set_value(core.LoDTensor(jnp.stack(vals)))
    return {}
