"""Recurrent ops — dynamic_lstm/dynamic_lstmp/dynamic_gru over LoD input,
gru_unit/lstm_unit single steps, fused multi-layer lstm, gather_tree
(reference: paddle/fluid/operators/lstm_op.cc, lstmp_op.cc, gru_op.cc,
gru_unit_op.cc, lstm_unit_op.cc, cudnn_lstm_op.cc, gather_tree_op.cc).

TPU design: the reference reorders LoD rows into time-major "batches"
(math/sequence2batch.h) and steps a per-timestep GEMM; here the packed
sequence is padded to ``[N, maxT, ·]`` with host-static LoD indices and the
recurrence is one ``lax.scan`` whose per-step update is masked past each
sequence's length — XLA keeps the whole scan on-device and the gate matmuls
on the MXU. Grads fall out of vjp through the scan.

Gate layout convention (documented contract of this framework): LSTM gates
are ordered ``[i, f, c, o]`` along the last axis; GRU gates ``[u, r, c]``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, out, mark_no_grad


# --------------------------------------------------------------------------
# LoD pack <-> pad helpers (host-static indices)
# --------------------------------------------------------------------------
def _offs_of(attrs, slot):
    lods = attrs.get("_lod") or {}
    vals = lods.get(slot)
    if not vals or vals[0] is None:
        raise ValueError(f"rnn op: input '{slot}' must carry LoD")
    return np.asarray(vals[0][-1], np.int64)


def _pad_from_lod(x, offs):
    """packed [T, D] -> padded [N, maxT, D] + bool mask [N, maxT]."""
    lens = offs[1:] - offs[:-1]
    n, maxT = len(lens), int(lens.max()) if len(lens) else 0
    pos = np.arange(maxT)[None, :] + offs[:-1, None]
    valid = np.arange(maxT)[None, :] < lens[:, None]
    idx = np.where(valid, pos, 0)
    padded = jnp.take(x, jnp.asarray(idx), axis=0)
    padded = padded * jnp.asarray(valid[..., None], x.dtype)
    return padded, valid, lens


def _unpad_to_packed(padded, offs):
    """padded [N, maxT, D] -> packed [T, D] in LoD row order."""
    lens = offs[1:] - offs[:-1]
    rows = [np.stack([np.full(int(L), i), np.arange(int(L))], 1)
            for i, L in enumerate(lens)]
    rc = np.concatenate(rows) if rows else np.zeros((0, 2), np.int64)
    return padded[jnp.asarray(rc[:, 0]), jnp.asarray(rc[:, 1])]


def _act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": (lambda v: v),
            "": jnp.tanh}[name or "tanh"]


# --------------------------------------------------------------------------
# scan cores (padded time-major scan with per-step masking)
# --------------------------------------------------------------------------
def _lstm_scan(xw, h0, c0, w_rec, bias, mask, gate_act, cell_act, cand_act,
               peephole=None, proj=None, proj_act="tanh"):
    """xw: [N, T, 4H] pre-projected input. w_rec is [H, 4H] (lstm) or
    [P, 4H] (lstmp, where the RECURRENT state is the P-dim projection —
    reference lstmp_op.h projects inside the recurrence, not after it).
    Returns padded (H-or-P state, C) [N, T, ·]."""
    H = w_rec.shape[1] // 4
    ga, ca, na = _act(gate_act), _act(cell_act), _act(cand_act)
    pa = _act(proj_act)

    def step(carry, t_in):
        h, c = carry              # h: [N, H] or [N, P] with projection
        x_t, m_t = t_in           # [N, 4H], [N, 1]
        g = x_t + h @ w_rec
        if bias is not None:
            g = g + bias.reshape(1, -1)[:, :4 * H]
        i, f, cc, o = jnp.split(g, 4, axis=-1)
        if peephole is not None:
            w_ic, w_fc, w_oc = peephole
            i = i + c * w_ic
            f = f + c * w_fc
        i, f = ga(i), ga(f)
        cand = na(cc)
        c_new = f * c + i * cand
        if peephole is not None:
            o = o + c_new * w_oc
        o = ga(o)
        h_new = o * ca(c_new)
        if proj is not None:
            h_new = pa(h_new @ proj)
        h = jnp.where(m_t, h_new, h)
        c = jnp.where(m_t, c_new, c)
        return (h, c), (h, c)

    xw_t = jnp.swapaxes(xw, 0, 1)               # [T, N, 4H]
    m_t = jnp.swapaxes(mask, 0, 1)[..., None]   # [T, N, 1]
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), (xw_t, m_t))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


def _gru_scan(xw, h0, w, bias, mask, gate_act, cand_act, origin_mode):
    """xw: [N, T, 3H]; w: [H, 3H] ([:, :2H] update/reset, [:, 2H:] cand)."""
    H = w.shape[0]
    ga, na = _act(gate_act), _act(cand_act)
    w_ur, w_c = w[:, :2 * H], w[:, 2 * H:]

    def step(h, t_in):
        x_t, m_t = t_in
        if bias is not None:
            x_t = x_t + bias.reshape(1, -1)
        xu, xr, xc = jnp.split(x_t, 3, axis=-1)
        ur = jnp.concatenate([xu, xr], -1) + h @ w_ur
        u, r = jnp.split(ga(ur), 2, axis=-1)
        c = na(xc + (r * h) @ w_c)
        if origin_mode:
            h_new = u * h + (1.0 - u) * c
        else:
            h_new = (1.0 - u) * h + u * c
        h = jnp.where(m_t, h_new, h)
        return h, h

    xw_t = jnp.swapaxes(xw, 0, 1)
    m_t = jnp.swapaxes(mask, 0, 1)[..., None]
    _, hs = jax.lax.scan(step, h0, (xw_t, m_t))
    return jnp.swapaxes(hs, 0, 1)


# --------------------------------------------------------------------------
# dynamic_lstm / dynamic_lstmp (reference: lstm_op.cc, lstmp_op.cc)
# --------------------------------------------------------------------------
def _dyn_lstm_common(ins, attrs, proj_weight=None):
    x = first(ins, "Input")            # packed [T, 4H], pre-projected
    w = first(ins, "Weight")           # [H or P, 4H] recurrent
    bias = first(ins, "Bias")
    h0, c0 = first(ins, "H0"), first(ins, "C0")
    offs = _offs_of(attrs, "Input")
    H = w.shape[1] // 4
    n = len(offs) - 1
    use_peepholes = attrs.get("use_peepholes", False)
    peep = None
    if use_peepholes and bias is not None:
        b = bias.reshape(-1)
        peep = (b[4 * H:5 * H], b[5 * H:6 * H], b[6 * H:7 * H])
    if attrs.get("is_reverse", False):
        # reverse rows within each sequence, scan, reverse back
        rev_idx = np.concatenate(
            [np.arange(offs[i + 1] - 1, offs[i] - 1, -1)
             for i in range(n)]) if n else np.zeros(0, np.int64)
        x = jnp.take(x, jnp.asarray(rev_idx), axis=0)
    padded, valid, _lens = _pad_from_lod(x, offs)
    dtype = x.dtype
    if h0 is None:
        h0 = jnp.zeros((n, w.shape[0]), dtype)
    if c0 is None:
        c0 = jnp.zeros((n, H), dtype)
    hs, cs = _lstm_scan(
        padded, h0, c0, w, bias, jnp.asarray(valid),
        attrs.get("gate_activation", "sigmoid"),
        attrs.get("cell_activation", "tanh"),
        attrs.get("candidate_activation", "tanh"), peephole=peep,
        proj=proj_weight,
        proj_act=attrs.get("proj_activation", "identity"))
    h_packed = _unpad_to_packed(hs, offs)
    c_packed = _unpad_to_packed(cs, offs)
    if attrs.get("is_reverse", False):
        h_packed = jnp.take(h_packed, jnp.asarray(rev_idx), axis=0)
        c_packed = jnp.take(c_packed, jnp.asarray(rev_idx), axis=0)
    return h_packed, c_packed


@register_op("dynamic_lstm", needs_lod=True,
             diff_inputs=["Input", "Weight", "Bias", "H0", "C0"],
             attr_defaults={"use_peepholes": True, "is_reverse": False,
                            "gate_activation": "sigmoid",
                            "cell_activation": "tanh",
                            "candidate_activation": "tanh"})
def _dynamic_lstm(ins, attrs):
    h, c = _dyn_lstm_common(ins, attrs)
    lod = (attrs.get("_lod") or {}).get("Input")[0]
    return {"Hidden": [h], "Cell": [c],
            "_lod": {"Hidden": [lod], "Cell": [lod]}}


@register_op("dynamic_lstmp", needs_lod=True,
             diff_inputs=["Input", "Weight", "ProjWeight", "Bias", "H0", "C0"],
             attr_defaults={"use_peepholes": True, "is_reverse": False,
                            "gate_activation": "sigmoid",
                            "cell_activation": "tanh",
                            "candidate_activation": "tanh",
                            "proj_activation": "tanh"})
def _dynamic_lstmp(ins, attrs):
    h, c = _dyn_lstm_common(ins, attrs, proj_weight=first(ins, "ProjWeight"))
    lod = (attrs.get("_lod") or {}).get("Input")[0]
    return {"Projection": [h], "Cell": [c],
            "_lod": {"Projection": [lod], "Cell": [lod]}}


# --------------------------------------------------------------------------
# dynamic_gru (reference: gru_op.cc)
# --------------------------------------------------------------------------
@register_op("dynamic_gru", needs_lod=True,
             diff_inputs=["Input", "Weight", "Bias", "H0"],
             attr_defaults={"is_reverse": False, "origin_mode": False,
                            "gate_activation": "sigmoid",
                            "activation": "tanh"})
def _dynamic_gru(ins, attrs):
    x = first(ins, "Input")            # packed [T, 3H]
    w = first(ins, "Weight")           # [H, 3H]
    bias = first(ins, "Bias")
    h0 = first(ins, "H0")
    offs = _offs_of(attrs, "Input")
    n = len(offs) - 1
    H = w.shape[0]
    if attrs.get("is_reverse", False):
        rev_idx = np.concatenate(
            [np.arange(offs[i + 1] - 1, offs[i] - 1, -1)
             for i in range(n)]) if n else np.zeros(0, np.int64)
        x = jnp.take(x, jnp.asarray(rev_idx), axis=0)
    padded, valid, _lens = _pad_from_lod(x, offs)
    if h0 is None:
        h0 = jnp.zeros((n, H), x.dtype)
    hs = _gru_scan(padded, h0, w, bias, jnp.asarray(valid),
                   attrs.get("gate_activation", "sigmoid"),
                   attrs.get("activation", "tanh"),
                   attrs.get("origin_mode", False))
    h_packed = _unpad_to_packed(hs, offs)
    if attrs.get("is_reverse", False):
        h_packed = jnp.take(h_packed, jnp.asarray(rev_idx), axis=0)
    lod = (attrs.get("_lod") or {}).get("Input")[0]
    return {"Hidden": [h_packed], "_lod": {"Hidden": [lod]}}


# --------------------------------------------------------------------------
# single-step units (reference: gru_unit_op.cc, lstm_unit_op.cc)
# --------------------------------------------------------------------------
@register_op("gru_unit",
             diff_inputs=["Input", "HiddenPrev", "Weight", "Bias"],
             attr_defaults={"activation": "tanh",
                            "gate_activation": "sigmoid",
                            "origin_mode": False})
def _gru_unit(ins, attrs):
    x = first(ins, "Input")            # [N, 3H]
    h_prev = first(ins, "HiddenPrev")  # [N, H]
    w = first(ins, "Weight")           # [H, 3H]
    bias = first(ins, "Bias")
    H = w.shape[0]
    ga, na = _act(attrs.get("gate_activation")), _act(attrs.get("activation"))
    if bias is not None:
        x = x + bias.reshape(1, -1)
    xu, xr, xc = jnp.split(x, 3, axis=-1)
    ur = jnp.concatenate([xu, xr], -1) + h_prev @ w[:, :2 * H]
    u, r = jnp.split(ga(ur), 2, axis=-1)
    reset_h = r * h_prev
    c = na(xc + reset_h @ w[:, 2 * H:])
    if attrs.get("origin_mode", False):
        h = u * h_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * h_prev + u * c
    gate = jnp.concatenate([u, r, c], -1)
    return out(Gate=gate, ResetHiddenPrev=reset_h, Hidden=h)


@register_op("lstm_unit", diff_inputs=["X", "C_prev"],
             attr_defaults={"forget_bias": 0.0})
def _lstm_unit(ins, attrs):
    x = first(ins, "X")                # [N, 4H] pre-projected gates
    c_prev = first(ins, "C_prev")
    i, f, cc, o = jnp.split(x, 4, axis=-1)
    f = f + attrs.get("forget_bias", 0.0)
    c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(cc)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return out(C=c, H=h)


# --------------------------------------------------------------------------
# fused multi-layer lstm (reference: cudnn_lstm_op.cc / layers.lstm)
# --------------------------------------------------------------------------
@register_op("lstm", needs_rng=True,
             diff_inputs=["Input", "W", "InitH", "InitC"],
             attr_defaults={"max_len": 0, "hidden_size": 0, "num_layers": 1,
                            "is_bidirec": False, "dropout_prob": 0.0,
                            "input_size": 0, "is_test": False, "seed": 0})
def _lstm(ins, attrs):
    """Dense multi-layer (bi)LSTM over padded [B, T, D] input. The flat W
    buffer packs per-layer/direction [Wx, Wh, b] the way the reference
    packs cudnn weights (cudnn_lstm_op.cc) — layout documented in
    layers.lstm which allocates it."""
    x = first(ins, "Input")            # [B, T, D]
    w_flat = first(ins, "W").reshape(-1)
    init_h = first(ins, "InitH")       # [L*dirs, B, H]
    init_c = first(ins, "InitC")
    H = int(attrs["hidden_size"])
    L = int(attrs.get("num_layers", 1))
    bidi = bool(attrs.get("is_bidirec", False))
    dirs = 2 if bidi else 1
    B, T, _D = x.shape
    mask = jnp.ones((B, T), bool)
    ptr = 0
    layer_in = x
    last_hs, last_cs = [], []
    for layer in range(L):
        outs_dir = []
        in_dim = layer_in.shape[-1]
        for d in range(dirs):
            wx = w_flat[ptr:ptr + in_dim * 4 * H].reshape(in_dim, 4 * H)
            ptr += in_dim * 4 * H
            wh = w_flat[ptr:ptr + H * 4 * H].reshape(H, 4 * H)
            ptr += H * 4 * H
            b = w_flat[ptr:ptr + 4 * H]
            ptr += 4 * H
            inp = layer_in[:, ::-1] if d == 1 else layer_in
            xw = inp @ wx
            h0 = init_h[layer * dirs + d]
            c0 = init_c[layer * dirs + d]
            hs, cs = _lstm_scan(xw, h0, c0, wh, b, mask,
                                "sigmoid", "tanh", "tanh")
            last_hs.append(hs[:, -1])
            last_cs.append(cs[:, -1])
            outs_dir.append(hs[:, ::-1] if d == 1 else hs)
        layer_in = (jnp.concatenate(outs_dir, -1) if bidi else outs_dir[0])
        p = attrs.get("dropout_prob", 0.0)
        if p and not attrs.get("is_test", False) and layer < L - 1:
            keep = jax.random.bernoulli(
                jax.random.fold_in(attrs["_rng"], layer), 1.0 - p,
                layer_in.shape)
            layer_in = jnp.where(keep, layer_in / (1.0 - p),
                                 jnp.zeros_like(layer_in))
    return out(Out=layer_in, LastH=jnp.stack(last_hs),
               LastC=jnp.stack(last_cs))


# --------------------------------------------------------------------------
# gather_tree (reference: gather_tree_op.cc — beam-search backtrace)
# --------------------------------------------------------------------------
@register_op("gather_tree", no_grad=True)
def _gather_tree(ins, attrs):
    ids = jnp.asarray(first(ins, "Ids"))    # [max_time, batch, beam]
    parents = jnp.asarray(first(ins, "Parents"))
    T = ids.shape[0]
    beams = ids.shape[2]
    beam_idx = jnp.arange(beams)[None, :]

    def step(carry, t):
        parent = carry                      # [batch, beam]
        tok = jnp.take_along_axis(ids[t], parent, axis=1)
        parent_new = jnp.take_along_axis(parents[t], parent, axis=1)
        return parent_new, tok

    init = jnp.broadcast_to(beam_idx, ids.shape[1:]).astype(ids.dtype)
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return out(Out=toks[::-1])


# --------------------------------------------------------------------------
# beam_search / beam_search_decode (reference: beam_search_op.cc,
# beam_search_decode_op.cc — the v1.7 LoD-based While-loop decode path).
# Host ops (stateful): selection counts are data-dependent; the
# tensor-based fast path on TPU is layers.BeamSearchDecoder + gather_tree.
# --------------------------------------------------------------------------
@register_op("beam_search", needs_lod=True, stateful=True, no_grad=True,
             attr_defaults={"level": 0, "beam_size": 1, "end_id": 0,
                            "is_accumulated": True})
def _beam_search(ins, attrs):
    import numpy as _np
    pre_ids = _np.asarray(first(ins, "pre_ids")).reshape(-1)
    pre_scores = _np.asarray(first(ins, "pre_scores")).reshape(-1)
    ids_in = first(ins, "ids")
    cand_ids = (_np.asarray(ids_in) if ids_in is not None else None)
    cand_scores = _np.asarray(first(ins, "scores"))
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    lods = (attrs.get("_lod") or {}).get("pre_ids")
    if lods and lods[0] and len(lods[0]) >= 1:
        src_offs = _np.asarray(lods[0][0], _np.int64)
    else:  # single source covering all branches
        src_offs = _np.asarray([0, len(pre_ids)], _np.int64)
    sel_ids, sel_scores = [], []
    sel_counts_per_branch = _np.zeros(len(pre_ids), _np.int64)
    src_counts = []
    for s in range(len(src_offs) - 1):
        lo, hi = int(src_offs[s]), int(src_offs[s + 1])
        cands = []  # (score, token, parent_branch)
        for b in range(lo, hi):
            if pre_ids[b] == end_id and pre_ids[b] != -1:
                # finished branch: carries itself forward unchanged
                cands.append((float(pre_scores[b]), end_id, b))
                continue
            for k in range(cand_scores.shape[1]):
                tok = (int(cand_ids[b, k]) if cand_ids is not None else k)
                cands.append((float(cand_scores[b, k]), tok, b))
        cands.sort(key=lambda c: -c[0])
        top = cands[:beam_size]
        top.sort(key=lambda c: (c[2], -c[0]))  # group rows by parent branch
        for sc, tok, b in top:
            sel_ids.append(tok)
            sel_scores.append(sc)
            sel_counts_per_branch[b] += 1
        src_counts.append(len(top))
    lod0 = _np.concatenate([[0], _np.cumsum(src_counts)])
    lod1 = _np.concatenate([[0], _np.cumsum(sel_counts_per_branch)])
    o_ids = jnp.asarray(_np.asarray(sel_ids, _np.int64).reshape(-1, 1))
    o_sc = jnp.asarray(_np.asarray(sel_scores, _np.float32).reshape(-1, 1))
    new_lod = (tuple(int(v) for v in lod0), tuple(int(v) for v in lod1))
    return {"selected_ids": [o_ids], "selected_scores": [o_sc],
            "parent_idx": [jnp.asarray(
                _np.repeat(_np.arange(len(pre_ids)), sel_counts_per_branch))],
            "_lod": {"selected_ids": [new_lod],
                     "selected_scores": [new_lod]}}


@register_op("beam_search_decode", needs_lod=True, stateful=True,
             no_grad=True, attr_defaults={"beam_size": 1, "end_id": 0})
def _beam_search_decode(ins, attrs):
    """Backtracks a LoDTensorArray of per-step beam selections into full
    hypotheses (reference: beam_search_decode_op.cc). Reads the arrays from
    the scope via _ctx (LoDTensorArray is a host container)."""
    import numpy as _np
    ctx = attrs["_ctx"]
    end_id = int(attrs.get("end_id", 0))
    ids_arr = ctx.scope.find_var(ctx.op.input("Ids")[0]).value()
    scores_arr = ctx.scope.find_var(ctx.op.input("Scores")[0]).value()
    steps = []
    for t in range(len(ids_arr)):
        it, st = ids_arr[t], scores_arr[t]
        steps.append((
            _np.asarray(it.array).reshape(-1),
            _np.asarray(st.array).reshape(-1),
            [_np.asarray(l, _np.int64) for l in it.lod()]))
    if not steps:
        raise ValueError("beam_search_decode: empty Ids array")
    n_src = len(steps[0][2][0]) - 1
    hyps, hyp_scores = [[] for _ in range(n_src)], [[] for _ in range(n_src)]

    def parent_of(lod1, row):
        return int(_np.searchsorted(lod1, row, side="right") - 1)

    T = len(steps)
    last_ids, last_scores, last_lod = steps[-1]
    for s in range(n_src):
        lo, hi = int(steps[-1][2][0][s]), int(steps[-1][2][0][s + 1])
        for row in range(lo, hi):
            toks, r = [], row
            for t in range(T - 1, -1, -1):
                ids_t, sc_t, lod_t = steps[t]
                toks.append(int(ids_t[r]))
                if t > 0:
                    r = parent_of(lod_t[1], r)
            toks.reverse()
            # trim everything after the first end_id
            if end_id in toks:
                toks = toks[:toks.index(end_id) + 1]
            hyps[s].append(toks)
            hyp_scores[s].append(float(last_scores[row]))
    flat_ids, flat_sc, lens, src_counts = [], [], [], []
    for s in range(n_src):
        src_counts.append(len(hyps[s]))
        for toks, sc in zip(hyps[s], hyp_scores[s]):
            flat_ids.extend(toks)
            flat_sc.extend([sc] * len(toks))
            lens.append(len(toks))
    lod0 = _np.concatenate([[0], _np.cumsum(src_counts)])
    lod1 = _np.concatenate([[0], _np.cumsum(lens)])
    new_lod = (tuple(int(v) for v in lod0), tuple(int(v) for v in lod1))
    return {"SentenceIds": [jnp.asarray(_np.asarray(flat_ids, _np.int32))],
            "SentenceScores": [jnp.asarray(_np.asarray(flat_sc, _np.float32))],
            "_lod": {"SentenceIds": [new_lod], "SentenceScores": [new_lod]}}


# --------------------------------------------------------------------------
# reference op-type aliases: serialized reference programs use the raw op
# names `gru` / `lstmp` (gru_op.cc, lstmp_op.cc); our layers emit the
# dynamic_* names. Same kernels, registered twice.
# --------------------------------------------------------------------------
register_op("gru", needs_lod=True,
            diff_inputs=["Input", "Weight", "Bias", "H0"],
            attr_defaults={"is_reverse": False, "origin_mode": False,
                           "gate_activation": "sigmoid",
                           "activation": "tanh"})(_dynamic_gru)
register_op("lstmp", needs_lod=True,
            diff_inputs=["Input", "Weight", "ProjWeight", "Bias", "H0", "C0"],
            attr_defaults={"use_peepholes": True, "is_reverse": False,
                           "gate_activation": "sigmoid",
                           "cell_activation": "tanh",
                           "candidate_activation": "tanh",
                           "proj_activation": "tanh"})(_dynamic_lstmp)
