"""Flash attention (Pallas TPU) — replaces the reference's fused transformer
attention kernel (reference: operators/fused/multihead_matmul_op.cu, which
does QK^T→softmax→V with cuBLAS batched GEMMs in one op).

TPU design (FlashAttention-2 style, written for the MXU/VMEM hierarchy):

Forward: grid (B·H, S/blk_q, S/blk_k) with the K dimension innermost —
Pallas TPU executes the innermost grid dimension sequentially, so the
online-softmax state (f32 accumulator, running row-max m, normalizer l)
lives in VMEM scratch and is carried across K blocks. Only one
(blk_q × D) Q tile and one (blk_k × D) K/V tile are resident per step, so
sequence length is NOT bounded by VMEM (the round-1 full-K/V-in-VMEM
S≤2048 restriction is gone); VMEM per step is ~4·blk·D·4B ≈ 400KB at
blk=128, D=64. Score tiles hit the MXU via jnp.dot with
preferred_element_type=f32; softmax runs in f32 on the VPU. The kernel
also emits the log-sum-exp per row, the residual the backward needs.

Backward: two Pallas kernels (the FlashAttention-2 recipe):
  dK/dV: grid (B·H, S/blk_k, S/blk_q) accumulating over Q blocks,
  dQ:    grid (B·H, S/blk_q, S/blk_k) accumulating over K blocks,
both recomputing P = exp(scale·QKᵀ − lse) tile-by-tile from the stored
lse — no O(S²) materialization anywhere. delta = rowsum(dO ∘ O) is a
cheap elementwise+reduce that XLA fuses outside the kernels.

Causal masking is top-left aligned; fully-masked K blocks are skipped
with pl.when (upper-triangular blocks cost nothing).

CPU/tests: `interpret_mode(True)` (or PADDLE_TPU_FLASH_INTERPRET=1) runs
the very same kernels through the Pallas interpreter so the suite
exercises the real kernel, not a fallback. Ragged lengths (S or Sk not
divisible by the block) STAY on the kernel: boundary blocks are handled
by in-kernel bounds masking, with padded tile regions zeroed at load
(they are uninitialized — NaN under the interpreter — and 0·NaN would
leak through the contractions). The pure-XLA reference path remains
only for backends with no Pallas at all.
"""
from __future__ import annotations

import contextlib
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

if _HAS_PALLAS:
    # jax renamed TPUCompilerParams -> CompilerParams (~0.6); accept both,
    # and degrade to the no-pallas path (like any other pallas
    # incompatibility) if a future jax drops both names
    _compiler_params = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    if _compiler_params is None:
        _HAS_PALLAS = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30  # finite mask value: avoids inf-inf → NaN in the rescale
# Mosaic requires the last two dims of every block shape to be divisible
# by (8, 128) or equal to the array dims. Row-statistics arrays (lse,
# delta) therefore carry a broadcast 128-lane minor dimension on the
# wire — the same layout jax's own TPU flash kernel uses for l/m.
LANES = 128

_INTERPRET = os.environ.get("PADDLE_TPU_FLASH_INTERPRET", "") in ("1", "true")


def interpret_mode(enable: bool):
    """Force the Pallas kernels through the interpreter (CPU testing)."""
    global _INTERPRET
    _INTERPRET = bool(enable)


@contextlib.contextmanager
def interpret_guard():
    global _INTERPRET
    prev = _INTERPRET
    _INTERPRET = True
    try:
        yield
    finally:
        _INTERPRET = prev


def _ref_attention(q, k, v, sm_scale, causal=False):
    """Pure-jax reference: q,k,v [B,H,S,D]. Matches the kernel's
    f32-accumulation contract: bf16 operands accumulate in f32
    (preferred_element_type), so softmax statistics are f32 — this is
    also the CPU dispatch target of the flash path, and the einsum path
    in ops/attention_ops.py follows the same contract."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        S, Sk = q.shape[2], k.shape[2]
        mask = jnp.arange(S)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _mxu_operand(x):
    """Dot operand in MXU-native dtype: bf16 tiles feed the MXU dots
    directly (f32 accumulation comes from preferred_element_type), which
    runs at full systolic-array rate; anything else upcasts to f32. The
    softmax statistics (m/l/lse/delta) and accumulators stay f32 either
    way."""
    return x if x.dtype == jnp.bfloat16 else x.astype(jnp.float32)


def _mask_cols(s, k_start, blk_q, blk_k, sk_len):
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(cols < sk_len, s, NEG_INF)


def _zero_pad_rows(t, start, limit):
    """Zero a tile's rows past the true length — padded regions of a
    boundary block are UNINITIALIZED (NaN under the interpreter), and
    0·NaN = NaN would leak through the contractions."""
    rows = start + jax.lax.broadcasted_iota(jnp.int32, t.shape, 0)
    return jnp.where(rows < limit, t, 0.0)


def _valid_rows(q_start, blk_q, blk_k, s_len):
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    return rows < s_len


def _mask_scores(s, q_start, k_start, blk_q, blk_k):
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(rows >= cols, s, NEG_INF)


def _keep_mask(seed, bh, q_start, k_start, blk_q, blk_k, rate):
    """Deterministic counter-based dropout mask for one score tile:
    a Wang-style integer mix over (seed, batch·head, absolute row,
    absolute col) — plain VPU integer ops, so the SAME mask regenerates
    in the backward kernels and in the interpreter (the TPU PRNG
    primitives have no CPU interpret rule). Keep probability 1-rate to
    24-bit resolution."""
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    x = (rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         ^ cols.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         ^ (seed.astype(jnp.uint32) + jnp.uint32(0x27D4EB2F)
            * bh.astype(jnp.uint32)))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(int(rate * float(1 << 24)))
    return ((x & jnp.uint32(0xFFFFFF)) >= thresh)


def keep_mask_reference(seed, bh, rows, cols, rate):
    """Numpy twin of _keep_mask for exact-parity tests."""
    rows = np.asarray(rows, np.uint32)[:, None]
    cols = np.asarray(cols, np.uint32)[None, :]
    x = (rows * np.uint32(0x9E3779B1)
         ^ cols * np.uint32(0x85EBCA77)
         ^ np.uint32((seed + 0x27D4EB2F * bh) & 0xFFFFFFFF))
    x = x ^ (x >> np.uint32(16))
    x = (x * np.uint32(0x7FEB352D)) & np.uint32(0xFFFFFFFF)
    x = x ^ (x >> np.uint32(15))
    x = (x * np.uint32(0x846CA68B)) & np.uint32(0xFFFFFFFF)
    x = x ^ (x >> np.uint32(16))
    thresh = np.uint32(int(rate * float(1 << 24)))
    return (x & np.uint32(0xFFFFFF)) >= thresh


def _with_optional_bias(kernel, n_named, has_bias):
    """Adapter shared by all three pallas_calls: refs arrive as
    (inputs..., outputs..., scratch...); the kernels take bias_ref (or
    None) right after their ``n_named`` data inputs."""
    def _inner(*refs):
        named = refs[:n_named]
        if has_bias:
            return kernel(*named, refs[n_named], *refs[n_named + 1:])
        return kernel(*named, None, *refs[n_named:])
    return _inner


def _append_bias_input(in_specs, args, bias, H, blk_k, k_axis):
    """Append the key-padding bias input as [B, 1, Sk] (cast once to
    f32) — the middle singleton makes the block's second-to-last dim
    equal to the array dim, which Mosaic accepts for any size.
    ``k_axis``: which grid dimension indexes K blocks (1 for the bwd-kv
    kernel, 2 for fwd/bwd-q)."""
    if bias is None:
        return
    if k_axis == 1:
        spec = pl.BlockSpec((1, 1, blk_k), lambda b, j, i: (b // H, 0, j))
    else:
        spec = pl.BlockSpec((1, 1, blk_k), lambda b, i, j: (b // H, 0, j))
    in_specs.append(spec)
    args.append(bias.astype(jnp.float32).reshape(bias.shape[0], 1,
                                                 bias.shape[-1]))


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------
def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, sm_scale, causal, blk_q, blk_k, dropout_rate,
                has_bias, sk_len=0):
    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * blk_q
    k_start = ki * blk_k

    def _block():
        q = _mxu_operand(q_ref[0])
        k = _mxu_operand(k_ref[0])
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [blk_q, blk_k]
        if has_bias:
            # key-padding bias [B, 1, Sk] broadcast over query rows (the
            # reference BiasQK padding-mask form); clamped so -inf masks
            # can't produce inf-inf → NaN in the rescale
            s = s + jnp.maximum(bias_ref[0], NEG_INF)
        if sk_len:
            # ragged Sk: the last K block is padded — mask the columns
            # past the true length (padded bias/K values are overridden)
            s = _mask_cols(s, k_start, blk_q, blk_k, sk_len)
        if causal:
            s = _mask_scores(s, q_start, k_start, blk_q, blk_k)
        m_prev = m_ref[:, :1]                             # [blk_q, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # the normalizer l uses the FULL probabilities (softmax first);
        # dropout scales only the value accumulation — elementwise, so it
        # commutes with the final 1/l
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              blk_q, blk_k, dropout_rate)
            p = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        v = _mxu_operand(v_ref[0])
        if sk_len:
            v = _zero_pad_rows(v, k_start, sk_len)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # blocks strictly above the diagonal are fully masked: skip them
        @pl.when(k_start <= q_start + blk_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        # fully-masked rows (every key at the clamped NEG_INF, e.g. a
        # key-padding bias masking ALL keys): emit zeros, and poison the
        # lse to +1e30 so the backward's exp(s - lse) underflows to 0 —
        # zero grads instead of data-dependent garbage. Same semantics
        # as _ref_attention_bias.
        dead = m <= NEG_INF * 0.5
        safe_l = jnp.where(dead, 1.0, l)
        o_ref[0] = jnp.where(dead, 0.0,
                             acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse_col = jnp.where(dead, -NEG_INF, m + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse_col, lse_ref.shape[1:])


def _pallas_fwd(q, k, v, seed, sm_scale, causal, blk_q, blk_k,
                dropout_rate=0.0, bias=None):
    B, H, S, D = q.shape
    Sk = k.shape[2]
    qf, kf, vf = (t.reshape(B * H, t.shape[2], D) for t in (q, k, v))
    grid = (B * H, pl.cdiv(S, blk_q), pl.cdiv(Sk, blk_k))
    has_bias = bias is not None
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             blk_q=blk_q, blk_k=blk_k,
                             dropout_rate=dropout_rate, has_bias=has_bias,
                             sk_len=0 if Sk % blk_k == 0 else Sk)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                # seed
        pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
    ]
    args = [seed, qf, kf, vf]
    _append_bias_input(in_specs, args, bias, H, blk_k, k_axis=2)

    o, lse = pl.pallas_call(
        _with_optional_bias(kern, 4, has_bias),
        out_shape=(jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
                   jax.ShapeDtypeStruct((B * H, S, LANES), jnp.float32)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, blk_q, LANES),
                                lambda b, i, j: (b, i, 0))),
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET and not _on_tpu(),
    )(*args)
    # lse stays in its (B·H, S, LANES) wire form — the backward consumes
    # it as-is, so no slice-then-rebroadcast materialization
    return o.reshape(B, H, S, D), lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------
def _bwd_kv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, bias_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                   *, sm_scale, causal, blk_q, blk_k, dropout_rate,
                   has_bias, s_len=0, sk_len=0):
    bh, ki, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = qi * blk_q
    k_start = ki * blk_k

    def _block():
        q = _mxu_operand(q_ref[0])
        kk = _mxu_operand(k_ref[0])
        vv = _mxu_operand(v_ref[0])
        do = _mxu_operand(do_ref[0])
        if s_len:
            q = _zero_pad_rows(q, q_start, s_len)
            do = _zero_pad_rows(do, q_start, s_len)
        lse = lse_ref[0][:, :1]                           # [blk_q, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if has_bias:
            s = s + jnp.maximum(bias_ref[0], NEG_INF)
        if causal:
            s = _mask_scores(s, q_start, k_start, blk_q, blk_k)
        p = jnp.exp(s - lse)                              # [blk_q, blk_k]
        if s_len:
            # ragged S: padded Q/dO/lse/delta rows would contribute
            # garbage to EVERY dk/dv column — zero their probabilities
            p = jnp.where(_valid_rows(q_start, blk_q, blk_k, s_len),
                          p, 0.0)
        dp = jax.lax.dot_general(
            do, vv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # dO·Vᵀ
        if dropout_rate > 0.0:
            # regenerate the forward's mask; dV sees the dropped/scaled
            # probabilities, dS sees the masked dP (softmax-bwd delta
            # identity still holds: delta = rowsum(dO∘O))
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              blk_q, blk_k,
                              dropout_rate).astype(jnp.float32)
            p_eff = p * keep / (1.0 - dropout_rate)
            dp = dp * keep / (1.0 - dropout_rate)
        else:
            p_eff = p
        dv_acc[...] += jax.lax.dot_general(
            p_eff.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # p'ᵀ·dO
        ds = p * (dp - delta) * sm_scale
        if s_len:
            # padded lse/delta rows are NaN and 0·NaN = NaN — hard-zero
            ds = jnp.where(_valid_rows(q_start, blk_q, blk_k, s_len),
                           ds, 0.0)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # dsᵀ·Q

    if causal:
        @pl.when(k_start <= q_start + blk_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_q_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                  delta_ref, bias_ref, dq_ref, dq_acc,
                  *, sm_scale, causal, blk_q, blk_k, dropout_rate,
                  has_bias, sk_len=0):
    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q_start = qi * blk_q
    k_start = ki * blk_k

    def _block():
        q = _mxu_operand(q_ref[0])
        kk = _mxu_operand(k_ref[0])
        vv = _mxu_operand(v_ref[0])
        do = _mxu_operand(do_ref[0])
        if sk_len:
            kk = _zero_pad_rows(kk, k_start, sk_len)
            vv = _zero_pad_rows(vv, k_start, sk_len)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if has_bias:
            s = s + jnp.maximum(bias_ref[0], NEG_INF)
        if sk_len:
            # ragged Sk: padded K/V columns must not leak into dq
            s = _mask_cols(s, k_start, blk_q, blk_k, sk_len)
        if causal:
            s = _mask_scores(s, q_start, k_start, blk_q, blk_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, vv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              blk_q, blk_k,
                              dropout_rate).astype(jnp.float32)
            dp = dp * keep / (1.0 - dropout_rate)
        ds = p * (dp - delta) * sm_scale
        dq_acc[...] += jnp.dot(ds.astype(kk.dtype), kk,
                               preferred_element_type=jnp.float32)

    if causal:
        @pl.when(k_start <= q_start + blk_q - 1)
        def _():
            _block()
    else:
        _block()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _pallas_bwd(q, k, v, o, lse, seed, g, sm_scale, causal, blk_q, blk_k,
                dropout_rate=0.0, bias=None):
    B, H, S, D = q.shape
    Sk = k.shape[2]
    BH = B * H
    qf, kf, vf, of, gf = (t.reshape(BH, t.shape[2], D)
                          for t in (q, k, v, o, g))
    # row statistics enter the kernels with the broadcast 128-lane minor
    # dim (see LANES), materialized HERE as transients — the residual
    # held from forward to backward is the 2-D (BH, S) slice, 1/128th
    # the memory (at S=2048 the lane form would pin 32 MB per layer).
    lsef = jnp.broadcast_to(lse.reshape(BH, S)[:, :, None], (BH, S, LANES))
    delta2 = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32), -1)
    delta = jnp.broadcast_to(delta2[:, :, None], (BH, S, LANES))
    interp = _INTERPRET and not _on_tpu()
    has_bias = bias is not None
    ragged_s = 0 if S % blk_q == 0 else S
    ragged_sk = 0 if Sk % blk_k == 0 else Sk
    common = dict(sm_scale=sm_scale, causal=causal, blk_q=blk_q,
                  blk_k=blk_k, dropout_rate=dropout_rate,
                  has_bias=has_bias)

    kv_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                    # seed
        pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),   # q
        pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),   # k
        pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),   # v
        pl.BlockSpec((1, blk_q, D), lambda b, j, i: (b, i, 0)),   # do
        pl.BlockSpec((1, blk_q, LANES), lambda b, j, i: (b, i, 0)),  # lse
        pl.BlockSpec((1, blk_q, LANES), lambda b, j, i: (b, i, 0)),  # delta
    ]
    kv_args = [seed, qf, kf, vf, gf, lsef, delta]
    _append_bias_input(kv_specs, kv_args, bias, H, blk_k, k_axis=1)

    dk, dv = pl.pallas_call(
        _with_optional_bias(
            functools.partial(_bwd_kv_kernel, s_len=ragged_s,
                              sk_len=ragged_sk, **common), 7, has_bias),
        out_shape=(jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, D), v.dtype)),
        grid=(BH, pl.cdiv(Sk, blk_k), pl.cdiv(S, blk_q)),
        in_specs=kv_specs,
        out_specs=(pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0))),
        scratch_shapes=[pltpu.VMEM((blk_k, D), jnp.float32),
                        pltpu.VMEM((blk_k, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(*kv_args)

    q_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                    # seed
        pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),   # q
        pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),   # k
        pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),   # v
        pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),   # do
        pl.BlockSpec((1, blk_q, LANES), lambda b, i, j: (b, i, 0)),  # lse
        pl.BlockSpec((1, blk_q, LANES), lambda b, i, j: (b, i, 0)),  # delta
    ]
    q_args = [seed, qf, kf, vf, gf, lsef, delta]
    _append_bias_input(q_specs, q_args, bias, H, blk_k, k_axis=2)

    dq = pl.pallas_call(
        _with_optional_bias(
            functools.partial(_bwd_q_kernel, sk_len=ragged_sk, **common),
            7, has_bias),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=(BH, pl.cdiv(S, blk_q), pl.cdiv(Sk, blk_k)),
        in_specs=q_specs,
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(*q_args)

    shape = (B, H, S, D)
    return dq.reshape(shape), dk.reshape(B, H, Sk, D), dv.reshape(B, H, Sk, D)


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------
_BLOCK_OVERRIDE = None  # (blk_q, blk_k) set by block_override()


@contextlib.contextmanager
def block_override(blk_q, blk_k):
    """Pin the kernel block sizes inside the context — the hardware
    bring-up sweep (tools/flash_smoke.py) uses this to measure
    blk_q×blk_k configurations; the override applies to forward AND the
    custom-vjp backward, so wrap the whole grad computation."""
    global _BLOCK_OVERRIDE
    prev = _BLOCK_OVERRIDE
    _BLOCK_OVERRIDE = (int(blk_q), int(blk_k))
    try:
        yield
    finally:
        _BLOCK_OVERRIDE = prev


_TUNED = None  # lazy: (seq_len, head_dim) -> (blk_q, blk_k) from sweep


def _tuned_blocks(S, D):
    """Best measured (blk_q, blk_k) for the nearest swept seq length at
    the SAME head_dim — the hardware sweep (tools/flash_smoke.py) banks
    its fastest config per (seq, head_dim) bucket, fingerprint-stamped
    so a kernel edit invalidates it. Returns None (defaults apply) when
    no valid table exists, the fingerprint mismatches, or no entry
    matches this head_dim (blocks tuned at another D could blow the
    VMEM budget here)."""
    global _TUNED
    if _TUNED is None:
        import json
        table = {}
        try:
            from tools.flash_smoke import kernel_fingerprint, tuning_path
            data = json.load(open(tuning_path()))
            if data.get("kfp") == kernel_fingerprint():
                for k, v in (data.get("entries") or {}).items():
                    s, d = k.split(":")
                    table[(int(s), int(d))] = (int(v[0]), int(v[1]))
        except Exception:
            pass  # no table / stale / not importable: defaults apply
        _TUNED = table
    cands = [sd for sd in _TUNED if sd[1] == D]
    if not cands:
        return None
    nearest = min(cands, key=lambda sd: abs(sd[0] - S))
    return _TUNED[nearest]


def _block_sizes(S, Sk, D=64):
    """Ragged S/Sk are supported via in-kernel bounds masking, so blocks
    need not divide the lengths. Inputs smaller than the default block
    use the EXACT dimension as the block — a block equal to the array
    dim is always Mosaic-legal regardless of (8, 128) alignment, so tiny
    and tiny-ragged shapes lower without padding games. A banked
    hardware sweep overrides the defaults (see _tuned_blocks)."""
    if _BLOCK_OVERRIDE is not None:
        bq, bk = _BLOCK_OVERRIDE
        return (S if S <= bq else bq), (Sk if Sk <= bk else bk)
    tuned = _tuned_blocks(max(S, Sk), D)
    dq, dk = tuned if tuned else (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    blk_q = S if S <= dq else dq
    blk_k = Sk if Sk <= dk else dk
    return blk_q, blk_k


def _pallas_ok(q, k):
    # ragged lengths are handled in-kernel (bounds masking); the only
    # remaining requirement is a Pallas backend (TPU, or the interpreter
    # for tests). q/k stay in the signature for future shape gating.
    del q, k
    return _HAS_PALLAS and (_on_tpu() or _INTERPRET)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_pallas(q, k, v, seed, bias, sm_scale, causal, dropout_rate):
    blk_q, blk_k = _block_sizes(q.shape[2], k.shape[2], q.shape[3])
    o, _ = _pallas_fwd(q, k, v, seed, sm_scale, causal, blk_q, blk_k,
                       dropout_rate, bias=bias)
    return o


def _fp_fwd(q, k, v, seed, bias, sm_scale, causal, dropout_rate):
    blk_q, blk_k = _block_sizes(q.shape[2], k.shape[2], q.shape[3])
    o, lse = _pallas_fwd(q, k, v, seed, sm_scale, causal, blk_q, blk_k,
                         dropout_rate, bias=bias)
    # residual: the 2-D row stat, not the 128-lane wire form (128× less
    # memory held across fwd→bwd; the bwd re-broadcasts transiently)
    return o, (q, k, v, o, lse[:, :, 0], seed, bias)


def _fp_bwd(sm_scale, causal, dropout_rate, res, g):
    q, k, v, o, lse, seed, bias = res
    blk_q, blk_k = _block_sizes(q.shape[2], k.shape[2], q.shape[3])
    dq, dk, dv = _pallas_bwd(q, k, v, o, lse, seed, g, sm_scale, causal,
                             blk_q, blk_k, dropout_rate, bias=bias)
    dseed = np.zeros(seed.shape, jax.dtypes.float0)  # int arg: zero tangent
    dbias = None if bias is None else jnp.zeros_like(bias)  # mask input
    return dq, dk, dv, dseed, dbias


_flash_pallas.defvjp(_fp_fwd, _fp_bwd)

# numpy, NOT jnp: a lazily-created jnp array inside someone's jit trace
# would cache a tracer in this global and poison every later trace
_ZERO_SEED = np.zeros((1,), np.int32)


def flash_attention(q, k, v, sm_scale, causal=False, dropout_rate=0.0,
                    dropout_seed=None, bias=None):
    """q,k,v: [B,H,S,D] → [B,H,S,D]. Pallas flash kernel when the backend
    (or interpret mode) supports it; pure-XLA reference otherwise.
    dropout_rate > 0 applies attention-probability dropout INSIDE the
    kernel (mask regenerated in the backward from dropout_seed, an int32
    [1] array — pass a fresh per-step value when training). ``bias`` is
    an additive key-padding mask [B, Sk] broadcast over query rows (the
    reference BiasQK padding form); it is a constant wrt gradients."""
    if dropout_rate > 0.0 and dropout_seed is None:
        # a silent default seed would drop the SAME attention entries
        # every step — training bias with no symptom
        raise ValueError(
            "flash_attention: dropout_rate > 0 requires dropout_seed "
            "(int32 [1] array, fresh per training step)")
    if not (q.dtype == k.dtype == v.dtype):
        # the in-kernel MXU-native dots require matching operand dtypes
        # (bf16 tiles are fed to the MXU unconverted) — normalize mixed
        # inputs up front instead of failing inside the kernel trace
        ct = jnp.result_type(q.dtype, k.dtype, v.dtype)
        q, k, v = (t.astype(ct) for t in (q, k, v))
    if _pallas_ok(q, k):
        if dropout_seed is None:
            dropout_seed = _ZERO_SEED
        return _flash_pallas(q, k, v, dropout_seed, bias, sm_scale,
                             causal, float(dropout_rate))
    if dropout_rate > 0.0:
        raise NotImplementedError(
            "attention dropout requires the Pallas path (a TPU backend "
            "or interpret_mode(True))")
    o = _ref_attention(q, k, v, sm_scale, causal) if bias is None else \
        _ref_attention_bias(q, k, v, sm_scale, causal, bias)
    return o


def _ref_attention_bias(q, k, v, sm_scale, causal, bias):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    s = s + jnp.maximum(bias.astype(jnp.float32), NEG_INF)[:, None, None, :]
    if causal:
        S, Sk = q.shape[2], k.shape[2]
        mask = jnp.arange(S)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    # fully-masked rows → zeros (matches the Pallas kernel's finalize)
    dead = jnp.max(s, axis=-1, keepdims=True) <= NEG_INF * 0.5
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    p = jnp.where(dead, 0.0, p).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
