"""Flash attention (Pallas TPU) — replaces the reference's fused transformer
attention kernel (reference: operators/fused/multihead_matmul_op.cu, which
does QK^T→softmax→V with cuBLAS batched GEMMs in one op).

TPU design: one pallas_call per (batch·head, q-block): the q block and the
full K/V for that head live in VMEM; scores tile onto the MXU; softmax is
computed in fp32 on the VPU. For round-1 the full-S K/V fits VMEM for
BERT-scale sequences (S≤2048, d≤128 → ≤2·2048·128·4B = 2MB); the blocked
online-softmax variant (and ring attention over ICI for long context) hangs
off the same entry point.

Backward: flash-style recompute — custom_vjp whose bwd re-derives grads
from the pure-jax reference attention under XLA (one extra forward, fused).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

DEFAULT_BLOCK_Q = 256


def _ref_attention(q, k, v, sm_scale, causal=False):
    """Pure-jax reference: q,k,v [B,H,S,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, blk_q):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale        # [blk_q, d]
    k = k_ref[0].astype(jnp.float32)                   # [S, d]
    v = v_ref[0].astype(jnp.float32)                   # [S, d]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [blk_q, S]
    if causal:
        S = k.shape[0]
        rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32,
                                                     (blk_q, S), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (blk_q, S), 1)
        s = jnp.where(rows >= cols, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    o_ref[0] = jnp.dot(p, v,
                       preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _pallas_attention(q, k, v, sm_scale, causal=False,
                      blk_q=DEFAULT_BLOCK_Q):
    B, H, S, D = q.shape
    blk_q = min(blk_q, S)
    assert S % blk_q == 0, (S, blk_q)
    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    grid = (B * H, S // blk_q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          blk_q=blk_q),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
    )(qf, kf, vf)
    return out.reshape(B, H, S, D)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, sm_scale, causal=False):
    """q,k,v: [B,H,S,D] → [B,H,S,D]."""
    if _HAS_PALLAS and _on_tpu():
        return _pallas_attention(q, k, v, sm_scale, causal)
    return _ref_attention(q, k, v, sm_scale, causal)


def _fa_fwd(q, k, v, sm_scale, causal):
    return flash_attention(q, k, v, sm_scale, causal), (q, k, v)


def _fa_bwd(sm_scale, causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _ref_attention(q, k, v, sm_scale,
                                                    causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
