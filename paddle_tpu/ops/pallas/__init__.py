"""Pallas TPU kernels — hand-tiled hot ops (the TPU-native replacement for
the reference's fused CUDA ops under operators/fused/)."""
