"""Operator registry — TPU-native equivalent of the reference's OpInfoMap +
kernel registry (reference: paddle/fluid/framework/op_info.h:124,
op_registry.h:223, operator.h:442).

Design inversion for TPU: the reference registers per-device C++ kernel
functions chosen at run time by OpKernelType; here every op has ONE pure
JAX kernel ``kernel(ins, attrs) -> outs`` that the executor either applies
eagerly (interpreter oracle) or traces into a single XLA computation for the
whole block (compiled mode). Device placement, layout, fusion and memory are
XLA's job.

Gradient strategy (replaces reference GradOpDescMaker C++ classes,
grad_op_desc_maker.h): by default an op's grad is derived mechanically from
its forward kernel with ``jax.vjp``. The generated ``<op>_grad`` op follows
the reference slot convention: inputs = forward inputs + forward outputs +
``<out_slot>@GRAD``; outputs = ``<in_slot>@GRAD``. When forward+backward are
jitted together XLA CSE merges the re-traced forward, so this costs nothing
at run time. Ops whose reference grad semantics differ (dropout via Mask,
integer-indexed scatters, …) register custom grad ops / grad makers.

Kernel calling convention:
    ins:   dict slot_name -> list of jnp arrays (or None for absent
           dispensable slots). Duplicable slots hold len>1 lists.
    attrs: dict of python attr values. The executor injects:
           ``_rng``   (jax PRNG key) if the op declared needs_rng,
           ``_ctx``   (ExecContext) if the op declared needs_ctx —
                      such ops are stateful and break pure tracing.
    returns: dict slot_name -> list of jnp arrays.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class OpInfo:
    __slots__ = (
        "type", "kernel", "infer_shape", "infer_dtype", "grad_maker",
        "no_grad", "needs_rng", "stateful", "diff_input_slots",
        "diff_output_slots", "attr_defaults", "input_slots", "output_slots",
        "needs_lod", "host_inputs",
    )

    def __init__(self, type_: str):
        self.type = type_
        self.kernel: Optional[Callable] = None
        self.infer_shape: Optional[Callable] = None
        self.infer_dtype: Optional[Callable] = None
        self.grad_maker: Optional[Callable] = None  # custom: (op) -> [opdesc dicts]
        self.no_grad = False
        self.needs_rng = False
        self.stateful = False
        self.diff_input_slots: Optional[Sequence[str]] = None
        self.diff_output_slots: Optional[Sequence[str]] = None
        self.attr_defaults: Dict[str, Any] = {}
        self.input_slots: Optional[Sequence[str]] = None
        self.output_slots: Optional[Sequence[str]] = None
        self.needs_lod = False
        # input slots whose VALUES the kernel reads host-side (trace-time):
        # the executor must run blocks containing such ops interpreted —
        # feeding them as traced jit arguments would TracerError
        self.host_inputs: Sequence[str] = ()


class OpInfoMap:
    def __init__(self):
        self._map: Dict[str, OpInfo] = {}

    def get(self, type_: str) -> OpInfo:
        info = self._map.get(type_)
        if info is None:
            raise KeyError(f"operator '{type_}' is not registered")
        return info

    def has(self, type_: str) -> bool:
        return type_ in self._map

    def get_or_create(self, type_: str) -> OpInfo:
        if type_ not in self._map:
            self._map[type_] = OpInfo(type_)
        return self._map[type_]

    def all_op_types(self):
        return sorted(self._map.keys())


OPS = OpInfoMap()


def resolve_base_info(op_type: str):
    """Registry info for an op type, resolving *_grad / *_grad_grad names
    to their base op. None for unknown types. Shared by the executor's
    compilability checks and the ir-level segmentation analysis — ONE
    resolver so the two can never classify the same op differently."""
    t = op_type
    if OPS.has(t):
        return OPS.get(t)
    while t.endswith("_grad"):
        t = t[:-5]
        if OPS.has(t):
            return OPS.get(t)
    return None


def register_op(type_: str, *, no_grad: bool = False, needs_rng: bool = False,
                stateful: bool = False, needs_lod: bool = False,
                diff_inputs: Optional[Sequence[str]] = None,
                diff_outputs: Optional[Sequence[str]] = None,
                infer_shape: Optional[Callable] = None,
                attr_defaults: Optional[Dict[str, Any]] = None,
                inputs: Optional[Sequence[str]] = None,
                outputs: Optional[Sequence[str]] = None,
                host_inputs: Optional[Sequence[str]] = None):
    """Decorator registering a forward kernel under op name ``type_``.

    ``needs_lod``: the kernel consumes LoD (variable-length sequence)
    metadata. The executor injects ``attrs["_lod"] = {slot: [levels|None]}``
    where ``levels`` is a tuple of offset-tuples — HOST-STATIC under jit
    (the jit cache is keyed per feed-LoD bucket), so segment ids derived
    from it are XLA constants (TPU-friendly; replaces the reference's
    per-step dynamic LoD InferShape, lod_tensor.h:104). Kernels may return
    a special ``"_lod"`` entry ``{out_slot: [levels|None]}`` to set output
    LoD; absent that, the executor shares the first lod-bearing input's LoD
    with any output of matching leading length (the reference's ShareLoD
    default)."""
    def deco(fn: Callable):
        info = OPS.get_or_create(type_)
        info.kernel = fn
        info.no_grad = no_grad
        info.needs_rng = needs_rng
        info.stateful = stateful
        info.needs_lod = needs_lod
        info.diff_input_slots = diff_inputs
        info.diff_output_slots = diff_outputs
        info.infer_shape = infer_shape
        info.attr_defaults = dict(attr_defaults or {})
        info.input_slots = inputs
        info.output_slots = outputs
        info.host_inputs = tuple(host_inputs or ())
        return fn
    return deco


def register_grad_maker(type_: str):
    """Decorator registering a custom grad maker for op ``type_``. The maker
    receives the forward Operator and a dict mapping each forward-output var
    name to its grad var name, and returns a list of op-desc dicts:
    ``{"type":..., "inputs": {...}, "outputs": {...}, "attrs": {...}}``."""
    def deco(fn: Callable):
        OPS.get_or_create(type_).grad_maker = fn
        return fn
    return deco


def mark_no_grad(*types: str):
    for t in types:
        OPS.get_or_create(t).no_grad = True


# --------------------------------------------------------------------------
# kernel helpers
# --------------------------------------------------------------------------
def first(ins: Dict[str, List], slot: str):
    """Single (non-duplicable) input."""
    v = ins.get(slot)
    if not v:
        return None
    return v[0]


def seq(ins: Dict[str, List], slot: str) -> List:
    return ins.get(slot) or []


def out(**kwargs) -> Dict[str, List]:
    """outs(Out=x, Mask=[m]) — scalars are wrapped into 1-element lists."""
    res = {}
    for k, v in kwargs.items():
        if v is None:
            continue
        res[k] = v if isinstance(v, list) else [v]
    return res


# --------------------------------------------------------------------------
# generic vjp-based grad execution
# --------------------------------------------------------------------------
def _is_diff_leaf(x) -> bool:
    return x is not None and jnp.issubdtype(jnp.result_type(x), jnp.inexact)


def run_generic_grad(fwd_type: str, ins: Dict[str, List], attrs: Dict,
                     wanted_grad_slots: Sequence[str],
                     fwd_input_slots: Sequence[str]) -> Dict[str, List]:
    """Execute ``<fwd_type>_grad`` via jax.vjp over the forward kernel.

    ``ins`` holds forward inputs/outputs by their original slot names plus
    output grads under ``<slot>@GRAD``. ``fwd_input_slots`` names the slots
    that were genuine forward inputs (recorded by the default grad maker in
    the grad op's ``_fwd_in`` attr — slot names like "Y" are inputs for some
    ops and outputs for others, so this must be explicit). Returns
    ``<slot>@GRAD`` lists for the requested input slots."""
    info = OPS.get(fwd_type)
    return _vjp_through(info.kernel, info.diff_input_slots, ins, attrs,
                        wanted_grad_slots, fwd_input_slots)


def run_generic_grad_grad(base_type: str, ins: Dict[str, List], attrs: Dict,
                          wanted_grad_slots: Sequence[str],
                          gradop_slots: Sequence[str]) -> Dict[str, List]:
    """Execute ``<base>_grad_grad`` — the vjp of the generic grad
    computation itself (static double grad: gradient-penalty losses).

    ``gradop_slots`` names the ``<base>_grad`` op's own input slots
    (primals + outputs + output cotangents); ``ins`` additionally holds
    the first-order grads under their slots plus the incoming
    second-order cotangents under ``<slot>@GRAD@GRAD``-style names. The
    base op's true forward slots ride in ``attrs["_fwd_in_base"]``."""
    base_attrs = {k: v for k, v in attrs.items()
                  if k not in ("_fwd_in", "_fwd_in_base")}
    base_fwd = list(attrs.get("_fwd_in_base")
                    or [s for s in gradop_slots
                        if not s.endswith(GRAD_SUFFIX)])
    base_attrs["_fwd_in"] = base_fwd
    # the inner grad op's outputs = slots that carry a cotangent here
    inner_out_slots = [s for s in ins
                       if s.endswith(GRAD_SUFFIX)
                       and s + GRAD_SUFFIX in ins]

    def inner_kernel(merged, _attrs):
        gouts = run_generic_grad(base_type, merged, base_attrs,
                                 inner_out_slots, base_fwd)
        fixed = {}
        for s, vals in gouts.items():
            prim = merged.get(s[:-len(GRAD_SUFFIX)], [])
            fixed[s] = [
                v if v is not None else
                (jnp.zeros_like(prim[i]) if i < len(prim)
                 and _is_diff_leaf(prim[i]) else None)
                for i, v in enumerate(vals)]
        return fixed

    return _vjp_through(inner_kernel, None, ins, base_attrs,
                        wanted_grad_slots, gradop_slots)


def _vjp_through(kernel, diff_input_slots, ins: Dict[str, List],
                 attrs: Dict, wanted_grad_slots: Sequence[str],
                 fwd_input_slots: Sequence[str]) -> Dict[str, List]:
    """Shared vjp core: differentiate ``kernel(ins, attrs)`` w.r.t. the
    differentiable leaves of ``fwd_input_slots``, with cotangents taken
    from ``<slot>@GRAD`` entries of ``ins``."""
    fwd_in_slots = [s for s in fwd_input_slots if s in ins]
    # Partition forward-input leaves into differentiable / constant.
    diff_sel: Dict[str, List[bool]] = {}
    allowed = set(diff_input_slots) if diff_input_slots else None
    for s in fwd_in_slots:
        vals = ins[s] or []
        diff_sel[s] = [
            _is_diff_leaf(v) and (allowed is None or s in allowed)
            for v in vals
        ]
    diff_part = {s: [v for v, d in zip(ins[s], diff_sel[s]) if d]
                 for s in fwd_in_slots}
    diff_part = {s: v for s, v in diff_part.items() if v}

    def fwd(dp):
        merged = {}
        for s in fwd_in_slots:
            vals = list(ins[s] or [])
            it = iter(dp.get(s, []))
            merged[s] = [next(it) if d else v for v, d in zip(vals, diff_sel[s])]
        outs = kernel(merged, attrs)
        # Only outputs that have incoming grads (or are float) participate;
        # "_lod"-style metadata entries are not tensors.
        return {k: v for k, v in outs.items()
                if not k.startswith("_") and any(_is_diff_leaf(x) for x in v)}

    primals_out, vjp_fn = jax.vjp(fwd, diff_part)

    cotangents = {}
    for oslot, ovals in primals_out.items():
        gslot = oslot + GRAD_SUFFIX
        gvals = ins.get(gslot)
        cots = []
        for i, ov in enumerate(ovals):
            if ov is None:  # non-diff entry kept for slot alignment
                cots.append(None)
                continue
            g = gvals[i] if gvals is not None and i < len(gvals) and gvals[i] is not None else None
            if g is None:
                g = jnp.zeros_like(ov)
            else:
                g = jnp.asarray(g, ov.dtype) if g.dtype != ov.dtype else g
                if g.shape != ov.shape:
                    g = jnp.broadcast_to(g, ov.shape)
            cots.append(g)
        cotangents[oslot] = cots

    (grads_in,) = vjp_fn(cotangents)

    result: Dict[str, List] = {}
    for s in fwd_in_slots:
        gslot = s + GRAD_SUFFIX
        if gslot not in wanted_grad_slots:
            continue
        gl = []
        it = iter(grads_in.get(s, []))
        for v, d in zip(ins[s] or [], diff_sel[s]):
            gl.append(next(it) if d else None)
        result[gslot] = gl
    return result
