"""Framework-level ops: feed/fetch, control flow (while / conditional_block),
LoDTensorArray ops, save/load, print, py_func
(reference: operators/feed_op.cc, fetch_op.cc, controlflow/while_op.cc,
controlflow/conditional_block_op.cc, controlflow/tensor_array_read_write_op.cc,
save_op.cc, load_op.cc, assign_op.cc, print_op.cc, py_func_op.cc).

These are ``stateful``: they touch the Scope / host side and therefore run in
the interpreter path. The executor's compiled path refuses programs that
contain them in the hot block (control flow lowers to lax primitives via the
compiled path's dedicated handling — see executor._lower_control_flow).
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp

from .registry import register_op, first, seq, out, mark_no_grad
from ..fluid import core


@register_op("feed", stateful=True, no_grad=True, attr_defaults={"col": 0})
def _feed(ins, attrs):
    ctx = attrs["_ctx"]
    name = ctx.op.output("Out")[0]
    col = attrs.get("col", 0)
    feed_var = ctx.scope.find_var(ctx.op.input("X")[0])
    val = feed_var.value()[col]
    ctx.scope.var(name).set_value(val if isinstance(val, core.LoDTensor)
                                  else core.LoDTensor(jnp.asarray(val)))
    return {}


@register_op("fetch", stateful=True, no_grad=True, attr_defaults={"col": 0})
def _fetch(ins, attrs):
    ctx = attrs["_ctx"]
    src = ctx.scope.find_var(ctx.op.input("X")[0]).value()
    fetch_var = ctx.scope.var(ctx.op.output("Out")[0])
    lst = fetch_var.value()
    if not isinstance(lst, list):
        lst = core.LoDTensorArray()
        fetch_var.set_value(lst)
    col = attrs.get("col", 0)
    while len(lst) <= col:
        lst.append(None)
    lst[col] = src
    return {}


@register_op("while", stateful=True, no_grad=True,
             attr_defaults={"is_test": False})
def _while(ins, attrs):
    ctx = attrs["_ctx"]
    block = attrs["sub_block"]
    cond_name = ctx.op.input("Condition")[0]
    max_iters = 10_000_000
    it = 0
    while True:
        cond = ctx.scope.find_var(cond_name)
        c = np.asarray(cond.get_tensor().array).reshape(-1)
        if not bool(c[0]):
            break
        ctx.executor._run_block_eager(block, ctx.scope, ctx.rng_base)
        it += 1
        if it > max_iters:
            raise RuntimeError("while op exceeded max iterations")
    return {}


@register_op("conditional_block", stateful=True, no_grad=True,
             attr_defaults={"is_scalar_condition": False})
def _conditional_block(ins, attrs):
    ctx = attrs["_ctx"]
    block = attrs["sub_block"]
    if attrs.get("is_scalar_condition", False):
        cvar = ctx.scope.find_var(ctx.op.input("Cond")[0])
        run = bool(np.asarray(cvar.get_tensor().array).reshape(-1)[0])
    else:
        xs = [ctx.scope.find_var(n) for n in ctx.op.input("Input")]
        run = all(v is not None and v.is_initialized() for v in xs)
    if run:
        ctx.executor._run_block_eager(block, ctx.scope, ctx.rng_base)
    return {}


@register_op("select_input", stateful=True, no_grad=True)
def _select_input(ins, attrs):
    ctx = attrs["_ctx"]
    mask = int(np.asarray(first(ins, "Mask")).reshape(-1)[0])
    src = ctx.scope.find_var(ctx.op.input("X")[mask]).value()
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(src)
    return {}


@register_op("select_output", stateful=True, no_grad=True)
def _select_output(ins, attrs):
    ctx = attrs["_ctx"]
    mask = int(np.asarray(first(ins, "Mask")).reshape(-1)[0])
    src = ctx.scope.find_var(ctx.op.input("X")[0]).value()
    ctx.scope.var(ctx.op.output("Out")[mask]).set_value(src)
    return {}


# ---- LoDTensorArray ------------------------------------------------------
@register_op("write_to_array", stateful=True, no_grad=True)
def _write_to_array(ins, attrs):
    ctx = attrs["_ctx"]
    i = int(np.asarray(first(ins, "I")).reshape(-1)[0])
    arr = ctx.scope.var(ctx.op.output("Out")[0]).get_lod_tensor_array()
    x = ctx.scope.find_var(ctx.op.input("X")[0]).get_tensor()
    while len(arr) <= i:
        arr.append(core.LoDTensor())
    arr[i] = core.LoDTensor(x.array, x.lod())
    return {}


@register_op("read_from_array", stateful=True, no_grad=True)
def _read_from_array(ins, attrs):
    ctx = attrs["_ctx"]
    i = int(np.asarray(first(ins, "I")).reshape(-1)[0])
    arr = ctx.scope.find_var(ctx.op.input("X")[0]).get_lod_tensor_array()
    t = arr[i]
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(t.array, t.lod()))
    return {}


@register_op("lod_array_length", stateful=True, no_grad=True)
def _lod_array_length(ins, attrs):
    ctx = attrs["_ctx"]
    arr = ctx.scope.find_var(ctx.op.input("X")[0]).get_lod_tensor_array()
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(jnp.asarray([len(arr)], jnp.int32)))
    return {}


@register_op("tensor_array_to_tensor", stateful=True, no_grad=True,
             attr_defaults={"axis": 0, "use_stack": False})
def _tensor_array_to_tensor(ins, attrs):
    ctx = attrs["_ctx"]
    arr = ctx.scope.find_var(ctx.op.input("X")[0]).get_lod_tensor_array()
    xs = [t.array for t in arr]
    ax = attrs.get("axis", 0)
    o = jnp.stack(xs, ax) if attrs.get("use_stack", False) else jnp.concatenate(xs, ax)
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(core.LoDTensor(o))
    idx = jnp.asarray([x.shape[ax] for x in xs], jnp.int32)
    outs = ctx.op.output("OutIndex")
    if outs:
        ctx.scope.var(outs[0]).set_value(core.LoDTensor(idx))
    return {}


@register_op("array_to_lod_tensor", stateful=True, no_grad=True)
def _array_to_lod_tensor(ins, attrs):
    ctx = attrs["_ctx"]
    arr = ctx.scope.find_var(ctx.op.input("X")[0]).get_lod_tensor_array()
    rt_in = ctx.op.input("RankTable") if "RankTable" in ctx.op.input_names \
        else []
    if rt_in:
        # invert lod_tensor_to_array: arr[t] row r is step t of the rank-r
        # sequence; reassemble sequences and restore ORIGINAL order + LoD
        table = ctx.scope.find_var(rt_in[0]).get_lod_rank_table()
        width = arr[0].array.shape[1:] if arr else ()
        dt = arr[0].array.dtype if arr else jnp.float32
        empty = jnp.zeros((0,) + tuple(width), dt)
        per_seq = {}
        for r, (i, l) in enumerate(table.items):
            steps = [arr[t].array[r] for t in range(l)]
            per_seq[i] = jnp.stack(steps) if steps else empty
        order = sorted(per_seq)
        o = jnp.concatenate([per_seq[i] for i in order], axis=0)
        lens = [int(per_seq[i].shape[0]) for i in order]
        offs = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
        ctx.scope.var(ctx.op.output("Out")[0]).set_value(
            core.LoDTensor(o, (offs,)))
        return {}
    o = jnp.concatenate([t.array for t in arr], axis=0)
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(core.LoDTensor(o))
    return {}


# ---- save / load (wire format: see fluid/io.py serializer) ---------------
@register_op("save", stateful=True, no_grad=True,
             attr_defaults={"overwrite": True, "save_as_fp16": False,
                            "file_path": ""})
def _save(ins, attrs):
    from ..fluid.io import _serialize_lod_tensor
    ctx = attrs["_ctx"]
    path = attrs["file_path"]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if os.path.exists(path) and not attrs.get("overwrite", True):
        raise RuntimeError(f"{path} exists and overwrite is False")
    t = ctx.scope.find_var(ctx.op.input("X")[0]).get_tensor()
    with open(path, "wb") as f:
        f.write(_serialize_lod_tensor(t, attrs.get("save_as_fp16", False)))
    return {}


@register_op("load", stateful=True, no_grad=True,
             attr_defaults={"file_path": "", "load_as_fp16": False})
def _load(ins, attrs):
    from ..fluid.io import _deserialize_lod_tensor
    ctx = attrs["_ctx"]
    with open(attrs["file_path"], "rb") as f:
        t = _deserialize_lod_tensor(f.read())
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(t)
    return {}


@register_op("save_combine", stateful=True, no_grad=True,
             attr_defaults={"overwrite": True, "save_as_fp16": False,
                            "file_path": ""})
def _save_combine(ins, attrs):
    from ..fluid.io import _serialize_lod_tensor
    ctx = attrs["_ctx"]
    path = attrs["file_path"]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        for name in ctx.op.input("X"):
            t = ctx.scope.find_var(name).get_tensor()
            f.write(_serialize_lod_tensor(t, attrs.get("save_as_fp16", False)))
    return {}


@register_op("load_combine", stateful=True, no_grad=True,
             attr_defaults={"file_path": "", "load_as_fp16": False,
                            "model_from_memory": False})
def _load_combine(ins, attrs):
    from ..fluid.io import _deserialize_lod_tensor_stream
    ctx = attrs["_ctx"]
    with open(attrs["file_path"], "rb") as f:
        data = f.read()
    tensors = _deserialize_lod_tensor_stream(data, len(ctx.op.output("Out")))
    for name, t in zip(ctx.op.output("Out"), tensors):
        ctx.scope.var(name).set_value(t)
    return {}


@register_op("print", stateful=True, no_grad=True,
             attr_defaults={"first_n": -1, "message": "", "summarize": 20,
                            "print_tensor_name": True, "print_tensor_type": True,
                            "print_tensor_shape": True, "print_tensor_lod": True,
                            "print_phase": "BOTH"})
def _print(ins, attrs):
    ctx = attrs["_ctx"]
    name = ctx.op.input("In")[0]
    t = ctx.scope.find_var(name).get_tensor()
    msg = attrs.get("message", "")
    print(f"{msg} Variable: {name} shape: {t.shape()} data: "
          f"{np.asarray(t.array).reshape(-1)[:attrs.get('summarize', 20)]}")
    o = ctx.op.output("Out")
    if o:
        ctx.scope.var(o[0]).set_value(core.LoDTensor(t.array, t.lod()))
    return {}


@register_op("assert", stateful=True, no_grad=True,
             attr_defaults={"summarize": -1})
def _assert(ins, attrs):
    ctx = attrs["_ctx"]
    cond = np.asarray(first(ins, "Cond")).reshape(-1)
    if not bool(cond.all()):
        data = [np.asarray(ctx.scope.find_var(n).get_tensor().array)
                for n in ctx.op.input("Data")]
        raise AssertionError(f"Assert failed; data={data}")
    return {}


@register_op("py_func", stateful=True, no_grad=True,
             attr_defaults={"forward_callable_id": 0, "backward_callable_id": -1,
                            "backward_skip_vars": []})
def _py_func(ins, attrs):
    from ..fluid.layers.py_func_registry import get_callable
    fn = get_callable(attrs["forward_callable_id"])
    xs = [np.asarray(x) for x in seq(ins, "X")]
    res = fn(*xs)
    if not isinstance(res, (list, tuple)):
        res = [res]
    return out(Out=[jnp.asarray(np.asarray(r)) for r in res])


@register_op("delete_var", stateful=True, no_grad=True)
def _delete_var(ins, attrs):
    ctx = attrs["_ctx"]
    for n in ctx.op.input("X"):
        ctx.scope.erase(n)
    return {}


@register_op("rnn_memory_helper", inputs=("X",))
def _rnn_memory_helper(ins, attrs):
    return out(Out=first(ins, "X"))


@register_op("fake_init", stateful=True, no_grad=True,
             attr_defaults={"shape": [], "dtype": 5})
def _fake_init(ins, attrs):
    """Marks the output var initialized without meaningful contents
    (reference fake_init_op.cc: allocates, leaves memory unset — trainers
    use it for vars the pserver owns). Zeros keep it deterministic."""
    ctx = attrs["_ctx"]
    from ..fluid.core import dtype_to_jnp
    shape = [int(s) for s in attrs.get("shape", [])]
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(jnp.zeros(shape, dtype_to_jnp(attrs.get("dtype", 5)))))
    return {}


@register_op("get_tensor_from_selected_rows", stateful=True, no_grad=True)
def _get_tensor_from_selected_rows(ins, attrs):
    ctx = attrs["_ctx"]
    sr = ctx.scope.find_var(ctx.op.input("X")[0]).get_selected_rows()
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(sr.get_tensor().array))
    return {}


@register_op("merge_selected_rows", stateful=True, no_grad=True)
def _merge_selected_rows(ins, attrs):
    ctx = attrs["_ctx"]
    sr = ctx.scope.find_var(ctx.op.input("X")[0]).get_selected_rows()
    rows = np.asarray(sr.rows())
    val = np.asarray(sr.get_tensor().array)
    uniq, inv = np.unique(rows, return_inverse=True)
    merged = np.zeros((len(uniq),) + val.shape[1:], val.dtype)
    np.add.at(merged, inv, val)
    o = ctx.scope.var(ctx.op.output("Out")[0]).get_selected_rows()
    o.set_rows(uniq.tolist())
    o.set_height(sr.height())
    o.get_tensor().set(merged)
    return {}
