"""Fused attention ops.

``fused_attention_qkv``: the TPU-native fused attention op used by
models/bert.py — Q/K/V [B, S, H·D] → context [B, S, H·D], dispatching to
the Pallas flash-attention kernel on TPU.

``multihead_matmul``: wire-compatible with the reference's fused inference
op (reference: operators/fused/multihead_matmul_op.cu — Input [B,S,3,H,D]
packed QKV + BiasQK additive mask), so reference-transpiled inference
programs run.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register_op, register_grad_maker, first, out
from .math_ops import mxu_available as _mxu_backend
from .pallas.flash_attention import flash_attention, _pallas_ok, \
    _ref_attention


def _keypad_bias(bias, q, k):
    """[B, Sk] view of ``bias`` iff it is EXACTLY the key-padding form
    [B, 1, 1, Sk] (else None). A merely broadcastable bias (e.g.
    [B,1,1,1] or [1,1,1,Sk]) must NOT qualify — the kernel's (1, blk_k)
    bias block indexes the real B and Sk extents. q, k: [B, H, S, D]."""
    if bias is not None and bias.ndim == 4 and bias.shape[1] == 1 \
            and bias.shape[2] == 1 and bias.shape[0] == q.shape[0] \
            and bias.shape[3] == k.shape[2]:
        return bias.reshape(bias.shape[0], bias.shape[3])
    return None


def _split_heads(x, n_head):
    b, s, hd = x.shape
    d = hd // n_head
    return jnp.transpose(x.reshape(b, s, n_head, d), (0, 2, 1, 3))


def _merge_heads(x):
    b, h, s, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b, s, h * d)


@register_op("fused_attention_qkv", inputs=("Q", "K", "V", "Bias"),
             diff_inputs=("Q", "K", "V"), needs_rng=True,
             attr_defaults={"num_heads": 1, "dropout_rate": 0.0,
                            "causal": False})
def _fused_attention_qkv(ins, attrs):
    """Optional Bias: additive attention mask broadcastable to
    [B, H, Sq, Sk] (e.g. padding mask [B, 1, 1, Sk] with -inf/0).

    Dispatch: the Pallas flash kernel serves the no-bias case AND the
    exact key-padding bias form [B, 1, 1, Sk] (in-kernel); attention
    dropout runs INSIDE the kernel (mask regenerated in the backward,
    seeded per step from the executor rng). The einsum path (XLA fuses
    it) serves every other bias shape and shapes the kernel doesn't
    cover. Causal masking is TOP-LEFT aligned (query i sees keys <= i)
    on both paths."""
    q = first(ins, "Q")
    k = first(ins, "K")
    v = first(ins, "V")
    bias = first(ins, "Bias")
    h = attrs.get("num_heads", 1)
    d = q.shape[-1] // h
    sm_scale = 1.0 / math.sqrt(d)
    out_dtype = q.dtype
    from ..fluid import core as _core
    if _core.globals_["FLAGS_use_bf16_matmul"] and q.dtype == jnp.float32 \
            and _mxu_backend():
        # MXU-native attention (same contract as _mm in math_ops): bf16
        # QK^T/PV matmuls — softmax statistics stay f32 inside both the
        # flash kernel and the einsum path; output restored to f32
        q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    qh, kh, vh = (_split_heads(t, h) for t in (q, k, v))
    causal = attrs.get("causal", False)
    drop = float(attrs.get("dropout_rate", 0.0) or 0.0)
    kp_bias = _keypad_bias(bias, qh, kh)
    flash_can = _pallas_ok(qh, kh) and (bias is None or kp_bias is not None)
    if (bias is None and drop == 0.0) or flash_can:
        seed = None
        if drop > 0.0:
            seed = jax.random.randint(attrs["_rng"], (1,), 0,
                                      2 ** 31 - 1, dtype=jnp.int32)
        o = flash_attention(qh, kh, vh, sm_scale, causal,
                            dropout_rate=drop, dropout_seed=seed,
                            bias=kp_bias if flash_can else None)
    else:
        # f32-accumulation contract shared with the flash kernel: bf16
        # MXU tiles accumulate in f32 (preferred_element_type), so the
        # softmax statistics see f32 scores — NOT scores rounded to bf16
        # by a bf16-output dot. Without this the two dispatch paths
        # diverge numerically for the same program depending on bias
        # shape (r5 advisor finding).
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                       preferred_element_type=jnp.float32) * sm_scale
        if bias is not None:
            s = s + bias.astype(jnp.float32)
        if causal:
            S, Sk = qh.shape[2], kh.shape[2]
            idx_q = jnp.arange(S)[:, None]
            idx_k = jnp.arange(Sk)[None, :]
            s = jnp.where(idx_q >= idx_k, s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s, axis=-1).astype(qh.dtype)
        if drop > 0.0:
            keep = jax.random.bernoulli(attrs["_rng"], 1.0 - drop, p.shape)
            p = jnp.where(keep, p / (1.0 - drop), 0.0).astype(p.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vh,
                       preferred_element_type=jnp.float32)
    return out(Out=_merge_heads(o).astype(out_dtype))


@register_op("multihead_matmul", inputs=("Input", "W", "Bias", "BiasQK"),
             diff_inputs=("Input", "W", "Bias"),
             attr_defaults={"transpose_Q": False, "transpose_K": True,
                            "transpose_V": False, "alpha": 1.0,
                            "head_number": 1})
def _multihead_matmul(ins, attrs):
    """Reference contract (operators/fused/multihead_matmul_op.cc:80 —
    MultiHeadMatMulV2Op): Input is the RAW hidden [B, S, N] with the
    packed projection W [N, 3, H·D] and Bias [3, H·D] (the layout
    multihead_matmul_fuse_pass_v2 packs, ir/multihead_matmul_fuse_pass.cc:470);
    the op does QKV projection + alpha·QKᵀ + BiasQK + softmax + PV + merge
    in one fused computation. Pre-projected packed-QKV inputs
    ([B,S,3,H,D] / [B,S,3HD] without W) are also accepted."""
    x = first(ins, "Input")
    w = first(ins, "W")
    b = first(ins, "Bias")
    bias_qk = first(ins, "BiasQK")
    h = attrs.get("head_number", 1)
    alpha = attrs.get("alpha", 1.0)
    if w is not None and w.ndim >= 3:  # raw hidden + packed projection
        wm = w.reshape(w.shape[0], 3, -1)            # [N, 3, H·D]
        qkv = jnp.einsum("bsn,nch->bsch", x, wm)     # [B, S, 3, H·D]
        if b is not None:
            qkv = qkv + b.reshape(3, -1)
        q = _split_heads(qkv[:, :, 0], h)
        k = _split_heads(qkv[:, :, 1], h)
        v = _split_heads(qkv[:, :, 2], h)
    elif x.ndim == 5:  # [B, S, 3, H, D]
        q = jnp.transpose(x[:, :, 0], (0, 2, 1, 3))
        k = jnp.transpose(x[:, :, 1], (0, 2, 1, 3))
        v = jnp.transpose(x[:, :, 2], (0, 2, 1, 3))
    else:  # [B, S, 3·H·D]
        bsz, s, hd3 = x.shape
        x5 = x.reshape(bsz, s, 3, h, hd3 // (3 * h))
        q = jnp.transpose(x5[:, :, 0], (0, 2, 1, 3))
        k = jnp.transpose(x5[:, :, 1], (0, 2, 1, 3))
        v = jnp.transpose(x5[:, :, 2], (0, 2, 1, 3))
    # Fast path (the reference op IS its fast path — multihead_matmul_op.cu):
    # no bias, or the exact key-padding BiasQK form [B,1,1,Sk] (the common
    # BERT inference padding mask), dispatches to the Pallas flash kernel
    # via its in-kernel bias input. Generic [B,H,Sq,Sk] biases keep the
    # einsum path (XLA fuses it).
    kp_bias = _keypad_bias(bias_qk, q, k)
    if _pallas_ok(q, k) and (bias_qk is None or kp_bias is not None):
        o = flash_attention(q, k, v, alpha, causal=False, bias=kp_bias)
    else:
        # same f32-accumulation contract as the flash path (see
        # _fused_attention_qkv above)
        s_mat = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * alpha
        if bias_qk is not None:
            s_mat = s_mat + bias_qk.astype(jnp.float32)
        p = jax.nn.softmax(s_mat, axis=-1).astype(q.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v,
                       preferred_element_type=jnp.float32).astype(q.dtype)
    return out(Out=_merge_heads(o))
