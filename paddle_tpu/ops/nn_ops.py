"""Neural-network op kernels: conv, pooling, normalization, softmax/losses,
embedding, dropout, interpolation (reference: paddle/fluid/operators/
conv_op.cc + conv_cudnn_op.cu, pool_op.cc, batch_norm_op.cc, layer_norm_op.cc,
softmax_op.cc, softmax_with_cross_entropy_op.cc, cross_entropy_op.cc,
lookup_table_op.cc, dropout_op.cc, interpolate_op.cc …).

conv/pool map to lax.conv_general_dilated / lax.reduce_window so XLA tiles
them onto the MXU; dropout keeps its reference Mask-output contract so its
grad is mask-multiply (custom grad op below) rather than a replayed RNG.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, register_grad_maker, first, seq, out
from ..fluid.core import dtype_to_jnp


# --------------------------------------------------------------------------
# softmax & cross entropy
# --------------------------------------------------------------------------
@register_op("softmax", inputs=("X",), attr_defaults={"axis": -1})
def _softmax(ins, attrs):
    return out(Out=jax.nn.softmax(first(ins, "X"), axis=attrs.get("axis", -1)))


@register_op("log_softmax", inputs=("X",), attr_defaults={"axis": -1})
def _log_softmax(ins, attrs):
    return out(Out=jax.nn.log_softmax(first(ins, "X"), axis=attrs.get("axis", -1)))


@register_op("cross_entropy", inputs=("X", "Label"), diff_inputs=("X",),
             attr_defaults={"soft_label": False, "ignore_index": -100})
def _cross_entropy(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    eps = 1e-20
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
            lbl = jnp.squeeze(lbl, -1)
        picked = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(picked + eps)
        ign = attrs.get("ignore_index", -100)
        loss = jnp.where(lbl[..., None] == ign, 0.0, loss)
    return out(Y=loss)


@register_op("cross_entropy2", inputs=("X", "Label"), diff_inputs=("X",),
             attr_defaults={"ignore_index": -100})
def _cross_entropy2(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    lbl = jnp.squeeze(label, -1) if label.ndim == x.ndim else label
    picked = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
    loss = -jnp.log(picked + 1e-20)
    return out(Y=loss, XShape=jnp.zeros((0,) + x.shape, x.dtype),
               MatchX=picked)


@register_op("softmax_with_cross_entropy", inputs=("Logits", "Label"),
             diff_inputs=("Logits",),
             attr_defaults={"soft_label": False, "ignore_index": -100,
                            "numeric_stable_mode": True, "axis": -1})
def _softmax_with_cross_entropy(ins, attrs):
    logits, label = first(ins, "Logits"), first(ins, "Label")
    axis = attrs.get("axis", -1) % logits.ndim
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lbl.astype(jnp.int32), axis), axis=axis)
        loss = -picked
        ign = attrs.get("ignore_index", -100)
        loss = jnp.where(jnp.expand_dims(lbl, axis) == ign, 0.0, loss)
    return out(Softmax=softmax, Loss=loss)


@register_op("sigmoid_cross_entropy_with_logits", inputs=("X", "Label"),
             diff_inputs=("X",),
             attr_defaults={"ignore_index": -100, "normalize": False})
def _sigmoid_ce(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ign = attrs.get("ignore_index", -100)
    mask = label != ign
    loss = jnp.where(mask, loss, 0.0)
    if attrs.get("normalize", False):
        loss = loss / jnp.maximum(jnp.sum(mask.astype(x.dtype)), 1.0)
    return out(Out=loss)


@register_op("bce_loss", inputs=("X", "Label"), diff_inputs=("X",))
def _bce_loss(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    eps = 1e-12
    return out(Out=-(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps)))


@register_op("square_error_cost", inputs=("X", "Y"))
def _square_error_cost(ins, attrs):
    d = first(ins, "X") - first(ins, "Y")
    return out(Out=jnp.square(d))


@register_op("log_loss", inputs=("Predicted", "Labels"),
             diff_inputs=("Predicted",), attr_defaults={"epsilon": 1e-4})
def _log_loss(ins, attrs):
    p, l = first(ins, "Predicted"), first(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    return out(Loss=-l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps))


@register_op("huber_loss", inputs=("X", "Y"), diff_inputs=("X",),
             attr_defaults={"delta": 1.0})
def _huber_loss(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    d = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    return out(Out=loss, Residual=r)


@register_op("smooth_l1_loss", inputs=("X", "Y", "InsideWeight", "OutsideWeight"),
             diff_inputs=("X",), attr_defaults={"sigma": 1.0})
def _smooth_l1(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    iw, ow = first(ins, "InsideWeight"), first(ins, "OutsideWeight")
    sigma2 = attrs.get("sigma", 1.0) ** 2
    d = x - y
    if iw is not None:
        d = d * iw
    ad = jnp.abs(d)
    l = jnp.where(ad < 1.0 / sigma2, 0.5 * d * d * sigma2, ad - 0.5 / sigma2)
    if ow is not None:
        l = l * ow
    return out(Out=jnp.sum(l.reshape(l.shape[0], -1), -1, keepdims=True), Diff=d)


@register_op("kldiv_loss", inputs=("X", "Target"), diff_inputs=("X",),
             attr_defaults={"reduction": "mean"})
def _kldiv_loss(ins, attrs):
    x, t = first(ins, "X"), first(ins, "Target")
    loss = jnp.where(t > 0, t * (jnp.log(t) - x), 0.0)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss).reshape((1,))
    elif red == "sum":
        loss = jnp.sum(loss).reshape((1,))
    elif red == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape((1,))
    return out(Loss=loss)


@register_op("hinge_loss", inputs=("Logits", "Labels"), diff_inputs=("Logits",))
def _hinge_loss(ins, attrs):
    logits, labels = first(ins, "Logits"), first(ins, "Labels")
    return out(Loss=jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_op("rank_loss", inputs=("Label", "Left", "Right"),
             diff_inputs=("Left", "Right"))
def _rank_loss(ins, attrs):
    label, left, right = first(ins, "Label"), first(ins, "Left"), first(ins, "Right")
    d = left - right
    return out(Out=jnp.log1p(jnp.exp(d)) - label * d)


@register_op("margin_rank_loss", inputs=("Label", "X1", "X2"),
             diff_inputs=("X1", "X2"), attr_defaults={"margin": 0.0})
def _margin_rank_loss(ins, attrs):
    label, x1, x2 = first(ins, "Label"), first(ins, "X1"), first(ins, "X2")
    o = jnp.maximum(-label * (x1 - x2) + attrs.get("margin", 0.0), 0.0)
    return out(Out=o, Activated=(o > 0).astype(x1.dtype))


@register_op("nll_loss", inputs=("X", "Label", "Weight"), diff_inputs=("X",),
             attr_defaults={"ignore_index": -100, "reduction": "mean"})
def _nll_loss(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    w = first(ins, "Weight")
    lbl = label.astype(jnp.int32)
    picked = -jnp.take_along_axis(x, lbl[:, None], axis=1)[:, 0]
    wt = jnp.ones_like(picked) if w is None else w[lbl]
    ign = attrs.get("ignore_index", -100)
    wt = jnp.where(label == ign, 0.0, wt)
    loss = picked * wt
    total_w = jnp.sum(wt)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return out(Out=(jnp.sum(loss) / jnp.maximum(total_w, 1e-10)).reshape((1,)),
                   Total_weight=total_w.reshape((1,)))
    if red == "sum":
        return out(Out=jnp.sum(loss).reshape((1,)), Total_weight=total_w.reshape((1,)))
    return out(Out=loss, Total_weight=total_w.reshape((1,)))


@register_op("mse_loss", inputs=("X", "Y"))
def _mse_loss(ins, attrs):
    return out(Out=jnp.mean(jnp.square(first(ins, "X") - first(ins, "Y"))).reshape((1,)))


@register_op("bpr_loss", inputs=("X", "Label"), diff_inputs=("X",))
def _bpr_loss(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    lbl = jnp.squeeze(label, -1) if label.ndim == x.ndim else label
    lbl = lbl.astype(jnp.int32)
    pos = jnp.take_along_axis(x, lbl[:, None], axis=1)
    terms = -jnp.log(jax.nn.sigmoid(pos - x) + 1e-8)
    # exclude the positive column itself; average over the N-1 negatives
    # (reference: operators/bpr_loss_op.h)
    mask = jax.nn.one_hot(lbl, x.shape[1], dtype=x.dtype)
    loss = jnp.sum(terms * (1.0 - mask), axis=1, keepdims=True) \
        / (x.shape[1] - 1)
    return out(Y=loss)


# --------------------------------------------------------------------------
# embedding
# --------------------------------------------------------------------------
def _lookup(w, ids, padding_idx):
    o = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        o = jnp.where((ids == padding_idx)[..., None], 0.0, o)
    return o


@register_op("lookup_table", inputs=("W", "Ids"), diff_inputs=("W",),
             attr_defaults={"padding_idx": -1, "is_sparse": False,
                            "is_distributed": False, "remote_prefetch": False})
def _lookup_table(ins, attrs):
    w, ids = first(ins, "W"), first(ins, "Ids")
    ids2 = jnp.squeeze(ids, -1)  # v1 contract: Ids shape [..., 1]
    pad = attrs.get("padding_idx", -1)
    return out(Out=_lookup(w, ids2, pad if pad >= 0 else None))


@register_op("lookup_table_v2", inputs=("W", "Ids"), diff_inputs=("W",),
             attr_defaults={"padding_idx": -1, "is_sparse": False,
                            "is_distributed": False, "remote_prefetch": False})
def _lookup_table_v2(ins, attrs):
    w, ids = first(ins, "W"), first(ins, "Ids")
    pad = attrs.get("padding_idx", -1)
    return out(Out=_lookup(w, ids, pad if pad >= 0 else None))


# --------------------------------------------------------------------------
# dropout — Mask output contract kept so grad = mask multiply
# --------------------------------------------------------------------------
@register_op("dropout", inputs=("X", "Seed"), needs_rng=True,
             attr_defaults={"dropout_prob": 0.5, "is_test": False,
                            "dropout_implementation": "downgrade_in_infer",
                            "fix_seed": False, "seed": 0})
def _dropout(ins, attrs):
    x = first(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        o = x if impl == "upscale_in_train" else x * (1.0 - p)
        return out(Out=o, Mask=jnp.ones_like(x, jnp.uint8))
    keep = jax.random.bernoulli(attrs["_rng"], 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        o = jnp.where(keep, x / max(1.0 - p, 1e-10), 0.0) if p < 1.0 else jnp.zeros_like(x)
    else:
        o = jnp.where(keep, x, 0.0)
    return out(Out=o, Mask=keep.astype(jnp.uint8))


@register_op("dropout_grad", no_grad=True)
def _dropout_grad(ins, attrs):
    g = first(ins, "Out@GRAD")
    mask = first(ins, "Mask")
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    gx = g * mask.astype(g.dtype)
    if impl == "upscale_in_train" and p < 1.0:
        gx = gx / (1.0 - p)
    return out(**{"X@GRAD": gx})


@register_grad_maker("dropout")
def _dropout_grad_maker(op, grad_map):
    return [{
        "type": "dropout_grad",
        "inputs": {"Out@GRAD": [grad_map[op.output("Out")[0]]],
                   "Mask": op.output("Mask")},
        "outputs": {"X@GRAD": [grad_map[op.input("X")[0]]]},
        "attrs": {k: v for k, v in op.attrs.items() if not k.startswith("_")},
    }]


# --------------------------------------------------------------------------
# normalization
# --------------------------------------------------------------------------
@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance", "MomentumTensor"),
             diff_inputs=("X", "Scale", "Bias"),
             attr_defaults={"momentum": 0.9, "epsilon": 1e-5,
                            "data_layout": "NCHW", "is_test": False,
                            "use_global_stats": False, "trainable_statistics": False,
                            "fuse_with_relu": False})
def _batch_norm(ins, attrs):
    x = first(ins, "X")
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    mean, var = first(ins, "Mean"), first(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    use_stats = attrs.get("is_test", False) or attrs.get("use_global_stats", False)
    if use_stats:
        bm, bv = mean, var
        new_mean, new_var = mean, var
        saved_var_inv = lax.rsqrt(bv + eps)
    else:
        x32 = x.astype(jnp.float32)
        bm = jnp.mean(x32, axes)
        bv = jnp.mean(jnp.square(x32), axes) - jnp.square(bm)
        bm, bv = bm.astype(x.dtype), bv.astype(x.dtype)
        new_mean = momentum * mean + (1 - momentum) * bm
        new_var = momentum * var + (1 - momentum) * bv
        saved_var_inv = lax.rsqrt(bv + eps)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    y = (x - bm.reshape(bshape)) * saved_var_inv.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    if attrs.get("fuse_with_relu", False):
        y = jnp.maximum(y, 0)
    return out(Y=y, MeanOut=new_mean, VarianceOut=new_var,
               SavedMean=bm, SavedVariance=saved_var_inv)


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             diff_inputs=("X", "Scale", "Bias"),
             attr_defaults={"epsilon": 1e-5, "begin_norm_axis": 1})
def _layer_norm(ins, attrs):
    x = first(ins, "X")
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    bna = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(bna, x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axes, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axes, keepdims=True)
    y = ((x32 - mean) * lax.rsqrt(var + eps)).astype(x.dtype)
    d = int(np.prod(x.shape[bna:]))
    if scale is not None:
        y = y * scale.reshape((1,) * bna + x.shape[bna:])
    if bias is not None:
        y = y + bias.reshape((1,) * bna + x.shape[bna:])
    flat = x.shape[:bna]
    return out(Y=y, Mean=mean.reshape(flat).astype(x.dtype),
               Variance=var.reshape(flat).astype(x.dtype))


@register_op("instance_norm", inputs=("X", "Scale", "Bias"),
             diff_inputs=("X", "Scale", "Bias"),
             attr_defaults={"epsilon": 1e-5})
def _instance_norm(ins, attrs):
    x = first(ins, "X")
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axes, keepdims=True)
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv
    c = x.shape[1]
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    n = x.shape[0]
    return out(Y=y, SavedMean=mean.reshape(n * c),
               SavedVariance=inv.reshape(n * c))


@register_op("group_norm", inputs=("X", "Scale", "Bias"),
             diff_inputs=("X", "Scale", "Bias"),
             attr_defaults={"epsilon": 1e-5, "groups": 1, "data_layout": "NCHW"})
def _group_norm(ins, attrs):
    x = first(ins, "X")
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axes, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return out(Y=y, Mean=mean.reshape(n, g), Variance=var.reshape(n, g))


@register_op("norm", inputs=("X",), attr_defaults={"axis": -1, "epsilon": 1e-10})
def _norm(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", -1)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), ax, keepdims=True)
                    + attrs.get("epsilon", 1e-10))
    return out(Out=x / norm, Norm=norm)


@register_op("data_norm", inputs=("X", "BatchSize", "BatchSum", "BatchSquareSum"),
             diff_inputs=("X",), attr_defaults={"epsilon": 1e-4})
def _data_norm(ins, attrs):
    x = first(ins, "X")
    bsize = first(ins, "BatchSize")
    bsum = first(ins, "BatchSum")
    bsq = first(ins, "BatchSquareSum")
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    return out(Y=(x - means) * scales, Means=means, Scales=scales)


@register_op("lrn", inputs=("X",),
             attr_defaults={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75,
                            "data_format": "NCHW"})
def _lrn(ins, attrs):
    x = first(ins, "X")
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    if nhwc:
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, k = attrs.get("n", 5), attrs.get("k", 2.0)
    alpha, beta = attrs.get("alpha", 1e-4), attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    mid = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * mid
    o = x / (mid ** beta)
    if nhwc:
        o = jnp.transpose(o, (0, 2, 3, 1))
        mid = jnp.transpose(mid, (0, 2, 3, 1))
    return out(Out=o, MidOut=mid)


# --------------------------------------------------------------------------
# conv / pool
# --------------------------------------------------------------------------
def _conv_padding(paddings, algo, ndim, ksize, strides, dilations, in_shape):
    if algo == "SAME":
        pads = []
        for i in range(ndim):
            o = -(-in_shape[i] // strides[i])
            eff = (ksize[i] - 1) * dilations[i] + 1
            total = max((o - 1) * strides[i] + eff - in_shape[i], 0)
            pads.append((total // 2, total - total // 2))
        return pads
    if algo == "VALID":
        return [(0, 0)] * ndim
    p = list(paddings)
    if len(p) == ndim:
        return [(x, x) for x in p]
    return [(p[2 * i], p[2 * i + 1]) for i in range(ndim)]


@register_op("conv2d", inputs=("Input", "Filter", "Bias", "ResidualData"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "padding_algorithm": "EXPLICIT",
                            "data_format": "NCHW", "use_cudnn": True,
                            "exhaustive_search": False})
def _conv2d(ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")
    fmt = attrs.get("data_format", "NCHW")
    if fmt in ("NCHW", "AnyLayout"):
        dn = ("NCHW", "OIHW", "NCHW")
        spatial = x.shape[2:]
    else:
        dn = ("NHWC", "OIHW", "NHWC")
        spatial = x.shape[1:3]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1])]
    pads = _conv_padding(attrs.get("paddings", [0, 0]),
                         attrs.get("padding_algorithm", "EXPLICIT"),
                         2, w.shape[2:], strides, dil, spatial)
    from ..fluid import core as _core
    from .math_ops import mxu_available
    orig_dtype = x.dtype
    if _core.globals_["FLAGS_use_bf16_matmul"] and x.dtype == jnp.float32 \
            and mxu_available():
        # bf16 in AND out: a mixed-dtype conv (preferred_element_type=f32)
        # has no transpose rule in this jax version, which breaks the
        # generic vjp grad path; the MXU still accumulates in f32
        # internally, the output just rounds to bf16 once
        x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    o = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dil,
        dimension_numbers=dn, feature_group_count=attrs.get("groups", 1))
    o = o.astype(orig_dtype)
    b = first(ins, "Bias")
    if b is not None:
        c_axis = 1 if fmt in ("NCHW", "AnyLayout") else 3
        bshape = [1] * o.ndim
        bshape[c_axis] = b.shape[0]
        o = o + b.reshape(bshape)
    return out(Output=o)


@register_op("depthwise_conv2d", inputs=("Input", "Filter", "Bias"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "padding_algorithm": "EXPLICIT",
                            "data_format": "NCHW", "use_cudnn": False})
def _depthwise_conv2d(ins, attrs):
    return _conv2d(ins, attrs)


@register_op("conv3d", inputs=("Input", "Filter", "Bias"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                            "dilations": [1, 1, 1], "groups": 1,
                            "padding_algorithm": "EXPLICIT",
                            "data_format": "NCDHW", "use_cudnn": True})
def _conv3d(ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1])]
    pads = _conv_padding(attrs.get("paddings", [0, 0, 0]),
                         attrs.get("padding_algorithm", "EXPLICIT"),
                         3, w.shape[2:], strides, dil, x.shape[2:])
    o = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1))
    return out(Output=o)


@register_op("conv2d_transpose", inputs=("Input", "Filter", "Bias"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "output_size": [], "padding_algorithm": "EXPLICIT",
                            "data_format": "NCHW", "use_cudnn": True})
def _conv2d_transpose(ins, attrs):
    x, w = first(ins, "Input"), first(ins, "Filter")  # w: [in_c, out_c/g, kh, kw]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1])]
    pads = _conv_padding(attrs.get("paddings", [0, 0]),
                         attrs.get("padding_algorithm", "EXPLICIT"),
                         2, w.shape[2:], strides, dil, x.shape[2:])
    g = attrs.get("groups", 1)
    kh, kw = w.shape[2], w.shape[3]
    # grad-of-conv formulation: transposed conv = lhs-dilated conv with
    # flipped, transposed kernel
    w_t = jnp.swapaxes(w, 0, 1)[:, :, ::-1, ::-1]  # [out_c/g, in_c, kh, kw]
    if g > 1:
        w_t = w_t.reshape(w.shape[1], g, w.shape[0] // g, kh, kw)
        w_t = jnp.concatenate([w_t[:, i] for i in range(g)], axis=0)
    tp = [((kh - 1) * dil[0] - pads[0][0], (kh - 1) * dil[0] - pads[0][1]),
          ((kw - 1) * dil[1] - pads[1][0], (kw - 1) * dil[1] - pads[1][1])]
    o = lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1), padding=tp, lhs_dilation=strides,
        rhs_dilation=dil, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=g)
    osize = attrs.get("output_size") or []
    if osize:
        # paddle allows any size in [natural, natural+stride): pad up or
        # crop down to the requested size
        grow = [max(0, osize[i] - o.shape[2 + i]) for i in (0, 1)]
        if any(grow):
            o = jnp.pad(o, [(0, 0), (0, 0), (0, grow[0]), (0, grow[1])])
        o = o[:, :, :osize[0], :osize[1]]
    b = first(ins, "Bias")
    if b is not None:
        o = o + b.reshape(1, -1, 1, 1)
    return out(Output=o)


def _avg_pool_slices(x, ksize, strides, pads, exclusive):
    """NCHW avg pool as sum over kh·kw strided slices, divided by a static
    valid-element count map (exclusive=True: pad elements don't count)."""
    n, c, H, W = x.shape
    kh, kw = ksize
    sh, sw = strides
    (pt, pb), (pl_, pr) = pads
    xp = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl_, pr)])
    oh = (H + pt + pb - kh) // sh + 1
    ow = (W + pl_ + pr - kw) // sw + 1
    o = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, 0, i, j),
                          (n, c, i + (oh - 1) * sh + 1,
                           j + (ow - 1) * sw + 1), (1, 1, sh, sw))
            o = s if o is None else o + s
    if exclusive and (pt or pb or pl_ or pr):
        ones = np.zeros((H + pt + pb, W + pl_ + pr), np.float32)
        ones[pt:pt + H, pl_:pl_ + W] = 1.0
        cnt = np.zeros((oh, ow), np.float32)
        for i in range(kh):
            for j in range(kw):
                cnt += ones[i:i + (oh - 1) * sh + 1:sh,
                            j:j + (ow - 1) * sw + 1:sw]
        cnt = np.maximum(cnt, 1.0)
        return o / jnp.asarray(cnt, x.dtype)
    return o / float(kh * kw)


def _max_pool_slices(x, ksize, strides, pads, init):
    """NCHW max pool as max over kh·kw strided slices."""
    n, c, H, W = x.shape
    kh, kw = ksize
    sh, sw = strides
    (pt, pb), (pl_, pr) = pads
    xp = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl_, pr)],
                 constant_values=init)
    oh = (H + pt + pb - kh) // sh + 1
    ow = (W + pl_ + pr - kw) // sw + 1
    o = None
    for i in range(kh):
        for j in range(kw):
            s = lax.slice(xp, (0, 0, i, j),
                          (n, c, i + (oh - 1) * sh + 1,
                           j + (ow - 1) * sw + 1), (1, 1, sh, sw))
            o = s if o is None else jnp.maximum(o, s)
    return o


def _pool2d_impl(x, attrs):
    ptype = attrs.get("pooling_type", "max")
    ksize = [int(k) for k in attrs.get("ksize", [1, 1])]
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    fmt = attrs.get("data_format", "NCHW")
    ch_last = fmt == "NHWC"
    hw = x.shape[2:4] if not ch_last else x.shape[1:3]
    if attrs.get("global_pooling", False) or (
            attrs.get("adaptive", False) and ksize == [1, 1]):
        axes = (2, 3) if not ch_last else (1, 2)
        red = jnp.max if ptype == "max" else jnp.mean
        return red(x, axis=axes, keepdims=True)
    if attrs.get("adaptive", False):
        axes = (2, 3) if not ch_last else (1, 2)
        oh, ow = ksize
        n, c = x.shape[0], (x.shape[1] if not ch_last else x.shape[3])
        assert hw[0] % oh == 0 and hw[1] % ow == 0, \
            "adaptive pool requires divisible sizes in this build"
        xr = (x.reshape(n, c, oh, hw[0] // oh, ow, hw[1] // ow)
              if not ch_last else
              x.reshape(n, oh, hw[0] // oh, ow, hw[1] // ow, c))
        rax = (3, 5) if not ch_last else (2, 4)
        red = jnp.max if ptype == "max" else jnp.mean
        return red(xr, axis=rax)
    pads = _conv_padding(attrs.get("paddings", [0, 0]),
                         attrs.get("padding_algorithm", "EXPLICIT"),
                         2, ksize, strides, [1, 1], hw)
    if not ch_last:
        wdims = (1, 1, ksize[0], ksize[1])
        wstrides = (1, 1, strides[0], strides[1])
        wpads = [(0, 0), (0, 0), pads[0], pads[1]]
    else:
        wdims = (1, ksize[0], ksize[1], 1)
        wstrides = (1, strides[0], strides[1], 1)
        wpads = [(0, 0), pads[0], pads[1], (0, 0)]
    if ptype == "max":
        # stacked-slices max (differentiable through jnp.max; the
        # reduce_window max path lacks a vjp under this jax version)
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        if ch_last:
            x_nchw = jnp.transpose(x, (0, 3, 1, 2))
            o = _max_pool_slices(x_nchw, ksize, strides, pads, init)
            return jnp.transpose(o, (0, 2, 3, 1))
        return _max_pool_slices(x, ksize, strides, pads, init)
    # avg: stacked-slices sum (reduce_window(add) also lacks a vjp here);
    # the per-window divisor is a static constant map
    if ch_last:
        x_nchw = jnp.transpose(x, (0, 3, 1, 2))
        o = _avg_pool_slices(x_nchw, ksize, strides, pads,
                             attrs.get("exclusive", True))
        return jnp.transpose(o, (0, 2, 3, 1))
    return _avg_pool_slices(x, ksize, strides, pads,
                            attrs.get("exclusive", True))


@register_op("pool2d", inputs=("X",),
             attr_defaults={"pooling_type": "max", "ksize": [1, 1],
                            "global_pooling": False, "strides": [1, 1],
                            "paddings": [0, 0], "exclusive": True,
                            "adaptive": False, "ceil_mode": False,
                            "use_cudnn": True, "data_format": "NCHW",
                            "padding_algorithm": "EXPLICIT"})
def _pool2d(ins, attrs):
    return out(Out=_pool2d_impl(first(ins, "X"), attrs))


@register_op("pool3d", inputs=("X",),
             attr_defaults={"pooling_type": "max", "ksize": [1, 1, 1],
                            "global_pooling": False, "strides": [1, 1, 1],
                            "paddings": [0, 0, 0], "exclusive": True,
                            "adaptive": False, "ceil_mode": False,
                            "use_cudnn": True, "data_format": "NCDHW",
                            "padding_algorithm": "EXPLICIT"})
def _pool3d(ins, attrs):
    x = first(ins, "X")
    ksize = [int(k) for k in attrs.get("ksize")]
    strides = [int(s) for s in attrs.get("strides")]
    if attrs.get("global_pooling", False) or (
            attrs.get("adaptive", False) and ksize == [1, 1, 1]):
        red = jnp.max if attrs.get("pooling_type") == "max" else jnp.mean
        return out(Out=red(x, axis=(2, 3, 4), keepdims=True))
    if attrs.get("adaptive", False):
        od, oh, ow = ksize
        n, c = x.shape[:2]
        d, h, w = x.shape[2:]
        assert d % od == 0 and h % oh == 0 and w % ow == 0, \
            "adaptive pool3d requires divisible sizes in this build"
        xr = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        red = jnp.max if attrs.get("pooling_type") == "max" else jnp.mean
        return out(Out=red(xr, axis=(3, 5, 7)))
    pads = _conv_padding(attrs.get("paddings"), attrs.get("padding_algorithm"),
                         3, ksize, strides, [1, 1, 1], x.shape[2:])
    wdims = (1, 1) + tuple(ksize)
    # stacked-slices pooling: differentiable (reduce_window max/add lack a
    # vjp under this jax version)
    is_max = attrs.get("pooling_type", "max") == "max"
    kd, kh, kw = ksize
    sd, sh, sw = strides
    n, c, D, H, W = x.shape
    init = -jnp.inf if is_max else 0.0
    xp = jnp.pad(x, [(0, 0), (0, 0), pads[0], pads[1], pads[2]],
                 constant_values=init)
    od = (D + sum(pads[0]) - kd) // sd + 1
    oh = (H + sum(pads[1]) - kh) // sh + 1
    ow = (W + sum(pads[2]) - kw) // sw + 1
    o = None
    for a in range(kd):
        for i in range(kh):
            for j in range(kw):
                s = lax.slice(xp, (0, 0, a, i, j),
                              (n, c, a + (od - 1) * sd + 1,
                               i + (oh - 1) * sh + 1,
                               j + (ow - 1) * sw + 1),
                              (1, 1, sd, sh, sw))
                if o is None:
                    o = s
                else:
                    o = jnp.maximum(o, s) if is_max else o + s
    if is_max:
        return out(Out=o)
    if attrs.get("exclusive", True) and any(sum(p) for p in pads):
        ones = np.zeros((D + sum(pads[0]), H + sum(pads[1]),
                         W + sum(pads[2])), np.float32)
        ones[pads[0][0]:pads[0][0] + D, pads[1][0]:pads[1][0] + H,
             pads[2][0]:pads[2][0] + W] = 1.0
        cnt = np.zeros((od, oh, ow), np.float32)
        for a in range(kd):
            for i in range(kh):
                for j in range(kw):
                    cnt += ones[a:a + (od - 1) * sd + 1:sd,
                                i:i + (oh - 1) * sh + 1:sh,
                                j:j + (ow - 1) * sw + 1:sw]
        return out(Out=o / jnp.asarray(np.maximum(cnt, 1.0), x.dtype))
    return out(Out=o / float(kd * kh * kw))


@register_op("max_pool2d_with_index", inputs=("X",),
             attr_defaults={"ksize": [1, 1], "strides": [1, 1],
                            "paddings": [0, 0], "global_pooling": False,
                            "adaptive": False})
def _max_pool2d_with_index(ins, attrs):
    x = first(ins, "X")
    kh, kw = [int(k) for k in attrs.get("ksize", [1, 1])]
    sh, sw = [int(s) for s in attrs.get("strides", [1, 1])]
    ph, pw = [int(p) for p in attrs.get("paddings", [0, 0])]
    if attrs.get("global_pooling", False):
        kh, kw = x.shape[2], x.shape[3]
        sh, sw, ph, pw = kh, kw, 0, 0
    n, c, H, W = x.shape
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)],
                 constant_values=neg)
    # flat input index (within the unpadded HxW plane, reference Mask
    # contract: operators/math/pooling.cc MaxPool2dWithIndex)
    flat_idx = (jnp.arange(H + 2 * ph)[:, None] - ph) * W \
        + (jnp.arange(W + 2 * pw)[None, :] - pw)
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    patches, idx_patches = [], []
    for i in range(kh):
        for j in range(kw):
            patches.append(lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
            idx_patches.append(lax.slice(
                flat_idx, (i, j),
                (i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1), (sh, sw)))
    stacked = jnp.stack(patches, axis=-1)            # [n,c,oh,ow,kh*kw]
    sidx = jnp.stack(idx_patches, axis=-1)           # [oh,ow,kh*kw]
    arg = jnp.argmax(stacked, axis=-1)
    o = jnp.max(stacked, axis=-1)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(sidx, stacked.shape), arg[..., None], -1)[..., 0]
    return out(Out=o, Mask=mask.astype(jnp.int32))


@register_op("max_pool3d_with_index", inputs=("X",),
             attr_defaults={"ksize": [1, 1, 1], "strides": [1, 1, 1],
                            "paddings": [0, 0, 0], "global_pooling": False,
                            "adaptive": False})
def _max_pool3d_with_index(ins, attrs):
    """3d max pool returning the flat DxHxW argmax per window (reference
    math/pooling.cc MaxPool3dWithIndex). Adaptive mode needs divisible
    sizes (static-shape TPU build)."""
    x = first(ins, "X")
    n, c, D, H, W = x.shape
    if attrs.get("adaptive", False):
        od, oh, ow = [int(k) for k in attrs.get("ksize")]
        assert D % od == 0 and H % oh == 0 and W % ow == 0
        kd, kh, kw = D // od, H // oh, W // ow
        xr = x.reshape(n, c, od, kd, oh, kh, ow, kw)
        xr = jnp.transpose(xr, (0, 1, 2, 4, 6, 3, 5, 7)).reshape(
            n, c, od, oh, ow, kd * kh * kw)
        arg = jnp.argmax(xr, axis=-1)
        o = jnp.max(xr, axis=-1)
        # local (di,hi,wi) within the bin -> flat index in the full plane
        di = arg // (kh * kw)
        hi = (arg // kw) % kh
        wi = arg % kw
        gd = jnp.arange(od)[None, None, :, None, None] * kd + di
        gh = jnp.arange(oh)[None, None, None, :, None] * kh + hi
        gw = jnp.arange(ow)[None, None, None, None, :] * kw + wi
        return out(Out=o, Mask=(gd * H * W + gh * W + gw).astype(jnp.int32))
    kd, kh, kw = [int(k) for k in attrs.get("ksize")]
    sd, sh, sw = [int(s) for s in attrs.get("strides")]
    pd, ph, pw = [int(p) for p in attrs.get("paddings")]
    if attrs.get("global_pooling", False):
        kd, kh, kw = D, H, W
        sd, sh, sw, pd, ph, pw = kd, kh, kw, 0, 0, 0
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, [(0, 0), (0, 0), (pd, pd), (ph, ph), (pw, pw)],
                 constant_values=neg)
    flat_idx = ((jnp.arange(D + 2 * pd)[:, None, None] - pd) * H * W
                + (jnp.arange(H + 2 * ph)[None, :, None] - ph) * W
                + (jnp.arange(W + 2 * pw)[None, None, :] - pw))
    od = (D + 2 * pd - kd) // sd + 1
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    patches, idx_patches = [], []
    for a in range(kd):
        for i in range(kh):
            for j in range(kw):
                patches.append(lax.slice(
                    xp, (0, 0, a, i, j),
                    (n, c, a + (od - 1) * sd + 1, i + (oh - 1) * sh + 1,
                     j + (ow - 1) * sw + 1), (1, 1, sd, sh, sw)))
                idx_patches.append(lax.slice(
                    flat_idx, (a, i, j),
                    (a + (od - 1) * sd + 1, i + (oh - 1) * sh + 1,
                     j + (ow - 1) * sw + 1), (sd, sh, sw)))
    stacked = jnp.stack(patches, axis=-1)
    sidx = jnp.stack(idx_patches, axis=-1)
    arg = jnp.argmax(stacked, axis=-1)
    o = jnp.max(stacked, axis=-1)
    mask = jnp.take_along_axis(
        jnp.broadcast_to(sidx, stacked.shape), arg[..., None], -1)[..., 0]
    return out(Out=o, Mask=mask.astype(jnp.int32))


# --------------------------------------------------------------------------
# interpolation / image
# --------------------------------------------------------------------------
def _interp_size(ins, attrs, x):
    ost = first(ins, "OutSize")
    if ost is not None:
        v = np.asarray(ost)
        return int(v[0]), int(v[1])
    st = seq(ins, "SizeTensor")
    if st:
        return (int(np.asarray(st[0]).reshape(())),
                int(np.asarray(st[1]).reshape(())))
    sc = first(ins, "Scale")
    scale = (float(np.asarray(sc).reshape(())) if sc is not None
             else attrs.get("scale", 0.0))
    if scale and scale > 0:
        return int(x.shape[2] * scale), int(x.shape[3] * scale)
    return attrs.get("out_h", -1), attrs.get("out_w", -1)


@register_op("nearest_interp", inputs=("X", "OutSize", "SizeTensor", "Scale"),
             diff_inputs=("X",),
             attr_defaults={"out_h": -1, "out_w": -1, "scale": 0.0,
                            "interp_method": "nearest", "align_corners": True,
                            "align_mode": 1, "data_layout": "NCHW"})
def _nearest_interp(ins, attrs):
    x = first(ins, "X")
    oh, ow = _interp_size(ins, attrs, x)
    h, w = x.shape[2], x.shape[3]
    if attrs.get("align_corners", True) and oh > 1 and ow > 1:
        hi = jnp.round(jnp.arange(oh) * (h - 1) / (oh - 1)).astype(jnp.int32)
        wi = jnp.round(jnp.arange(ow) * (w - 1) / (ow - 1)).astype(jnp.int32)
    else:
        hi = jnp.floor(jnp.arange(oh) * h / oh).astype(jnp.int32)
        wi = jnp.floor(jnp.arange(ow) * w / ow).astype(jnp.int32)
    return out(Out=x[:, :, hi][:, :, :, wi])


@register_op("bilinear_interp", inputs=("X", "OutSize", "SizeTensor", "Scale"),
             diff_inputs=("X",),
             attr_defaults={"out_h": -1, "out_w": -1, "scale": 0.0,
                            "interp_method": "bilinear", "align_corners": True,
                            "align_mode": 1, "data_layout": "NCHW"})
def _bilinear_interp(ins, attrs):
    x = first(ins, "X")
    oh, ow = _interp_size(ins, attrs, x)
    h, w = x.shape[2], x.shape[3]
    ac = attrs.get("align_corners", True)
    am = attrs.get("align_mode", 1)
    if ac:
        hs = jnp.arange(oh) * ((h - 1) / max(oh - 1, 1))
        ws = jnp.arange(ow) * ((w - 1) / max(ow - 1, 1))
    elif am == 0:
        hs = jnp.clip((jnp.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
        ws = jnp.clip((jnp.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
    else:
        hs = jnp.clip(jnp.arange(oh) * h / oh, 0, h - 1)
        ws = jnp.clip(jnp.arange(ow) * w / ow, 0, w - 1)
    h0 = jnp.floor(hs).astype(jnp.int32)
    w0 = jnp.floor(ws).astype(jnp.int32)
    h1 = jnp.minimum(h0 + 1, h - 1)
    w1 = jnp.minimum(w0 + 1, w - 1)
    ah = (hs - h0)[None, None, :, None]
    aw = (ws - w0)[None, None, None, :]
    v00 = x[:, :, h0][:, :, :, w0]
    v01 = x[:, :, h0][:, :, :, w1]
    v10 = x[:, :, h1][:, :, :, w0]
    v11 = x[:, :, h1][:, :, :, w1]
    o = (v00 * (1 - ah) * (1 - aw) + v01 * (1 - ah) * aw
         + v10 * ah * (1 - aw) + v11 * ah * aw)
    return out(Out=o.astype(x.dtype))


@register_op("pixel_shuffle", inputs=("X",), attr_defaults={"upscale_factor": 1})
def _pixel_shuffle(ins, attrs):
    x = first(ins, "X")
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    o = x.reshape(n, c // (r * r), r, r, h, w)
    o = jnp.transpose(o, (0, 1, 4, 2, 5, 3))
    return out(Out=o.reshape(n, c // (r * r), h * r, w * r))


@register_op("space_to_depth", inputs=("X",), attr_defaults={"blocksize": 1})
def _space_to_depth(ins, attrs):
    x = first(ins, "X")
    b = attrs.get("blocksize", 1)
    n, c, h, w = x.shape
    o = x.reshape(n, c, h // b, b, w // b, b)
    o = jnp.transpose(o, (0, 3, 5, 1, 2, 4))
    return out(Out=o.reshape(n, c * b * b, h // b, w // b))


@register_op("shuffle_channel", inputs=("X",), attr_defaults={"group": 1})
def _shuffle_channel(ins, attrs):
    x = first(ins, "X")
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    return out(Out=jnp.transpose(x.reshape(n, g, c // g, h, w),
                                 (0, 2, 1, 3, 4)).reshape(x.shape))


# --------------------------------------------------------------------------
# metrics
# --------------------------------------------------------------------------
@register_op("accuracy", inputs=("Out", "Indices", "Label"), no_grad=True)
def _accuracy(ins, attrs):
    idx, label = first(ins, "Indices"), first(ins, "Label")
    lbl = label.reshape(-1, 1)
    correct = jnp.any(idx == lbl, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = idx.shape[0]
    return out(Accuracy=(num_correct / total).reshape((1,)),
               Correct=num_correct.astype(jnp.int32).reshape((1,)),
               Total=jnp.asarray([total], jnp.int32))


@register_op("auc", inputs=("Predict", "Label", "StatPos", "StatNeg"),
             no_grad=True, stateful=True,
             attr_defaults={"curve": "ROC", "num_thresholds": 4095,
                            "slide_steps": 1})
def _auc(ins, attrs):
    pred = np.asarray(first(ins, "Predict"))
    label = np.asarray(first(ins, "Label")).reshape(-1)
    stat_pos = np.asarray(first(ins, "StatPos")).copy().reshape(-1)
    stat_neg = np.asarray(first(ins, "StatNeg")).copy().reshape(-1)
    nt = attrs.get("num_thresholds", 4095)
    buckets = np.minimum((pred[:, 1] * nt).astype(np.int64), nt)
    for b, l in zip(buckets, label):
        if l:
            stat_pos[b] += 1
        else:
            stat_neg[b] += 1
    from ..utils.metrics import auc_from_histograms
    auc_val = auc_from_histograms(stat_pos, stat_neg)
    return out(AUC=jnp.asarray([auc_val], jnp.float32),
               StatPosOut=jnp.asarray(stat_pos),
               StatNegOut=jnp.asarray(stat_neg))


# sync_batch_norm (reference: operators/sync_batch_norm_op.cu + the
# sync_batch_norm BuildStrategy flag, pybind.cc:2266): the reference
# hand-inserts NCCL allreduces of batch statistics. Here the batch is
# SHARDED over the dp mesh axis inside ONE jitted computation, so the
# kernel's plain batch-axis mean/var reductions are already global — XLA
# inserts the cross-replica psum. Same kernel as batch_norm, by design.
register_op("sync_batch_norm",
            inputs=("X", "Scale", "Bias", "Mean", "Variance",
                    "MomentumTensor"),
            diff_inputs=("X", "Scale", "Bias"),
            attr_defaults={"momentum": 0.9, "epsilon": 1e-5,
                           "data_layout": "NCHW", "is_test": False,
                           "use_global_stats": False,
                           "trainable_statistics": False,
                           "fuse_with_relu": False})(_batch_norm)
