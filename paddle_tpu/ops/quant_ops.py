"""Fake-quantization ops for QAT and post-training quant (reference:
paddle/fluid/operators/fake_quantize_op.cc — fake_quantize_abs_max,
fake_quantize_moving_average_abs_max, fake_quantize_range_abs_max,
fake_channel_wise_quantize_abs_max, fake_dequantize_max_abs,
fake_quantize_dequantize_moving_average_abs_max).

Quant math: scale = max|x| (per tensor or channel); q = round(x / scale *
(2^(bits-1) - 1)), clipped; dequant multiplies back. Gradients are
straight-through (identity within range) via custom grad makers — the jit
fuses the whole quant-dequant pair into the surrounding computation."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, register_grad_maker, first, out


def _qrange(bits):
    return float((1 << (int(bits) - 1)) - 1)


def _ste_grad_maker(op_type, x_slot="X", out_slot="Out"):
    @register_grad_maker(op_type)
    def _maker(op, grad_map, _x=x_slot, _o=out_slot):
        g_out = grad_map.get(op.output(_o)[0])
        g_in = grad_map.get(op.input(_x)[0])
        if not g_out or not g_in or "@EMPTY@" in (g_out, g_in):
            return None
        return [{"type": "assign", "inputs": {"X": [g_out]},
                 "outputs": {"Out": [g_in]}, "attrs": {}}]
    return _maker


@register_op("fake_quantize_abs_max", diff_inputs=["X"],
             attr_defaults={"bit_length": 8})
def _fake_quantize_abs_max(ins, attrs):
    x = first(ins, "X")
    r = _qrange(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q, OutScale=scale.reshape(1))


_ste_grad_maker("fake_quantize_abs_max")


@register_op("fake_dequantize_max_abs", diff_inputs=["X"],
             attr_defaults={"max_range": 127.0})
def _fake_dequantize_max_abs(ins, attrs):
    x = first(ins, "X")
    scale = first(ins, "Scale")
    return out(Out=x * scale.reshape(()) / float(attrs["max_range"]))


@register_op("fake_quantize_dequantize_abs_max", diff_inputs=["X"],
             attr_defaults={"bit_length": 8})
def _fake_qdq_abs_max(ins, attrs):
    x = first(ins, "X")
    r = _qrange(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q * s / r, OutScale=scale.reshape(1))


_ste_grad_maker("fake_quantize_dequantize_abs_max")


@register_op("fake_quantize_moving_average_abs_max", diff_inputs=["X"],
             attr_defaults={"bit_length": 8, "moving_rate": 0.9,
                            "is_test": False})
def _fake_quant_moving(ins, attrs):
    x = first(ins, "X")
    in_scale = first(ins, "InScale")
    r = _qrange(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False):
        scale = in_scale.reshape(())
    else:
        m = attrs.get("moving_rate", 0.9)
        prev = in_scale.reshape(())
        scale = jnp.where(prev > 0, m * prev + (1 - m) * cur, cur)
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q, OutScale=scale.reshape(1))


_ste_grad_maker("fake_quantize_moving_average_abs_max")


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             diff_inputs=["X"],
             attr_defaults={"bit_length": 8, "moving_rate": 0.9,
                            "is_test": False})
def _fake_qdq_moving(ins, attrs):
    x = first(ins, "X")
    in_scale = first(ins, "InScale")
    r = _qrange(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False) and in_scale is not None:
        scale = in_scale.reshape(())
    elif in_scale is not None:
        m = attrs.get("moving_rate", 0.9)
        prev = in_scale.reshape(())
        scale = jnp.where(prev > 0, m * prev + (1 - m) * cur, cur)
    else:
        scale = cur
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q * s / r, OutScale=scale.reshape(1))


_ste_grad_maker("fake_quantize_dequantize_moving_average_abs_max")


@register_op("fake_channel_wise_quantize_abs_max", diff_inputs=["X"],
             attr_defaults={"bit_length": 8, "quant_axis": 0})
def _fake_channel_quant(ins, attrs):
    x = first(ins, "X")
    r = _qrange(attrs.get("bit_length", 8))
    ax = int(attrs.get("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != ax)
    scale = jnp.max(jnp.abs(x), axis=red)
    shape = [1] * x.ndim
    shape[ax] = -1
    s = jnp.where(scale > 0, scale, 1.0).reshape(shape)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q, OutScale=scale)


_ste_grad_maker("fake_channel_wise_quantize_abs_max")


@register_op("fake_quantize_range_abs_max", diff_inputs=["X"],
             attr_defaults={"bit_length": 8, "window_size": 10000,
                            "is_test": False})
def _fake_quant_range(ins, attrs):
    x = first(ins, "X")
    in_scale = first(ins, "InScale")
    r = _qrange(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    scale = (in_scale.reshape(()) if attrs.get("is_test", False)
             and in_scale is not None
             else (jnp.maximum(cur, in_scale.reshape(()))
                   if in_scale is not None else cur))
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s * r), -r, r)
    return out(Out=q, OutScale=scale.reshape(1))


_ste_grad_maker("fake_quantize_range_abs_max")
