"""Final Appendix-A op batch: INT8 quant runtime ops (reference:
operators/quantize_op.cc, dequantize_op.cc, requantize_op.cc,
fake_dequantize_op.cc (dequantize_abs_max, fake_channel_wise_dequantize_
max_abs), dequantize_log_op.cc, fake_quantize_op.cc
(moving_average_abs_max_scale), lookup_table_dequant_op.cc), PSLib-style
sparse pull/push (pull_sparse_op.cc, push_dense_op.cc, pull_box_sparse,
lookup_sparse_table_op.cc), PS plumbing (split_selected_rows_op.cc,
split_byref_op.cc, recv_save_op.cc, ref_by_trainer_id_op.cc,
prefetch_op.cc, fl_listen_and_serv), DGC (dgc_op.cc, dgc_clip_by_norm,
dgc_momentum), reader ops (create_py_reader, read — reader/
create_py_reader_op.cc, read_op.cc), cudnn_lstm alias, run_program, and
engine-offload stubs (tensorrt_engine, lite_engine).

Sparse pull/push run against host-resident tables in the scope (the
single-process PSLib fallback; multi-host sparse rides the ps_rpc plane
from the transpiler path). Quant ops are pure JAX."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, seq, out
from ..fluid import core


# --------------------------------------------------------------------------
# INT8 runtime quant family
# --------------------------------------------------------------------------
def _round_away(x):
    # C++ std::round semantics (half away from zero); jnp.round is
    # half-to-even
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


@register_op("quantize", inputs=("Input",), no_grad=True,
             attr_defaults={"Scale": 1.0, "is_negative_input": False})
def _quantize(ins, attrs):
    x = first(ins, "Input")
    s = attrs.get("Scale", 1.0)
    q = _round_away(x * s)
    if attrs.get("is_negative_input", False):
        return out(Output=jnp.clip(q, -128, 127).astype(jnp.int8))
    return out(Output=jnp.clip(q, 0, 255).astype(jnp.uint8))


@register_op("dequantize", inputs=("Input",), no_grad=True,
             attr_defaults={"Scale": 1.0})
def _dequantize(ins, attrs):
    x = first(ins, "Input")
    return out(Output=x.astype(jnp.float32) / attrs.get("Scale", 1.0))


@register_op("requantize", inputs=("Input",), no_grad=True,
             attr_defaults={"Scale_in": 1.0, "Scale_out": 1.0})
def _requantize(ins, attrs):
    x = first(ins, "Input")
    r = attrs.get("Scale_out", 1.0) / attrs.get("Scale_in", 1.0)
    return out(Output=jnp.clip(_round_away(x.astype(jnp.float32) * r),
                               -128, 127).astype(x.dtype))


@register_op("dequantize_abs_max", inputs=("X", "Scale"), no_grad=True,
             attr_defaults={"max_range": 127.0})
def _dequantize_abs_max(ins, attrs):
    x, scale = first(ins, "X"), first(ins, "Scale")
    return out(Out=x.astype(jnp.float32) * scale.reshape(())
               / attrs.get("max_range", 127.0))


@register_op("dequantize_log", inputs=("X", "Dict"), no_grad=True)
def _dequantize_log(ins, attrs):
    """4-bit log-quant decode: code's low bits index the dict, high bit is
    the sign (reference dequantize_log_op.cc)."""
    x, d = first(ins, "X"), first(ins, "Dict")
    code = x.astype(jnp.int32)
    neg = code >= 128
    idx = jnp.where(neg, code - 128, code)
    v = d.reshape(-1)[idx]
    return out(Out=jnp.where(neg, -v, v))


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=("X", "Scales"), diff_inputs=["X"],
             attr_defaults={"quant_bits": [8], "quant_axis": 0,
                            "x_num_col_dims": 1})
def _fake_channel_wise_dequantize_max_abs(ins, attrs):
    x = first(ins, "X")
    scales = seq(ins, "Scales")
    bits = attrs.get("quant_bits", [8])
    ax = attrs.get("quant_axis", 0)
    s0 = scales[0]
    shape = [1] * x.ndim
    shape[ax] = -1
    o = x * s0.reshape(shape) / (2.0 ** (bits[0] - 1) - 1)
    if len(scales) > 1 and scales[1] is not None:
        o = o * scales[1].reshape(()) / (2.0 ** (bits[1] - 1) - 1)
    return out(Out=o)


@register_op("moving_average_abs_max_scale", inputs=("X", "InScale",
                                                     "InAccum", "InState"),
             no_grad=True, stateful=False,
             attr_defaults={"moving_rate": 0.9, "is_test": False})
def _moving_average_abs_max_scale(ins, attrs):
    x = first(ins, "X")
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x))
    in_state = first(ins, "InState")
    in_accum = first(ins, "InAccum")
    if attrs.get("is_test", False):
        scale = first(ins, "InScale")
        return {"Out": [x], "OutScale": [scale]}
    state = jnp.asarray(
        in_state.reshape(()) if in_state is not None else 0.0,
        jnp.float32) * rate + 1.0
    accum = jnp.asarray(
        in_accum.reshape(()) if in_accum is not None else 0.0,
        jnp.float32) * rate + cur
    scale = accum / state
    return {"Out": [x], "OutScale": [scale.reshape(1)],
            "OutState": [state.reshape(1)], "OutAccum": [accum.reshape(1)]}


@register_op("lookup_table_dequant", inputs=("W", "Ids"), diff_inputs=(),
             no_grad=True,
             attr_defaults={"padding_idx": -1, "is_sparse": False})
def _lookup_table_dequant(ins, attrs):
    """Embedding lookup over a row-quantized table: each row stores
    [min, scale_range] as two float32 then uint8 codes; value =
    min + code * scale_range / 255 (reference lookup_table_dequant_op.h)."""
    w, ids = first(ins, "W"), first(ins, "Ids")
    idv = ids.reshape(-1)
    rows = w[idv]                         # [N, 2 + ceil(D/4)] float32 view
    mins = rows[:, 0:1]
    rng = rows[:, 1:2]
    codes = rows[:, 2:]
    # codes packed 4-per-float: reinterpret bytes
    byte_view = jax.lax.bitcast_convert_type(
        codes.astype(jnp.float32), jnp.uint8).reshape(codes.shape[0], -1)
    vals = mins + byte_view.astype(jnp.float32) * rng / 255.0
    shape = tuple(ids.shape[:-1]) + (vals.shape[1],)
    return out(Out=vals.reshape(shape))


# --------------------------------------------------------------------------
# PSLib-style sparse/dense pull & push (host-table fallback)
# --------------------------------------------------------------------------
def _table_of(ctx, name):
    var = ctx.scope.find_var(name)
    if var is None:
        raise RuntimeError(f"sparse table var '{name}' not found in scope")
    return var


def _pull_sparse_impl(ins, attrs):
    ctx = attrs["_ctx"]
    ids_names = ctx.op.input("Ids")
    w_name = (ctx.op.input("W") or [None])[0]
    emb_dim = int(attrs.get("EmbeddingDim", attrs.get("size", 8)))
    outs = ctx.op.output("Out")
    for idn, on in zip(ids_names, outs):
        ids = np.asarray(ctx.scope.find_var(idn).get_tensor().array)
        if w_name:
            tbl = np.asarray(_table_of(ctx, w_name).value().array)
            vals = tbl[ids.reshape(-1) % len(tbl)][:, :emb_dim]
        else:
            vals = np.zeros((ids.size, emb_dim), np.float32)
        shape = tuple(ids.shape[:-1]) + (emb_dim,) if ids.ndim > 1 \
            else (ids.shape[0], emb_dim)
        ctx.scope.var(on).set_value(
            core.LoDTensor(jnp.asarray(vals.reshape(shape),
                                       jnp.float32)))
    return {}


register_op("pull_sparse", stateful=True, no_grad=True,
            attr_defaults={"EmbeddingDim": 8, "TableId": 0})(
    _pull_sparse_impl)
register_op("pull_sparse_v2", stateful=True, no_grad=True,
            attr_defaults={"EmbeddingDim": 8, "TableId": 0})(
    _pull_sparse_impl)
register_op("pull_box_sparse", stateful=True, no_grad=True,
            attr_defaults={"size": 1})(_pull_sparse_impl)


def _push_sparse_impl(ins, attrs):
    ctx = attrs["_ctx"]
    ids_names = ctx.op.input("Ids")
    w_name = (ctx.op.input("W") or [None])[0]
    grads = ctx.op.input("Grads") or ctx.op.input("Out@GRAD") or []
    lr = float(attrs.get("lr", 0.01))
    if not w_name:
        return {}
    var = _table_of(ctx, w_name)
    tbl = np.asarray(var.value().array).copy()
    for idn, gn in zip(ids_names, grads):
        gvar = ctx.scope.find_var(gn)
        if gvar is None:
            continue
        ids = np.asarray(ctx.scope.find_var(idn).get_tensor().array)
        g = np.asarray(gvar.get_tensor().array).reshape(ids.size, -1)
        np.subtract.at(tbl, ids.reshape(-1) % len(tbl),
                       lr * np.pad(g, ((0, 0),
                                       (0, tbl.shape[1] - g.shape[1]))))
    var.set_value(core.LoDTensor(jnp.asarray(tbl)))
    return {}


register_op("push_sparse", stateful=True, no_grad=True,
            attr_defaults={"EmbeddingDim": 8, "TableId": 0, "lr": 0.01})(
    _push_sparse_impl)
register_op("push_sparse_v2", stateful=True, no_grad=True,
            attr_defaults={"EmbeddingDim": 8, "TableId": 0, "lr": 0.01})(
    _push_sparse_impl)
register_op("push_box_sparse", stateful=True, no_grad=True,
            attr_defaults={"size": 1, "lr": 0.01})(_push_sparse_impl)


@register_op("push_dense", stateful=True, no_grad=True,
             attr_defaults={"TableId": 0, "ScaleDataNorm": -1.0,
                            "InputNames": []})
def _push_dense(ins, attrs):
    # dense grads ride the collective path on TPU; the PSLib dense push is
    # a no-op acknowledgement here (single-process fallback)
    return {}


@register_op("lookup_sparse_table", stateful=True, no_grad=True,
             attr_defaults={"value_names": ["Param"], "padding_idx": -1,
                            "auto_grown_table": True, "is_test": False})
def _lookup_sparse_table(ins, attrs):
    """Lookup into a SelectedRows-backed table, auto-growing missing rows
    with zeros (reference lookup_sparse_table_op.cc)."""
    ctx = attrs["_ctx"]
    ids = np.asarray(ctx.scope.find_var(
        ctx.op.input("Ids")[0]).get_tensor().array).reshape(-1)
    wvar = ctx.scope.find_var(ctx.op.input("W")[0])
    holder = wvar.value()
    if isinstance(holder, core.SelectedRows):
        rows = list(holder.rows())
        val = np.asarray(holder.get_tensor().array)
        row_of = {r: i for i, r in enumerate(rows)}
        D = val.shape[1] if val.ndim == 2 else 1
        outv = np.zeros((len(ids), D), np.float32)
        grown = False
        for j, idv in enumerate(ids):
            if int(idv) in row_of:
                outv[j] = val[row_of[int(idv)]]
            elif attrs.get("auto_grown_table", True) and \
                    not attrs.get("is_test", False):
                rows.append(int(idv))
                val = np.concatenate([val, np.zeros((1, D), val.dtype)])
                row_of[int(idv)] = len(rows) - 1
                grown = True
        if grown:
            holder.set_rows(rows)
            holder.get_tensor().set(jnp.asarray(val))
    else:
        tbl = np.asarray(holder.array)
        outv = tbl[ids % len(tbl)]
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(
        core.LoDTensor(jnp.asarray(outv)))
    return {}


@register_op("split_selected_rows", stateful=True, no_grad=True,
             attr_defaults={"height_sections": []})
def _split_selected_rows(ins, attrs):
    """Split a SelectedRows by height sections for per-pserver dispatch
    (reference split_selected_rows_op.cc)."""
    ctx = attrs["_ctx"]
    src = ctx.scope.find_var(ctx.op.input("X")[0]).value()
    secs = [int(s) for s in attrs.get("height_sections", [])]
    bounds = np.concatenate([[0], np.cumsum(secs)])
    rows = np.asarray(src.rows(), np.int64)
    val = np.asarray(src.get_tensor().array)
    for k, on in enumerate(ctx.op.output("Out")):
        sel = (rows >= bounds[k]) & (rows < bounds[k + 1])
        piece = core.SelectedRows(rows=(rows[sel] - bounds[k]).tolist(),
                                  height=secs[k])
        piece.get_tensor().set(jnp.asarray(val[sel]))
        ctx.scope.var(on).set_value(piece)
    return {}


@register_op("split_byref", stateful=True, no_grad=True,
             attr_defaults={"sections": [], "num": 0})
def _split_byref(ins, attrs):
    """Row-split without copy semantics (reference split_byref_op.cc; under
    XLA 'by reference' has no meaning, plain slices)."""
    ctx = attrs["_ctx"]
    x = ctx.scope.find_var(ctx.op.input("X")[0]).get_tensor().array
    outs = ctx.op.output("Out")
    secs = [int(s) for s in attrs.get("sections") or []]
    if not secs:
        n = int(attrs.get("num", len(outs))) or len(outs)
        secs = [x.shape[0] // n] * n
    off = 0
    for on, s in zip(outs, secs):
        ctx.scope.var(on).set_value(core.LoDTensor(x[off:off + s]))
        off += s
    return {}


@register_op("recv_save", stateful=True, no_grad=True,
             attr_defaults={"endpoints": [], "file_path": "", "shape": [],
                            "slice_shapes": [], "slice_varnames": [],
                            "remote_varnames": [], "is_sparse": False,
                            "trainer_id": 0})
def _recv_save(ins, attrs):
    """Fetch parameter slices from pservers and save the concatenation to
    disk (reference recv_save_op.cc)."""
    from ..fluid.ps_rpc import VarClient
    from ..fluid.io import _serialize_lod_tensor
    pieces = []
    for ep, name in zip(attrs.get("endpoints") or [],
                        attrs.get("remote_varnames") or []):
        c = VarClient(ep)
        v = c.get_var(name)
        pieces.append(np.asarray(v))
    if pieces:
        full = np.concatenate([p.reshape(-1) for p in pieces]).reshape(
            [int(s) for s in attrs.get("shape")])
        with open(attrs["file_path"], "wb") as f:
            f.write(_serialize_lod_tensor(core.LoDTensor(
                jnp.asarray(full)), None))
    return {}


@register_op("ref_by_trainer_id", stateful=True, no_grad=True)
def _ref_by_trainer_id(ins, attrs):
    """Select X[trainer_id] (reference ref_by_trainer_id_op.cc)."""
    ctx = attrs["_ctx"]
    tid = int(np.asarray(ctx.scope.find_var(
        ctx.op.input("TrainerId")[0]).get_tensor().array).reshape(-1)[0])
    src = ctx.scope.find_var(ctx.op.input("X")[tid]).value()
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(src)
    return {}


@register_op("prefetch", stateful=True, no_grad=True,
             attr_defaults={"epmap": [], "table_names": [],
                            "trainer_id": 0})
def _prefetch(ins, attrs):
    """Prefetch remote embedding rows by id (reference prefetch_op.cc) —
    same remote path as distributed_lookup_table."""
    from .distributed_ops import _distributed_lookup_table
    return _distributed_lookup_table(ins, attrs)


# fl_listen_and_serv: federated variant — same server loop
def _fl_listen_and_serv(ins, attrs):
    from .distributed_ops import _listen_and_serv
    return _listen_and_serv(ins, attrs)


register_op("fl_listen_and_serv", stateful=True, no_grad=True,
            attr_defaults={"endpoint": "", "sync_mode": True, "Fanin": 1,
                           "grad_to_block_id": [], "sparse_lr": 0.01,
                           "distributed_mode": 0})(_fl_listen_and_serv)


# --------------------------------------------------------------------------
# DGC — deep gradient compression (reference dgc_op.cc): top-k sparsify
# with momentum correction; U/V are the velocity/error-feedback buffers
# --------------------------------------------------------------------------
@register_op("dgc", inputs=("U", "V", "Grad", "Param",
                            "current_step", "nranks"),
             no_grad=True,
             attr_defaults={"m": 0.9, "use_nesterov": False,
                            "sparsity": [0.999], "rampup_begin_step": 0.0,
                            "rampup_step": 1.0, "regular_coeff": 0.0,
                            "regular_type": 0})
def _dgc(ins, attrs):
    u, v, g = first(ins, "U"), first(ins, "V"), first(ins, "Grad")
    step_t = first(ins, "current_step")
    step = jnp.asarray(step_t.reshape(()) if step_t is not None else 0.0,
                       jnp.float32)
    m = attrs.get("m", 0.9)
    begin = attrs.get("rampup_begin_step", 0.0)
    sparsity = attrs.get("sparsity", [0.999]) or [0.999]
    s = float(sparsity[-1])
    numel = g.size
    # momentum correction + error feedback (DGC paper / dgc_op.h)
    u_new = m * u + g
    v_new = v + u_new
    flat = v_new.reshape(-1)
    k = max(1, int(numel * (1.0 - s)))
    thr = jnp.sort(jnp.abs(flat))[numel - k]
    mask = (jnp.abs(flat) >= thr)
    encode = jnp.where(mask, flat, 0.0)
    residual = jnp.where(mask, 0.0, flat)
    ramping = step >= begin
    u_out = jnp.where(ramping, jnp.where(mask, 0.0, u_new.reshape(-1)),
                      u_new.reshape(-1)).reshape(u.shape)
    v_out = jnp.where(ramping, residual, jnp.zeros_like(flat)).reshape(
        v.shape)
    g_out = jnp.where(ramping, encode, g.reshape(-1)).reshape(g.shape)
    return {"U_out": [u_out], "V_out": [v_out],
            "EncodeGrad": [g_out.reshape(-1)], "Grad_out": [g_out],
            "k": [jnp.asarray([float(k)], jnp.float32)],
            "GatherBuff": [g_out.reshape(-1)]}


@register_op("dgc_clip_by_norm", inputs=("X", "current_step"),
             diff_inputs=("X",),
             attr_defaults={"max_norm": 1.0, "rampup_begin_step": 0.0})
def _dgc_clip_by_norm(ins, attrs):
    x = first(ins, "X")
    step = first(ins, "current_step")
    begin = attrs.get("rampup_begin_step", 0.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    mx = attrs.get("max_norm", 1.0)
    clipped = x * jnp.minimum(1.0, mx / jnp.maximum(norm, 1e-10))
    use = (step.reshape(())[()] if step is not None else 0.0)
    return out(Out=jnp.where(
        (jnp.asarray(use, jnp.float32) >= begin), clipped, x))


@register_op("dgc_momentum",
             inputs=("Param", "Grad", "Velocity", "LearningRate",
                     "current_step", "nranks"),
             no_grad=True, stateful=False,
             attr_defaults={"mu": 0.9, "use_nesterov": False,
                            "rampup_begin_step": 0.0})
def _dgc_momentum(ins, attrs):
    """Before rampup_begin_step: plain momentum; after: SGD (the momentum
    correction then lives inside the dgc op — reference
    dgc_momentum_op.h)."""
    p = first(ins, "Param")
    g = first(ins, "Grad")
    vel = first(ins, "Velocity")
    lr = first(ins, "LearningRate").reshape(())
    step = first(ins, "current_step")
    mu = attrs.get("mu", 0.9)
    begin = attrs.get("rampup_begin_step", 0.0)
    use_mom = (jnp.asarray(step.reshape(()) if step is not None else 0.0,
                           jnp.float32) < begin)
    new_vel = mu * vel + g
    upd_mom = p - lr * (g + mu * new_vel if attrs.get("use_nesterov", False)
                        else new_vel)
    upd_sgd = p - lr * g
    return {"ParamOut": [jnp.where(use_mom, upd_mom, upd_sgd)],
            "VelocityOut": [jnp.where(use_mom, new_vel, vel)]}


# --------------------------------------------------------------------------
# reader ops
# --------------------------------------------------------------------------
@register_op("create_py_reader", stateful=True, no_grad=True,
             attr_defaults={"shape_concat": [], "lod_levels": [],
                            "ranks": [], "dtypes": []})
def _create_py_reader(ins, attrs):
    """Bind a blocking queue var into a reader var (reference
    reader/create_py_reader_op.cc)."""
    ctx = attrs["_ctx"]
    qvar = ctx.scope.find_var(ctx.op.input("blocking_queue")[0])
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(qvar.value())
    return {}


def _identity_reader(ins, attrs):
    ctx = attrs["_ctx"]
    src = ctx.scope.find_var(ctx.op.input("UnderlyingReader")[0]).value()
    ctx.scope.var(ctx.op.output("Out")[0]).set_value(src)
    return {}


register_op("create_double_buffer_reader", stateful=True, no_grad=True,
            attr_defaults={"place": ""})(_identity_reader)
register_op("create_custom_reader", stateful=True, no_grad=True,
            attr_defaults={})(_identity_reader)


@register_op("read", stateful=True, no_grad=True,
             attr_defaults={"throw_eof_exp": True})
def _read(ins, attrs):
    """Pop one batch from the reader queue into the output vars
    (reference reader/read_op.cc); raises StopIteration at end of data."""
    ctx = attrs["_ctx"]
    q = ctx.scope.find_var(ctx.op.input("Reader")[0]).value()
    batch = q.pop()
    if batch is None:
        raise StopIteration("read op: reader exhausted")
    outs = ctx.op.output("Out")
    for on, arr in zip(outs, batch):
        if isinstance(arr, core.LoDTensor):
            ctx.scope.var(on).set_value(arr)
        else:
            ctx.scope.var(on).set_value(
                core.LoDTensor(jnp.asarray(arr)))
    return {}


# --------------------------------------------------------------------------
# cudnn_lstm alias / run_program / engine stubs
# --------------------------------------------------------------------------
def _cudnn_lstm(ins, attrs):
    """Dense multi-layer (bi)LSTM — same kernel as the `lstm` op; the
    cudnn-specific weight-buffer layout is shared (rnn_ops._lstm)."""
    from .rnn_ops import _lstm
    return _lstm(ins, attrs)


register_op("cudnn_lstm", needs_rng=True,
            diff_inputs=["Input", "W", "InitH", "InitC"],
            attr_defaults={"max_len": 0, "hidden_size": 0, "num_layers": 1,
                           "is_bidirec": False, "dropout_prob": 0.0,
                           "input_size": 0, "is_test": False,
                           "seed": 0})(_cudnn_lstm)


@register_op("run_program", stateful=True, no_grad=True,
             attr_defaults={"is_test": False})
def _run_program(ins, attrs):
    """Execute a captured sub-block over the current scope (reference
    run_program_op.cc — the dygraph-to-static bridge)."""
    ctx = attrs["_ctx"]
    block = attrs.get("sub_block") or attrs.get("global_block")
    if block is None:
        raise ValueError("run_program: missing sub_block attr")
    ctx.executor._run_block_eager(block, ctx.scope, ctx.rng_base)
    return {}


def _engine_stub(name, what):
    @register_op(name, stateful=True, no_grad=True)
    def _stub(ins, attrs):
        raise NotImplementedError(
            f"{name}: {what} On TPU the inference path is XLA ahead-of-time "
            "compilation (AnalysisPredictor compiles the whole program); "
            "no engine subgraph offload exists or is needed.")
    return _stub


_engine_stub("tensorrt_engine", "TensorRT subgraph offload op.")
_engine_stub("lite_engine", "Paddle-Lite subgraph offload op.")
