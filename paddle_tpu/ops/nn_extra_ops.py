"""Additional NN op kernels: maxout, affine_channel, position encoding,
bilinear tensor product, CVM, FSP, temporal shift, unfold, mean_iou,
sequence_mask, row_conv, focal loss, iou (reference: the same-named ops
under paddle/fluid/operators/)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, seq, out
from ..fluid.core import dtype_to_jnp


@register_op("maxout", inputs=("X",), attr_defaults={"groups": 1, "axis": 1})
def _maxout(ins, attrs):
    x = first(ins, "X")
    g = attrs.get("groups", 1)
    ax = attrs.get("axis", 1) % x.ndim
    c = x.shape[ax]
    shape = x.shape[:ax] + (c // g, g) + x.shape[ax + 1:]
    return out(Out=jnp.max(x.reshape(shape), axis=ax + 1))


@register_op("affine_channel", inputs=("X", "Scale", "Bias"),
             diff_inputs=("X", "Scale", "Bias"),
             attr_defaults={"data_layout": "NCHW"})
def _affine_channel(ins, attrs):
    x, scale, bias = first(ins, "X"), first(ins, "Scale"), first(ins, "Bias")
    c_axis = 1 if attrs.get("data_layout", "NCHW") == "NCHW" else x.ndim - 1
    shp = [1] * x.ndim
    shp[c_axis] = x.shape[c_axis]
    return out(Out=x * scale.reshape(shp) + bias.reshape(shp))


@register_op("add_position_encoding", inputs=("X",),
             attr_defaults={"alpha": 1.0, "beta": 1.0})
def _add_position_encoding(ins, attrs):
    x = first(ins, "X")
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=x.dtype) / half)[None, :]
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return out(Out=attrs.get("alpha", 1.0) * x
               + attrs.get("beta", 1.0) * enc[None, :, :])


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"),
             diff_inputs=("X", "Y", "Weight", "Bias"))
def _bilinear_tensor_product(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    w = first(ins, "Weight")  # [size, dx, dy]
    o = jnp.einsum("bi,kij,bj->bk", x, w, y)
    b = first(ins, "Bias")
    if b is not None:
        o = o + b.reshape(1, -1)
    return out(Out=o)


@register_op("cvm", inputs=("X", "CVM"), diff_inputs=("X",),
             attr_defaults={"use_cvm": True})
def _cvm(ins, attrs):
    x = first(ins, "X")
    if attrs.get("use_cvm", True):
        show_clk = jnp.log(jnp.maximum(x[:, :2], 0.0) + 1.0)
        return out(Y=jnp.concatenate([show_clk, x[:, 2:]], axis=1))
    return out(Y=x[:, 2:])


@register_op("fsp", inputs=("X", "Y"))
def _fsp(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    n, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    h = x.shape[2] * x.shape[3]
    xf = x.reshape(n, cx, h)
    yf = y.reshape(n, cy, h)
    return out(Out=jnp.einsum("nch,ndh->ncd", xf, yf) / h)


@register_op("temporal_shift", inputs=("X",),
             attr_defaults={"seg_num": 1, "shift_ratio": 0.25})
def _temporal_shift(ins, attrs):
    x = first(ins, "X")
    seg = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg
    xr = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    pad = jnp.pad(xr, [(0, 0), (1, 1), (0, 0), (0, 0), (0, 0)])
    slice1 = pad[:, :seg, :c1]
    slice2 = pad[:, 2:seg + 2, c1:c2]
    slice3 = pad[:, 1:seg + 1, c2:]
    return out(Out=jnp.concatenate([slice1, slice2, slice3],
                                   axis=2).reshape(nt, c, h, w))


@register_op("unfold", inputs=("X",), diff_inputs=("X",),
             attr_defaults={"kernel_sizes": [1, 1], "strides": [1, 1],
                            "paddings": [0, 0, 0, 0], "dilations": [1, 1]})
def _unfold(ins, attrs):
    x = first(ins, "X")
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs["strides"]
    p = attrs["paddings"]
    dh, dw = attrs["dilations"]
    n, c = x.shape[0], x.shape[1]
    xp = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
    H, W = xp.shape[2], xp.shape[3]
    oh = (H - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(lax.slice(
                xp, (0, 0, i * dh, j * dw),
                (n, c, i * dh + (oh - 1) * sh + 1, j * dw + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
    stacked = jnp.stack(patches, axis=2)  # [n, c, kh*kw, oh, ow]
    return out(Y=stacked.reshape(n, c * kh * kw, oh * ow))


@register_op("mean_iou", inputs=("Predictions", "Labels"), no_grad=True,
             attr_defaults={"num_classes": 2})
def _mean_iou(ins, attrs):
    pred = first(ins, "Predictions").reshape(-1)
    label = first(ins, "Labels").reshape(-1)
    k = attrs["num_classes"]
    valid = (label >= 0) & (label < k)
    idx = label * k + pred
    cm = jnp.zeros((k * k,), jnp.int32).at[idx].add(valid.astype(jnp.int32))
    cm = cm.reshape(k, k)
    inter = jnp.diag(cm).astype(jnp.float32)
    union = (jnp.sum(cm, 0) + jnp.sum(cm, 1)).astype(jnp.float32) - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    denom = jnp.maximum(jnp.sum(union > 0), 1)
    wrong = (jnp.sum(cm, 0) - jnp.diag(cm)).astype(jnp.int32)
    correct = jnp.diag(cm).astype(jnp.int32)
    return out(OutMeanIou=(jnp.sum(iou) / denom).reshape((1,)),
               OutWrong=wrong, OutCorrect=correct)


@register_op("sequence_mask", inputs=("X", "MaxLenTensor"), no_grad=True,
             attr_defaults={"maxlen": -1, "out_dtype": 3})
def _sequence_mask(ins, attrs):
    x = first(ins, "X")
    mt = first(ins, "MaxLenTensor")
    maxlen = attrs.get("maxlen", -1)
    if mt is not None:
        maxlen = int(np.asarray(mt).reshape(()))
    if maxlen is None or maxlen < 0:
        maxlen = int(np.asarray(jnp.max(x)))
    rng = jnp.arange(maxlen)
    mask = rng[None, :] < x.reshape(-1, 1)
    mask = mask.reshape(x.shape + (maxlen,))
    return out(Y=mask.astype(dtype_to_jnp(attrs.get("out_dtype", 3))))


@register_op("row_conv", inputs=("X", "Filter"), diff_inputs=("X", "Filter"))
def _row_conv(ins, attrs):
    x, w = first(ins, "X"), first(ins, "Filter")
    # batched dense path: x [B, T, D], filter [future+1, D]
    k = w.shape[0]
    t = x.shape[-2]
    pad = jnp.pad(x, [(0, 0)] * (x.ndim - 2) + [(0, k - 1), (0, 0)])
    o = sum(pad[..., i:i + t, :] * w[i] for i in range(k))
    return out(Out=o)


@register_op("sigmoid_focal_loss", inputs=("X", "Label", "FgNum"),
             diff_inputs=("X",),
             attr_defaults={"gamma": 2.0, "alpha": 0.25})
def _sigmoid_focal_loss(ins, attrs):
    x, label, fg = first(ins, "X"), first(ins, "Label"), first(ins, "FgNum")
    gamma, alpha = attrs.get("gamma", 2.0), attrs.get("alpha", 0.25)
    n, c = x.shape
    fg = jnp.maximum(fg.reshape(()).astype(x.dtype), 1.0)
    t = jax.nn.one_hot(jnp.squeeze(label, -1) if label.ndim == 2 else label,
                       c + 1)[:, 1:]
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    return out(Out=a_t * ((1 - p_t) ** gamma) * ce / fg)


@register_op("iou_similarity", inputs=("X", "Y"), no_grad=True,
             attr_defaults={"box_normalized": True})
def _iou_similarity(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    norm = attrs.get("box_normalized", True)
    eps = 0.0 if norm else 1.0
    ax1, ay1, ax2, ay2 = [x[..., i] for i in range(4)]
    bx1, by1, bx2, by2 = [y[..., i] for i in range(4)]
    area_a = (ax2 - ax1 + eps) * (ay2 - ay1 + eps)
    area_b = (bx2 - bx1 + eps) * (by2 - by1 + eps)
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1 + eps, 0.0)
    ih = jnp.maximum(iy2 - iy1 + eps, 0.0)
    inter = iw * ih
    return out(Out=inter / (area_a[:, None] + area_b[None, :] - inter))


@register_op("grid_sampler", inputs=("X", "Grid"),
             diff_inputs=("X", "Grid"))
def _grid_sampler(ins, attrs):
    x, grid = first(ins, "X"), first(ins, "Grid")
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wa = (x1 - gx) * (y1 - gy)
    wb = (x1 - gx) * (gy - y0)
    wc = (gx - x0) * (y1 - gy)
    wd = (gx - x0) * (gy - y0)

    def sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        bidx = jnp.arange(n)[:, None, None]
        return x[bidx, :, yi, xi]  # [n, oh, ow, c]
    va = sample(x0, y0)
    vb = sample(x0, y1)
    vc = sample(x1, y0)
    vd = sample(x1, y1)
    o = (va * wa[..., None] + vb * wb[..., None] + vc * wc[..., None]
         + vd * wd[..., None])
    return out(Output=jnp.transpose(o, (0, 3, 1, 2)))


@register_op("pad_constant_batch_size_like", inputs=("X", "Y"),
             diff_inputs=("Y",))
def _pad_constant_bsl(ins, attrs):
    return out(Out=first(ins, "Y"))


@register_op("squared_l2_distance", inputs=("X", "Y"))
def _squared_l2_distance(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    d = x - y
    return out(sub_result=d,
               Out=jnp.sum(jnp.square(d).reshape(d.shape[0], -1), -1,
                           keepdims=True))


@register_op("center_loss",
             inputs=("X", "Label", "Centers", "CenterUpdateRate"),
             diff_inputs=("X",), attr_defaults={"cluster_num": 0,
                                                "need_update": True})
def _center_loss(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    centers = first(ins, "Centers")
    rate = first(ins, "CenterUpdateRate").reshape(())
    lbl = label.reshape(-1).astype(jnp.int32)
    picked = centers[lbl]
    diff = x - picked
    loss = 0.5 * jnp.sum(jnp.square(diff), -1, keepdims=True)
    counts = jnp.zeros((centers.shape[0],), x.dtype).at[lbl].add(1.0)
    upd = jnp.zeros_like(centers).at[lbl].add(diff)
    new_centers = centers + rate * upd / (counts[:, None] + 1.0)
    return out(Loss=loss, SampleCenterDiff=diff, CentersOut=new_centers)
