"""Collective ops — the reference's NCCL data path re-expressed as XLA ICI
collectives (reference: paddle/fluid/operators/collective/ —
c_allreduce_op.h:73,105 looks up an NCCL comm by ring_id and issues
ncclAllReduce on the comm stream; c_broadcast, c_allgather, c_reducescatter,
c_comm_init*, c_gen_nccl_id, c_sync_*_stream).

TPU design: a ring_id maps to a *named mesh axis* (registered by the
parallel runtime — parallel/env.py). Inside a pjit/shard_map trace over that
axis the kernels lower to lax.psum / all_gather / psum_scatter / ppermute,
which XLA schedules on ICI. Outside any mesh (single chip, world_size 1)
they are identity — exactly matching NCCL semantics with one rank.
Stream-sync ops are no-ops: XLA's schedule already orders compute and
collectives. Comm-bootstrap ops (c_gen_nccl_id/c_comm_init) register the
ring→axis mapping instead of exchanging NCCL ids."""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, out

# ring_id -> mesh axis name; None = not in a mesh (identity collectives)
_RING_AXIS: Dict[int, Optional[str]] = {}


def set_ring_axis(ring_id: int, axis_name: Optional[str]):
    _RING_AXIS[ring_id] = axis_name


def get_ring_axis(ring_id: int) -> Optional[str]:
    return _RING_AXIS.get(int(ring_id))


def _axis_in_scope(axis: Optional[str]) -> bool:
    if axis is None:
        return False
    try:
        lax.axis_index(axis)  # raises NameError outside the axis scope
        return True
    except NameError:
        return False
    except Exception:
        return False


def _register_allreduce(name, op):
    @register_op(name, attr_defaults={"ring_id": 0, "use_calc_stream": False})
    def _kernel(ins, attrs, _op=op):
        x = first(ins, "X")
        axis = get_ring_axis(attrs.get("ring_id", 0))
        if not _axis_in_scope(axis):
            return out(Out=x)
        return out(Out=_op(x, axis))
    return _kernel


_register_allreduce("c_allreduce_sum", lambda x, a: lax.psum(x, a))
_register_allreduce("c_allreduce_max", lambda x, a: lax.pmax(x, a))
_register_allreduce("c_allreduce_min", lambda x, a: lax.pmin(x, a))
_register_allreduce("c_allreduce_prod",
                    lambda x, a: jnp.exp(lax.psum(jnp.log(x), a)))
_register_allreduce("allreduce", lambda x, a: lax.psum(x, a))


@register_op("c_broadcast", attr_defaults={"ring_id": 0, "root": 0,
                                           "use_calc_stream": False})
def _c_broadcast(ins, attrs):
    x = first(ins, "X")
    axis = get_ring_axis(attrs.get("ring_id", 0))
    if not _axis_in_scope(axis):
        return out(Out=x)
    root = attrs.get("root", 0)
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return out(Out=lax.psum(masked, axis))


@register_op("broadcast", attr_defaults={"root": 0, "sync_mode": False})
def _broadcast(ins, attrs):
    return _c_broadcast(ins, {"ring_id": 0, "root": attrs.get("root", 0)})


@register_op("c_allgather", attr_defaults={"ring_id": 0, "nranks": 1,
                                           "use_calc_stream": False})
def _c_allgather(ins, attrs):
    x = first(ins, "X")
    axis = get_ring_axis(attrs.get("ring_id", 0))
    if not _axis_in_scope(axis):
        return out(Out=x)
    return out(Out=lax.all_gather(x, axis, axis=0, tiled=True))


@register_op("c_reducescatter", attr_defaults={"ring_id": 0, "nranks": 1,
                                               "use_calc_stream": False})
def _c_reducescatter(ins, attrs):
    x = first(ins, "X")
    axis = get_ring_axis(attrs.get("ring_id", 0))
    if not _axis_in_scope(axis):
        return out(Out=x)
    return out(Out=lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True))


@register_op("c_sync_calc_stream")
def _c_sync_calc_stream(ins, attrs):
    return out(Out=first(ins, "X"))  # XLA schedule orders compute


@register_op("c_sync_comm_stream", attr_defaults={"ring_id": 0})
def _c_sync_comm_stream(ins, attrs):
    return out(Out=first(ins, "X"))  # XLA schedule orders collectives


@register_op("c_comm_init", stateful=True, no_grad=True,
             attr_defaults={"nranks": 1, "rank": 0, "ring_id": 0,
                            "device_id": 0})
def _c_comm_init(ins, attrs):
    # NCCL comm creation ⇒ ring→axis registration (axis named by the
    # parallel runtime; default data-parallel axis is "dp")
    ring = attrs.get("ring_id", 0)
    if get_ring_axis(ring) is None and attrs.get("nranks", 1) > 1:
        set_ring_axis(ring, "dp")
    return {}


@register_op("c_comm_init_all", stateful=True, no_grad=True,
             attr_defaults={"devices": [], "ring_id": 0})
def _c_comm_init_all(ins, attrs):
    ring = attrs.get("ring_id", 0)
    if get_ring_axis(ring) is None:
        set_ring_axis(ring, "dp")
    return {}


@register_op("c_gen_nccl_id", stateful=True, no_grad=True,
             attr_defaults={"rank": 0, "endpoint": "",
                            "other_endpoints": [], "ring_id": 0})
def _c_gen_nccl_id(ins, attrs):
    return {}  # no NCCL id on TPU: ICI topology is static


@register_op("gen_nccl_id", stateful=True, no_grad=True,
             attr_defaults={"trainers": [], "trainer_id": 0,
                            "nccl_comm_num": 1,
                            "use_hierarchical_allreduce": False,
                            "hierarchical_allreduce_inter_nranks": 1})
def _gen_nccl_id(ins, attrs):
    return {}


# Legacy single-op NCCL path (reference: operators/nccl/nccl_op.cu.cc —
# the pre-c_* allreduce op). Same semantics as c_allreduce_sum on the dp
# mesh axis; registered so reference-era programs still load.
from .registry import OPS as _OPS
if not _OPS.has("nccl"):
    _nccl_info = _OPS.get_or_create("nccl")
    _src = _OPS.get("allreduce") if _OPS.has("allreduce") else \
        _OPS.get("c_allreduce_sum")
    _nccl_info.kernel = _src.kernel
    _nccl_info.no_grad = True
    _nccl_info.stateful = _src.stateful
    _nccl_info.attr_defaults = dict(_src.attr_defaults)
