"""Vision op batch 2: crop, affine_grid, unpool, SPP, position-sensitive /
precise RoI pooling, transposed 3d/depthwise convs, deformable convs,
conv_shift, bicubic/trilinear interpolation, similarity_focus,
polygon_box_transform, inplace_abn (reference: the same-named ops under
paddle/fluid/operators/ — crop_op.cc, affine_grid_op.cc, unpool_op.cc,
spp_op.cc, psroi_pool_op.cc, prroi_pool_op.cc, conv_transpose_op.cc,
deformable_conv_op.cc, conv_shift_op.cc, interpolate_op.cc,
similarity_focus_op.cc, polygon_box_transform_op.cc, inplace_abn_op.cc).

All kernels are pure JAX: gathers/scatters and einsums XLA maps onto the
TPU VPU/MXU; no per-pixel host loops."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, seq, out


# --------------------------------------------------------------------------
# crop family
# --------------------------------------------------------------------------
def _crop_impl(x, offsets, shape):
    offsets = [int(o) for o in offsets]
    shape = [x.shape[i] if s in (-1, 0) else int(s)
             for i, s in enumerate(shape)]
    return lax.slice(x, offsets, [o + s for o, s in zip(offsets, shape)])


@register_op("crop", inputs=("X", "Y", "Offsets"), diff_inputs=("X",),
             attr_defaults={"offsets": [], "shape": []})
def _crop(ins, attrs):
    x = first(ins, "X")
    y = first(ins, "Y")
    shape = list(y.shape) if y is not None else attrs.get("shape") or list(x.shape)
    off_t = first(ins, "Offsets")
    offsets = (list(np.asarray(off_t).astype(int)) if off_t is not None
               else attrs.get("offsets") or [0] * x.ndim)
    return out(Out=_crop_impl(x, offsets, shape))


@register_op("crop_tensor", inputs=("X", "Shape", "Offsets", "ShapeTensor",
                                    "OffsetsTensor"),
             diff_inputs=("X",),
             attr_defaults={"offsets": [], "shape": []})
def _crop_tensor(ins, attrs):
    x = first(ins, "X")
    sh_t = first(ins, "Shape")
    if sh_t is not None:
        shape = list(np.asarray(sh_t).astype(int))
    elif seq(ins, "ShapeTensor"):
        shape = [int(np.asarray(s).reshape(())) for s in seq(ins, "ShapeTensor")]
    else:
        shape = attrs.get("shape") or list(x.shape)
    off_t = first(ins, "Offsets")
    if off_t is not None:
        offsets = list(np.asarray(off_t).astype(int))
    elif seq(ins, "OffsetsTensor"):
        offsets = [int(np.asarray(o).reshape(()))
                   for o in seq(ins, "OffsetsTensor")]
    else:
        offsets = attrs.get("offsets") or [0] * x.ndim
    return out(Out=_crop_impl(x, offsets, shape))


# --------------------------------------------------------------------------
# affine_grid — theta [N,2,3] -> sampling grid [N,H,W,2] in [-1,1] coords
# --------------------------------------------------------------------------
@register_op("affine_grid", inputs=("Theta", "OutputShape"),
             diff_inputs=("Theta",),
             attr_defaults={"output_shape": [], "align_corners": True})
def _affine_grid(ins, attrs):
    theta = first(ins, "Theta")
    osh = first(ins, "OutputShape")
    if osh is not None:
        n, c, h, w = [int(v) for v in np.asarray(osh)]
    else:
        n, c, h, w = [int(v) for v in attrs.get("output_shape")]
    ac = attrs.get("align_corners", True)
    if ac:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        xs = (jnp.arange(w) * 2 + 1) / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
    o = jnp.einsum("hwk,njk->nhwj", base.astype(theta.dtype), theta)
    return out(Output=o)


# --------------------------------------------------------------------------
# unpool — max-unpooling by the Mask produced by max_pool2d_with_index
# --------------------------------------------------------------------------
@register_op("unpool", inputs=("X", "Indices"), diff_inputs=("X",),
             attr_defaults={"unpooling_type": "max", "ksize": [2, 2],
                            "strides": [2, 2], "paddings": [0, 0]})
def _unpool(ins, attrs):
    x, idx = first(ins, "X"), first(ins, "Indices")
    n, c, ih, iw = x.shape
    kh, kw = [int(k) for k in attrs.get("ksize", [2, 2])]
    sh, sw = [int(s) for s in attrs.get("strides", [2, 2])]
    ph, pw = [int(p) for p in attrs.get("paddings", [0, 0])]
    oh = (ih - 1) * sh - 2 * ph + kh
    ow = (iw - 1) * sw - 2 * pw + kw
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    flat = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
        idx.reshape(n, c, ih * iw).astype(jnp.int32)].add(
            x.reshape(n, c, ih * iw))
    return out(Out=flat.reshape(n, c, oh, ow))


# --------------------------------------------------------------------------
# spp — spatial pyramid pooling: level p pools to 2^p x 2^p bins, concat
# --------------------------------------------------------------------------
@register_op("spp", inputs=("X",),
             attr_defaults={"pyramid_height": 1, "pooling_type": "max"})
def _spp(ins, attrs):
    x = first(ins, "X")
    n, c, h, w = x.shape
    ptype = attrs.get("pooling_type", "max")
    pieces = []
    for p in range(int(attrs.get("pyramid_height", 1))):
        bins = 2 ** p
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        if ptype == "max":
            neg = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                   else jnp.iinfo(x.dtype).min)
            xp = jnp.pad(x, [(0, 0), (0, 0), (ph, kh * bins - h - ph),
                             (pw, kw * bins - w - pw)], constant_values=neg)
            r = jnp.max(xp.reshape(n, c, bins, kh, bins, kw), axis=(3, 5))
        else:
            xp = jnp.pad(x, [(0, 0), (0, 0), (ph, kh * bins - h - ph),
                             (pw, kw * bins - w - pw)])
            r = jnp.mean(xp.reshape(n, c, bins, kh, bins, kw), axis=(3, 5))
        pieces.append(r.reshape(n, c * bins * bins))
    return out(Out=jnp.concatenate(pieces, axis=1))


# --------------------------------------------------------------------------
# position-sensitive / precise RoI pooling
# --------------------------------------------------------------------------
def _roi_batch_ids(attrs, slot, num_rois):
    """Map each RoI to its image index from the slot's host-static LoD
    (same contract as detection_ops._roi_align)."""
    lod = (attrs.get("_lod") or {}).get(slot)
    if lod and lod[0]:
        offs = np.asarray(lod[0][-1], np.int64)
        bids = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
        return jnp.asarray(bids[:num_rois], jnp.int32)
    return jnp.zeros(num_rois, jnp.int32)


@register_op("psroi_pool", inputs=("X", "ROIs"), diff_inputs=("X",),
             needs_lod=True,
             attr_defaults={"output_channels": 1, "spatial_scale": 1.0,
                            "pooled_height": 1, "pooled_width": 1})
def _psroi_pool(ins, attrs):
    x, rois = first(ins, "X"), first(ins, "ROIs")
    n, c, h, w = x.shape
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    oc = int(attrs.get("output_channels", 1))
    scale = attrs.get("spatial_scale", 1.0)
    batch_ids = _roi_batch_ids(attrs, "ROIs", rois.shape[0])

    x0 = jnp.round(rois[:, 0]) * scale
    y0 = jnp.round(rois[:, 1]) * scale
    x1 = jnp.round(rois[:, 2] + 1.0) * scale
    y1 = jnp.round(rois[:, 3] + 1.0) * scale
    rw = jnp.maximum(x1 - x0, 0.1)
    rh = jnp.maximum(y1 - y0, 0.1)
    bin_h = rh / ph          # [R]
    bin_w = rw / pw
    # per (roi, oc, i, j): average x[b, oc*ph*pw block, bin] — gather a
    # fixed 2x2 sample grid per bin (TPU-friendly static shapes)
    S = 2
    iy = jnp.arange(ph)
    ix = jnp.arange(pw)
    sy = (jnp.arange(S) + 0.5) / S
    # sample coords [R, ph, S]
    ys = y0[:, None, None] + (iy[None, :, None] + sy[None, None, :]) * bin_h[:, None, None]
    xs = x0[:, None, None] + (ix[None, :, None] + sy[None, None, :]) * bin_w[:, None, None]
    yc = jnp.clip(ys, 0, h - 1).astype(jnp.int32)
    xc = jnp.clip(xs, 0, w - 1).astype(jnp.int32)
    # channel map: out channel k, bin (i,j) reads input channel k*ph*pw + i*pw + j
    chan = (jnp.arange(oc)[:, None, None] * (ph * pw)
            + iy[None, :, None] * pw + ix[None, None, :])  # [oc,ph,pw]
    # gather: v[r, k, i, j, a, b] = x[bid[r], chan[k,i,j], yc[r,i,a], xc[r,j,b]]
    v = x[batch_ids[:, None, None, None, None, None],
          chan[None, :, :, :, None, None],
          yc[:, None, :, None, :, None],
          xc[:, None, None, :, None, :]]
    return out(Out=jnp.mean(v, axis=(4, 5)))


@register_op("prroi_pool", inputs=("X", "ROIs", "BatchRoINums"),
             diff_inputs=("X",), needs_lod=True,
             host_inputs=("BatchRoINums",),
             attr_defaults={"spatial_scale": 1.0, "pooled_height": 1,
                            "pooled_width": 1})
def _prroi_pool(ins, attrs):
    """Precise RoI pooling (integral of bilinear surface) approximated by a
    dense 4x4 bilinear sample grid per bin — differentiable and static."""
    x, rois = first(ins, "X"), first(ins, "ROIs")
    n, c, h, w = x.shape
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = attrs.get("spatial_scale", 1.0)
    brn = first(ins, "BatchRoINums")
    if brn is not None:
        counts = np.asarray(brn).astype(int)
        bids = np.repeat(np.arange(len(counts)), counts)
        if len(bids) < rois.shape[0]:
            bids = np.pad(bids, (0, rois.shape[0] - len(bids)))
        batch_ids = jnp.asarray(bids[:rois.shape[0]], jnp.int32)
    else:
        batch_ids = _roi_batch_ids(attrs, "ROIs", rois.shape[0])
    x0, y0, x1, y1 = (rois[:, 0] * scale, rois[:, 1] * scale,
                      rois[:, 2] * scale, rois[:, 3] * scale)
    bin_h = jnp.maximum(y1 - y0, 0.0) / ph
    bin_w = jnp.maximum(x1 - x0, 0.0) / pw
    S = 4
    fy = (jnp.arange(S) + 0.5) / S
    ys = (y0[:, None, None] + (jnp.arange(ph)[None, :, None] + fy[None, None, :])
          * bin_h[:, None, None])            # [R,ph,S]
    xs = (x0[:, None, None] + (jnp.arange(pw)[None, :, None] + fy[None, None, :])
          * bin_w[:, None, None])            # [R,pw,S]
    ysc = jnp.clip(ys, 0, h - 1)
    xsc = jnp.clip(xs, 0, w - 1)
    yi0 = jnp.floor(ysc).astype(jnp.int32)
    xi0 = jnp.floor(xsc).astype(jnp.int32)
    yi1 = jnp.minimum(yi0 + 1, h - 1)
    xi1 = jnp.minimum(xi0 + 1, w - 1)
    wy = ysc - yi0
    wx = xsc - xi0
    b = batch_ids[:, None, None, None, None, None]

    def g(yi, xi):
        return x[b, jnp.arange(c)[None, :, None, None, None, None],
                 yi[:, None, :, None, :, None], xi[:, None, None, :, None, :]]
    v = (g(yi0, xi0) * (1 - wy)[:, None, :, None, :, None] * (1 - wx)[:, None, None, :, None, :]
         + g(yi0, xi1) * (1 - wy)[:, None, :, None, :, None] * wx[:, None, None, :, None, :]
         + g(yi1, xi0) * wy[:, None, :, None, :, None] * (1 - wx)[:, None, None, :, None, :]
         + g(yi1, xi1) * wy[:, None, :, None, :, None] * wx[:, None, None, :, None, :])
    return out(Out=jnp.mean(v, axis=(4, 5)))


# --------------------------------------------------------------------------
# transposed convs (3d / depthwise)
# --------------------------------------------------------------------------
@register_op("conv3d_transpose", inputs=("Input", "Filter", "Bias"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                            "dilations": [1, 1, 1], "groups": 1,
                            "output_size": [], "padding_algorithm": "EXPLICIT",
                            "data_format": "NCDHW", "use_cudnn": True})
def _conv3d_transpose(ins, attrs):
    from .nn_ops import _conv_padding
    x, w = first(ins, "Input"), first(ins, "Filter")  # w: [in_c, out_c/g, kd, kh, kw]
    strides = [int(s) for s in attrs.get("strides", [1, 1, 1])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1])]
    pads = _conv_padding(attrs.get("paddings", [0, 0, 0]),
                         attrs.get("padding_algorithm", "EXPLICIT"),
                         3, w.shape[2:], strides, dil, x.shape[2:])
    g = attrs.get("groups", 1)
    k = w.shape[2:]
    w_t = jnp.swapaxes(w, 0, 1)[:, :, ::-1, ::-1, ::-1]
    if g > 1:
        w_t = w_t.reshape(w.shape[1], g, w.shape[0] // g, *k)
        w_t = jnp.concatenate([w_t[:, i] for i in range(g)], axis=0)
    tp = [((k[i] - 1) * dil[i] - pads[i][0], (k[i] - 1) * dil[i] - pads[i][1])
          for i in range(3)]
    o = lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1, 1), padding=tp, lhs_dilation=strides,
        rhs_dilation=dil, dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=g)
    osize = attrs.get("output_size") or []
    if osize:
        # pad up or crop down into paddle's legal [natural, natural+stride)
        grow = [max(0, osize[i] - o.shape[2 + i]) for i in (0, 1, 2)]
        if any(grow):
            o = jnp.pad(o, [(0, 0), (0, 0), (0, grow[0]), (0, grow[1]),
                            (0, grow[2])])
        o = o[:, :, :osize[0], :osize[1], :osize[2]]
    b = first(ins, "Bias")
    if b is not None:
        o = o + b.reshape(1, -1, 1, 1, 1)
    return out(Output=o)


@register_op("depthwise_conv2d_transpose", inputs=("Input", "Filter", "Bias"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "output_size": [], "padding_algorithm": "EXPLICIT",
                            "data_format": "NCHW", "use_cudnn": False})
def _depthwise_conv2d_transpose(ins, attrs):
    from .nn_ops import _conv2d_transpose
    return _conv2d_transpose(ins, attrs)


# --------------------------------------------------------------------------
# deformable convs — bilinear sampling at offset positions, then matmul
# --------------------------------------------------------------------------
def _bilinear_at(x, ys, xs):
    """x [C,H,W]; ys/xs [...]: bilinear sample, per-corner zero padding
    outside the image (matches the reference deformable_im2col: a corner
    out of range contributes 0, so border samples keep fractional weight
    rather than being clipped to full weight)."""
    c, h, w = x.shape
    y0f = jnp.floor(ys)
    x0f = jnp.floor(xs)
    y0 = y0f.astype(jnp.int32)
    x0 = x0f.astype(jnp.int32)
    wy = ys - y0f
    wx = xs - x0f

    def corner(yi, xi, wgt):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        v = x[:, jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
        return v * (wgt * valid)
    return (corner(y0, x0, (1 - wy) * (1 - wx))
            + corner(y0, x0 + 1, (1 - wy) * wx)
            + corner(y0 + 1, x0, wy * (1 - wx))
            + corner(y0 + 1, x0 + 1, wy * wx))


def _deformable_conv_impl(ins, attrs, modulated):
    x = first(ins, "Input")
    offset = first(ins, "Offset")
    mask = first(ins, "Mask") if modulated else None
    w = first(ins, "Filter")  # [out_c, in_c/g, kh, kw]
    n, cin, H, W = x.shape
    oc, cpg, kh, kw = w.shape
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("paddings", [0, 0])]
    dil = [int(d) for d in attrs.get("dilations", [1, 1])]
    g = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    oh = (H + 2 * pads[0] - (dil[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (W + 2 * pads[1] - (dil[1] * (kw - 1) + 1)) // strides[1] + 1
    # base sampling positions [oh,ow,kh,kw]
    py = (jnp.arange(oh)[:, None, None, None] * strides[0] - pads[0]
          + jnp.arange(kh)[None, None, :, None] * dil[0])
    px = (jnp.arange(ow)[None, :, None, None] * strides[1] - pads[1]
          + jnp.arange(kw)[None, None, None, :] * dil[1])
    py = jnp.broadcast_to(py, (oh, ow, kh, kw)).astype(x.dtype)
    px = jnp.broadcast_to(px, (oh, ow, kh, kw)).astype(x.dtype)
    # offset layout [N, dg*2*kh*kw, oh, ow]: (dy,dx) interleaved per tap
    off = offset.reshape(n, dg, kh * kw, 2, oh, ow)
    dy = jnp.transpose(off[:, :, :, 0], (0, 1, 3, 4, 2)).reshape(
        n, dg, oh, ow, kh, kw)
    dx = jnp.transpose(off[:, :, :, 1], (0, 1, 3, 4, 2)).reshape(
        n, dg, oh, ow, kh, kw)
    if mask is not None:
        m = jnp.transpose(mask.reshape(n, dg, kh * kw, oh, ow),
                          (0, 1, 3, 4, 2)).reshape(n, dg, oh, ow, kh, kw)
    cols = []
    cper = cin // dg
    for d in range(dg):
        ys = py[None] + dy[:, d]
        xs = px[None] + dx[:, d]
        sampled = jax.vmap(
            lambda xi, yi, xj: _bilinear_at(xi, yi, xj)
        )(x[:, d * cper:(d + 1) * cper], ys, xs)  # [n, cper, oh,ow,kh,kw]
        if mask is not None:
            sampled = sampled * m[:, d][:, None]
        cols.append(sampled)
    col = jnp.concatenate(cols, axis=1)  # [n, cin, oh, ow, kh, kw]
    # grouped contraction with the filter
    col = col.reshape(n, g, cin // g, oh, ow, kh, kw)
    wg = w.reshape(g, oc // g, cpg, kh, kw)
    o = jnp.einsum("ngchwij,gocij->ngohw", col, wg).reshape(n, oc, oh, ow)
    return out(Output=o)


@register_op("deformable_conv",
             inputs=("Input", "Offset", "Mask", "Filter"),
             diff_inputs=("Input", "Offset", "Mask", "Filter"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "deformable_groups": 1, "im2col_step": 64})
def _deformable_conv(ins, attrs):
    return _deformable_conv_impl(ins, attrs, modulated=True)


@register_op("deformable_conv_v1", inputs=("Input", "Offset", "Filter"),
             diff_inputs=("Input", "Offset", "Filter"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "deformable_groups": 1, "im2col_step": 64})
def _deformable_conv_v1(ins, attrs):
    return _deformable_conv_impl(ins, attrs, modulated=False)


@register_op("deformable_psroi_pooling",
             inputs=("Input", "ROIs", "Trans"),
             diff_inputs=("Input", "Trans"), needs_lod=True,
             attr_defaults={"no_trans": False, "spatial_scale": 1.0,
                            "output_dim": 1, "group_size": [1],
                            "pooled_height": 1, "pooled_width": 1,
                            "part_size": [1], "sample_per_part": 4,
                            "trans_std": 0.1})
def _deformable_psroi_pooling(ins, attrs):
    x, rois = first(ins, "Input"), first(ins, "ROIs")
    trans = first(ins, "Trans")
    n, c, h, w = x.shape
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    od = int(attrs.get("output_dim", 1))
    scale = attrs.get("spatial_scale", 1.0)
    ts = attrs.get("trans_std", 0.1)
    no_trans = attrs.get("no_trans", False)
    batch_ids = _roi_batch_ids(attrs, "ROIs", rois.shape[0])
    R = rois.shape[0]
    x0 = jnp.round(rois[:, 0]) * scale - 0.5
    y0 = jnp.round(rois[:, 1]) * scale - 0.5
    x1 = (jnp.round(rois[:, 2]) + 1.0) * scale - 0.5
    y1 = (jnp.round(rois[:, 3]) + 1.0) * scale - 0.5
    rw = jnp.maximum(x1 - x0, 0.1)
    rh = jnp.maximum(y1 - y0, 0.1)
    bin_h = (rh / ph)[:, None, None]
    bin_w = (rw / pw)[:, None, None]
    iy = jnp.arange(ph)[None, :, None]
    ix = jnp.arange(pw)[None, None, :]
    if no_trans or trans is None:
        dy = jnp.zeros((R, ph, pw))
        dx = jnp.zeros((R, ph, pw))
    else:
        # trans [R, 2, part_h, part_w] -> nearest part per bin
        pth, ptw = trans.shape[2], trans.shape[3]
        pyi = jnp.clip((iy * pth // ph), 0, pth - 1)
        pxi = jnp.clip((ix * ptw // pw), 0, ptw - 1)
        dy = trans[jnp.arange(R)[:, None, None], 0, pyi, pxi] * ts * rh[:, None, None]
        dx = trans[jnp.arange(R)[:, None, None], 1, pyi, pxi] * ts * rw[:, None, None]
    S = int(attrs.get("sample_per_part", 4))
    fs = (jnp.arange(S) + 0.5) / S
    ys = (y0[:, None, None] + iy * bin_h + dy)[..., None] + fs * bin_h[..., None]
    xs = (x0[:, None, None] + ix * bin_w + dx)[..., None] + fs * bin_w[..., None]
    gs = attrs.get("group_size", [1])
    gh = int(gs[0])
    gw = int(gs[1] if len(gs) > 1 else gs[0])
    # PS channel map with group_size: bin (i,j) reads input channel
    # (k*gh + floor(i*gh/ph))*gw + floor(j*gw/pw)
    gy = jnp.arange(ph) * gh // ph
    gx = jnp.arange(pw) * gw // pw
    chan = ((jnp.arange(od)[:, None, None] * gh + gy[None, :, None]) * gw
            + gx[None, None, :])  # [od,ph,pw]
    yc = jnp.clip(ys, 0, h - 1)
    xc = jnp.clip(xs, 0, w - 1)
    yi0 = jnp.floor(yc).astype(jnp.int32)
    xi0 = jnp.floor(xc).astype(jnp.int32)
    yi1 = jnp.minimum(yi0 + 1, h - 1)
    xi1 = jnp.minimum(xi0 + 1, w - 1)
    wy = yc - yi0
    wx = xc - xi0
    b = batch_ids[:, None, None, None, None, None]
    ch = chan[None, :, :, :, None, None]

    def g(yi, xi):
        return x[b, ch, yi[:, None, :, :, :, None], xi[:, None, :, :, None, :]]
    wyE = wy[:, None, :, :, :, None]
    wxE = wx[:, None, :, :, None, :]
    v = (g(yi0, xi0) * (1 - wyE) * (1 - wxE) + g(yi0, xi1) * (1 - wyE) * wxE
         + g(yi1, xi0) * wyE * (1 - wxE) + g(yi1, xi1) * wyE * wxE)
    o = jnp.mean(v, axis=(4, 5))
    return out(Output=o.astype(x.dtype), TopCount=jnp.ones_like(o))


# --------------------------------------------------------------------------
# conv_shift — circular correlation (NTM addressing)
# --------------------------------------------------------------------------
@register_op("conv_shift", inputs=("X", "Y"), diff_inputs=("X", "Y"))
def _conv_shift(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    b, w = x.shape
    k = y.shape[1]
    half = k // 2
    shifts = [jnp.roll(x, half - j, axis=1) for j in range(k)]
    stacked = jnp.stack(shifts, axis=2)          # [b, w, k]
    return out(Out=jnp.einsum("bwk,bk->bw", stacked, y))


# --------------------------------------------------------------------------
# bicubic / trilinear interpolation
# --------------------------------------------------------------------------
def _cubic_w(t, a=-0.75):
    t = jnp.abs(t)
    t2, t3 = t * t, t * t * t
    w1 = (a + 2) * t3 - (a + 3) * t2 + 1
    w2 = a * t3 - 5 * a * t2 + 8 * a * t - 4 * a
    return jnp.where(t <= 1, w1, jnp.where(t < 2, w2, 0.0))


@register_op("bicubic_interp", inputs=("X", "OutSize", "SizeTensor", "Scale"),
             diff_inputs=("X",),
             attr_defaults={"out_h": -1, "out_w": -1, "scale": 0.0,
                            "interp_method": "bicubic", "align_corners": True,
                            "align_mode": 1, "data_layout": "NCHW"})
def _bicubic_interp(ins, attrs):
    from .nn_ops import _interp_size
    x = first(ins, "X")
    oh, ow = _interp_size(ins, attrs, x)
    h, w = x.shape[2], x.shape[3]
    if attrs.get("align_corners", True):
        hs = jnp.arange(oh) * ((h - 1) / max(oh - 1, 1))
        ws = jnp.arange(ow) * ((w - 1) / max(ow - 1, 1))
    else:
        hs = (jnp.arange(oh) + 0.5) * h / oh - 0.5
        ws = (jnp.arange(ow) + 0.5) * w / ow - 0.5
    h0 = jnp.floor(hs).astype(jnp.int32)
    w0 = jnp.floor(ws).astype(jnp.int32)
    fy = hs - h0
    fx = ws - w0
    o = 0.0
    for i in range(-1, 3):
        wyi = _cubic_w(fy - i)[None, None, :, None]
        hi = jnp.clip(h0 + i, 0, h - 1)
        row = 0.0
        for j in range(-1, 3):
            wxj = _cubic_w(fx - j)[None, None, None, :]
            wj = jnp.clip(w0 + j, 0, w - 1)
            row = row + x[:, :, hi][:, :, :, wj] * wxj
        o = o + row * wyi
    return out(Out=o.astype(x.dtype))


@register_op("trilinear_interp", inputs=("X", "OutSize", "SizeTensor", "Scale"),
             diff_inputs=("X",),
             attr_defaults={"out_d": -1, "out_h": -1, "out_w": -1,
                            "scale": 0.0, "interp_method": "trilinear",
                            "align_corners": True, "align_mode": 1,
                            "data_layout": "NCDHW"})
def _trilinear_interp(ins, attrs):
    x = first(ins, "X")
    ost = first(ins, "OutSize")
    st = seq(ins, "SizeTensor")
    if ost is not None:
        od, oh, ow = [int(v) for v in np.asarray(ost)]
    elif st:
        od, oh, ow = [int(np.asarray(s).reshape(())) for s in st[:3]]
    else:
        sct = first(ins, "Scale")
        sc = (float(np.asarray(sct).reshape(())) if sct is not None
              else attrs.get("scale", 0.0))
        if sc and sc > 0:
            od, oh, ow = (int(x.shape[2] * sc), int(x.shape[3] * sc),
                          int(x.shape[4] * sc))
        else:
            od, oh, ow = (attrs.get("out_d"), attrs.get("out_h"),
                          attrs.get("out_w"))
    d, h, w = x.shape[2:]
    ac = attrs.get("align_corners", True)

    def axis_coords(o, n):
        if ac:
            return jnp.arange(o) * ((n - 1) / max(o - 1, 1))
        if attrs.get("align_mode", 1) == 0:
            return jnp.clip((jnp.arange(o) + 0.5) * n / o - 0.5, 0, n - 1)
        return jnp.clip(jnp.arange(o) * n / o, 0, n - 1)
    ds, hs, ws = axis_coords(od, d), axis_coords(oh, h), axis_coords(ow, w)
    d0 = jnp.floor(ds).astype(jnp.int32); d1 = jnp.minimum(d0 + 1, d - 1)
    h0 = jnp.floor(hs).astype(jnp.int32); h1 = jnp.minimum(h0 + 1, h - 1)
    w0 = jnp.floor(ws).astype(jnp.int32); w1 = jnp.minimum(w0 + 1, w - 1)
    ad = (ds - d0)[None, None, :, None, None]
    ah = (hs - h0)[None, None, None, :, None]
    aw = (ws - w0)[None, None, None, None, :]

    def gv(di, hi, wi):
        return x[:, :, di][:, :, :, hi][:, :, :, :, wi]
    o = (gv(d0, h0, w0) * (1 - ad) * (1 - ah) * (1 - aw)
         + gv(d0, h0, w1) * (1 - ad) * (1 - ah) * aw
         + gv(d0, h1, w0) * (1 - ad) * ah * (1 - aw)
         + gv(d0, h1, w1) * (1 - ad) * ah * aw
         + gv(d1, h0, w0) * ad * (1 - ah) * (1 - aw)
         + gv(d1, h0, w1) * ad * (1 - ah) * aw
         + gv(d1, h1, w0) * ad * ah * (1 - aw)
         + gv(d1, h1, w1) * ad * ah * aw)
    return out(Out=o.astype(x.dtype))


# --------------------------------------------------------------------------
# similarity_focus / polygon_box_transform / inplace_abn
# --------------------------------------------------------------------------
@register_op("similarity_focus", inputs=("X",),
             attr_defaults={"axis": 1, "indexes": [0]})
def _similarity_focus(ins, attrs):
    """For each selected channel (indexes along `axis`), mark with 1 the
    rows/cols holding per-row / per-col maxima; union over indexes
    (reference similarity_focus_op.h greedy selection approximated by the
    row/col argmax union — static-shape TPU formulation)."""
    x = first(ins, "X")
    ax = attrs.get("axis", 1)
    idxs = attrs.get("indexes", [0])
    # the two dims remaining after removing batch + the selected axis
    rem = [a for a in (1, 2, 3) if a != ax]
    d1, d2 = x.shape[rem[0]], x.shape[rem[1]]
    masks = jnp.zeros((x.shape[0], d1, d2), x.dtype)
    for k in idxs:
        plane = jnp.take(x, k, axis=ax)  # [n, d1, d2]
        rmax = jnp.argmax(plane, axis=2)          # [n, d1]
        cmax = jnp.argmax(plane, axis=1)          # [n, d2]
        rm = jax.nn.one_hot(rmax, d2, dtype=x.dtype)          # [n,d1,d2]
        cm = jnp.transpose(jax.nn.one_hot(cmax, d1, dtype=x.dtype),
                           (0, 2, 1))
        # union of per-row and per-column maxima of every selected plane
        masks = jnp.maximum(masks, jnp.maximum(rm, cm))
    o = jnp.broadcast_to(jnp.expand_dims(masks, ax), x.shape)
    return out(Out=o)


@register_op("polygon_box_transform", inputs=("Input",))
def _polygon_box_transform(ins, attrs):
    """EAST geometry decoding: for x-offset channels (even) the absolute
    coordinate is 4*col - offset; for y channels 4*row - offset; zero
    offsets stay zero (reference polygon_box_transform_op.cc)."""
    x = first(ins, "Input")
    n, c, h, w = x.shape
    col = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype)[None, :], (h, w))
    row = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, col[None, None], row[None, None]) * 4.0
    return out(Output=jnp.where(x != 0, base - x, x))


@register_op("inplace_abn",
             inputs=("X", "Scale", "Bias", "Mean", "Variance"),
             diff_inputs=("X", "Scale", "Bias"), stateful=True,
             attr_defaults={"momentum": 0.9, "epsilon": 1e-5,
                            "is_test": False, "data_layout": "NCHW",
                            "activation": "identity", "alpha": 0.01,
                            "use_global_stats": False,
                            "trainable_statistics": False})
def _inplace_abn(ins, attrs):
    """Activated batch norm — batch_norm followed by identity/elu/leakyrelu
    (reference inplace_abn_op.cc; the in-place memory trick is moot under
    XLA's buffer planner)."""
    from .nn_ops import _batch_norm
    r = _batch_norm(ins, attrs)
    act = attrs.get("activation", "identity")
    y = r["Y"][0] if isinstance(r["Y"], list) else r["Y"]
    if act == "elu":
        a = attrs.get("alpha", 1.0)
        y = jnp.where(y > 0, y, a * (jnp.exp(y) - 1.0))
    elif act == "leaky_relu":
        a = attrs.get("alpha", 0.01)
        y = jnp.where(y > 0, y, a * y)
    r["Y"] = [y]
    return r
