"""Detection-training long tail (reference:
operators/detection/rpn_target_assign_op.cc (also retinanet variant),
retinanet_detection_output_op.cc, locality_aware_nms_op.cc,
box_decoder_and_assign_op.cc, generate_proposal_labels_op.cc,
generate_mask_labels_op.cc, mine_hard_examples_op.cc,
roi_perspective_transform_op.cc).

All are host-side sampling/matching ops in the reference too (CPU-only
kernels); here host numpy flagged ``stateful`` so the executor runs their
blocks eagerly. Box coordinates follow the reference xyxy convention."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, seq, out
from .detection_ops import _iou_xyxy, _nms


# persistent sampling stream: a per-call RandomState(seed=0) would replay
# the identical fg/bg sample every training step
_SAMPLER = np.random.RandomState(12345)


def _rng_of(attrs):
    seed = int(attrs.get("seed", 0))
    return np.random.RandomState(seed) if seed else _SAMPLER


def _lod_offs(attrs, slot, n):
    lod = (attrs.get("_lod") or {}).get(slot)
    if lod and lod[0]:
        return np.asarray(lod[0][-1], np.int64)
    return np.asarray([0, n], np.int64)


def _box_encode(gt, anchor, weights=(1.0, 1.0, 1.0, 1.0)):
    """encode_center_size deltas of gt w.r.t. anchors (both [N,4] xyxy)."""
    aw = anchor[:, 2] - anchor[:, 0] + 1.0
    ah = anchor[:, 3] - anchor[:, 1] + 1.0
    ax = anchor[:, 0] + aw * 0.5
    ay = anchor[:, 1] + ah * 0.5
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gx = gt[:, 0] + gw * 0.5
    gy = gt[:, 1] + gh * 0.5
    wx, wy, ww, wh = weights
    return np.stack([wx * (gx - ax) / aw, wy * (gy - ay) / ah,
                     ww * np.log(gw / aw), wh * np.log(gh / ah)], axis=1)


def _iou_matrix(a, b, norm=False):
    """[Na,4] x [Nb,4] -> [Na,Nb] IoU (xyxy, +1 pixel convention)."""
    if len(a) == 0 or len(b) == 0:
        return np.zeros((len(a), len(b)), np.float32)
    off = 0.0 if norm else 1.0
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(ix2 - ix1 + off, 0) * np.maximum(iy2 - iy1 + off, 0)
    ar_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    ar_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    return inter / np.maximum(ar_a[:, None] + ar_b[None, :] - inter, 1e-10)


def _rpn_assign_one(anchors, gts, rng, pos_thr, neg_thr, fg_frac, batch,
                    use_random, retinanet=False, gt_labels=None,
                    valid=None):
    """Shared anchor-target sampling. Returns (fg_idx, bg_idx, gt_of_fg)."""
    if valid is None:
        valid = np.ones(len(anchors), bool)
    iou = _iou_matrix(anchors, gts)
    if iou.size == 0:
        return (np.zeros(0, np.int64),
                np.where(valid)[0][:batch], np.zeros(0, np.int64))
    best_gt = iou.argmax(axis=1)
    best_iou = iou.max(axis=1)
    fg_mask = best_iou >= pos_thr
    # every gt's best anchor is positive regardless of threshold
    fg_mask[iou.argmax(axis=0)] = True
    fg_mask &= valid
    bg_mask = (best_iou < neg_thr) & ~fg_mask & valid
    fg_idx = np.where(fg_mask)[0]
    bg_idx = np.where(bg_mask)[0]
    if retinanet:
        # retinanet keeps ALL fg/bg (focal loss handles imbalance)
        return fg_idx, bg_idx, best_gt[fg_idx]
    n_fg = int(batch * fg_frac)
    if len(fg_idx) > n_fg:
        fg_idx = (rng.permutation(fg_idx)[:n_fg] if use_random
                  else fg_idx[:n_fg])
    n_bg = batch - len(fg_idx)
    if len(bg_idx) > n_bg:
        bg_idx = (rng.permutation(bg_idx)[:n_bg] if use_random
                  else bg_idx[:n_bg])
    return fg_idx, bg_idx, best_gt[fg_idx]


def _rpn_like(ins, attrs, retinanet):
    anchors = np.asarray(first(ins, "Anchor")).reshape(-1, 4)
    gtb = np.asarray(first(ins, "GtBoxes"))
    goffs = _lod_offs(attrs, "GtBoxes", len(gtb))
    glab = (np.asarray(first(ins, "GtLabels")).reshape(-1)
            if retinanet else None)
    crowd_in = first(ins, "IsCrowd")
    crowd = (np.asarray(crowd_in).reshape(-1).astype(bool)
             if crowd_in is not None else np.zeros(len(gtb), bool))
    im_info = first(ins, "ImInfo")
    rng = _rng_of(attrs)
    A = len(anchors)
    # straddle filter: anchors poking further than straddle_thresh outside
    # the image are excluded from sampling (reference rpn_target_assign_op)
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    if im_info is not None and straddle >= 0 and not retinanet:
        hi = np.asarray(im_info)[0]
        h, w = float(hi[0]), float(hi[1])
        inside = ((anchors[:, 0] >= -straddle) & (anchors[:, 1] >= -straddle)
                  & (anchors[:, 2] < w + straddle)
                  & (anchors[:, 3] < h + straddle))
    else:
        inside = np.ones(A, bool)
    loc_idx, score_idx, tgt_lab, tgt_box, fg_counts = [], [], [], [], []
    lens_loc, lens_score = [], []
    for i in range(len(goffs) - 1):
        keep_gt = ~crowd[goffs[i]:goffs[i + 1]]
        gts = gtb[goffs[i]:goffs[i + 1]][keep_gt]
        labs = (glab[goffs[i]:goffs[i + 1]][keep_gt]
                if retinanet else None)
        fg, bg, gt_of = _rpn_assign_one(
            anchors, gts, rng,
            attrs.get("rpn_positive_overlap", 0.7),
            attrs.get("rpn_negative_overlap", 0.3),
            attrs.get("rpn_fg_fraction", 0.5),
            int(attrs.get("rpn_batch_size_per_im", 256)),
            attrs.get("use_random", True), retinanet=retinanet,
            valid=inside)
        base = i * A
        loc_idx.extend(base + fg)
        score_idx.extend(base + np.concatenate([fg, bg]))
        if retinanet:
            tgt_lab.extend([int(labs[g]) for g in gt_of] + [0] * len(bg))
        else:
            tgt_lab.extend([1] * len(fg) + [0] * len(bg))
        if len(fg):
            tgt_box.append(_box_encode(gts[gt_of], anchors[fg]))
        fg_counts.append(len(fg))
        lens_loc.append(len(fg))
        lens_score.append(len(fg) + len(bg))
    tb = (np.concatenate(tgt_box, axis=0) if tgt_box
          else np.zeros((0, 4), np.float32))
    li = np.asarray(loc_idx, np.int32)[:, None]
    si = np.asarray(score_idx, np.int32)[:, None]
    tl = np.asarray(tgt_lab, np.int32)[:, None]
    lod_of = lambda lens: (tuple(
        int(v) for v in np.concatenate([[0], np.cumsum(lens)])),)
    res = {"LocationIndex": [jnp.asarray(li.reshape(-1))],
           "ScoreIndex": [jnp.asarray(si.reshape(-1))],
           "TargetLabel": [jnp.asarray(tl)],
           "TargetBBox": [jnp.asarray(tb.astype(np.float32))],
           "BBoxInsideWeight": [jnp.ones((len(tb), 4), jnp.float32)],
           "_lod": {"LocationIndex": [lod_of(lens_loc)],
                    "ScoreIndex": [lod_of(lens_score)],
                    "TargetLabel": [lod_of(lens_score)],
                    "TargetBBox": [lod_of(lens_loc)]}}
    if retinanet:
        res["ForegroundNumber"] = [jnp.asarray(
            np.asarray(fg_counts, np.int32)[:, None])]
    return res


@register_op("rpn_target_assign", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo"),
             attr_defaults={"rpn_batch_size_per_im": 256,
                            "rpn_straddle_thresh": 0.0,
                            "rpn_fg_fraction": 0.5,
                            "rpn_positive_overlap": 0.7,
                            "rpn_negative_overlap": 0.3,
                            "use_random": True, "seed": 0})
def _rpn_target_assign(ins, attrs):
    return _rpn_like(ins, attrs, retinanet=False)


@register_op("retinanet_target_assign", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("Anchor", "GtBoxes", "GtLabels", "IsCrowd", "ImInfo"),
             attr_defaults={"positive_overlap": 0.5,
                            "negative_overlap": 0.4, "seed": 0})
def _retinanet_target_assign(ins, attrs):
    a2 = dict(attrs)
    a2["rpn_positive_overlap"] = attrs.get("positive_overlap", 0.5)
    a2["rpn_negative_overlap"] = attrs.get("negative_overlap", 0.4)
    return _rpn_like(ins, a2, retinanet=True)


@register_op("retinanet_detection_output", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("BBoxes", "Scores", "Anchors", "ImInfo"),
             attr_defaults={"score_threshold": 0.05, "nms_top_k": 1000,
                            "nms_threshold": 0.3, "keep_top_k": 100,
                            "nms_eta": 1.0})
def _retinanet_detection_output(ins, attrs):
    """Decode per-FPN-level regression against anchors, merge levels, NMS
    per class (reference retinanet_detection_output_op.cc). Single-image
    batch per LoD row of ImInfo."""
    bbox_levels = [np.asarray(b) for b in seq(ins, "BBoxes")]
    score_levels = [np.asarray(s) for s in seq(ins, "Scores")]
    anchor_levels = [np.asarray(a).reshape(-1, 4)
                     for a in seq(ins, "Anchors")]
    im_info = np.asarray(first(ins, "ImInfo"))
    n_img = im_info.shape[0]
    thr = attrs.get("score_threshold", 0.05)
    out_rows, lens = [], []
    for i in range(n_img):
        boxes_all, scores_all, labels_all = [], [], []
        for bl, sl, al in zip(bbox_levels, score_levels, anchor_levels):
            deltas = bl[i] if bl.ndim == 3 else bl
            scores = sl[i] if sl.ndim == 3 else sl
            # decode center-size deltas vs anchors
            aw = al[:, 2] - al[:, 0] + 1.0
            ah = al[:, 3] - al[:, 1] + 1.0
            ax = al[:, 0] + aw / 2
            ay = al[:, 1] + ah / 2
            cx = deltas[:, 0] * aw + ax
            cy = deltas[:, 1] * ah + ay
            w = np.exp(np.clip(deltas[:, 2], -10, 10)) * aw
            h = np.exp(np.clip(deltas[:, 3], -10, 10)) * ah
            dec = np.stack([cx - w / 2, cy - h / 2,
                            cx + w / 2, cy + h / 2], axis=1)
            C = scores.shape[1]
            for c in range(C):
                sel = np.where(scores[:, c] > thr)[0]
                boxes_all.append(dec[sel])
                scores_all.append(scores[sel, c])
                labels_all.append(np.full(len(sel), c, np.int64))
        boxes = np.concatenate(boxes_all) if boxes_all else np.zeros((0, 4))
        scores = np.concatenate(scores_all) if scores_all else np.zeros(0)
        labels = np.concatenate(labels_all) if labels_all else np.zeros(0, np.int64)
        rows = []
        for c in np.unique(labels):
            selc = labels == c
            keep = _nms(boxes[selc], scores[selc],
                        attrs.get("nms_threshold", 0.3),
                        attrs.get("nms_top_k", 1000), norm=False,
                        eta=attrs.get("nms_eta", 1.0))
            bsel = boxes[selc][keep]
            ssel = scores[selc][keep]
            for b, s_ in zip(bsel, ssel):
                rows.append([float(c), float(s_), *map(float, b)])
        rows.sort(key=lambda r: -r[1])
        rows = rows[:int(attrs.get("keep_top_k", 100))]
        out_rows.extend(rows)
        lens.append(len(rows))
    o = (np.asarray(out_rows, np.float32) if out_rows
         else np.zeros((0, 6), np.float32))
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Out": [jnp.asarray(o)], "_lod": {"Out": [(lod0,)]}}


@register_op("locality_aware_nms", stateful=True, no_grad=True,
             needs_lod=True, inputs=("BBoxes", "Scores"),
             attr_defaults={"score_threshold": 0.0, "nms_top_k": -1,
                            "nms_threshold": 0.3, "keep_top_k": -1,
                            "background_label": -1, "normalized": False,
                            "nms_eta": 1.0})
def _locality_aware_nms(ins, attrs):
    """EAST-style NMS: first weighted-merge consecutive overlapping boxes
    (score-weighted average of coordinates), then standard NMS
    (reference locality_aware_nms_op.cc)."""
    boxes = np.asarray(first(ins, "BBoxes"))
    scores = np.asarray(first(ins, "Scores"))
    if boxes.ndim == 3:
        boxes = boxes[0]
    if scores.ndim == 3:
        scores = scores[0]
    C = scores.shape[0] if scores.ndim == 2 else 1
    scores = scores.reshape(C, -1)
    thr = attrs.get("nms_threshold", 0.3)
    rows = []
    for c in range(C):
        if c == attrs.get("background_label", -1):
            continue
        s = scores[c].copy()
        sel = np.where(s > attrs.get("score_threshold", 0.0))[0]
        merged_boxes, merged_scores = [], []
        for i in sel:   # locality pass: merge into the previous if overlap
            b, sc = boxes[i].astype(np.float64), float(s[i])
            if merged_boxes and _iou_xyxy(
                    merged_boxes[-1], b,
                    attrs.get("normalized", False)) > thr:
                pb, ps = merged_boxes[-1], merged_scores[-1]
                wsum = ps + sc
                merged_boxes[-1] = (pb * ps + b * sc) / wsum
                merged_scores[-1] = wsum
            else:
                merged_boxes.append(b)
                merged_scores.append(sc)
        if not merged_boxes:
            continue
        mb = np.asarray(merged_boxes)
        ms = np.asarray(merged_scores)
        keep = _nms(mb, ms, thr, attrs.get("nms_top_k", -1),
                    attrs.get("normalized", False),
                    attrs.get("nms_eta", 1.0))
        for k in keep:
            rows.append([float(c), float(ms[k]), *map(float, mb[k])])
    rows.sort(key=lambda r: -r[1])
    if attrs.get("keep_top_k", -1) > 0:
        rows = rows[:attrs["keep_top_k"]]
    o = (np.asarray(rows, np.float32) if rows
         else np.zeros((0, 6), np.float32))
    lod0 = (0, len(rows))
    return {"Out": [jnp.asarray(o)], "_lod": {"Out": [(lod0,)]}}


@register_op("box_decoder_and_assign", no_grad=True,
             inputs=("PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"),
             attr_defaults={"box_clip": 4.135})
def _box_decoder_and_assign(ins, attrs):
    """Decode per-class box deltas vs priors and pick each roi's best-class
    box (reference box_decoder_and_assign_op.cc)."""
    prior = first(ins, "PriorBox")         # [R, 4]
    pvar = first(ins, "PriorBoxVar")       # [4] or [R,4]
    deltas = first(ins, "TargetBox")       # [R, C*4]
    score = first(ins, "BoxScore")         # [R, C]
    clip = attrs.get("box_clip", 4.135)
    R = prior.shape[0]
    C = score.shape[1]
    d = deltas.reshape(R, C, 4)
    if pvar is not None:
        pv = pvar.reshape(-1, 4) if pvar.ndim > 1 else pvar.reshape(1, 4)
        d = d * pv[:, None, :] if pv.shape[0] == R else d * pv[None, :, :]
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    cx = d[:, :, 0] * pw[:, None] + px[:, None]
    cy = d[:, :, 1] * ph[:, None] + py[:, None]
    w = jnp.exp(jnp.minimum(d[:, :, 2], clip)) * pw[:, None]
    h = jnp.exp(jnp.minimum(d[:, :, 3], clip)) * ph[:, None]
    dec = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=2)
    best = jnp.argmax(score, axis=1)
    assigned = dec[jnp.arange(R), best]
    return {"DecodeBox": [dec.reshape(R, C * 4)],
            "OutputAssignBox": [assigned]}


@register_op("mine_hard_examples", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"),
             attr_defaults={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                            "mining_type": "max_negative", "sample_size": 0})
def _mine_hard_examples(ins, attrs):
    """SSD hard-negative mining (reference mine_hard_examples_op.cc):
    keep the highest-loss negatives up to neg_pos_ratio * #pos per image."""
    cls_loss = np.asarray(first(ins, "ClsLoss"))     # [N, P]
    loc_loss = first(ins, "LocLoss")
    loss = cls_loss + (np.asarray(loc_loss) if loc_loss is not None else 0.0)
    match = np.asarray(first(ins, "MatchIndices"))   # [N, P]
    dist = first(ins, "MatchDist")
    dist = np.asarray(dist) if dist is not None else None
    ratio = attrs.get("neg_pos_ratio", 3.0)
    neg_thr = attrs.get("neg_dist_threshold", 0.5)
    N, P = match.shape
    hard_mode = attrs.get("mining_type", "max_negative") == "hard_example"
    neg_rows, neg_lens = [], []
    upd = match.copy()
    for i in range(N):
        pos = match[i] != -1
        n_pos = int(pos.sum())
        n_neg = int(n_pos * ratio)
        if hard_mode and attrs.get("sample_size", 0):
            n_neg = int(attrs["sample_size"])
        cand = np.where(~pos & ((dist[i] < neg_thr) if dist is not None
                                else np.ones(P, bool)))[0]
        cand = cand[np.argsort(-loss[i][cand])][:n_neg]
        neg_rows.extend(int(c) for c in sorted(cand))
        neg_lens.append(len(cand))
        if hard_mode:
            # hard-example mode resets matches outside positives + the
            # selected hard negatives (reference mine_hard_examples_op.cc)
            keep = pos.copy()
            keep[cand] = True
            upd[i][~keep] = -1
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(neg_lens)]))
    neg = (np.asarray(neg_rows, np.int32)[:, None] if neg_rows
           else np.zeros((0, 1), np.int32))
    return {"NegIndices": [jnp.asarray(neg)],
            "UpdatedMatchIndices": [jnp.asarray(upd)],
            "_lod": {"NegIndices": [(lod0,)]}}


@register_op("generate_proposal_labels", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes", "ImInfo"),
             attr_defaults={"batch_size_per_im": 256, "fg_fraction": 0.25,
                            "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
                            "bg_thresh_lo": 0.0,
                            "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2],
                            "class_nums": 81, "use_random": True,
                            "is_cls_agnostic": False, "is_cascade_rcnn": False,
                            "seed": 0})
def _generate_proposal_labels(ins, attrs):
    """Fast R-CNN stage-2 sampling (reference generate_proposal_labels_op):
    match proposals to gt, sample fg/bg per image, emit rois + per-class
    regression targets."""
    rois = np.asarray(first(ins, "RpnRois"))
    gcls = np.asarray(first(ins, "GtClasses")).reshape(-1)
    gbox = np.asarray(first(ins, "GtBoxes"))
    roffs = _lod_offs(attrs, "RpnRois", len(rois))
    goffs = _lod_offs(attrs, "GtBoxes", len(gbox))
    B = int(attrs.get("batch_size_per_im", 256))
    fgf = attrs.get("fg_fraction", 0.25)
    fgt = attrs.get("fg_thresh", 0.5)
    bgh = attrs.get("bg_thresh_hi", 0.5)
    bgl = attrs.get("bg_thresh_lo", 0.0)
    C = int(attrs.get("class_nums", 81))
    wts = attrs.get("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2])
    rng = _rng_of(attrs)
    use_rand = attrs.get("use_random", True)
    crowd_in = first(ins, "IsCrowd")
    crowd = (np.asarray(crowd_in).reshape(-1).astype(bool)
             if crowd_in is not None else np.zeros(len(gbox), bool))
    o_rois, o_lab, o_tgt, o_inw, lens = [], [], [], [], []
    for i in range(len(roffs) - 1):
        r = rois[roffs[i]:roffs[i + 1]]
        keep_gt = ~crowd[goffs[i]:goffs[i + 1]]
        g = gbox[goffs[i]:goffs[i + 1]][keep_gt]
        gl = gcls[goffs[i]:goffs[i + 1]][keep_gt]
        # gt boxes join the proposal pool (reference behavior)
        r = np.concatenate([r, g], axis=0) if len(g) else r
        iou = _iou_matrix(r, g, norm=True)
        best = iou.argmax(axis=1) if iou.size else np.zeros(len(r), np.int64)
        biou = iou.max(axis=1) if iou.size else np.zeros(len(r))
        fg = np.where(biou >= fgt)[0]
        bg = np.where((biou < bgh) & (biou >= bgl))[0]
        nfg = min(int(B * fgf), len(fg))
        nbg = min(B - nfg, len(bg))
        if use_rand:
            fg = rng.permutation(fg)[:nfg]
            bg = rng.permutation(bg)[:nbg]
        else:
            fg, bg = fg[:nfg], bg[:nbg]
        sel = np.concatenate([fg, bg]).astype(np.int64)
        labs = np.concatenate([gl[best[fg]].astype(np.int64),
                               np.zeros(len(bg), np.int64)])
        tgts = np.zeros((len(sel), 4 * C), np.float32)
        inw = np.zeros((len(sel), 4 * C), np.float32)
        if len(fg):
            enc = _box_encode(g[best[fg]], r[fg],
                              [1.0 / w for w in wts])
            for k, (lab, e) in enumerate(zip(labs[:len(fg)], enc)):
                c = 1 if attrs.get("is_cls_agnostic", False) else int(lab)
                tgts[k, 4 * c:4 * c + 4] = e
                inw[k, 4 * c:4 * c + 4] = 1.0
        o_rois.append(r[sel])
        o_lab.append(labs)
        o_tgt.append(tgts)
        o_inw.append(inw)
        lens.append(len(sel))
    rois_o = np.concatenate(o_rois) if o_rois else np.zeros((0, 4), np.float32)
    lab_o = np.concatenate(o_lab) if o_lab else np.zeros(0, np.int64)
    tgt_o = np.concatenate(o_tgt) if o_tgt else np.zeros((0, 4 * C), np.float32)
    inw_o = np.concatenate(o_inw) if o_inw else np.zeros((0, 4 * C), np.float32)
    lod0 = (tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)])),)
    return {"Rois": [jnp.asarray(rois_o.astype(np.float32))],
            "LabelsInt32": [jnp.asarray(lab_o.astype(np.int32)[:, None])],
            "BboxTargets": [jnp.asarray(tgt_o)],
            "BboxInsideWeights": [jnp.asarray(inw_o)],
            "BboxOutsideWeights": [jnp.asarray((inw_o > 0)
                                               .astype(np.float32))],
            "_lod": {"Rois": [lod0], "LabelsInt32": [lod0],
                     "BboxTargets": [lod0], "BboxInsideWeights": [lod0],
                     "BboxOutsideWeights": [lod0]}}


def _rasterize_polygon(poly, h, w):
    """Even-odd fill of one polygon [[x0,y0,x1,y1,...]] onto an h x w grid."""
    ys, xs = np.mgrid[0:h, 0:w]
    px = np.asarray(poly[0::2])
    py = np.asarray(poly[1::2])
    n = len(px)
    inside = np.zeros((h, w), bool)
    j = n - 1
    for i in range(n):
        cond = ((py[i] > ys + 0.5) != (py[j] > ys + 0.5))
        with np.errstate(divide="ignore", invalid="ignore"):
            xcross = (px[j] - px[i]) * (ys + 0.5 - py[i]) \
                / (py[j] - py[i] + 1e-12) + px[i]
        inside ^= cond & (xs + 0.5 < xcross)
        j = i
    return inside


@register_op("generate_mask_labels", stateful=True, no_grad=True,
             needs_lod=True,
             inputs=("ImInfo", "GtClasses", "IsCrowd", "GtSegms", "Rois",
                     "LabelsInt32"),
             attr_defaults={"num_classes": 81, "resolution": 14})
def _generate_mask_labels(ins, attrs):
    """Mask R-CNN mask targets (reference generate_mask_labels_op.cc):
    rasterize each fg roi's matched gt polygon into a resolution^2 grid.
    Matching is by gt index order per image (gt polygons in GtSegms LoD)."""
    rois = np.asarray(first(ins, "Rois"))
    labels = np.asarray(first(ins, "LabelsInt32")).reshape(-1)
    segs = np.asarray(first(ins, "GtSegms"))      # [S, 2] flattened xy pairs
    roffs = _lod_offs(attrs, "Rois", len(rois))
    lods = (attrs.get("_lod") or {}).get("GtSegms")
    res = int(attrs.get("resolution", 14))
    C = int(attrs.get("num_classes", 81))
    # polygons per gt: 2-level LoD (gt -> polys -> points)
    if lods and lods[0] and len(lods[0]) >= 2:
        gt_offs = np.asarray(lods[0][0], np.int64)    # gt -> poly index
        pt_offs = np.asarray(lods[0][-1], np.int64)   # poly -> point index
    else:
        gt_offs = np.asarray([0, 1], np.int64)
        pt_offs = np.asarray([0, len(segs)], np.int64)
    # gt polygons are distributed per image by GtClasses' LoD
    gcls_offs = _lod_offs(attrs, "GtClasses", len(gt_offs) - 1)
    n_gt = len(gt_offs) - 1
    # each gt's FIRST polygon + its bbox (for roi->gt matching)
    gt_polys, gt_boxes = [], np.zeros((n_gt, 4), np.float64)
    for g_ in range(n_gt):
        p0 = pt_offs[gt_offs[g_]]
        p1 = pt_offs[min(gt_offs[g_] + 1, len(pt_offs) - 1)]
        poly_ = segs[p0:p1].reshape(-1)
        gt_polys.append(poly_)
        xs_, ys_ = poly_[0::2], poly_[1::2]
        if len(xs_):
            gt_boxes[g_] = [xs_.min(), ys_.min(), xs_.max(), ys_.max()]
    mask_rois, mask_lens, roi_has, masks = [], [], [], []
    for i in range(len(roffs) - 1):
        rs = rois[roffs[i]:roffs[i + 1]]
        ls = labels[roffs[i]:roffs[i + 1]]
        g_lo = int(gcls_offs[min(i, len(gcls_offs) - 2)])
        g_hi = int(gcls_offs[min(i + 1, len(gcls_offs) - 1)])
        n_this = 0
        for k, (r, lab) in enumerate(zip(rs, ls)):
            if lab <= 0 or g_hi <= g_lo:
                continue
            # match this roi to the image's gt with the highest bbox IoU
            ious = _iou_matrix(r[None, :4].astype(np.float64),
                               gt_boxes[g_lo:g_hi], norm=True)[0]
            gi = g_lo + int(np.argmax(ious))
            poly = gt_polys[gi]
            x1, y1, x2, y2 = r[:4]
            w = max(x2 - x1, 1e-3)
            h = max(y2 - y1, 1e-3)
            # polygon into roi-local resolution grid
            local = poly.copy().astype(np.float64)
            local[0::2] = (local[0::2] - x1) / w * res
            local[1::2] = (local[1::2] - y1) / h * res
            m = _rasterize_polygon(local, res, res)
            cls_mask = np.full((C, res, res), 0, np.int32)
            cls_mask[int(lab)] = m.astype(np.int32)
            masks.append(cls_mask.reshape(-1))
            mask_rois.append(r[:4])
            roi_has.append(k + int(roffs[i]))
            n_this += 1
        mask_lens.append(n_this)
    mr = (np.asarray(mask_rois, np.float32) if mask_rois
          else np.zeros((0, 4), np.float32))
    mi = (np.asarray(masks, np.int32) if masks
          else np.zeros((0, C * res * res), np.int32))
    ridx = (np.asarray(roi_has, np.int32)[:, None] if roi_has
            else np.zeros((0, 1), np.int32))
    lod0 = (tuple(int(v)
                  for v in np.concatenate([[0], np.cumsum(mask_lens)])),)
    return {"MaskRois": [jnp.asarray(mr)],
            "RoiHasMaskInt32": [jnp.asarray(ridx)],
            "MaskInt32": [jnp.asarray(mi)],
            "_lod": {"MaskRois": [lod0], "RoiHasMaskInt32": [lod0],
                     "MaskInt32": [lod0]}}


@register_op("roi_perspective_transform", stateful=True,
             needs_lod=True, inputs=("X", "ROIs"),
             attr_defaults={"transformed_height": 8, "transformed_width": 8,
                            "spatial_scale": 1.0})
def _roi_perspective_transform(ins, attrs):
    """Warp quadrilateral rois to a fixed rectangle by the homography
    mapping the output grid onto the quad, bilinear-sampling the input
    (reference roi_perspective_transform_op.cc). ROIs rows are 8 coords
    (x1 y1 ... x4 y4, clockwise from top-left)."""
    x = np.asarray(first(ins, "X"))        # [N, C, H, W]
    rois = np.asarray(first(ins, "ROIs"))  # [R, 8]
    offs = _lod_offs(attrs, "ROIs", len(rois))
    bids = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
    th = int(attrs.get("transformed_height", 8))
    tw = int(attrs.get("transformed_width", 8))
    scale = attrs.get("spatial_scale", 1.0)
    n, c, H, W = x.shape
    outs, mats, masks = [], [], []
    for r in range(len(rois)):
        quad = rois[r].reshape(4, 2) * scale
        # homography from unit rect corners to quad (DLT, 4 points)
        src = np.asarray([[0, 0], [tw - 1, 0], [tw - 1, th - 1],
                          [0, th - 1]], np.float64)
        A = []
        for (sx, sy), (dx_, dy_) in zip(src, quad):
            A.append([sx, sy, 1, 0, 0, 0, -dx_ * sx, -dx_ * sy, -dx_])
            A.append([0, 0, 0, sx, sy, 1, -dy_ * sx, -dy_ * sy, -dy_])
        _, _, vt = np.linalg.svd(np.asarray(A))
        Hm = vt[-1].reshape(3, 3)
        gy, gx = np.mgrid[0:th, 0:tw]
        ones = np.ones_like(gx)
        pts = Hm @ np.stack([gx.ravel(), gy.ravel(),
                             ones.ravel()]).astype(np.float64)
        px = pts[0] / (pts[2] + 1e-12)
        py = pts[1] / (pts[2] + 1e-12)
        x0 = np.floor(px).astype(int)
        y0 = np.floor(py).astype(int)
        wx = px - x0
        wy = py - y0
        img = x[bids[r]]

        def g(yi, xi):
            valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            v = img[:, np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)]
            return v * valid
        v = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x0 + 1) * (1 - wy) * wx
             + g(y0 + 1, x0) * wy * (1 - wx) + g(y0 + 1, x0 + 1) * wy * wx)
        outs.append(v.reshape(c, th, tw))
        mats.append(Hm.reshape(9) / (Hm[2, 2] if Hm[2, 2] != 0 else 1.0))
        in_img = ((px >= 0) & (px <= W - 1) & (py >= 0) & (py <= H - 1))
        masks.append(in_img.reshape(1, th, tw))
    o = (np.stack(outs) if outs
         else np.zeros((0, c, th, tw), np.float32))
    mat = (np.stack(mats) if mats else np.zeros((0, 9), np.float32))
    msk = (np.stack(masks) if masks
           else np.zeros((0, 1, th, tw), bool))
    return {"Out": [jnp.asarray(o.astype(np.float32))],
            "Out2InIdx": [jnp.zeros((len(rois), 1), jnp.int32)],
            "Out2InWeights": [jnp.ones((len(rois), 1), jnp.float32)],
            "Mask": [jnp.asarray(msk.astype(np.int32))],
            "TransformMatrix": [jnp.asarray(mat.astype(np.float32))]}
