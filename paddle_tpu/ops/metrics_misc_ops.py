"""Metric + misc op batch (reference: chunk_eval_op.cc,
precision_recall_op.cc, positive_negative_pair_op.cc, detection_map_op.cc,
modified_huber_loss_op.cc, sample_logits_op.cc, partial_concat_op.cc,
partial_sum_op.cc, batch_fc_op.cc, shuffle_batch_op.cc, fill_op.cc,
fill_zeros_like_op.cc (fill_zeros_like2), coalesce_tensor_op.cc,
get_places_op.cc, tdm_child_op.cc, tdm_sampler_op.cc, rank_attention_op.cc,
tree_conv_op.cc, match_matrix_tensor_op.cc, var_conv_2d_op.cc,
pyramid_hash_op.cc, sequence_topk_avg_pooling_op.cc, filter_by_instag_op.cc).

Metric ops run host-side numpy (no_grad, stateful where they accumulate);
compute ops are pure JAX."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, first, seq, out


# --------------------------------------------------------------------------
# chunk_eval — IOB/IOE/IOBES/plain chunking F1 (reference chunk_eval_op.h)
# --------------------------------------------------------------------------
def _extract_chunks(tags, scheme, num_types, excluded):
    """Return the set of (begin, end, type) chunks of an int tag sequence.
    Tag encoding (reference chunk_eval_op.h): tag = type*tag_num + pos,
    pos order B,I[,E,S] per scheme; the O tag is num_types*tag_num."""
    tag_num = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    o_tag = num_types * tag_num
    chunks, state = [], {"start": None, "type": None}

    def flush(end):
        if state["start"] is not None and state["type"] not in excluded:
            chunks.append((state["start"], end, state["type"]))
        state["start"] = None
    for i, t in enumerate(tags):
        t = int(t)
        if t < 0 or t >= o_tag:                  # O / invalid closes chunk
            flush(i - 1)
            continue
        ty, pos = t // tag_num, t % tag_num
        if scheme == "plain":
            begins = True          # every tag is its own single-token chunk
        elif scheme == "IOB":
            begins = pos == 0 or state["start"] is None or ty != state["type"]
        elif scheme == "IOE":
            begins = state["start"] is None or ty != state["type"]
        else:  # IOBES: B=0 I=1 E=2 S=3
            begins = pos in (0, 3) or state["start"] is None \
                or ty != state["type"]
        if begins:
            flush(i - 1)
            state["start"], state["type"] = i, ty
        if scheme == "plain" or (scheme == "IOE" and pos == 1) or \
                (scheme == "IOBES" and pos in (2, 3)):
            flush(i)
    flush(len(tags) - 1)
    return set(chunks)


@register_op("chunk_eval", stateful=True, inputs=("Inference", "Label", "SeqLength"),
             no_grad=True, needs_lod=True,
             attr_defaults={"num_chunk_types": 1, "chunk_scheme": "IOB",
                            "excluded_chunk_types": []})
def _chunk_eval(ins, attrs):
    inf_raw = np.asarray(first(ins, "Inference"))
    lab_raw = np.asarray(first(ins, "Label"))
    inf = inf_raw.reshape(-1)
    lab = lab_raw.reshape(-1)
    lods = (attrs.get("_lod") or {}).get("Inference")
    seq_len = first(ins, "SeqLength")
    if lods and lods[0]:
        offs = np.asarray(lods[0][-1], np.int64)
    elif seq_len is not None and inf_raw.ndim >= 2:
        # padded [N, T] layout: per-row lengths delimit the sequences
        T = inf_raw.shape[1]
        lens = np.asarray(seq_len).reshape(-1)
        starts = np.arange(len(lens)) * T
        offs = None
        spans = [(int(s), int(s + l)) for s, l in zip(starts, lens)]
    else:
        offs = np.asarray([0, len(inf)], np.int64)
    scheme = attrs.get("chunk_scheme", "IOB")
    nt = int(attrs.get("num_chunk_types", 1))
    excl = set(attrs.get("excluded_chunk_types") or [])
    if offs is not None:
        spans = [(int(offs[i]), int(offs[i + 1]))
                 for i in range(len(offs) - 1)]
    n_inf = n_lab = n_cor = 0
    for s, e in spans:
        ci = _extract_chunks(inf[s:e], scheme, nt, excl)
        cl = _extract_chunks(lab[s:e], scheme, nt, excl)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    i64 = lambda v: jnp.asarray([v], jnp.int32)
    return {"Precision": [jnp.asarray([p], jnp.float32)],
            "Recall": [jnp.asarray([r], jnp.float32)],
            "F1-Score": [jnp.asarray([f1], jnp.float32)],
            "NumInferChunks": [i64(n_inf)],
            "NumLabelChunks": [i64(n_lab)],
            "NumCorrectChunks": [i64(n_cor)]}


# --------------------------------------------------------------------------
# precision_recall — multiclass macro/micro P/R/F1 with state accumulation
# --------------------------------------------------------------------------
@register_op("precision_recall", stateful=True,
             inputs=("MaxProbs", "Indices", "Labels", "Weights",
                     "StatesInfo"),
             no_grad=True, attr_defaults={"class_number": 1})
def _precision_recall(ins, attrs):
    idx = np.asarray(first(ins, "Indices")).reshape(-1)
    lab = np.asarray(first(ins, "Labels")).reshape(-1)
    w = first(ins, "Weights")
    w = (np.asarray(w).reshape(-1) if w is not None
         else np.ones_like(lab, np.float32))
    C = int(attrs.get("class_number", 1))
    tp = np.zeros(C); fp = np.zeros(C); fn = np.zeros(C)
    for p_, l_, wi in zip(idx, lab, w):
        if p_ == l_:
            tp[l_] += wi
        else:
            fp[p_] += wi
            fn[l_] += wi

    def metrics(tp_, fp_, fn_):
        prec = np.where(tp_ + fp_ > 0, tp_ / np.maximum(tp_ + fp_, 1e-12), 0)
        rec = np.where(tp_ + fn_ > 0, tp_ / np.maximum(tp_ + fn_, 1e-12), 0)
        f1 = np.where(prec + rec > 0,
                      2 * prec * rec / np.maximum(prec + rec, 1e-12), 0)
        macro = [prec.mean(), rec.mean(), f1.mean()]
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = stp / (stp + sfp) if stp + sfp else 0.0
        mr = stp / (stp + sfn) if stp + sfn else 0.0
        mf = 2 * mp * mr / (mp + mr) if mp + mr else 0.0
        return np.asarray(macro + [mp, mr, mf], np.float32)
    batch = metrics(tp, fp, fn)
    st = first(ins, "StatesInfo")
    if st is not None:
        sa = np.asarray(st).reshape(C, 4).astype(np.float64)
        tp2, fp2, fn2 = tp + sa[:, 0], fp + sa[:, 1], fn + sa[:, 3]
    else:
        tp2, fp2, fn2 = tp, fp, fn
    acc = metrics(tp2, fp2, fn2)
    states = np.stack([tp2, fp2, np.zeros(C), fn2], axis=1)
    return {"BatchMetrics": [jnp.asarray(batch)],
            "AccumMetrics": [jnp.asarray(acc)],
            "AccumStatesInfo": [jnp.asarray(states, jnp.float32)]}


@register_op("positive_negative_pair", stateful=True,
             inputs=("Score", "Label", "QueryID", "AccumulatePositivePair",
                     "AccumulateNegativePair", "AccumulateNeutralPair",
                     "Weight"),
             no_grad=True, attr_defaults={"column": -1})
def _positive_negative_pair(ins, attrs):
    score = np.asarray(first(ins, "Score"))
    col = int(attrs.get("column", -1))
    s = score[:, col]
    lab = np.asarray(first(ins, "Label")).reshape(-1)
    qid = np.asarray(first(ins, "QueryID")).reshape(-1)
    pos = neg = neu = 0.0
    for q in np.unique(qid):
        sel = np.where(qid == q)[0]
        for a in range(len(sel)):
            for b in range(a + 1, len(sel)):
                i, j = sel[a], sel[b]
                if lab[i] == lab[j]:
                    continue
                hi, lo = (i, j) if lab[i] > lab[j] else (j, i)
                if s[hi] > s[lo]:
                    pos += 1
                elif s[hi] < s[lo]:
                    neg += 1
                else:
                    neu += 1
    for slot, v in (("AccumulatePositivePair", pos),
                    ("AccumulateNegativePair", neg),
                    ("AccumulateNeutralPair", neu)):
        prev = first(ins, slot)
        if prev is not None:
            v += float(np.asarray(prev).reshape(()))
        if slot == "AccumulatePositivePair":
            pos = v
        elif slot == "AccumulateNegativePair":
            neg = v
        else:
            neu = v
    f32 = lambda v: jnp.asarray([v], jnp.float32)
    return {"PositivePair": [f32(pos)], "NegativePair": [f32(neg)],
            "NeutralPair": [f32(neu)]}


# --------------------------------------------------------------------------
# detection_map — PASCAL VOC mAP over one batch (reference detection_map_op)
# --------------------------------------------------------------------------
@register_op("detection_map", stateful=True,
             inputs=("DetectRes", "Label", "HasState", "PosCount",
                     "TruePos", "FalsePos"),
             no_grad=True, needs_lod=True,
             attr_defaults={"overlap_threshold": 0.5, "class_num": 1,
                            "background_label": 0, "evaluate_difficult": True,
                            "ap_type": "integral"})
def _detection_map(ins, attrs):
    det = np.asarray(first(ins, "DetectRes"))     # [M, 6] label,score,x1,y1,x2,y2
    gt = np.asarray(first(ins, "Label"))          # [N, 6] label,x1,y1,x2,y2(,difficult)
    lods = attrs.get("_lod") or {}
    doffs = (np.asarray(lods["DetectRes"][0][-1], np.int64)
             if lods.get("DetectRes") and lods["DetectRes"][0]
             else np.asarray([0, len(det)], np.int64))
    goffs = (np.asarray(lods["Label"][0][-1], np.int64)
             if lods.get("Label") and lods["Label"][0]
             else np.asarray([0, len(gt)], np.int64))
    thr = attrs.get("overlap_threshold", 0.5)
    bg = int(attrs.get("background_label", 0))
    ap_type = attrs.get("ap_type", "integral")
    eval_diff = attrs.get("evaluate_difficult", True)
    C = int(attrs.get("class_num", 1))
    # gt layout: [label, difficult, x1, y1, x2, y2] (6 cols) or
    # [label, x1, y1, x2, y2] (5 cols, no difficult flag)
    has_diff = gt.shape[1] == 6
    box_col = 2 if has_diff else 1

    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0
    # prior state: per-class positive counts + (score, hit) records
    npos_c = np.zeros(C, np.int64)
    scored_c = {c: [] for c in range(C)}
    pc_in = first(ins, "PosCount")
    tp_in, fp_in = first(ins, "TruePos"), first(ins, "FalsePos")
    in_lods = attrs.get("_lod") or {}
    if pc_in is not None and first(ins, "HasState") is not None \
            and int(np.asarray(first(ins, "HasState")).reshape(-1)[0]):
        npos_c += np.asarray(pc_in).reshape(-1)[:C]
        for arr, lodname, hit in ((tp_in, "TruePos", 1),
                                  (fp_in, "FalsePos", 0)):
            if arr is None:
                continue
            a = np.asarray(arr).reshape(-1, 2)
            lod = in_lods.get(lodname)
            o = (np.asarray(lod[0][-1], np.int64) if lod and lod[0]
                 else np.asarray([0, len(a)], np.int64))
            for c in range(min(C, len(o) - 1)):
                for row in a[o[c]:o[c + 1]]:
                    scored_c[c].append((float(row[0]), hit))
    for i in range(len(doffs) - 1):
        d = det[doffs[i]:doffs[i + 1]]
        g_raw = gt[goffs[i]:goffs[i + 1]]
        for c in set(int(v) for v in g_raw[:, 0]) | \
                set(int(v) for v in d[:, 0]):
            if c == bg or c < 0 or c >= C:
                continue
            gc = g_raw[g_raw[:, 0] == c]
            diff = (gc[:, 1].astype(bool) if has_diff
                    else np.zeros(len(gc), bool))
            g = gc[:, box_col:box_col + 4]
            npos_c[c] += int(len(g) if eval_diff else (~diff).sum())
            dc = d[d[:, 0] == c]
            used = np.zeros(len(g), bool)
            for row in dc[np.argsort(-dc[:, 1])]:
                best, bi = 0.0, -1
                for j in range(len(g)):
                    o = iou(row[2:6], g[j])
                    if o > best:
                        best, bi = o, j
                if best >= thr and bi >= 0:
                    if not eval_diff and diff[bi]:
                        continue   # difficult gt: detection not counted
                    if not used[bi]:
                        used[bi] = True
                        scored_c[c].append((float(row[1]), 1))
                    else:
                        scored_c[c].append((float(row[1]), 0))
                else:
                    scored_c[c].append((float(row[1]), 0))
    aps = []
    for c in range(C):
        if c == bg or npos_c[c] == 0:
            continue
        scored = sorted(scored_c[c], key=lambda t: -t[0])
        tps = np.cumsum([t[1] for t in scored]) if scored else np.zeros(0)
        fps = np.cumsum([1 - t[1] for t in scored]) if scored else np.zeros(0)
        rec = tps / npos_c[c] if len(tps) else np.zeros(0)
        prec = tps / np.maximum(tps + fps, 1e-12) if len(tps) else np.zeros(0)
        if ap_type == "11point":
            ap = np.mean([max([p for r_, p in zip(rec, prec) if r_ >= t],
                              default=0.0) for t in np.linspace(0, 1, 11)])
        else:
            ap, prev_r = 0.0, 0.0
            for r_, p in zip(rec, prec):
                ap += (r_ - prev_r) * p
                prev_r = r_
        aps.append(ap)
    m = float(np.mean(aps)) if aps else 0.0
    # accumulated state out: per-class LoD of (score, hit) records
    tp_rows, fp_rows, tp_lens, fp_lens = [], [], [], []
    for c in range(C):
        tps = [(s, h) for s, h in scored_c[c] if h == 1]
        fps = [(s, h) for s, h in scored_c[c] if h == 0]
        tp_rows.extend(tps)
        fp_rows.extend(fps)
        tp_lens.append(len(tps))
        fp_lens.append(len(fps))
    tp_arr = (np.asarray(tp_rows, np.float32).reshape(-1, 2)
              if tp_rows else np.zeros((0, 2), np.float32))
    fp_arr = (np.asarray(fp_rows, np.float32).reshape(-1, 2)
              if fp_rows else np.zeros((0, 2), np.float32))
    lod_of = lambda lens: (tuple(
        int(v) for v in np.concatenate([[0], np.cumsum(lens)])),)
    return {"MAP": [jnp.asarray([m], jnp.float32)],
            "AccumPosCount": [jnp.asarray(npos_c[:, None], jnp.int32)],
            "AccumTruePos": [jnp.asarray(tp_arr)],
            "AccumFalsePos": [jnp.asarray(fp_arr)],
            "_lod": {"AccumTruePos": [lod_of(tp_lens)],
                     "AccumFalsePos": [lod_of(fp_lens)]}}


# --------------------------------------------------------------------------
# small compute ops
# --------------------------------------------------------------------------
@register_op("modified_huber_loss", inputs=("X", "Y"), diff_inputs=("X",))
def _modified_huber_loss(ins, attrs):
    x = first(ins, "X")            # prediction in [-1,1] space
    y = first(ins, "Y")            # {0,1}
    yy = 2.0 * y.astype(x.dtype) - 1.0
    z = yy * x
    loss = jnp.where(z < -1.0, -4.0 * z, jnp.square(jnp.maximum(1.0 - z, 0.0)))
    return {"Out": [loss.reshape(-1, 1)], "IntermediateVal": [z]}


@register_op("partial_concat", inputs=("X",), diff_inputs=("X",),
             attr_defaults={"start_index": 0, "length": -1})
def _partial_concat(ins, attrs):
    xs = seq(ins, "X")
    s = int(attrs.get("start_index", 0))
    if s < 0:
        s += xs[0].shape[1]
    ln = int(attrs.get("length", -1))
    cols = [x[:, s:(None if ln < 0 else s + ln)] for x in xs]
    return out(Out=jnp.concatenate(cols, axis=1))


@register_op("partial_sum", inputs=("X",), diff_inputs=("X",),
             attr_defaults={"start_index": 0, "length": -1})
def _partial_sum(ins, attrs):
    xs = seq(ins, "X")
    s = int(attrs.get("start_index", 0))
    if s < 0:
        s += xs[0].shape[1]
    ln = int(attrs.get("length", -1))
    acc = None
    for x in xs:
        v = x[:, s:(None if ln < 0 else s + ln)]
        acc = v if acc is None else acc + v
    return out(Out=acc)


@register_op("batch_fc", inputs=("Input", "W", "Bias"),
             diff_inputs=("Input", "W", "Bias"))
def _batch_fc(ins, attrs):
    x = first(ins, "Input")        # [slot, batch, in]
    w = first(ins, "W")            # [slot, in, out]
    b = first(ins, "Bias")         # [slot, 1, out]
    o = jnp.einsum("sbi,sio->sbo", x, w)
    if b is not None:
        o = o + b
    return out(Out=jnp.maximum(o, 0))


@register_op("shuffle_batch", inputs=("X", "Seed"), needs_rng=True,
             host_inputs=("Seed",),
             attr_defaults={"startup_seed": 0})
def _shuffle_batch(ins, attrs):
    x = first(ins, "X")
    seed_in = first(ins, "Seed")
    rng = (jax.random.key(int(np.asarray(seed_in).reshape(())))
           if seed_in is not None and int(np.asarray(seed_in).reshape(())) != 0
           else attrs["_rng"])
    perm = jax.random.permutation(rng, x.shape[0])
    return {"Out": [x[perm]], "ShuffleIdx": [perm.astype(jnp.int32)],
            "SeedOut": [jnp.asarray([0], jnp.int32)]}


@register_op("fill", no_grad=True,
             attr_defaults={"value": [], "shape": [], "dtype": 5,
                            "force_cpu": False})
def _fill(ins, attrs):
    from ..fluid.core import dtype_to_jnp
    vals = np.asarray(attrs.get("value", []), np.float64)
    shape = [int(s) for s in attrs.get("shape", [])]
    return out(Out=jnp.asarray(vals.reshape(shape),
                               dtype_to_jnp(attrs.get("dtype", 5))))


@register_op("fill_zeros_like2", inputs=("X",), no_grad=True,
             attr_defaults={"dtype": 5})
def _fill_zeros_like2(ins, attrs):
    return out(Out=jnp.zeros_like(first(ins, "X")))


@register_op("get_places", no_grad=True,
             attr_defaults={"device_count": 0, "device_type": "CPU"})
def _get_places(ins, attrs):
    n = int(attrs.get("device_count", 0)) or jax.device_count()
    return out(Out=jnp.arange(n, dtype=jnp.int32))


@register_op("coalesce_tensor", inputs=("Input",),
             attr_defaults={"copy_data": True, "set_constant": False,
                            "constant": 0.0, "dtype": 5})
def _coalesce_tensor(ins, attrs):
    """Fuse a var list into one flat buffer + per-var views (reference
    coalesce_tensor_op.cc). Under XLA there is no aliasing win, so
    FusedOutput is a concat copy and Output passes tensors through."""
    xs = seq(ins, "Input")
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    if attrs.get("set_constant", False):
        flat = jnp.full_like(flat, attrs.get("constant", 0.0))
    return {"Output": list(xs), "FusedOutput": [flat]}


@register_op("sample_logits",
             inputs=("Logits", "Labels", "CustomizedSamples",
                     "CustomizedProbabilities"),
             diff_inputs=("Logits",), needs_rng=True,
             attr_defaults={"num_samples": 1, "uniq": True,
                            "remove_accidental_hits": True,
                            "use_customized_samples": False, "seed": 0})
def _sample_logits(ins, attrs):
    """Sampled-softmax helper (reference sample_logits_op.h): gather the
    true-label logits plus num_samples log-uniform negative classes,
    subtracting log Q(class) so downstream softmax estimates the full
    softmax; accidental hits (sampled class == a true label of the row)
    are masked to -1e20."""
    logits = first(ins, "Logits")          # [N, K]
    labels = first(ins, "Labels")          # [N, NT] int64
    n, K = logits.shape
    nt = labels.shape[1]
    S = int(attrs.get("num_samples", 1))
    if attrs.get("use_customized_samples", False):
        samples = first(ins, "CustomizedSamples")
        probs = first(ins, "CustomizedProbabilities")
    else:
        rng = (jax.random.key(int(attrs["seed"])) if attrs.get("seed", 0)
               else attrs["_rng"])
        # log-uniform (Zipf) over classes: P(c)=log((c+2)/(c+1))/log(K+1)
        u = jax.random.uniform(rng, (n, S))
        neg = (jnp.exp(u * jnp.log(K + 1.0)) - 1.0).astype(jnp.int32)
        neg = jnp.clip(neg, 0, K - 1)
        samples = jnp.concatenate([labels.astype(jnp.int32), neg], axis=1)
        q = jnp.log((samples + 2.0) / (samples + 1.0)) / jnp.log(K + 1.0)
        probs = q
    gathered = jnp.take_along_axis(logits, samples.astype(jnp.int32), axis=1)
    sampled_logits = gathered - jnp.log(probs + 1e-20)
    if attrs.get("remove_accidental_hits", True):
        neg_part = samples[:, nt:]
        hit = (neg_part[:, :, None] == labels[:, None, :]).any(-1)
        mask = jnp.concatenate(
            [jnp.zeros((n, nt), bool), hit], axis=1)
        sampled_logits = jnp.where(mask, -1e20, sampled_logits)
    sampled_labels = jnp.broadcast_to(jnp.arange(nt, dtype=jnp.int32),
                                      (n, nt))
    return {"Samples": [samples.astype(jnp.int32)],
            "Probabilities": [probs],
            "SampledLogits": [sampled_logits],
            "SampledLabels": [sampled_labels]}


# --------------------------------------------------------------------------
# filter_by_instag — static-shape formulation: non-matching rows zeroed,
# LossWeight marks survivors (reference filter_by_instag_op.h filters rows;
# under XLA static shapes we keep row count and zero+deweight instead,
# which preserves training semantics when the loss is weighted)
# --------------------------------------------------------------------------
@register_op("filter_by_instag", stateful=True, inputs=("Ins", "Ins_tag", "Filter_tag"),
             diff_inputs=("Ins",), needs_lod=True,
             attr_defaults={"is_lod": True, "out_val_if_empty": 0})
def _filter_by_instag(ins, attrs):
    x = first(ins, "Ins")
    tags = np.asarray(first(ins, "Ins_tag")).reshape(-1)
    filt = set(np.asarray(first(ins, "Filter_tag")).reshape(-1).tolist())
    keep = np.asarray([1.0 if t in filt else 0.0 for t in tags], np.float32)
    k = jnp.asarray(keep)[:, None]
    if keep.sum() == 0:
        # reference emits sentinel rows when nothing matches
        o = jnp.full_like(x, attrs.get("out_val_if_empty", 0))
    else:
        o = x * k.astype(x.dtype)
    idx = jnp.asarray(np.arange(len(tags), dtype=np.int64))
    return {"Out": [o], "LossWeight": [k],
            "IndexMap": [jnp.stack([idx, idx], axis=1)]}


# --------------------------------------------------------------------------
# TDM tree ops (reference tdm_child_op.h: tree_info row =
# [item_id, layer_id, parent_id, child0..childN-1]; child==0 => none)
# --------------------------------------------------------------------------
@register_op("tdm_child", inputs=("X", "TreeInfo"), no_grad=True,
             attr_defaults={"child_nums": 1, "dtype": 2})
def _tdm_child(ins, attrs):
    x = first(ins, "X")
    info = first(ins, "TreeInfo")
    cn = int(attrs.get("child_nums", 1))
    ids = x.reshape(-1).astype(jnp.int32)
    rows = info[ids]                        # [n, 3+cn]
    kids = rows[:, 3:3 + cn].astype(jnp.int32)
    has_child = ((ids != 0) & (rows[:, 3] != 0))[:, None]
    kids = jnp.where(has_child, kids, 0)
    is_item = (info[kids.reshape(-1), 0] != 0).reshape(kids.shape)
    mask = jnp.where(has_child, is_item, False)
    shape = x.shape[:-1] + (cn,) if x.shape[-1] == 1 else x.shape + (cn,)
    return {"Child": [kids.reshape(shape).astype(jnp.int32)],
            "LeafMask": [mask.reshape(shape).astype(jnp.int32)]}


@register_op("tdm_sampler", inputs=("X", "Travel", "Layer"), needs_rng=True,
             attr_defaults={"neg_samples_num_list": [], "layer_offset_lod": [],
                            "output_positive": True, "output_list": True,
                            "seed": 0, "dtype": 2})
def _tdm_sampler(ins, attrs):
    """Per positive item: its tree path (Travel row) plus per-layer negative
    samples drawn from that layer's nodes (reference tdm_sampler_op.h)."""
    x = first(ins, "X")
    travel = first(ins, "Travel")          # [item, layer_num] path node ids
    layer = first(ins, "Layer")            # flat node ids, split by offsets
    negs = [int(v) for v in attrs.get("neg_samples_num_list", [])]
    offs = [int(v) for v in attrs.get("layer_offset_lod", [])]
    ids = x.reshape(-1).astype(jnp.int32)
    n = ids.shape[0]
    rng = attrs["_rng"]
    out_cols, lab_cols, mask_cols = [], [], []
    for li, neg in enumerate(negs):
        pos = travel[ids, li][:, None]                    # [n,1]
        lo, hi = offs[li], offs[li + 1]
        rng, sub = jax.random.split(rng)
        samp = jax.random.randint(sub, (n, neg), lo, hi)
        negv = layer.reshape(-1)[samp]
        valid = (pos != 0)
        out_cols.append(jnp.concatenate([pos, negv], axis=1))
        lab_cols.append(jnp.concatenate(
            [jnp.ones_like(pos), jnp.zeros_like(negv)], axis=1))
        mask_cols.append(jnp.concatenate(
            [valid.astype(jnp.int32),
             jnp.broadcast_to(valid, negv.shape).astype(jnp.int32)], axis=1))
    o = jnp.concatenate(out_cols, axis=1)
    return {"Out": [o.astype(jnp.int32)[..., None]],
            "Labels": [jnp.concatenate(lab_cols, 1).astype(jnp.int32)[..., None]],
            "Mask": [jnp.concatenate(mask_cols, 1)[..., None]]}


@register_op("rank_attention", inputs=("X", "RankOffset", "RankParam"),
             diff_inputs=("X", "RankParam"),
             attr_defaults={"MaxRank": 3, "MaxSize": 0})
def _rank_attention(ins, attrs):
    """Ad-rank attention (reference rank_attention_op.cu): sample i with
    instance-rank r_i combines X[i] against parameter blocks selected by
    (r_i-1)*MaxRank + (rank_k-1) for each valid neighbour rank k in
    RankOffset; invalid slots contribute zero."""
    x = first(ins, "X")                    # [n, d]
    ro = first(ins, "RankOffset")          # [n, 1+2*MaxRank] ints
    p = first(ins, "RankParam")            # [max_rank*max_rank*d, out]
    mr = int(attrs.get("MaxRank", 3))
    n, d = x.shape
    ocol = p.shape[1]
    pb = p.reshape(mr * mr, d, ocol)
    ins_rank = ro[:, 0].astype(jnp.int32)  # [n]
    o = jnp.zeros((n, ocol), x.dtype)
    for k in range(mr):
        fea_rank = ro[:, 2 * k + 1].astype(jnp.int32)
        valid = (ins_rank > 0) & (fea_rank > 0)
        block_id = jnp.clip((ins_rank - 1) * mr + (fea_rank - 1), 0,
                            mr * mr - 1)
        contrib = jnp.einsum("nd,ndo->no", x, pb[block_id])
        o = o + jnp.where(valid[:, None], contrib, 0.0)
    return {"Out": [o], "InputHelp": [x], "InsRank": [ins_rank.astype(x.dtype)[:, None]]}


# --------------------------------------------------------------------------
# tree_conv — graph conv over trees (reference tree_conv_op.h: patches are
# (node, parent-chain) windows; here one-hop weighted aggregation per the
# EdgeSet adjacency, iterated max_depth times)
# --------------------------------------------------------------------------
@register_op("tree_conv", stateful=True, inputs=("NodesVector", "EdgeSet", "Filter"),
             diff_inputs=("NodesVector", "Filter"),
             attr_defaults={"max_depth": 2})
def _tree_conv(ins, attrs):
    nodes = first(ins, "NodesVector")      # [b, n, f]
    edges = first(ins, "EdgeSet")          # [b, e, 2] (parent, child)
    filt = first(ins, "Filter")            # [f, 3, out_size, num_filters]
    b, n, f = nodes.shape
    fdim, three, osz, nf = filt.shape
    # adjacency (symmetric) per batch from the edge list
    e = np.asarray(edges)
    o = []
    for bi in range(b):
        adj = np.zeros((n, n), np.float32)
        for pa, ch in e[bi]:
            if pa > 0 or ch > 0:
                adj[int(pa), int(ch)] = 1.0
        adjj = jnp.asarray(adj)
        x = nodes[bi]
        # W decomposed into self / down(children) / up(parent) roles
        w_self = filt[:, 0].reshape(f, osz * nf)
        w_down = filt[:, 1].reshape(f, osz * nf)
        w_up = filt[:, 2].reshape(f, osz * nf)
        h = (x @ w_self + (adjj @ x) @ w_down + (adjj.T @ x) @ w_up)
        o.append(jnp.tanh(h.reshape(n, osz, nf).max(axis=1)))
    return out(Out=jnp.stack(o))


@register_op("match_matrix_tensor", inputs=("X", "Y", "W"),
             diff_inputs=("X", "Y", "W"), needs_lod=True,
             attr_defaults={"dim_t": 1})
def _match_matrix_tensor(ins, attrs):
    """Text-match tensor: per sequence pair, out[t, i, j] =
    x_i^T W_t y_j (reference match_matrix_tensor_op.cc), flattened to the
    LoD layout [sum_i lenx_i*leny_i*dim_t, 1]."""
    x, y, w = first(ins, "X"), first(ins, "Y"), first(ins, "W")
    lods = attrs.get("_lod") or {}
    xo = np.asarray(lods["X"][0][-1], np.int64)
    yo = np.asarray(lods["Y"][0][-1], np.int64)
    dim_t = w.shape[1] if w.ndim == 3 else int(attrs.get("dim_t", 1))
    wt = w if w.ndim == 3 else w.reshape(x.shape[1], dim_t, y.shape[1])
    pieces, lens = [], []
    for i in range(len(xo) - 1):
        xs = x[xo[i]:xo[i + 1]]
        ys = y[yo[i]:yo[i + 1]]
        m = jnp.einsum("id,dte,ke->tik", xs, wt, ys)
        pieces.append(m.reshape(-1))
        lens.append(m.size)
    o = jnp.concatenate(pieces)[:, None]
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Out": [o], "Tmp": [o],
            "_lod": {"Out": [(lod0,)]}}


@register_op("var_conv_2d", inputs=("X", "ROW", "COLUMN", "W"),
             diff_inputs=("X", "W"), needs_lod=True,
             attr_defaults={"InputChannel": 1, "OutputChannel": 1,
                            "StrideH": 1, "StrideW": 1, "KernelH": 3,
                            "KernelW": 3})
def _var_conv_2d(ins, attrs):
    """Variable-size 2d conv over per-sequence images (reference
    var_conv_2d_op.cc): each sequence i is an image [in_c, row_i, col_i]
    flattened in X's LoD; conv each independently."""
    from jax import lax
    x = first(ins, "X")
    w = first(ins, "W")
    rows_lod = (attrs.get("_lod") or {}).get("ROW")
    cols_lod = (attrs.get("_lod") or {}).get("COLUMN")
    ro = np.asarray(rows_lod[0][-1], np.int64)
    co = np.asarray(cols_lod[0][-1], np.int64)
    ic = int(attrs.get("InputChannel", 1))
    oc = int(attrs.get("OutputChannel", 1))
    kh, kw = int(attrs.get("KernelH", 3)), int(attrs.get("KernelW", 3))
    sh, sw = int(attrs.get("StrideH", 1)), int(attrs.get("StrideW", 1))
    wk = w.reshape(oc, ic, kh, kw)
    flat = x.reshape(-1)
    pos = 0
    pieces, lens = [], []
    for i in range(len(ro) - 1):
        r = int(ro[i + 1] - ro[i])
        c = int(co[i + 1] - co[i])
        img = flat[pos:pos + ic * r * c].reshape(1, ic, r, c)
        pos += ic * r * c
        o = lax.conv_general_dilated(
            img, wk, (sh, sw),
            [((kh - 1) // 2, (kh - 1) // 2), ((kw - 1) // 2, (kw - 1) // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        pieces.append(o.reshape(-1))
        lens.append(o.size)
    res = jnp.concatenate(pieces)[:, None]
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Out": [res], "Col": [res], "_lod": {"Out": [(lod0,)]}}


@register_op("pyramid_hash", stateful=True, inputs=("X", "W", "WhiteList", "BlackList"),
             diff_inputs=("W",), needs_lod=True,
             attr_defaults={"num_emb": 8, "space_len": 1000, "pyramid_layer": 2,
                            "rand_len": 8, "drop_out_percent": 0,
                            "is_training": False, "use_filter": False,
                            "white_list_len": 0, "black_list_len": 0,
                            "seed": 0, "lr": 1.0, "distribute_update_vars": ""})
def _pyramid_hash(ins, attrs):
    """Pyramid text-hash embedding (reference pyramid_hash_op.cc): for each
    position, hash the n-grams (n=2..pyramid_layer+1) starting there into
    rand_len-wide rows of W and sum the gathered chunks into a num_emb
    vector. Hash is the same 32-bit avalanche mix as the hash op (not
    bit-identical to the reference's xxhash)."""
    x = first(ins, "X")
    w = first(ins, "W")                    # [space_len, 1] flat table
    lods = (attrs.get("_lod") or {}).get("X")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, x.shape[0]], np.int64))
    num_emb = int(attrs.get("num_emb", 8))
    rand_len = int(attrs.get("rand_len", 8))
    space = int(attrs.get("space_len", 1000))
    layers = int(attrs.get("pyramid_layer", 2))
    ids = np.asarray(x).reshape(-1)
    T = len(ids)
    chunks = num_emb // rand_len
    wflat = w.reshape(-1)
    acc = jnp.zeros((T, num_emb), w.dtype)
    for n_ in range(2, layers + 2):
        # host-computed n-gram keys (ids are host data by LoD contract)
        keys = np.zeros(T, np.uint64)
        valid = np.zeros(T, np.float32)
        for t in range(T):
            # n-gram must stay inside its sequence
            s_i = np.searchsorted(offs, t, side="right") - 1
            if t + n_ <= offs[s_i + 1]:
                k = np.uint64(0)
                for g in range(n_):
                    k = k * np.uint64(1000003) + np.uint64(ids[t + g])
                keys[t] = k
                valid[t] = 1.0
        cols = []
        for c in range(chunks):
            v = (keys ^ np.uint64(0x9E3779B97F4A7C15 + c * 0x2545F4914F6CDD1D)) \
                & np.uint64(0xFFFFFFFF)
            v = (v * np.uint64(2654435761)) & np.uint64(0xFFFFFFFF)
            base = (v % np.uint64(max(space - rand_len, 1))).astype(np.int64)
            idx = base[:, None] + np.arange(rand_len)[None, :]
            cols.append(wflat[jnp.asarray(idx)])
        g = jnp.concatenate(cols, axis=1) * jnp.asarray(valid)[:, None]
        acc = acc + g
    return {"Out": [acc], "X_Temp_Out": [x],
            "_lod": {"Out": [lods[0]] if lods else []}}


@register_op("sequence_topk_avg_pooling", inputs=("X", "ROW", "COLUMN"),
             diff_inputs=("X",), needs_lod=True,
             attr_defaults={"topks": [1], "channel_num": 1})
def _sequence_topk_avg_pooling(ins, attrs):
    """Top-k average pooling over per-pair match matrices (reference
    sequence_topk_avg_pooling_op.h): X holds [channel, row_i, col_i] per
    sequence; output per row is the average of its top-k column scores,
    concatenated over topks and channels."""
    x = first(ins, "X")
    topks = [int(t) for t in attrs.get("topks", [1])]
    ch = int(attrs.get("channel_num", 1))
    rows_lod = (attrs.get("_lod") or {}).get("ROW")
    cols_lod = (attrs.get("_lod") or {}).get("COLUMN")
    ro = np.asarray(rows_lod[0][-1], np.int64)
    co = np.asarray(cols_lod[0][-1], np.int64)
    flat = x.reshape(-1)
    pos = 0
    pieces, lens = [], []
    for i in range(len(ro) - 1):
        r = int(ro[i + 1] - ro[i])
        c = int(co[i + 1] - co[i])
        m = flat[pos:pos + ch * r * c].reshape(ch, r, c)
        pos += ch * r * c
        srt = jnp.sort(m, axis=2)[:, :, ::-1]          # desc per row
        feats = []
        for k in topks:
            kk = min(k, c) if c > 0 else 0
            if kk == 0:
                feats.append(jnp.zeros((ch, r), x.dtype))
            else:
                feats.append(jnp.sum(srt[:, :, :kk], axis=2) / k)
        f = jnp.stack(feats, axis=2)       # [ch, r, n_topk]
        pieces.append(jnp.transpose(f, (1, 0, 2)).reshape(r, -1))
        lens.append(r)
    o = jnp.concatenate(pieces, axis=0)
    lod0 = tuple(int(v) for v in np.concatenate([[0], np.cumsum(lens)]))
    return {"Out": [o], "pos": [jnp.zeros((1,), jnp.int32)],
            "_lod": {"Out": [(lod0,)]}}
