"""Tensor creation / manipulation op kernels (reference: the corresponding
operators under paddle/fluid/operators/: fill_constant_op.cc, reshape_op.cc,
transpose_op.cc, concat_op.cc, split_op.cc, gather/scatter, slice_op.cc,
one_hot, cast, random ops …).

Random ops draw from the executor-threaded PRNG key (attrs["_rng"]) instead
of stateful cuRAND generators — this keeps the whole block a pure function
of (state, feeds, step key), which is what lets it live under one jit.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, seq, out
from ..fluid.core import dtype_to_jnp


def _shape_from(ins, attrs, key="shape"):
    """Resolve shape from ShapeTensor/ShapeTensorList inputs or attr."""
    st = first(ins, "ShapeTensor")
    if st is not None:
        return [int(x) for x in np.asarray(st)]
    stl = seq(ins, "ShapeTensorList")
    if stl:
        return [int(np.asarray(s).reshape(())) for s in stl]
    return [int(s) for s in attrs.get(key, [])]


# --------------------------------------------------------------------------
# creation
# --------------------------------------------------------------------------
@register_op("fill_constant", inputs=("ShapeTensor", "ShapeTensorList", "ValueTensor"),
             no_grad=True, attr_defaults={"value": 0.0, "shape": [],
                                          "dtype": 5, "str_value": ""})
def _fill_constant(ins, attrs):
    shape = _shape_from(ins, attrs)
    dt = dtype_to_jnp(attrs.get("dtype", 5))
    vt = first(ins, "ValueTensor")
    if vt is not None:
        return out(Out=jnp.broadcast_to(vt.astype(dt).reshape(()), shape))
    sv = attrs.get("str_value", "")
    val = float(sv) if sv not in ("", None) else attrs.get("value", 0.0)
    return out(Out=jnp.full(shape, val, dt))


@register_op("fill_any_like", inputs=("X",), no_grad=True,
             attr_defaults={"value": 0.0, "dtype": -1})
def _fill_any_like(ins, attrs):
    x = first(ins, "X")
    dt = attrs.get("dtype", -1)
    dt = x.dtype if dt in (-1, None) else dtype_to_jnp(dt)
    return out(Out=jnp.full(x.shape, attrs.get("value", 0.0), dt))


@register_op("fill_zeros_like", inputs=("X",), no_grad=True)
def _fill_zeros_like(ins, attrs):
    return out(Out=jnp.zeros_like(first(ins, "X")))


@register_op("fill_constant_batch_size_like", inputs=("Input",), no_grad=True,
             attr_defaults={"shape": [], "value": 0.0, "dtype": 5,
                            "input_dim_idx": 0, "output_dim_idx": 0})
def _fill_constant_bsl(ins, attrs):
    x = first(ins, "Input")
    shape = [int(s) for s in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    return out(Out=jnp.full(shape, attrs.get("value", 0.0),
                            dtype_to_jnp(attrs.get("dtype", 5))))


@register_op("eye", no_grad=True,
             attr_defaults={"num_rows": 1, "num_columns": -1, "dtype": 5})
def _eye(ins, attrs):
    n = attrs["num_rows"]
    m = attrs.get("num_columns", -1)
    m = n if m in (-1, None) else m
    return out(Out=jnp.eye(n, m, dtype=dtype_to_jnp(attrs.get("dtype", 5))))


@register_op("diag", inputs=("Diagonal",), no_grad=True)
def _diag(ins, attrs):
    return out(Out=jnp.diag(first(ins, "Diagonal")))


@register_op("diag_embed", inputs=("Input",),
             attr_defaults={"offset": 0, "dim1": -2, "dim2": -1})
def _diag_embed(ins, attrs):
    x = first(ins, "Input")
    offset = int(attrs.get("offset", 0))
    n = x.shape[-1] + abs(offset)
    i = jnp.arange(x.shape[-1])
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    o = jnp.zeros(x.shape[:-1] + (n, n), x.dtype).at[..., r, c].set(x)
    ndim = o.ndim
    dim1 = int(attrs.get("dim1", -2)) % ndim
    dim2 = int(attrs.get("dim2", -1)) % ndim
    if (dim1, dim2) != (ndim - 2, ndim - 1):
        o = jnp.moveaxis(o, (ndim - 2, ndim - 1), (dim1, dim2))
    return out(Out=o)


@register_op("range", inputs=("Start", "End", "Step"), no_grad=True,
             stateful=True)  # output SHAPE depends on input values: host op
def _range(ins, attrs):
    s = float(np.asarray(first(ins, "Start")).reshape(()))
    e = float(np.asarray(first(ins, "End")).reshape(()))
    st = float(np.asarray(first(ins, "Step")).reshape(()))
    dt = first(ins, "Start").dtype
    return out(Out=jnp.arange(s, e, st, dtype=dt))


@register_op("linspace", inputs=("Start", "Stop", "Num"), no_grad=True,
             stateful=True)  # output SHAPE depends on Num's value: host op
def _linspace(ins, attrs):
    s = np.asarray(first(ins, "Start")).reshape(())
    e = np.asarray(first(ins, "Stop")).reshape(())
    n = int(np.asarray(first(ins, "Num")).reshape(()))
    return out(Out=jnp.linspace(s, e, n, dtype=first(ins, "Start").dtype))


@register_op("assign", inputs=("X",))
def _assign(ins, attrs):
    return out(Out=first(ins, "X"))


@register_op("assign_value", no_grad=True,
             attr_defaults={"shape": [], "dtype": 5, "fp32_values": [],
                            "int32_values": [], "int64_values": [],
                            "bool_values": []})
def _assign_value(ins, attrs):
    dt = attrs.get("dtype", 5)
    vals = (attrs.get("fp32_values") or attrs.get("int32_values")
            or attrs.get("int64_values") or attrs.get("bool_values") or [])
    return out(Out=jnp.asarray(np.array(vals, dtype=np.dtype(dtype_to_jnp(dt)))
                               .reshape([int(s) for s in attrs["shape"]])))


@register_op("shape", inputs=("Input",), no_grad=True)
def _shape(ins, attrs):
    return out(Out=jnp.asarray(first(ins, "Input").shape, jnp.int32))


@register_op("size", inputs=("Input",), no_grad=True)
def _size(ins, attrs):
    return out(Out=jnp.asarray(first(ins, "Input").size, jnp.int32).reshape((1,)))


@register_op("cast", inputs=("X",),
             attr_defaults={"in_dtype": 5, "out_dtype": 5})
def _cast(ins, attrs):
    return out(Out=first(ins, "X").astype(dtype_to_jnp(attrs["out_dtype"])))


# --------------------------------------------------------------------------
# random (rng threaded by executor via attrs["_rng"])
# --------------------------------------------------------------------------
@register_op("uniform_random", needs_rng=True, no_grad=True,
             inputs=("ShapeTensor", "ShapeTensorList"),
             attr_defaults={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                            "dtype": 5})
def _uniform_random(ins, attrs):
    shape = _shape_from(ins, attrs)
    dt = dtype_to_jnp(attrs.get("dtype", 5))
    return out(Out=jax.random.uniform(attrs["_rng"], shape, dt,
                                      attrs.get("min", -1.0),
                                      attrs.get("max", 1.0)))


@register_op("uniform_random_batch_size_like", needs_rng=True, no_grad=True,
             inputs=("Input",),
             attr_defaults={"shape": [], "min": -1.0, "max": 1.0, "seed": 0,
                            "dtype": 5, "input_dim_idx": 0, "output_dim_idx": 0})
def _uniform_random_bsl(ins, attrs):
    x = first(ins, "Input")
    shape = [int(s) for s in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    return out(Out=jax.random.uniform(attrs["_rng"], shape,
                                      dtype_to_jnp(attrs.get("dtype", 5)),
                                      attrs.get("min", -1.0), attrs.get("max", 1.0)))


@register_op("gaussian_random", needs_rng=True, no_grad=True,
             inputs=("ShapeTensor", "ShapeTensorList"),
             attr_defaults={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                            "dtype": 5})
def _gaussian_random(ins, attrs):
    shape = _shape_from(ins, attrs)
    dt = dtype_to_jnp(attrs.get("dtype", 5))
    return out(Out=attrs.get("mean", 0.0)
               + attrs.get("std", 1.0) * jax.random.normal(attrs["_rng"], shape, dt))


@register_op("gaussian_random_batch_size_like", needs_rng=True, no_grad=True,
             inputs=("Input",),
             attr_defaults={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                            "dtype": 5, "input_dim_idx": 0, "output_dim_idx": 0})
def _gaussian_random_bsl(ins, attrs):
    x = first(ins, "Input")
    shape = [int(s) for s in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    return out(Out=attrs.get("mean", 0.0) + attrs.get("std", 1.0)
               * jax.random.normal(attrs["_rng"], shape,
                                   dtype_to_jnp(attrs.get("dtype", 5))))


@register_op("truncated_gaussian_random", needs_rng=True, no_grad=True,
             attr_defaults={"shape": [], "mean": 0.0, "std": 1.0, "seed": 0,
                            "dtype": 5})
def _truncated_gaussian_random(ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    dt = dtype_to_jnp(attrs.get("dtype", 5))
    t = jax.random.truncated_normal(attrs["_rng"], -2.0, 2.0, shape, dt)
    return out(Out=attrs.get("mean", 0.0) + attrs.get("std", 1.0) * t)


@register_op("randint", needs_rng=True, no_grad=True,
             inputs=("ShapeTensor", "ShapeTensorList"),
             attr_defaults={"shape": [], "low": 0, "high": 0, "seed": 0,
                            "dtype": 3})
def _randint(ins, attrs):
    shape = _shape_from(ins, attrs)
    return out(Out=jax.random.randint(attrs["_rng"], shape, attrs.get("low", 0),
                                      attrs.get("high", 1),
                                      dtype_to_jnp(attrs.get("dtype", 3))))


@register_op("randperm", needs_rng=True, no_grad=True,
             attr_defaults={"n": 1, "seed": 0, "dtype": 3})
def _randperm(ins, attrs):
    return out(Out=jax.random.permutation(attrs["_rng"], attrs["n"]).astype(
        dtype_to_jnp(attrs.get("dtype", 3))))


@register_op("sampling_id", needs_rng=True, no_grad=True, inputs=("X",),
             attr_defaults={"min": 0.0, "max": 1.0, "seed": 0, "dtype": 5})
def _sampling_id(ins, attrs):
    """Draw one class index per row by inverse-CDF over the given
    probabilities: r ~ U[min,max), index = #{cumsum(p) < r} (reference
    sampling_id_op.h). seed!=0 pins the stream for reproducibility."""
    x = first(ins, "X")
    rng = (jax.random.key(int(attrs["seed"])) if attrs.get("seed", 0)
           else attrs["_rng"])
    r = jax.random.uniform(rng, (x.shape[0],), x.dtype,
                           attrs.get("min", 0.0), attrs.get("max", 1.0))
    cum = jnp.cumsum(x, axis=1)
    idx = jnp.sum((cum < r[:, None]).astype(jnp.int32), axis=1)
    idx = jnp.clip(idx, 0, x.shape[1] - 1)
    return out(Out=idx.astype(dtype_to_jnp(attrs.get("dtype", 5))
                              if attrs.get("dtype", 5) != 5 else jnp.int32))


@register_op("seed", no_grad=True, attr_defaults={"seed": 0})
def _seed(ins, attrs):
    return out(Out=jnp.asarray([attrs.get("seed", 0)], jnp.int32))


# --------------------------------------------------------------------------
# shape manipulation
# --------------------------------------------------------------------------
def _infer_reshape(x_shape, target):
    target = list(target)
    for i, t in enumerate(target):
        if t == 0:
            target[i] = x_shape[i]
    if -1 in target:
        known = int(np.prod([t for t in target if t != -1]))
        target[target.index(-1)] = int(np.prod(x_shape)) // max(known, 1)
    return target


@register_op("reshape", inputs=("X", "Shape", "ShapeTensor"),
             attr_defaults={"shape": []})
def _reshape(ins, attrs):
    x = first(ins, "X")
    sh = first(ins, "Shape")
    target = ([int(v) for v in np.asarray(sh)] if sh is not None
              else _shape_from(ins, attrs))
    return out(Out=x.reshape(_infer_reshape(x.shape, target)))


@register_op("reshape2", inputs=("X", "Shape", "ShapeTensor"),
             attr_defaults={"shape": []})
def _reshape2(ins, attrs):
    x = first(ins, "X")
    sh = first(ins, "Shape")
    target = ([int(v) for v in np.asarray(sh)] if sh is not None
              else _shape_from(ins, attrs))
    return out(Out=x.reshape(_infer_reshape(x.shape, target)),
               XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("transpose", inputs=("X",), attr_defaults={"axis": []})
def _transpose(ins, attrs):
    return out(Out=jnp.transpose(first(ins, "X"), attrs["axis"]))


@register_op("transpose2", inputs=("X",), attr_defaults={"axis": []})
def _transpose2(ins, attrs):
    x = first(ins, "X")
    return out(Out=jnp.transpose(x, attrs["axis"]),
               XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("flatten", inputs=("X",), attr_defaults={"axis": 1})
def _flatten(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", 1)
    return out(Out=x.reshape((int(np.prod(x.shape[:ax])), -1)))


@register_op("flatten2", inputs=("X",), attr_defaults={"axis": 1})
def _flatten2(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", 1)
    return out(Out=x.reshape((int(np.prod(x.shape[:ax])), -1)),
               XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("flatten_contiguous_range", inputs=("X",),
             attr_defaults={"start_axis": 1, "stop_axis": 1})
def _flatten_range(ins, attrs):
    x = first(ins, "X")
    s, e = attrs.get("start_axis", 1), attrs.get("stop_axis", 1)
    s, e = s % x.ndim, e % x.ndim
    shape = x.shape[:s] + (int(np.prod(x.shape[s:e + 1])),) + x.shape[e + 1:]
    return out(Out=x.reshape(shape), XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("squeeze", inputs=("X",), attr_defaults={"axes": []})
def _squeeze(ins, attrs):
    x = first(ins, "X")
    axes = [a % x.ndim for a in attrs.get("axes", [])]
    if not axes:
        axes = [i for i, s in enumerate(x.shape) if s == 1]
    axes = [a for a in axes if x.shape[a] == 1]
    return out(Out=jnp.squeeze(x, tuple(axes)))


@register_op("squeeze2", inputs=("X",), attr_defaults={"axes": []})
def _squeeze2(ins, attrs):
    x = first(ins, "X")
    o = _squeeze(ins, attrs)["Out"][0]
    return out(Out=o, XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("unsqueeze", inputs=("X",), attr_defaults={"axes": []})
def _unsqueeze(ins, attrs):
    x = first(ins, "X")
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return out(Out=x)


@register_op("unsqueeze2", inputs=("X",), attr_defaults={"axes": []})
def _unsqueeze2(ins, attrs):
    x = first(ins, "X")
    o = x
    for a in sorted(attrs["axes"]):
        o = jnp.expand_dims(o, a)
    return out(Out=o, XShape=jnp.zeros((0,) + x.shape, x.dtype))


@register_op("concat", inputs=("X", "AxisTensor"), attr_defaults={"axis": 0})
def _concat(ins, attrs):
    xs = seq(ins, "X")
    at = first(ins, "AxisTensor")
    ax = int(np.asarray(at).reshape(())) if at is not None else int(attrs.get("axis", 0))
    return out(Out=jnp.concatenate(xs, axis=ax))


@register_op("split", inputs=("X", "AxisTensor", "SectionsTensorList"),
             attr_defaults={"axis": 0, "num": 0, "sections": []})
def _split(ins, attrs):
    x = first(ins, "X")
    at = first(ins, "AxisTensor")
    ax = int(np.asarray(at).reshape(())) if at is not None else int(attrs.get("axis", 0))
    sections = attrs.get("sections") or []
    num = attrs.get("num", 0)
    if sections:
        sections = list(sections)
        if -1 in sections:
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = x.shape[ax] - known
        idx = np.cumsum(sections)[:-1].tolist()
        parts = jnp.split(x, idx, axis=ax)
    else:
        parts = jnp.split(x, num, axis=ax)
    return out(Out=list(parts))


@register_op("stack", inputs=("X",), attr_defaults={"axis": 0})
def _stack(ins, attrs):
    return out(Y=jnp.stack(seq(ins, "X"), axis=attrs.get("axis", 0)))


@register_op("unstack", inputs=("X",), attr_defaults={"axis": 0, "num": 0})
def _unstack(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", 0) % x.ndim
    return out(Y=[jnp.squeeze(s, ax) for s in jnp.split(x, x.shape[ax], axis=ax)])


@register_op("unbind", inputs=("X",), attr_defaults={"axis": 0})
def _unbind(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", 0) % x.ndim
    return out(Out=[jnp.squeeze(s, ax) for s in jnp.split(x, x.shape[ax], axis=ax)])


@register_op("expand", inputs=("X", "ExpandTimes"),
             attr_defaults={"expand_times": []})
def _expand(ins, attrs):
    x = first(ins, "X")
    et = first(ins, "ExpandTimes")
    times = ([int(v) for v in np.asarray(et)] if et is not None
             else [int(t) for t in attrs["expand_times"]])
    return out(Out=jnp.tile(x, times))


@register_op("expand_as", inputs=("X", "target_tensor"))
def _expand_as(ins, attrs):
    x, t = first(ins, "X"), first(ins, "target_tensor")
    times = [ts // xs for ts, xs in zip(t.shape, x.shape)]
    return out(Out=jnp.tile(x, times))


@register_op("tile", inputs=("X",), attr_defaults={"repeat_times": []})
def _tile(ins, attrs):
    return out(Out=jnp.tile(first(ins, "X"), attrs["repeat_times"]))


@register_op("slice", inputs=("Input", "StartsTensor", "EndsTensor"),
             attr_defaults={"axes": [], "starts": [], "ends": [],
                            "decrease_axis": [], "infer_flags": []})
def _slice(ins, attrs):
    x = first(ins, "Input")
    st = first(ins, "StartsTensor")
    et = first(ins, "EndsTensor")
    starts = ([int(v) for v in np.asarray(st)] if st is not None
              else [int(s) for s in attrs["starts"]])
    ends = ([int(v) for v in np.asarray(et)] if et is not None
            else [int(e) for e in attrs["ends"]])
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(attrs["axes"], starts, ends):
        dim = x.shape[ax]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[ax] = slice(s, e)
    o = x[tuple(idx)]
    dec = attrs.get("decrease_axis") or []
    if dec:
        o = jnp.squeeze(o, tuple(d for d in dec if o.shape[d] == 1))
        if o.ndim == 0:
            o = o.reshape((1,))
    return out(Out=o)


@register_op("strided_slice", inputs=("Input",),
             attr_defaults={"axes": [], "starts": [], "ends": [],
                            "strides": [], "decrease_axis": [],
                            "infer_flags": []})
def _strided_slice(ins, attrs):
    x = first(ins, "Input")
    idx = [slice(None)] * x.ndim
    for ax, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                            attrs["strides"]):
        idx[ax] = slice(s, e, st)
    o = x[tuple(idx)]
    dec = attrs.get("decrease_axis") or []
    if dec:
        o = jnp.squeeze(o, tuple(dec))
    return out(Out=o)


@register_op("gather", inputs=("X", "Index"), diff_inputs=("X",))
def _gather(ins, attrs):
    x, idx = first(ins, "X"), first(ins, "Index")
    return out(Out=jnp.take(x, idx.reshape(-1), axis=0))


@register_op("gather_nd", inputs=("X", "Index"), diff_inputs=("X",))
def _gather_nd(ins, attrs):
    x, idx = first(ins, "X"), first(ins, "Index")
    return out(Out=x[tuple(jnp.moveaxis(idx, -1, 0))])


@register_op("scatter", inputs=("X", "Ids", "Updates"),
             diff_inputs=("X", "Updates"), attr_defaults={"overwrite": True})
def _scatter(ins, attrs):
    x, ids, upd = first(ins, "X"), first(ins, "Ids"), first(ins, "Updates")
    ids = ids.reshape(-1)
    if attrs.get("overwrite", True):
        return out(Out=x.at[ids].set(upd))
    return out(Out=x.at[ids].set(0.0 * x[ids]).at[ids].add(upd))


@register_op("scatter_nd_add", inputs=("X", "Index", "Updates"),
             diff_inputs=("X", "Updates"))
def _scatter_nd_add(ins, attrs):
    x, idx, upd = first(ins, "X"), first(ins, "Index"), first(ins, "Updates")
    return out(Out=x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd))


@register_op("index_select", inputs=("X", "Index"), diff_inputs=("X",),
             attr_defaults={"dim": 0})
def _index_select(ins, attrs):
    return out(Out=jnp.take(first(ins, "X"), first(ins, "Index"),
                            axis=attrs.get("dim", 0)))


@register_op("index_sample", inputs=("X", "Index"), diff_inputs=("X",))
def _index_sample(ins, attrs):
    x, idx = first(ins, "X"), first(ins, "Index")
    return out(Out=jnp.take_along_axis(x, idx, axis=1))


@register_op("where", inputs=("Condition", "X", "Y"), diff_inputs=("X", "Y"))
def _where(ins, attrs):
    return out(Out=jnp.where(first(ins, "Condition"), first(ins, "X"),
                             first(ins, "Y")))


@register_op("where_index", inputs=("Condition",), no_grad=True, stateful=True)
def _where_index(ins, attrs):
    # data-dependent shape: interpreter-only (like reference where_index)
    cond = np.asarray(first(ins, "Condition"))
    return out(Out=jnp.asarray(np.stack(np.nonzero(cond), axis=1), jnp.int32))


@register_op("one_hot", inputs=("X", "depth_tensor"), no_grad=True,
             attr_defaults={"depth": 1, "dtype": 5, "allow_out_of_range": False})
def _one_hot(ins, attrs):
    x = first(ins, "X")
    dt = first(ins, "depth_tensor")
    depth = int(np.asarray(dt).reshape(())) if dt is not None else attrs["depth"]
    o = jax.nn.one_hot(jnp.squeeze(x, -1) if x.shape[-1] == 1 else x, depth,
                       dtype=dtype_to_jnp(attrs.get("dtype", 5)))
    return out(Out=o)


@register_op("one_hot_v2", inputs=("X", "depth_tensor"), no_grad=True,
             attr_defaults={"depth": 1, "dtype": 5, "allow_out_of_range": False})
def _one_hot_v2(ins, attrs):
    x = first(ins, "X")
    dt = first(ins, "depth_tensor")
    depth = int(np.asarray(dt).reshape(())) if dt is not None else attrs["depth"]
    return out(Out=jax.nn.one_hot(x, depth, dtype=dtype_to_jnp(attrs.get("dtype", 5))))


@register_op("arg_max", inputs=("X",), no_grad=True,
             attr_defaults={"axis": -1, "keepdims": False, "dtype": 3})
def _arg_max(ins, attrs):
    return out(Out=jnp.argmax(first(ins, "X"), axis=attrs.get("axis", -1)).astype(
        dtype_to_jnp(attrs.get("dtype", 3) if attrs.get("dtype", 3) > 0 else 3)))


@register_op("arg_min", inputs=("X",), no_grad=True,
             attr_defaults={"axis": -1, "keepdims": False, "dtype": 3})
def _arg_min(ins, attrs):
    return out(Out=jnp.argmin(first(ins, "X"), axis=attrs.get("axis", -1)).astype(jnp.int32))


@register_op("argsort", inputs=("X",), no_grad=True,
             attr_defaults={"axis": -1, "descending": False})
def _argsort(ins, attrs):
    x = first(ins, "X")
    ax = attrs.get("axis", -1)
    if attrs.get("descending", False):
        idx = jnp.argsort(-x, axis=ax)
    else:
        idx = jnp.argsort(x, axis=ax)
    o = jnp.take_along_axis(x, idx, axis=ax)
    return out(Out=o, Indices=idx.astype(jnp.int32))


@register_op("top_k", inputs=("X", "K"), diff_inputs=("X",),
             attr_defaults={"k": 1})
def _top_k(ins, attrs):
    x = first(ins, "X")
    kt = first(ins, "K")
    k = int(np.asarray(kt).reshape(())) if kt is not None else attrs.get("k", 1)
    vals, idx = lax.top_k(x, k)
    return out(Out=vals, Indices=idx.astype(jnp.int32))


@register_op("top_k_v2", inputs=("X", "K"), diff_inputs=("X",),
             attr_defaults={"k": 1, "axis": -1, "largest": True, "sorted": True})
def _top_k_v2(ins, attrs):
    x = first(ins, "X")
    kt = first(ins, "K")
    k = int(np.asarray(kt).reshape(())) if kt is not None else attrs.get("k", 1)
    ax = attrs.get("axis", -1) % x.ndim
    xs = jnp.moveaxis(x, ax, -1)
    if attrs.get("largest", True):
        vals, idx = lax.top_k(xs, k)
    else:
        vals, idx = lax.top_k(-xs, k)
        vals = -vals
    return out(Out=jnp.moveaxis(vals, -1, ax),
               Indices=jnp.moveaxis(idx, -1, ax).astype(jnp.int32))


@register_op("reverse", inputs=("X",), attr_defaults={"axis": []})
def _reverse(ins, attrs):
    x = first(ins, "X")
    return out(Out=jnp.flip(x, [a % x.ndim for a in attrs["axis"]]))


@register_op("flip", inputs=("X",), attr_defaults={"axis": []})
def _flip(ins, attrs):
    x = first(ins, "X")
    return out(Out=jnp.flip(x, [a % x.ndim for a in attrs["axis"]]))


@register_op("roll", inputs=("X",), attr_defaults={"shifts": [], "dims": []})
def _roll(ins, attrs):
    x = first(ins, "X")
    dims = attrs.get("dims") or attrs.get("axis") or []
    if not dims:
        return out(Out=jnp.roll(x.reshape(-1), attrs["shifts"][0]).reshape(x.shape))
    return out(Out=jnp.roll(x, attrs["shifts"], dims))


@register_op("pad", inputs=("X",),
             attr_defaults={"paddings": [], "pad_value": 0.0})
def _pad(ins, attrs):
    x = first(ins, "X")
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return out(Out=jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0)))


@register_op("pad2d", inputs=("X",),
             attr_defaults={"paddings": [0, 0, 0, 0], "mode": "constant",
                            "pad_value": 0.0, "data_format": "NCHW"})
def _pad2d(ins, attrs):
    x = first(ins, "X")
    p = attrs["paddings"]
    mode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[
        attrs.get("mode", "constant")]
    if attrs.get("data_format", "NCHW") == "NCHW":
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pads = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    kw = {"constant_values": attrs.get("pad_value", 0.0)} if mode == "constant" else {}
    return out(Out=jnp.pad(x, pads, mode=mode, **kw))


@register_op("pad_constant_like", inputs=("X", "Y"), diff_inputs=("Y",))
def _pad_constant_like(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return out(Out=jnp.pad(y, pads, constant_values=attrs.get("pad_value", 0.0)))


@register_op("meshgrid", inputs=("X",))
def _meshgrid(ins, attrs):
    return out(Out=list(jnp.meshgrid(*seq(ins, "X"), indexing="ij")))


@register_op("tril_triu", inputs=("X",),
             attr_defaults={"diagonal": 0, "lower": True})
def _tril_triu(ins, attrs):
    x = first(ins, "X")
    d = attrs.get("diagonal", 0)
    o = jnp.tril(x, d) if attrs.get("lower", True) else jnp.triu(x, d)
    return out(Out=o)


@register_op("unique", inputs=("X",), no_grad=True, stateful=True,
             attr_defaults={"dtype": 2})
def _unique(ins, attrs):
    x = np.asarray(first(ins, "X"))
    o, idx = np.unique(x, return_inverse=True)
    # reference keeps first-occurrence order
    order = np.argsort(np.unique(x, return_index=True)[1])
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    return out(Out=jnp.asarray(o[order]),
               Index=jnp.asarray(remap[idx], dtype_to_jnp(attrs.get("dtype", 2))))


@register_op("unique_with_counts", inputs=("X",), no_grad=True, stateful=True,
             attr_defaults={"dtype": 2})
def _unique_with_counts(ins, attrs):
    x = np.asarray(first(ins, "X"))
    o, first_idx, inv, counts = np.unique(x, return_index=True,
                                          return_inverse=True,
                                          return_counts=True)
    order = np.argsort(first_idx)
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    return out(Out=jnp.asarray(o[order]),
               Index=jnp.asarray(remap[inv], dtype_to_jnp(attrs.get("dtype", 2))),
               Count=jnp.asarray(counts[order], dtype_to_jnp(attrs.get("dtype", 2))))


@register_op("shard_index", inputs=("X",), no_grad=True,
             attr_defaults={"index_num": 0, "nshards": 1, "shard_id": 0,
                            "ignore_value": -1})
def _shard_index(ins, attrs):
    x = first(ins, "X")
    shard_size = (attrs["index_num"] + attrs["nshards"] - 1) // attrs["nshards"]
    lo = attrs["shard_id"] * shard_size
    in_shard = (x // shard_size) == attrs["shard_id"]
    return out(Out=jnp.where(in_shard, x - lo, attrs.get("ignore_value", -1)))


@register_op("multiplex", inputs=("X", "Ids"), diff_inputs=("X",))
def _multiplex(ins, attrs):
    xs = jnp.stack(seq(ins, "X"), axis=0)  # [k, n, d]
    ids = first(ins, "Ids").reshape(-1)
    n = xs.shape[1]
    return out(Out=xs[ids, jnp.arange(n)])


@register_op("cross", inputs=("X", "Y"), attr_defaults={"dim": -1})
def _cross(ins, attrs):
    d = attrs.get("dim", -1)
    return out(Out=jnp.cross(first(ins, "X"), first(ins, "Y"), axis=d))


@register_op("is_empty", inputs=("X",), no_grad=True)
def _is_empty(ins, attrs):
    return out(Out=jnp.asarray([first(ins, "X").size == 0]))


@register_op("label_smooth", inputs=("X", "PriorDist"), diff_inputs=("X",),
             attr_defaults={"epsilon": 0.0})
def _label_smooth(ins, attrs):
    x = first(ins, "X")
    eps = attrs.get("epsilon", 0.0)
    prior = first(ins, "PriorDist")
    k = x.shape[-1]
    if prior is None:
        return out(Out=(1 - eps) * x + eps / k)
    return out(Out=(1 - eps) * x + eps * prior.reshape((1,) * (x.ndim - 1) + (k,)))
