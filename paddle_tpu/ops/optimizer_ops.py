"""Optimizer update ops (reference: paddle/fluid/operators/optimizers/ —
sgd_op.cc, momentum_op.cc, adam_op.cc, adamax_op.cc, adagrad_op.cc,
adadelta_op.cc, rmsprop_op.cc, decayed_adagrad_op.cc, ftrl_op.cc, lamb_op.cc,
lars_momentum_op.cc, dpsgd_op.cc, proximal_gd_op.cc, proximal_adagrad_op.cc).

The reference mutates Param in place on its device stream; here each op
returns ParamOut/MomentOut arrays that the executor threads back into the
state dict — inside a jitted step the whole optimizer pass fuses with the
backward and XLA donates the old buffers, so updates stay in-place on HBM.

All are no_grad (nothing differentiates through an optimizer step).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, out


@register_op("sgd", no_grad=True)
def _sgd(ins, attrs):
    p, g, lr = first(ins, "Param"), first(ins, "Grad"), first(ins, "LearningRate")
    return out(ParamOut=p - lr.reshape(()).astype(p.dtype) * g.astype(p.dtype))


@register_op("momentum", no_grad=True,
             attr_defaults={"mu": 0.9, "use_nesterov": False,
                            "regularization_method": "",
                            "regularization_coeff": 0.0})
def _momentum(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    v = first(ins, "Velocity")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    if attrs.get("regularization_method", "") == "l2_decay":
        g = g + attrs.get("regularization_coeff", 0.0) * p
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return out(ParamOut=p_new, VelocityOut=v_new)


@register_op("adam", no_grad=True,
             attr_defaults={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                            "lazy_mode": False, "min_row_size_to_use_multithread": 1000})
def _adam(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    m, v = first(ins, "Moment1"), first(ins, "Moment2")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    b1p = first(ins, "Beta1Pow").reshape(()).astype(p.dtype)
    b2p = first(ins, "Beta2Pow").reshape(()).astype(p.dtype)
    b1t = first(ins, "Beta1Tensor")
    b2t = first(ins, "Beta2Tensor")
    b1 = b1t.reshape(()).astype(p.dtype) if b1t is not None else attrs.get("beta1", 0.9)
    b2 = b2t.reshape(()).astype(p.dtype) if b2t is not None else attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps * jnp.sqrt(1 - b2p))
    b1p_in, b2p_in = first(ins, "Beta1Pow"), first(ins, "Beta2Pow")
    return out(ParamOut=p_new, Moment1Out=m_new, Moment2Out=v_new,
               Beta1PowOut=(b1p * b1).reshape(b1p_in.shape).astype(b1p_in.dtype),
               Beta2PowOut=(b2p * b2).reshape(b2p_in.shape).astype(b2p_in.dtype))


@register_op("adamax", no_grad=True,
             attr_defaults={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
def _adamax(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    m, inf = first(ins, "Moment"), first(ins, "InfNorm")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    b1p = first(ins, "Beta1Pow").reshape(()).astype(p.dtype)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    p_new = p - (lr / (1 - b1p)) * (m_new / (inf_new + eps))
    return out(ParamOut=p_new, MomentOut=m_new, InfNormOut=inf_new)


@register_op("adagrad", no_grad=True, attr_defaults={"epsilon": 1e-6})
def _adagrad(ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    eps = attrs.get("epsilon", 1e-6)
    m_new = m + jnp.square(g)
    return out(ParamOut=p - lr * g / (jnp.sqrt(m_new) + eps), MomentOut=m_new)


@register_op("decayed_adagrad", no_grad=True,
             attr_defaults={"decay": 0.95, "epsilon": 1e-6})
def _decayed_adagrad(ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    d, eps = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6)
    m_new = d * m + (1 - d) * jnp.square(g)
    return out(ParamOut=p - lr * g / (jnp.sqrt(m_new) + eps), MomentOut=m_new)


@register_op("adadelta", no_grad=True,
             attr_defaults={"rho": 0.95, "epsilon": 1e-6})
def _adadelta(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    ag, au = first(ins, "AvgSquaredGrad"), first(ins, "AvgSquaredUpdate")
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((au + eps) / (ag_new + eps)) * g
    au_new = rho * au + (1 - rho) * jnp.square(upd)
    return out(ParamOut=p + upd, AvgSquaredGradOut=ag_new,
               AvgSquaredUpdateOut=au_new)


@register_op("rmsprop", no_grad=True,
             attr_defaults={"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10,
                            "centered": False})
def _rmsprop(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    ms, mom = first(ins, "MeanSquare"), first(ins, "Moment")
    mg = first(ins, "MeanGrad")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    rho = attrs.get("decay", 0.9)
    mu = attrs.get("momentum", 0.0)
    eps = attrs.get("epsilon", 1e-10)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered", False):
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - jnp.square(mg_new) + eps
    else:
        mg_new = mg
        denom = ms_new + eps
    mom_new = mu * mom + lr * g / jnp.sqrt(denom)
    res = out(ParamOut=p - mom_new, MeanSquareOut=ms_new, MomentOut=mom_new)
    if mg is not None:
        res.update(out(MeanGradOut=mg_new))
    return res


@register_op("ftrl", no_grad=True,
             attr_defaults={"l1": 0.0, "l2": 0.0, "lr_power": -0.5})
def _ftrl(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    sq, lin = first(ins, "SquaredAccumulator"), first(ins, "LinearAccumulator")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lp = attrs.get("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    if lp == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (new_sq ** (-lp) - sq ** (-lp)) / lr
    new_lin = lin + g - sigma * p
    if lp == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = new_sq ** (-lp) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    # zero-grad elements have denom==0 (fresh accumulator): keep the param
    p_new = jnp.where(denom > 0, pre / jnp.where(denom > 0, denom, 1.0), p)
    return out(ParamOut=p_new, SquaredAccumOut=new_sq, LinearAccumOut=new_lin)


@register_op("lamb", no_grad=True,
             attr_defaults={"weight_decay": 0.01, "beta1": 0.9, "beta2": 0.999,
                            "epsilon": 1e-6})
def _lamb(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    m, v = first(ins, "Moment1"), first(ins, "Moment2")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    b1p = first(ins, "Beta1Pow").reshape(()).astype(p.dtype)
    b2p = first(ins, "Beta2Pow").reshape(()).astype(p.dtype)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m_new / (1 - b1p)
    v_hat = v_new / (1 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    ratio = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    b1p_in, b2p_in = first(ins, "Beta1Pow"), first(ins, "Beta2Pow")
    return out(ParamOut=p - lr * ratio * r, Moment1Out=m_new, Moment2Out=v_new,
               Beta1PowOut=(b1p * b1).reshape(b1p_in.shape).astype(b1p_in.dtype),
               Beta2PowOut=(b2p * b2).reshape(b2p_in.shape).astype(b2p_in.dtype))


@register_op("lars_momentum", no_grad=True,
             attr_defaults={"mu": 0.9, "lars_coeff": 0.001,
                            "lars_weight_decay": 0.0005, "epsilon": 0.0})
def _lars_momentum(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    v = first(ins, "Velocity")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    lars_coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + wd * p_norm), lr)
    v_new = mu * v + local_lr * (g + wd * p)
    return out(ParamOut=p - v_new, VelocityOut=v_new)


@register_op("dpsgd", no_grad=True, needs_rng=True,
             attr_defaults={"clip": 10.0, "batch_size": 16.0, "sigma": 1.0})
def _dpsgd(ins, attrs):
    import jax
    p, g = first(ins, "Param"), first(ins, "Grad")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    bs = attrs.get("batch_size", 16.0)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    scale = jnp.minimum(1.0, clip / jnp.maximum(g_norm, 1e-10))
    noise = sigma * clip * jax.random.normal(attrs["_rng"], g.shape, g.dtype)
    return out(ParamOut=p - lr * (g * scale + noise) / bs)


@register_op("proximal_gd", no_grad=True,
             attr_defaults={"l1": 0.0, "l2": 0.0})
def _proximal_gd(ins, attrs):
    p, g = first(ins, "Param"), first(ins, "Grad")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0) / (1 + lr * l2)
    return out(ParamOut=p_new)


@register_op("proximal_adagrad", no_grad=True,
             attr_defaults={"l1": 0.0, "l2": 0.0})
def _proximal_adagrad(ins, attrs):
    p, g, m = first(ins, "Param"), first(ins, "Grad"), first(ins, "Moment")
    lr = first(ins, "LearningRate").reshape(()).astype(p.dtype)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    m_new = m + jnp.square(g)
    eff_lr = lr / jnp.sqrt(m_new)
    prox = p - eff_lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff_lr * l1, 0) / (1 + eff_lr * l2)
    return out(ParamOut=p_new, MomentOut=m_new)


@register_op("average_accumulates", no_grad=True,
             attr_defaults={"average_window": 0.0, "max_average_window": 0,
                            "min_average_window": 10000})
def _average_accumulates(ins, attrs):
    param = first(ins, "param")
    s1 = first(ins, "in_sum_1")
    s2 = first(ins, "in_sum_2")
    s3 = first(ins, "in_sum_3")
    num_acc = first(ins, "in_num_accumulates")
    old_num = first(ins, "in_old_num_accumulates")
    num_upd = first(ins, "in_num_updates")
    s1 = s1 + param
    num_acc = num_acc + 1
    num_upd = num_upd + 1
    return out(out_sum_1=s1, out_sum_2=s2, out_sum_3=s3,
               out_num_accumulates=num_acc, out_old_num_accumulates=old_num,
               out_num_updates=num_upd)
