"""Operator kernel library — importing this package registers all ops.

The registry (registry.py) replaces the reference's OpInfoMap/kernel
registries (reference: framework/op_registry.h, op_info.h); kernels are pure
JAX functions compiled by XLA rather than per-device C++ functors."""
from .registry import OPS, register_op, register_grad_maker  # noqa: F401

from . import math_ops       # noqa: F401
from . import tensor_ops     # noqa: F401
from . import nn_ops         # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import framework_ops  # noqa: F401
from . import nn_extra_ops   # noqa: F401
from . import collective_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import sequence_ops   # noqa: F401
from . import rnn_ops        # noqa: F401
from . import distributed_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import loss_extra_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import vision_ops  # noqa: F401
from . import misc_ops   # noqa: F401
from . import fused_ops  # noqa: F401
from . import metrics_misc_ops  # noqa: F401
from . import detection_train_ops  # noqa: F401
from . import lod_control_ops  # noqa: F401
from . import ps_quant_misc_ops  # noqa: F401
