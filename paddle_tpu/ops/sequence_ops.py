"""Sequence (LoD) operators — variable-length sequence math over packed
rows, the TPU-native rebuild of the reference's LoD op family
(reference: paddle/fluid/operators/sequence_ops/*.cc, framework/lod_tensor.h:104).

Representation inversion for TPU: the reference carries LoD on the tensor
and re-runs InferShape per step; here the packed buffer ``[total_rows, ...]``
is the device array and the LoD offsets are HOST-STATIC trace-time metadata
(executor keys the jit cache per LoD bucket). Every index/segment/mask array
derived from offsets is therefore an XLA constant: sequence pooling lowers
to segment-sum/max with constant segment ids, expansion/reversal/concat to
constant-index gathers — no dynamic shapes, MXU-friendly.

Kernels receive ``attrs["_lod"][slot] = [levels|None]`` where ``levels`` is
a tuple of offset tuples (last level = finest). They may return
``{"_lod": {out_slot: [levels]}}`` to declare output LoD.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import (register_op, register_grad_maker, first, seq, out,
                       mark_no_grad, OPS)


# --------------------------------------------------------------------------
# helpers (all host-side numpy on static offsets)
# --------------------------------------------------------------------------
def _lod_of(attrs, slot, idx=0):
    lods = attrs.get("_lod") or {}
    vals = lods.get(slot)
    if not vals or vals[idx] is None:
        return None
    return vals[idx]


def _offs(levels):
    """Finest-level offsets as an int64 numpy array."""
    return np.asarray(levels[-1], np.int64)


def _require_lod(attrs, slot, op_name):
    lv = _lod_of(attrs, slot)
    if lv is None:
        raise ValueError(f"{op_name}: input '{slot}' must carry LoD")
    return lv


def _lens(offs):
    return offs[1:] - offs[:-1]


def _seg_ids(offs):
    return np.repeat(np.arange(len(offs) - 1), _lens(offs))


def _offsets_from_lens(lens):
    return tuple(int(x) for x in np.concatenate([[0], np.cumsum(lens)]))


# --------------------------------------------------------------------------
# sequence_pool / first / last  (reference: sequence_ops/sequence_pool_op.cc)
# --------------------------------------------------------------------------
@register_op("sequence_pool", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"pooltype": "AVERAGE", "pad_value": 0.0})
def _sequence_pool(ins, attrs):
    x = first(ins, "X")
    levels = _require_lod(attrs, "X", "sequence_pool")
    offs = _offs(levels)
    n = len(offs) - 1
    lens = _lens(offs)
    segs = jnp.asarray(_seg_ids(offs))
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    lens_j = jnp.asarray(np.maximum(lens, 1)).reshape(
        (-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
    max_index = None
    if ptype == "SUM":
        o = jax.ops.segment_sum(x, segs, num_segments=n)
    elif ptype == "AVERAGE":
        o = jax.ops.segment_sum(x, segs, num_segments=n) / lens_j
    elif ptype == "SQRT":
        o = jax.ops.segment_sum(x, segs, num_segments=n) / jnp.sqrt(lens_j)
    elif ptype == "MAX":
        o = jax.ops.segment_max(x, segs, num_segments=n)
        # MaxIndex: flat row index of the per-feature max (parity with the
        # reference's MAX_INDEX output used by its grad kernel)
        eq = (x == o[segs])
        idx_src = jnp.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
        big = jnp.where(eq, idx_src, x.shape[0])
        max_index = jax.ops.segment_min(
            jnp.broadcast_to(big, x.shape), segs, num_segments=n
        ).astype(jnp.int32)
    elif ptype in ("FIRST", "LAST"):
        idx = offs[:-1] if ptype == "FIRST" else offs[1:] - 1
        o = jnp.take(x, jnp.asarray(np.where(lens > 0, idx, 0)), axis=0)
    else:
        raise ValueError(f"sequence_pool: unknown pooltype {ptype}")
    if np.any(lens == 0):
        empty = jnp.asarray(lens == 0).reshape(
            (-1,) + (1,) * (x.ndim - 1))
        o = jnp.where(empty, jnp.asarray(attrs.get("pad_value", 0.0), x.dtype), o)
    res = out(Out=o)
    if max_index is not None:
        res["MaxIndex"] = [max_index]
    # pooled output is one row per sequence: lod of upper levels only
    res["_lod"] = {"Out": [tuple(levels[:-1]) or None]}
    return res


# --------------------------------------------------------------------------
# sequence_softmax (reference: sequence_ops/sequence_softmax_op.cc)
# --------------------------------------------------------------------------
@register_op("sequence_softmax", needs_lod=True, diff_inputs=["X"])
def _sequence_softmax(ins, attrs):
    x = first(ins, "X")
    levels = _require_lod(attrs, "X", "sequence_softmax")
    offs = _offs(levels)
    n = len(offs) - 1
    segs = jnp.asarray(_seg_ids(offs))
    flat = x.reshape(x.shape[0])
    m = jax.ops.segment_max(flat, segs, num_segments=n)
    e = jnp.exp(flat - m[segs])
    s = jax.ops.segment_sum(e, segs, num_segments=n)
    y = (e / s[segs]).reshape(x.shape)
    return {"Out": [y], "_lod": {"Out": [levels]}}


# --------------------------------------------------------------------------
# sequence_expand / sequence_expand_as
# (reference: sequence_ops/sequence_expand_op.cc, sequence_expand_as_op.cc)
# --------------------------------------------------------------------------
@register_op("sequence_expand", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"ref_level": -1})
def _sequence_expand(ins, attrs):
    x = first(ins, "X")
    y_levels = _require_lod(attrs, "Y", "sequence_expand")
    ref_level = attrs.get("ref_level", -1)
    if ref_level < 0:
        ref_level += len(y_levels)
    y_offs = np.asarray(y_levels[ref_level], np.int64)
    rep = _lens(y_offs)  # times to repeat x's i-th sequence
    x_levels = _lod_of(attrs, "X")
    if x_levels is None:
        x_offs = np.arange(x.shape[0] + 1, dtype=np.int64)  # each row a seq
    else:
        x_offs = _offs(x_levels)
    nseq = len(x_offs) - 1
    if len(rep) != nseq:
        raise ValueError(
            f"sequence_expand: X has {nseq} sequences but Y ref_level has "
            f"{len(rep)}")
    idx_parts, new_lens = [], []
    for i in range(nseq):
        rows = np.arange(x_offs[i], x_offs[i + 1])
        for _ in range(int(rep[i])):
            idx_parts.append(rows)
            new_lens.append(len(rows))
    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    o = jnp.take(x, jnp.asarray(idx), axis=0)
    new_lod = (_offsets_from_lens(np.asarray(new_lens, np.int64)),)
    return {"Out": [o], "_lod": {"Out": [new_lod]}}


@register_op("sequence_expand_as", needs_lod=True, diff_inputs=["X"])
def _sequence_expand_as(ins, attrs):
    x = first(ins, "X")
    y_levels = _require_lod(attrs, "Y", "sequence_expand_as")
    rep = _lens(_offs(y_levels))
    if len(rep) != x.shape[0]:
        raise ValueError("sequence_expand_as: Y must have one sequence per "
                         "row of X")
    idx = np.repeat(np.arange(x.shape[0]), rep)
    o = jnp.take(x, jnp.asarray(idx), axis=0)
    return {"Out": [o], "_lod": {"Out": [(tuple(int(v) for v in _offs(y_levels)),)]}}


# --------------------------------------------------------------------------
# sequence_concat (reference: sequence_ops/sequence_concat_op.cc)
# --------------------------------------------------------------------------
@register_op("sequence_concat", needs_lod=True, diff_inputs=["X"])
def _sequence_concat(ins, attrs):
    xs = seq(ins, "X")
    lods = (attrs.get("_lod") or {}).get("X") or [None] * len(xs)
    all_offs = []
    for i, (x, lv) in enumerate(zip(xs, lods)):
        if lv is None:
            raise ValueError(f"sequence_concat: input {i} must carry LoD")
        all_offs.append(_offs(lv))
    nseq = len(all_offs[0]) - 1
    base = 0
    idx_parts, new_lens = [], []
    starts = np.concatenate(
        [[0], np.cumsum([x.shape[0] for x in xs])])[:-1]
    for s in range(nseq):
        total = 0
        for k, offs in enumerate(all_offs):
            rows = np.arange(offs[s], offs[s + 1]) + starts[k]
            idx_parts.append(rows)
            total += len(rows)
        new_lens.append(total)
    big = jnp.concatenate([jnp.asarray(x) for x in xs], axis=0)
    idx = np.concatenate(idx_parts)
    o = jnp.take(big, jnp.asarray(idx), axis=0)
    return {"Out": [o],
            "_lod": {"Out": [(_offsets_from_lens(np.asarray(new_lens)),)]}}


# --------------------------------------------------------------------------
# sequence_conv (reference: sequence_ops/sequence_conv_op.cc — context
# window projection; im2col across sequence boundaries is masked to zero)
# --------------------------------------------------------------------------
@register_op("sequence_conv", needs_lod=True, diff_inputs=["X", "Filter"],
             attr_defaults={"contextLength": 3, "contextStart": -1,
                            "contextStride": 1})
def _sequence_conv(ins, attrs):
    x = first(ins, "X")
    filt = first(ins, "Filter")  # [contextLength * D, out_D]
    levels = _require_lod(attrs, "X", "sequence_conv")
    offs = _offs(levels)
    clen = int(attrs.get("contextLength", 3))
    cstart = int(attrs.get("contextStart", -1))
    T, D = x.shape[0], x.shape[1]
    segs = _seg_ids(offs)
    seg_start = offs[:-1][segs] if T else np.zeros(0, np.int64)
    seg_end = offs[1:][segs] if T else np.zeros(0, np.int64)
    t = np.arange(T)
    cols = []
    masks = []
    for k in range(clen):
        src = t + cstart + k
        valid = (src >= seg_start) & (src < seg_end)
        cols.append(np.where(valid, src, 0))
        masks.append(valid)
    idx = np.stack(cols, 1)           # [T, clen]
    mask = np.stack(masks, 1)         # [T, clen]
    patches = jnp.take(x, jnp.asarray(idx), axis=0)  # [T, clen, D]
    patches = patches * jnp.asarray(mask[..., None], x.dtype)
    o = patches.reshape(T, clen * D) @ filt
    return {"Out": [o], "_lod": {"Out": [levels]}}


# --------------------------------------------------------------------------
# sequence_pad / sequence_unpad
# (reference: sequence_ops/sequence_pad_op.cc, sequence_unpad_op.cc)
# --------------------------------------------------------------------------
@register_op("sequence_pad", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"padded_length": -1})
def _sequence_pad(ins, attrs):
    x = first(ins, "X")
    pad_value = first(ins, "PadValue")
    levels = _require_lod(attrs, "X", "sequence_pad")
    offs = _offs(levels)
    lens = _lens(offs)
    n = len(lens)
    plen = int(attrs.get("padded_length", -1))
    maxlen = int(lens.max()) if n else 0
    if plen < 0:
        plen = maxlen
    if plen < maxlen:
        raise ValueError("sequence_pad: padded_length < longest sequence")
    pos = np.arange(plen)[None, :] + offs[:-1, None]     # [n, plen]
    valid = np.arange(plen)[None, :] < lens[:, None]
    idx = np.where(valid, pos, 0)
    o = jnp.take(x, jnp.asarray(idx), axis=0)            # [n, plen, ...]
    pv = jnp.asarray(pad_value, x.dtype)
    o = jnp.where(jnp.asarray(valid).reshape(valid.shape + (1,) * (x.ndim - 1)),
                  o, pv)
    # Length also carries X's LoD as metadata so a downstream sequence_unpad
    # can recover host-static lengths under jit (its Length *array* is a
    # tracer there)
    # device ints are 32-bit by policy; the executor fetch boundary widens
    # Length back to the declared int64 (reference sequence_pad_op.cc)
    return {"Out": [o], "Length": [jnp.asarray(lens, jnp.int32)],
            "_lod": {"Out": [None], "Length": [levels]}}


def _unpad_lens(ins, attrs):
    """Sequence lengths for unpad: prefer the LoD metadata sequence_pad
    attached to Length (host-static under jit); fall back to the concrete
    Length array in eager mode."""
    lv = _lod_of(attrs, "Length")
    if lv is not None:
        return _lens(_offs(lv))
    return np.asarray(first(ins, "Length"), np.int64)


def _unpad_indices(lens):
    rows = [np.stack([np.full(int(L), i), np.arange(int(L))], 1)
            for i, L in enumerate(lens)]
    return np.concatenate(rows) if rows else np.zeros((0, 2), np.int64)


@register_op("sequence_unpad", needs_lod=True, diff_inputs=["X"],
             host_inputs=("Length",))
def _sequence_unpad(ins, attrs):
    x = first(ins, "X")          # [n, plen, ...]
    lens = _unpad_lens(ins, attrs)
    rc = _unpad_indices(lens)
    o = x[jnp.asarray(rc[:, 0]), jnp.asarray(rc[:, 1])]
    return {"Out": [o], "_lod": {"Out": [(_offsets_from_lens(lens),)]}}


@register_grad_maker("sequence_unpad")
def _sequence_unpad_grad_maker(op, grad_map):
    return [{
        "type": "sequence_unpad_grad",
        "inputs": {"X": op.input("X"), "Length": op.input("Length"),
                   "Out@GRAD": [grad_map[op.output("Out")[0]]]},
        "outputs": {"X@GRAD": [grad_map[op.input("X")[0]]]},
        "attrs": {},
    }]


@register_op("sequence_unpad_grad", no_grad=True, needs_lod=True,
             host_inputs=("Length",))
def _sequence_unpad_grad(ins, attrs):
    x = first(ins, "X")
    g = first(ins, "Out@GRAD")
    rc = _unpad_indices(_unpad_lens(ins, attrs))
    gx = jnp.zeros_like(x).at[jnp.asarray(rc[:, 0]),
                              jnp.asarray(rc[:, 1])].set(g)
    return {"X@GRAD": [gx]}


# --------------------------------------------------------------------------
# sequence_reshape / sequence_reverse / sequence_slice / sequence_scatter
# --------------------------------------------------------------------------
@register_op("sequence_reshape", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"new_dim": 1})
def _sequence_reshape(ins, attrs):
    x = first(ins, "X")
    levels = _require_lod(attrs, "X", "sequence_reshape")
    offs = _offs(levels)
    new_dim = int(attrs["new_dim"])
    D = x.shape[1]
    new_offs = offs * D // new_dim
    if np.any((offs * D) % new_dim):
        raise ValueError("sequence_reshape: sequence byte length not "
                         "divisible by new_dim")
    o = x.reshape(-1, new_dim)
    return {"Out": [o],
            "_lod": {"Out": [(tuple(int(v) for v in new_offs),)]}}


@register_op("sequence_reverse", needs_lod=True, diff_inputs=["X"])
def _sequence_reverse(ins, attrs):
    x = first(ins, "X")
    levels = _require_lod(attrs, "X", "sequence_reverse")
    offs = _offs(levels)
    idx = np.concatenate(
        [np.arange(offs[i + 1] - 1, offs[i] - 1, -1)
         for i in range(len(offs) - 1)]
    ) if len(offs) > 1 else np.zeros(0, np.int64)
    o = jnp.take(x, jnp.asarray(idx), axis=0)
    return {"Y": [o], "_lod": {"Y": [levels]}}


def _slice_indices(ins, attrs, op_name):
    """Row indices selected per sequence by the Offset/Length inputs.
    Offset/Length are data — these ops are ``stateful`` (eager-only, like
    the reference's host-side LoD handling) because output extent is
    data-dependent."""
    offset = np.asarray(first(ins, "Offset"), np.int64).reshape(-1)
    length = np.asarray(first(ins, "Length"), np.int64).reshape(-1)
    offs = _offs(_require_lod(attrs, "X", op_name))
    idx_parts, new_lens = [], []
    for i in range(len(offs) - 1):
        s = offs[i] + offset[i]
        idx_parts.append(np.arange(s, s + length[i]))
        new_lens.append(int(length[i]))
    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    return idx, np.asarray(new_lens)


@register_op("sequence_slice", needs_lod=True, stateful=True,
             diff_inputs=["X"])
def _sequence_slice(ins, attrs):
    x = first(ins, "X")
    idx, new_lens = _slice_indices(ins, attrs, "sequence_slice")
    o = jnp.take(x, jnp.asarray(idx), axis=0)
    return {"Out": [o],
            "_lod": {"Out": [(_offsets_from_lens(new_lens),)]}}


@register_grad_maker("sequence_slice")
def _sequence_slice_grad_maker(op, grad_map):
    return [{
        "type": "sequence_slice_grad",
        "inputs": {"X": op.input("X"), "Offset": op.input("Offset"),
                   "Length": op.input("Length"),
                   "Out@GRAD": [grad_map[op.output("Out")[0]]]},
        "outputs": {"X@GRAD": [grad_map[op.input("X")[0]]]},
        "attrs": {},
    }]


@register_op("sequence_slice_grad", no_grad=True, needs_lod=True,
             stateful=True)
def _sequence_slice_grad(ins, attrs):
    x = first(ins, "X")
    g = first(ins, "Out@GRAD")
    idx, _ = _slice_indices(ins, attrs, "sequence_slice_grad")
    gx = jnp.zeros_like(x).at[jnp.asarray(idx)].set(g)
    return {"X@GRAD": [gx]}


@register_op("sequence_scatter", needs_lod=True,
             diff_inputs=["X", "Updates"])
def _sequence_scatter(ins, attrs):
    x = first(ins, "X")          # [n, d]
    ids = first(ins, "Ids")      # packed [total, 1] int
    upd = first(ins, "Updates")  # packed [total, 1]
    levels = _require_lod(attrs, "Ids", "sequence_scatter")
    offs = _offs(levels)
    rows = jnp.asarray(_seg_ids(offs))
    cols = ids.reshape(-1)
    o = x.at[rows, cols].add(upd.reshape(-1))
    return out(Out=o)


# --------------------------------------------------------------------------
# sequence_enumerate / sequence_erase / sequence_mask already exists
# --------------------------------------------------------------------------
@register_op("sequence_enumerate", needs_lod=True, no_grad=True,
             attr_defaults={"win_size": 1, "pad_value": 0})
def _sequence_enumerate(ins, attrs):
    x = first(ins, "X")
    levels = _require_lod(attrs, "X", "sequence_enumerate")
    offs = _offs(levels)
    win = int(attrs.get("win_size", 1))
    pad = attrs.get("pad_value", 0)
    T = x.shape[0]
    segs = _seg_ids(offs)
    seg_end = offs[1:][segs] if T else np.zeros(0, np.int64)
    t = np.arange(T)
    idx, mask = [], []
    for k in range(win):
        src = t + k
        valid = src < seg_end
        idx.append(np.where(valid, src, 0))
        mask.append(valid)
    idx = np.stack(idx, 1)
    mask = np.stack(mask, 1)
    vals = jnp.take(x.reshape(-1), jnp.asarray(idx.astype(np.int32)), axis=0)
    o = jnp.where(jnp.asarray(mask), vals,
                  jnp.asarray(pad, vals.dtype))  # vals carries the
    # canonical device dtype (int64 feeds land as int32 by policy)
    return {"Out": [o], "_lod": {"Out": [levels]}}


@register_op("sequence_erase", needs_lod=True, no_grad=True, stateful=True,
             attr_defaults={"tokens": []})
def _sequence_erase(ins, attrs):
    x = np.asarray(first(ins, "X"))  # host op: output size is data-dependent
    levels = _require_lod(attrs, "X", "sequence_erase")
    offs = _offs(levels)
    tokens = set(attrs.get("tokens", []))
    keep = ~np.isin(x.reshape(-1), list(tokens))
    new_lens = [int(keep[offs[i]:offs[i + 1]].sum())
                for i in range(len(offs) - 1)]
    o = jnp.asarray(x.reshape(-1)[keep].reshape(-1, *x.shape[1:]))
    return {"Out": [o],
            "_lod": {"Out": [(_offsets_from_lens(np.asarray(new_lens)),)]}}


# --------------------------------------------------------------------------
# lod_reset / lod_append (reference: lod_reset_op.cc, lod_append_op.cc)
# --------------------------------------------------------------------------
@register_op("lod_reset", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"target_lod": []})
def _lod_reset(ins, attrs):
    x = first(ins, "X")
    y = first(ins, "Y")
    if y is not None:
        y_levels = _lod_of(attrs, "Y")
        if y_levels is not None:
            new = y_levels
        else:  # Y holds offsets as data
            new = (tuple(int(v) for v in np.asarray(y).reshape(-1)),)
    else:
        tl = attrs.get("target_lod") or []
        new = (tuple(int(v) for v in tl),)
    return {"Out": [x], "_lod": {"Out": [new]}}


@register_op("lod_append", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"level": []})
def _lod_append(ins, attrs):
    x = first(ins, "X")
    cur = _lod_of(attrs, "X") or ()
    lvl = tuple(int(v) for v in attrs.get("level", []))
    return {"Out": [x], "_lod": {"Out": [tuple(cur) + (lvl,)]}}


# --------------------------------------------------------------------------
# im2sequence (reference: im2sequence_op.cc — image patches to sequence)
# --------------------------------------------------------------------------
@register_op("im2sequence", needs_lod=True, diff_inputs=["X"],
             attr_defaults={"kernels": [1, 1], "strides": [1, 1],
                            "paddings": [0, 0, 0, 0]})
def _im2sequence(ins, attrs):
    x = first(ins, "X")  # NCHW
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    pu, pl, pd, pr = attrs.get("paddings", [0, 0, 0, 0])
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(pu, pd), (pl, pr)])
    N, CKK, OH, OW = patches.shape
    o = patches.transpose(0, 2, 3, 1).reshape(N * OH * OW, CKK)
    lod = (_offsets_from_lens(np.full(N, OH * OW)),)
    return {"Out": [o], "_lod": {"Out": [lod]}}
