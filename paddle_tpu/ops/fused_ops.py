"""Fused-op family (reference: paddle/fluid/operators/fused/ and the
fusion_* CPU ops, plus fc_op.cc and conv2d_fusion_op.cc).

TPU inversion: the reference hand-fuses these for CUDA/CPU performance;
under XLA the composition below compiles into the same fused kernels
automatically, so each op here is a plain composition of primitives with
the reference's slot/attr contract. CUDA-codegen-only ops (fusion_group)
raise with an explanation — the pass that emits them never runs on TPU."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, first, seq, out


def _act(name, x, alpha=0.0):
    if name in (None, "", "identity", "linear"):
        return x
    if name == "relu":
        return jnp.maximum(x, 0)
    if name == "sigmoid":
        return jax.nn.sigmoid(x)
    if name == "tanh":
        return jnp.tanh(x)
    if name == "gelu":
        return jax.nn.gelu(x)
    if name == "leaky_relu":
        return jnp.where(x > 0, x, alpha * x)
    if name == "relu6":
        return jnp.clip(x, 0, 6)
    if name == "swish":
        return x * jax.nn.sigmoid(x)
    raise NotImplementedError(f"activation '{name}' in fused op")


# --------------------------------------------------------------------------
# fc — the standalone fc op (reference fc_op.cc; layers emit mul+add, the
# fc_fuse_pass and serialized inference programs emit this)
# --------------------------------------------------------------------------
@register_op("fc", inputs=("Input", "W", "Bias"),
             diff_inputs=("Input", "W", "Bias"),
             attr_defaults={"in_num_col_dims": 1,
                            "activation_type": "",
                            "use_mkldnn": False})
def _fc(ins, attrs):
    x, w = first(ins, "Input"), first(ins, "W")
    nd = int(attrs.get("in_num_col_dims", 1))
    lead = x.shape[:nd]
    xf = x.reshape((int(np.prod(lead)), -1))
    o = xf @ w
    b = first(ins, "Bias")
    if b is not None:
        o = o + b.reshape(1, -1)
    o = _act(attrs.get("activation_type", ""), o)
    return out(Out=o.reshape(lead + (w.shape[1],)))


# --------------------------------------------------------------------------
# fused elementwise + activation (reference fused_elemwise_activation_op)
# --------------------------------------------------------------------------
_BINARY = {"elementwise_add": jnp.add, "elementwise_sub": jnp.subtract,
           "elementwise_mul": jnp.multiply}


@register_op("fused_elemwise_activation", inputs=("X", "Y"),
             diff_inputs=("X", "Y"),
             attr_defaults={"functor_list": [], "axis": -1, "scale": 0.0,
                            "save_intermediate_out": False})
def _fused_elemwise_activation(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    fl = list(attrs.get("functor_list") or [])
    assert len(fl) == 2, "functor_list must hold [binary, unary] (either order)"

    def apply1(name, v):
        if name.startswith("scale"):
            return v * attrs.get("scale", 1.0)
        return _act(name, v)
    axis = attrs.get("axis", -1)
    yb = y
    if y.ndim < x.ndim:
        ax = axis if axis >= 0 else x.ndim - y.ndim
        shape = [1] * x.ndim
        for i, s in enumerate(y.shape):
            shape[ax + i] = s
        yb = y.reshape(shape)
    if fl[0] in _BINARY:                       # binary(x, unary(y))
        o = _BINARY[fl[0]](x, apply1(fl[1], yb))
        inter = apply1(fl[1], yb)
    else:                                      # unary(binary(x, y))
        inter = _BINARY[fl[1]](x, yb)
        o = apply1(fl[0], inter)
    return out(Out=o, IntermediateOut=inter)


@register_op("fused_batch_norm_act",
             inputs=("X", "Scale", "Bias", "Mean", "Variance"),
             diff_inputs=("X", "Scale", "Bias"), stateful=True,
             attr_defaults={"momentum": 0.9, "epsilon": 1e-5,
                            "act_type": "relu", "is_test": False,
                            "data_layout": "NCHW",
                            "use_global_stats": False})
def _fused_batch_norm_act(ins, attrs):
    from .nn_ops import _batch_norm
    r = _batch_norm(ins, attrs)
    y = r["Y"][0] if isinstance(r["Y"], list) else r["Y"]
    r["Y"] = [_act(attrs.get("act_type", "relu"), y)]
    return r


# --------------------------------------------------------------------------
# embedding fusions
# --------------------------------------------------------------------------
@register_op("fused_embedding_eltwise_layernorm",
             inputs=("Ids", "Embs", "Bias", "Scale"),
             diff_inputs=("Embs", "Bias", "Scale"),
             attr_defaults={"epsilon": 1e-5})
def _fused_embedding_eltwise_layernorm(ins, attrs):
    ids_list, emb_list = seq(ins, "Ids"), seq(ins, "Embs")
    acc = None
    for ids, emb in zip(ids_list, emb_list):
        idv = ids.reshape(ids.shape[0], -1)[:, :]  # [N, T] or [N,T,1]
        if ids.ndim == 3:
            idv = ids[..., 0]
        v = emb[idv]
        acc = v if acc is None else acc + v
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(acc, -1, keepdims=True)
    var = jnp.var(acc, -1, keepdims=True)
    o = (acc - mu) / jnp.sqrt(var + eps)
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    if scale is not None:
        o = o * scale
    if bias is not None:
        o = o + bias
    return out(Out=o)


@register_op("fused_embedding_seq_pool", inputs=("W", "Ids"),
             diff_inputs=("W",), needs_lod=True,
             attr_defaults={"combiner": "sum", "is_sparse": False})
def _fused_embedding_seq_pool(ins, attrs):
    w, ids = first(ins, "W"), first(ins, "Ids")
    lods = (attrs.get("_lod") or {}).get("Ids")
    offs = (np.asarray(lods[0][-1], np.int64) if lods and lods[0]
            else np.asarray([0, ids.shape[0]], np.int64))
    flat = ids.reshape(-1)
    emb = w[flat]                      # [T, D]
    segs = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
    o = jax.ops.segment_sum(emb, jnp.asarray(segs),
                            num_segments=len(offs) - 1)
    return out(Out=o)


@register_op("fused_fc_elementwise_layernorm",
             inputs=("X", "W", "Bias0", "Y", "Scale", "Bias1"),
             diff_inputs=("X", "W", "Bias0", "Y", "Scale", "Bias1"),
             attr_defaults={"epsilon": 1e-5, "begin_norm_axis": 1,
                            "activation_type": "", "x_num_col_dims": 1})
def _fused_fc_elementwise_layernorm(ins, attrs):
    x, w = first(ins, "X"), first(ins, "W")
    nd = int(attrs.get("x_num_col_dims", 1))
    lead = x.shape[:nd]
    o = x.reshape((int(np.prod(lead)), -1)) @ w
    b0 = first(ins, "Bias0")
    if b0 is not None:
        o = o + b0.reshape(1, -1)
    o = o.reshape(lead + (w.shape[1],))
    y = first(ins, "Y")
    o = o + y
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(o, -1, keepdims=True)
    var = jnp.var(o, -1, keepdims=True)
    o = (o - mu) / jnp.sqrt(var + eps)
    scale, b1 = first(ins, "Scale"), first(ins, "Bias1")
    if scale is not None:
        o = o * scale
    if b1 is not None:
        o = o + b1
    return out(Out=o)


@register_op("skip_layernorm", inputs=("X", "Y", "Scale", "Bias"),
             diff_inputs=("X", "Y", "Scale", "Bias"),
             attr_defaults={"epsilon": 1e-5, "begin_norm_axis": -1})
def _skip_layernorm(ins, attrs):
    """Residual add fused into layer_norm: Out = LN(X + Y) (the op the
    reference's skip_layernorm_fuse_pass targets for transformer
    inference)."""
    x, y = first(ins, "X"), first(ins, "Y")
    o = x + y
    eps = attrs.get("epsilon", 1e-5)
    bna = int(attrs.get("begin_norm_axis", -1))
    if bna < 0:
        bna = o.ndim - 1
    axes = tuple(range(bna, o.ndim))
    # statistics in f32 like the unfused layer_norm, so fusing never
    # degrades bf16 numerics
    of = o.astype(jnp.float32)
    mu = jnp.mean(of, axes, keepdims=True)
    var = jnp.var(of, axes, keepdims=True)
    o = ((of - mu) * jax.lax.rsqrt(var + eps)).astype(o.dtype)
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    if scale is not None:
        o = o * scale.reshape((1,) * bna + scale.shape)
    if bias is not None:
        o = o + bias.reshape((1,) * bna + bias.shape)
    return out(Out=o)


# --------------------------------------------------------------------------
# fused recurrent (fusion_gru / fusion_lstm: x-projection folded in)
# --------------------------------------------------------------------------
@register_op("fusion_gru", needs_lod=True,
             inputs=("X", "WeightX", "WeightH", "Bias", "H0"),
             diff_inputs=("X", "WeightX", "WeightH", "Bias", "H0"),
             attr_defaults={"is_reverse": False, "origin_mode": False,
                            "use_seq": True, "activation": "tanh",
                            "gate_activation": "sigmoid"})
def _fusion_gru(ins, attrs):
    from .rnn_ops import _dynamic_gru
    x, wx = first(ins, "X"), first(ins, "WeightX")
    xx = x @ wx
    ins2 = dict(ins)
    ins2["Input"] = [xx]
    ins2["Weight"] = ins.get("WeightH")
    lod = dict(attrs.get("_lod") or {})
    lod["Input"] = lod.get("X")
    r = _dynamic_gru(ins2, {**attrs, "_lod": lod})
    xlod = (lod.get("X") or [None])[0]
    return {"Hidden": r["Hidden"], "XX": [xx],
            "_lod": {"Hidden": [xlod], "XX": [xlod]}}


@register_op("fusion_lstm", needs_lod=True,
             inputs=("X", "WeightX", "WeightH", "Bias", "H0", "C0"),
             diff_inputs=("X", "WeightX", "WeightH", "Bias", "H0", "C0"),
             attr_defaults={"use_peepholes": False, "is_reverse": False,
                            "gate_activation": "sigmoid",
                            "cell_activation": "tanh",
                            "candidate_activation": "tanh"})
def _fusion_lstm(ins, attrs):
    from .rnn_ops import _dyn_lstm_common
    x, wx = first(ins, "X"), first(ins, "WeightX")
    xx = x @ wx
    ins2 = dict(ins)
    ins2["Input"] = [xx]
    ins2["Weight"] = ins.get("WeightH")
    lod = dict(attrs.get("_lod") or {})
    lod["Input"] = lod.get("X")
    h, c = _dyn_lstm_common(ins2, {**attrs, "_lod": lod})
    xlod = (lod.get("X") or [None])[0]
    return {"Hidden": [h], "Cell": [c], "XX": [xx],
            "_lod": {"Hidden": [xlod], "Cell": [xlod], "XX": [xlod]}}


# --------------------------------------------------------------------------
# misc CPU fusions as compositions
# --------------------------------------------------------------------------
@register_op("fusion_repeated_fc_relu", inputs=("X", "W", "Bias"),
             diff_inputs=("X", "W", "Bias"))
def _fusion_repeated_fc_relu(ins, attrs):
    x = first(ins, "X")
    ws, bs = seq(ins, "W"), seq(ins, "Bias")
    h = x
    relu_outs = []
    for i, (w, b) in enumerate(zip(ws, bs)):
        h = h @ w + b.reshape(1, -1)
        h = jnp.maximum(h, 0)
        relu_outs.append(h)
    return {"Out": [h], "ReluOut": relu_outs[:-1]}


@register_op("fusion_seqconv_eltadd_relu", needs_lod=True,
             inputs=("X", "Filter", "Bias"),
             diff_inputs=("X", "Filter", "Bias"),
             attr_defaults={"contextLength": 3, "contextStart": -1,
                            "contextStride": 1})
def _fusion_seqconv_eltadd_relu(ins, attrs):
    from .sequence_ops import _sequence_conv
    r = _sequence_conv(ins, attrs)
    o = r["Out"][0] if isinstance(r["Out"], list) else r["Out"]
    b = first(ins, "Bias")
    o = jnp.maximum(o + b.reshape(1, -1), 0)
    colmat = jnp.zeros((o.shape[0], 1), o.dtype)
    return {"Out": [o], "ColMat": [colmat],
            **({"_lod": r["_lod"]} if "_lod" in r else {})}


@register_op("fusion_seqpool_concat", needs_lod=True, inputs=("X",),
             attr_defaults={"pooltype": "SUM", "axis": 1})
def _fusion_seqpool_concat(ins, attrs):
    pools = []
    lods = (attrs.get("_lod") or {}).get("X") or []
    for i, x in enumerate(seq(ins, "X")):
        lod = lods[i] if i < len(lods) else None
        offs = (np.asarray(lod[-1], np.int64) if lod
                else np.asarray([0, x.shape[0]], np.int64))
        segs = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
        s = jax.ops.segment_sum(x, jnp.asarray(segs),
                                num_segments=len(offs) - 1)
        if attrs.get("pooltype", "SUM") == "AVERAGE":
            cnt = jnp.asarray(offs[1:] - offs[:-1], x.dtype)[:, None]
            s = s / jnp.maximum(cnt, 1)
        pools.append(s)
    return out(Out=jnp.concatenate(pools, axis=attrs.get("axis", 1)))


@register_op("fusion_seqpool_cvm_concat", needs_lod=True,
             inputs=("X", "CVM"),
             attr_defaults={"pooltype": "SUM", "use_cvm": True, "axis": 1})
def _fusion_seqpool_cvm_concat(ins, attrs):
    """seqpool each input, apply the CVM transform per pooled segment
    (log1p of the show/click columns when use_cvm, else drop them —
    reference fusion_seqpool_cvm_concat_op.cc), then concat."""
    lods = (attrs.get("_lod") or {}).get("X") or []
    pieces = []
    for i, x in enumerate(seq(ins, "X")):
        lod = lods[i] if i < len(lods) else None
        offs = (np.asarray(lod[-1], np.int64) if lod
                else np.asarray([0, x.shape[0]], np.int64))
        segs = np.repeat(np.arange(len(offs) - 1), offs[1:] - offs[:-1])
        s = jax.ops.segment_sum(x, jnp.asarray(segs),
                                num_segments=len(offs) - 1)
        if attrs.get("pooltype", "SUM") == "AVERAGE":
            cnt = jnp.asarray(offs[1:] - offs[:-1], x.dtype)[:, None]
            s = s / jnp.maximum(cnt, 1)
        if attrs.get("use_cvm", True):
            show_clk = jnp.log(jnp.maximum(s[:, :2], 0.0) + 1.0)
            s = jnp.concatenate([show_clk, s[:, 2:]], axis=1)
        else:
            s = s[:, 2:]
        pieces.append(s)
    return out(Out=jnp.concatenate(pieces, axis=attrs.get("axis", 1)))


@register_op("fusion_squared_mat_sub", inputs=("X", "Y"),
             diff_inputs=("X", "Y"), attr_defaults={"scalar": 1.0})
def _fusion_squared_mat_sub(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    xy = x @ y
    sq = (x * x) @ (y * y)
    s = attrs.get("scalar", 1.0)
    return {"Out": [s * (xy * xy - sq)], "SquaredX": [x * x],
            "SquaredY": [y * y], "SquaredXY": [xy * xy]}


@register_op("fusion_transpose_flatten_concat", inputs=("X",),
             attr_defaults={"trans_axis": [0, 1, 2, 3], "flatten_axis": 1,
                            "concat_axis": 1})
def _fusion_transpose_flatten_concat(ins, attrs):
    ta = [int(a) for a in attrs.get("trans_axis")]
    fa = int(attrs.get("flatten_axis", 1))
    ca = int(attrs.get("concat_axis", 1))
    pieces = []
    for x in seq(ins, "X"):
        t = jnp.transpose(x, ta)
        pieces.append(t.reshape(int(np.prod(t.shape[:fa])), -1))
    return out(Out=jnp.concatenate(pieces, axis=ca))


@register_op("fusion_seqexpand_concat_fc", needs_lod=True,
             inputs=("X", "FCWeight", "FCBias"),
             diff_inputs=("FCWeight", "FCBias"),
             attr_defaults={"fc_activation": "identity"})
def _fusion_seqexpand_concat_fc(ins, attrs):
    """First X input carries LoD [T, D0]; the rest are per-sequence rows
    [N, Di] broadcast (seq_expand) to each timestep, all concat'd then
    passed through one fc (reference fusion_seqexpand_concat_fc_op.cc)."""
    xs = seq(ins, "X")
    lods = (attrs.get("_lod") or {}).get("X") or []
    lod0 = lods[0] if lods else None
    offs = (np.asarray(lod0[-1], np.int64) if lod0
            else np.asarray([0, xs[0].shape[0]], np.int64))
    reps = offs[1:] - offs[:-1]
    row_of = jnp.asarray(np.repeat(np.arange(len(reps)), reps))
    cols = [xs[0]] + [jnp.take(x, row_of, axis=0) for x in xs[1:]]
    cat = jnp.concatenate(cols, axis=1)
    w, b = first(ins, "FCWeight"), first(ins, "FCBias")
    o = cat @ w
    if b is not None:
        o = o + b.reshape(1, -1)
    o = _act(attrs.get("fc_activation", "identity"), o)
    lodout = {"Out": [lod0]} if lod0 else {}
    return {"Out": [o], "FCOut": [o], **({"_lod": lodout} if lodout else {})}


@register_op("conv2d_fusion",
             inputs=("Input", "Filter", "Bias", "ResidualData"),
             diff_inputs=("Input", "Filter", "Bias"),
             attr_defaults={"strides": [1, 1], "paddings": [0, 0],
                            "dilations": [1, 1], "groups": 1,
                            "activation": "relu",
                            "padding_algorithm": "EXPLICIT",
                            "data_format": "NCHW", "use_cudnn": True})
def _conv2d_fusion(ins, attrs):
    from .nn_ops import _conv2d
    r = _conv2d(ins, attrs)
    o = r["Output"][0] if isinstance(r["Output"], list) else r["Output"]
    res = first(ins, "ResidualData")
    if res is not None:
        o = o + res
    return out(Output=_act(attrs.get("activation", "relu"), o))


def _cuda_codegen_stub(name, why):
    @register_op(name, no_grad=True)
    def _stub(ins, attrs):
        raise NotImplementedError(
            f"{name}: {why} On TPU the equivalent fusion happens inside "
            "XLA, and the IR pass that emits this op is never enabled.")
    return _stub


# pass-emitted CUDA/x86-codegen fusions with no TPU execution path:
_cuda_codegen_stub("fusion_group",
                   "runtime-compiled CUDA elementwise group "
                   "(ir/fusion_group/code_generator.cc).")
_cuda_codegen_stub("conv2d_inception_fusion",
                   "cuDNN-specific 4-branch inception kernel.")
_cuda_codegen_stub("attention_lstm",
                   "x86-JIT fused attention LSTM (attention_lstm_op.cc); "
                   "use the attention layers + dynamic_lstm composition.")
_cuda_codegen_stub("fused_embedding_fc_lstm",
                   "x86 fused embedding+fc+lstm (fused_embedding_fc_lstm_"
                   "op.cc); compose lookup_table + fusion_lstm instead.")
