"""paddle.device 2.0-preview (reference: python/paddle/device.py —
set_device/get_device/is_compiled_with_*)."""
from __future__ import annotations

from .fluid import core

__all__ = ["set_device", "get_device", "is_compiled_with_cuda",
           "is_compiled_with_tpu", "TPUPlace", "CPUPlace"]

from .fluid.core import TPUPlace, CPUPlace

# Resolved lazily on first use: probing the backend at import time would
# make `import paddle_tpu` hang/die whenever the TPU tunnel is broken.
_current = None
_current_idx = 0


def _default_device() -> str:
    global _current
    if _current is None:
        _current = "tpu" if core.is_compiled_with_tpu() else "cpu"
    return _current


def set_device(device: str):
    """'tpu', 'tpu:0', 'cpu' (reference accepts 'gpu:N')."""
    global _current, _current_idx
    kind = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if kind in ("tpu", "gpu", "cuda"):
        if not core.is_compiled_with_tpu():
            raise RuntimeError("no TPU backend available")
        _current, _current_idx = "tpu", idx
        return TPUPlace(idx)
    if kind == "cpu":
        _current, _current_idx = "cpu", 0
        return CPUPlace()
    raise ValueError(f"unknown device {device!r}")


def get_device() -> str:
    cur = _default_device()
    return cur + (f":{_current_idx}" if cur != "cpu" else "")


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return core.is_compiled_with_tpu()
