"""paddle_tpu.distributed — launcher (reference: python/paddle/distributed/)."""
