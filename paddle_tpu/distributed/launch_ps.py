"""Local parameter-server bringup — spawns pserver + trainer processes on
one host (reference: python/paddle/distributed/launch_ps.py; cloud_utils).

    python -m paddle_tpu.distributed.launch_ps \
        --worker_num 2 --server_num 2 train.py [args...]

Each child gets the PADDLE_* env contract the fleet role makers read
(reference role_maker.py PaddleCloudRoleMaker:442): TRAINING_ROLE,
PADDLE_PORT/PADDLE_PSERVERS_IP_PORT_LIST for servers,
PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM for workers."""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def launch_ps():
    parser = argparse.ArgumentParser("launch_ps")
    parser.add_argument("--worker_num", type=int, default=2)
    parser.add_argument("--server_num", type=int, default=2)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    ports = _free_ports(args.server_num)
    server_eps = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = []
    base_env = dict(os.environ,
                    PADDLE_PSERVERS_IP_PORT_LIST=server_eps,
                    PADDLE_TRAINERS_NUM=str(args.worker_num))
    cmd = [sys.executable, args.training_script] + args.training_script_args
    for i, p in enumerate(ports):
        env = dict(base_env, TRAINING_ROLE="PSERVER", PADDLE_PORT=str(p),
                   POD_IP="127.0.0.1", PADDLE_TRAINER_ID=str(i))
        procs.append(subprocess.Popen(cmd, env=env))
    for i in range(args.worker_num):
        env = dict(base_env, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i))
        procs.append(subprocess.Popen(cmd, env=env))
    # watch like launch.py: poll ALL children so a crash in any trainer
    # tears the pod down even while its peers block in a barrier
    # (reference launch.py:219 watch loop)
    import time
    trainers = procs[args.server_num:]
    rc = 0
    try:
        while True:
            codes = [p.poll() for p in trainers]
            if any(c not in (None, 0) for c in codes):
                rc = next(c for c in codes if c not in (None, 0))
                break
            if all(c == 0 for c in codes):
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(rc)


if __name__ == "__main__":
    launch_ps()
