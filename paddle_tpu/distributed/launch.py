"""Multi-process launcher (reference: python/paddle/distributed/launch.py:193
— builds the cluster from args/PaddleCloud env, spawns one worker per
device group with PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT set, watches children).

TPU inversion: ONE process per host (jax owns all local chips); multi-host
scale-out sets one worker per host and jax.distributed handles DCN. Usage:
    python -m paddle_tpu.distributed.launch --ips=h1,h2 train.py ...
Local multi-process testing (CPU devices):
    python -m paddle_tpu.distributed.launch --nproc=2 --devices_per_proc=4 train.py
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", default="127.0.0.1",
                   help="comma-separated host list (one worker per host)")
    p.add_argument("--nproc", type=int, default=None,
                   help="local processes to spawn (testing on CPU)")
    p.add_argument("--devices_per_proc", type=int, default=1)
    p.add_argument("--start_port", type=int, default=6170)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _local_addrs():
    import socket
    names = {"127.0.0.1", "localhost", socket.gethostname()}
    try:
        names.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    return names


def launch():
    args = _parse_args()
    hosts = [h for h in args.ips.split(",") if h]
    nproc = args.nproc if args.nproc is not None else len(hosts)
    endpoints = [f"{hosts[i % len(hosts)]}:{args.start_port + i}"
                 for i in range(nproc)]
    # one worker per host: only spawn the ranks whose endpoint names THIS
    # machine (reference launch.py filters by node IP the same way); local
    # --nproc testing spawns everything.
    local = _local_addrs()
    if len(hosts) > 1:
        ranks = [r for r in range(nproc)
                 if endpoints[r].rsplit(":", 1)[0] in local]
        if not ranks:
            raise SystemExit(
                f"none of --ips={args.ips} matches this host "
                f"({sorted(local)}); run the launcher on each host")
    else:
        ranks = list(range(nproc))
    procs = []
    for rank in ranks:
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        })
        if args.nproc is not None:
            # local testing: carve virtual CPU devices per process
            env.setdefault("JAX_PLATFORMS", "cpu")
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count="
                                + str(args.devices_per_proc))
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        log = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            log = open(os.path.join(args.log_dir,
                                    f"workerlog.{rank}"), "w")
        procs.append((subprocess.Popen(cmd, env=env, stdout=log,
                                       stderr=log), log))
    # watch loop (reference launch.py:219): tear the pod down on failure
    try:
        alive = True
        while alive:
            alive = False
            for proc, _ in procs:
                ret = proc.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    for q, _ in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    sys.exit(ret)
            time.sleep(1)
    finally:
        for _, log in procs:
            if log:
                log.close()


if __name__ == "__main__":
    launch()
