"""paddle.metric 2.0-preview (reference: python/paddle/metric/ — Accuracy,
Auc, Precision, Recall over the fluid metrics implementations)."""
from __future__ import annotations

from .fluid.metrics import (  # noqa: F401
    MetricBase, Accuracy, Auc, Precision, Recall, CompositeMetric,
    ChunkEvaluator, EditDistance)

__all__ = ["MetricBase", "Accuracy", "Auc", "Precision", "Recall",
           "CompositeMetric", "ChunkEvaluator", "EditDistance"]
