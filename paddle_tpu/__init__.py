"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle v1.7 "Fluid" (see SURVEY.md): Program/Block/Op/Var graph IR,
fluid.layers API, Executor, append_backward autodiff, optimizers, DyGraph,
Fleet distributed training — built on JAX/XLA/Pallas/pjit.

Programs compile to single XLA computations per block; parallelism is
sharding over a jax.sharding.Mesh (ICI collectives), not graph rewrites."""

__version__ = "0.1.0"

# jax version compat shims (PR 1 precedent: pltpu.TPUCompilerParams in
# ops/pallas/flash_attention.py, lax.pvary in parallel/pipeline.py).
# `from jax import shard_map` is the modern top-level export; on the
# installed jax 0.4.x it only exists at jax.experimental.shard_map —
# publish it at the top level so code written against either import
# works (same call signature: shard_map(f, mesh=, in_specs=,
# out_specs=)).
import jax as _jax  # noqa: E402

if not hasattr(_jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
        _jax.shard_map = _shard_map
    except ImportError:  # even older jax: leave it absent
        pass
del _jax

from . import ops          # registers the operator set
from . import fluid        # the Fluid-compatible front end
from . import inference    # AnalysisPredictor engine
from . import nn           # 2.0-preview namespaces
from . import tensor
from . import framework
from . import optimizer
from . import metric
from . import device
from . import distribution
from . import incubate
from . import dataset      # offline dataset readers (synthetic fallback)
from . import reader       # reader decorators (map/shuffle/buffered/...)
from . import version
from .batch import batch
from .framework import manual_seed, get_default_dtype, set_default_dtype
# tensor functions at top level (reference paddle/__init__.py re-exports)
from .tensor import *  # noqa: F401,F403

# 2.0-style convenience aliases (reference: python/paddle/__init__.py
# re-exports under torch-like names)
from .fluid import (Program, Executor, CPUPlace, TPUPlace, CUDAPlace,
                    program_guard, default_main_program,
                    default_startup_program, global_scope, scope_guard,
                    ParamAttr)
from .fluid.dygraph import (enable_dygraph, disable_dygraph, grad, no_grad,
                            to_variable)
from .fluid.framework import in_dygraph_mode as in_dynamic_mode


def enable_static():
    """2.0 naming: leave imperative mode (reference paddle.enable_static)."""
    disable_dygraph()


def disable_static():
    """2.0 naming: enter imperative mode (reference
    paddle.disable_static)."""
    enable_dygraph()


def summary(net, input_size=None, dtypes=None):
    """Parameter summary of a dygraph Layer (reference paddle.summary's
    role; prints the per-parameter shapes and the total count)."""
    import builtins
    rows = []
    total = 0
    for name, p in net.named_parameters():
        n = 1
        for s in p.shape:
            n *= int(s)
        total += n
        rows.append((name, tuple(p.shape), n))
    # builtins.max: `from .tensor import *` above shadows max with the
    # tensor reduction at module scope
    width = builtins.max((len(r[0]) for r in rows), default=10) + 2
    print(f"{'Param':<{width}}{'Shape':<20}{'Count':>12}")
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<20}{n:>12}")
    print(f"{'Total params:':<{width + 20}}{total:>12}")
    return {"total_params": total, "trainable_params": total}

__all__ = ["fluid", "ops", "inference", "__version__"]
