"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism (SURVEY.md §5 long-context:
absent in v1.7 — long sequences meant LoDTensor ragged batching,
lod_tensor.h:104, plus a fused attention op for inference,
operators/fused/multihead_matmul_op.cu). This module is the TPU-first
extension that makes long-context training first-class:

* `ring_attention` — blockwise attention with online-softmax accumulation;
  Q stays resident on its sequence shard while K/V blocks rotate around the
  "sp" mesh axis via `lax.ppermute` (one ICI hop per step, compute/comms
  overlap under XLA). Memory per chip is O(S/n · S/n) scores instead of
  O(S·S); max sequence length scales linearly with the ring size.
* `ulysses_attention` — all-to-all sequence parallelism: resharding
  [B,H,S/n,D] → [B,H/n,S,D] with `lax.all_to_all`, local (flash) attention
  over the full sequence on each chip's head slice, then the inverse
  all-to-all. Cheaper comms than the ring when H ≥ n.

Both are pure-JAX differentiable (ppermute/all_to_all transpose to their
inverses, so the backward pass is automatically the reverse ring/reshard)
and run under one `shard_map` over the "sp" axis.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import axis_mesh, shard_map

SEQUENCE_AXIS = "sp"
_NEG = -1e30

__all__ = ["SEQUENCE_AXIS", "sequence_mesh", "ring_attention",
           "ring_attention_local", "ulysses_attention"]


def sequence_mesh(n: int, devices=None) -> Mesh:
    return axis_mesh(n, SEQUENCE_AXIS, devices)


def _block_update(q, k, v, o, m, l, sm_scale, q_off, k_off, causal):
    """One flash/online-softmax accumulation step against a K/V block.

    q [B,H,s,D]; k,v [B,H,c,D]; o accum [B,H,s,D] (fp32);
    m,l running max / normalizer [B,H,s,1] (fp32).
    q_off/k_off: global sequence offsets of this q shard / k block.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, ck = q.shape[2], k.shape[2]
        rows = q_off + lax.broadcasted_iota(jnp.int32, (sq, ck), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (sq, ck), 1)
        mask = rows >= cols
        s = jnp.where(mask, s, _NEG)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if causal:
        p = jnp.where(mask, p, 0.0)
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o = o * alpha + jnp.einsum("bhqk,bhkd->bhqd",
                               p.astype(v.dtype), v).astype(jnp.float32)
    return o, m_new, l


def ring_attention_local(q, k, v, sm_scale=None, causal=False, *,
                         axis: str = SEQUENCE_AXIS, n: int):
    """One device's ring-attention body inside an OPEN shard_map region
    — q,k,v are this device's [B,H,S/n,D] sequence shards, ``axis`` the
    (manual) mesh axis the sequence shards over, ``n`` its size. This
    is the composable form: parallel/lm3d.py nests it inside a GPipe
    stage over the dp×pp×sp mesh with axis="sp". ``n == 1`` degrades to
    plain blockwise attention with no ppermute (so one code path covers
    every composition). ``ring_attention`` below is the standalone
    shard_map wrapper."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    B, H, sq, D = q.shape
    idx = lax.axis_index(axis) if n > 1 else 0
    right = [(i, (i + 1) % n) for i in range(n)]
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((B, H, sq, 1), _NEG, jnp.float32)
    l = jnp.zeros((B, H, sq, 1), jnp.float32)
    k_cur, v_cur = k, v
    for step in range(n):
        src = (idx - step) % n  # owner of the block we hold now
        o, m, l = _block_update(q, k_cur, v_cur, o, m, l, sm_scale,
                                q_off=idx * sq, k_off=src * sq,
                                causal=causal)
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, right)
            v_cur = lax.ppermute(v_cur, axis, right)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, sm_scale=None, causal=False, *, mesh,
                   axis: str = SEQUENCE_AXIS):
    """Attention over a sequence sharded on `axis`. q,k,v: [B,H,S,D] global
    (S = n · S_local). Returns [B,H,S,D] with the same sharding."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    n = mesh.shape[axis]
    seq_spec = P(None, None, axis, None)

    def per_device(q, k, v):
        return ring_attention_local(q, k, v, sm_scale, causal,
                                    axis=axis, n=n)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, sm_scale=None, causal=False, *, mesh,
                      axis: str = SEQUENCE_AXIS, attn_fn=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style). q,k,v:
    [B,H,S,D] sharded on S over `axis`; H must be divisible by the axis
    size. Internally each chip attends over the FULL sequence for H/n heads
    (using `attn_fn`, default the Pallas flash attention), so any local
    attention kernel becomes sequence-parallel for free."""
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    n = mesh.shape[axis]
    H = q.shape[1]
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by sp={n}")
    if attn_fn is None:
        from ..ops.pallas.flash_attention import flash_attention
        attn_fn = flash_attention
    seq_spec = P(None, None, axis, None)

    def per_device(q, k, v):
        # [B, H, s, D] -> [B, H/n, S, D]: split heads, gather sequence
        def fwd(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def inv(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)
        o = attn_fn(fwd(q), fwd(k), fwd(v), sm_scale, causal)
        return inv(o)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(seq_spec, seq_spec, seq_spec),
                   out_specs=seq_spec)
    return fn(q, k, v)
