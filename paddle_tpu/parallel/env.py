"""Distributed environment: mesh registry (ring_id → axis) + PADDLE_* env
contract (reference: launch env in python/paddle/distributed/launch.py:193
and role_maker.py:442)."""
from __future__ import annotations

import os
from typing import Optional

import jax

_mesh = None


def set_mesh(mesh):
    global _mesh
    _mesh = mesh


def get_mesh():
    return _mesh


def world_size() -> int:
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def rank() -> int:
    return int(os.getenv("PADDLE_TRAINER_ID", "0"))


def local_device_count() -> int:
    return len(jax.local_devices())
