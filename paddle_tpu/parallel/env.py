"""Distributed environment: mesh registry (ring_id → axis) + PADDLE_* env
contract (reference: launch env in python/paddle/distributed/launch.py:193
and role_maker.py:442)."""
from __future__ import annotations

import os
from typing import Optional

import jax

_mesh = None


def set_mesh(mesh):
    global _mesh
    _mesh = mesh


def get_mesh():
    return _mesh


def world_size() -> int:
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def rank() -> int:
    return int(os.getenv("PADDLE_TRAINER_ID", "0"))


def local_device_count() -> int:
    return len(jax.local_devices())


_distributed_initialized = False


def init_distributed(coordinator_address: Optional[str] = None) -> bool:
    """Bring up the cross-host runtime from the PADDLE_* env contract
    (reference: the NCCL-id bootstrap c_gen_nccl_id + NCCLCommContext init,
    collective/c_gen_nccl_id_op.cc — here one jax.distributed.initialize
    makes every host's chips visible as one global mesh over ICI/DCN).

    Coordinator: `JAX_COORDINATOR_ADDRESS` env if set, else trainer 0's
    endpoint from PADDLE_TRAINER_ENDPOINTS (free in this build's collective
    mode — no server binds it). Returns True if a multi-host init ran;
    single-process jobs are a no-op."""
    global _distributed_initialized
    n = world_size()
    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # older jax
        already = _distributed_initialized
    if n <= 1 or already:
        return False
    addr = (coordinator_address
            or os.getenv("JAX_COORDINATOR_ADDRESS")
            or os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")[0])
    if not addr:
        raise RuntimeError(
            "init_distributed needs PADDLE_TRAINER_ENDPOINTS or "
            "JAX_COORDINATOR_ADDRESS to locate the coordinator")
    # The CPU backend refuses cross-process computations ("Multiprocess
    # computations aren't implemented on the CPU backend") unless a CPU
    # collectives implementation is selected BEFORE the backend is
    # created; this jaxlib ships gloo, so multi-process CPU meshes (the
    # launch-parity lanes) need it switched on here, not at step time.
    if os.getenv("JAX_PLATFORMS", "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax: flag absent, single-host CPU still works
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=n, process_id=rank())
    _distributed_initialized = True
    return True
