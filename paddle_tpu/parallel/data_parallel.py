"""Data-parallel execution of CompiledProgram.with_data_parallel (reference:
ParallelExecutor path — compiler.py:308, parallel_executor.cc:442).

TPU design: no per-device graph clones or allreduce op-handles. The step
function the executor already traces is run under a 1-axis Mesh ("dp") with
the feed batch sharded on dim 0 and params replicated; the gradient
all-reduces come from XLA sharding propagation over ICI. Single device: a
plain jitted run."""
from __future__ import annotations

import jax

from .mesh import build_mesh


def run_data_parallel(executor, compiled_program, feed, fetch_list, scope,
                      return_numpy, param_shardings=None):
    n = len(jax.devices())
    if n <= 1:
        return executor.run(compiled_program._program, feed=feed,
                            fetch_list=fetch_list, scope=scope,
                            return_numpy=return_numpy,
                            param_shardings=param_shardings)
    mesh = getattr(compiled_program, "_mesh", None)
    if mesh is None:
        places = compiled_program._places
        num = len(places) if places else n
        mesh = build_mesh(num_devices=num)
        compiled_program._mesh = mesh
    return executor.run(compiled_program._program, feed=feed,
                        fetch_list=fetch_list, scope=scope,
                        return_numpy=return_numpy, mesh=mesh,
                        param_shardings=param_shardings)
