"""Data-parallel execution of CompiledProgram.with_data_parallel (reference:
ParallelExecutor path — compiler.py:308, parallel_executor.cc:442).

TPU design: no per-device graph clones or allreduce op-handles. The step
function the executor already traces is jitted under a 1-axis Mesh ("dp")
with the feed batch sharded on axis 0 and params replicated; grad psums are
inserted by XLA from the sharding propagation. Single-device: plain run.
"""
from __future__ import annotations

import jax


def run_data_parallel(executor, compiled_program, feed, fetch_list, scope,
                      return_numpy):
    # Round-1: single-process path — jit over the local mesh. With one
    # device this is exactly Executor.run; the mesh path lands with
    # parallel/fleet (see dryrun_multichip in __graft_entry__.py).
    return executor.run(compiled_program._program, feed=feed,
                        fetch_list=fetch_list, scope=scope,
                        return_numpy=return_numpy)
