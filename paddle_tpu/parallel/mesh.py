"""Mesh construction + feed sharding for data/model parallel execution.

Replaces the reference's multi-device machinery (reference:
framework/parallel_executor.cc:442 — per-device graph clones, NCCL comms,
allreduce op-handles) with sharding metadata: ONE jitted step function whose
feed batch is sharded over the "dp" mesh axis and whose parameters are
replicated; XLA's sharding propagation inserts the gradient all-reduces over
ICI. Multi-host: the same code with jax.distributed initialized — each host
provides its local shard via make_array_from_process_local_data (DCN/ICI
handled by XLA).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
MODEL_AXIS = "mp"

try:
    from jax import shard_map  # noqa: F401  (re-exported for parallel/*)
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401


def axis_mesh(n: int, axis_name: str, devices=None) -> Mesh:
    """1-D named-axis mesh over the first n devices (pp/sp helpers)."""
    devs = list(devices if devices is not None else jax.devices())[:n]
    if len(devs) != n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.asarray(devs), (axis_name,))


def mesh3d(dp: int = 2, pp: int = 2, sp: int = 2, devices=None) -> Mesh:
    """The composed 3D-parallel mesh ("dp", "pp", "sp") — data ×
    pipeline × sequence over dp·pp·sp devices (the 8-device virtual
    mesh at 2×2×2). Expert parallelism reuses one of these axes as the
    all-to-all group (parallel/lm3d.py dispatches experts over "dp"),
    so a 4th axis is never materialized."""
    n = dp * pp * sp
    devs = list(devices if devices is not None else jax.devices())[:n]
    if len(devs) != n:
        raise ValueError(
            f"mesh3d(dp={dp}, pp={pp}, sp={sp}) needs {n} devices, "
            f"have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(dp, pp, sp), ("dp", "pp", "sp"))


def build_mesh(num_devices: Optional[int] = None, model_parallel: int = 1,
               devices=None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    n = len(devs)
    mp = max(1, model_parallel)
    if n % mp != 0:
        raise ValueError(
            f"device count {n} is not divisible by model_parallel={mp}")
    dp = n // mp
    arr = np.asarray(devs).reshape(dp, mp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def shard_feed(mesh: Mesh, name: str, array, window: bool = False) -> jax.Array:
    """Place a host batch onto the mesh, sharded on dim 0. In multi-process
    mode the given array is this process's LOCAL shard. Meshes without a
    data axis (e.g. a pure "pp" pipeline mesh) replicate the feed.

    ``window=True``: the array is a [K, batch, ...] WINDOW STACK of K
    distinct batches (docs/INPUT_PIPELINE.md) — the window dim stays
    unsharded (it is the executor's scan axis) and the BATCH dim (dim 1)
    shards over "dp", so ONE device_put places the whole window and the
    per-step slices come out batch-sharded on-device. Window stacks too
    flat to carry a batch dim (ndim < 2) replicate."""
    arr = np.asarray(array)
    bdim = 1 if window else 0
    if DATA_AXIS not in mesh.shape or (window and arr.ndim < 2):
        repl = replicated(mesh)
        if jax.process_count() > 1:
            # device_put can't target non-addressable devices; every
            # process holds the identical full value
            return jax.make_array_from_process_local_data(
                repl, arr, global_shape=arr.shape)
        return jax.device_put(arr, repl)
    dp = mesh.shape[DATA_AXIS]
    if window:
        sharding = NamedSharding(mesh, P(
            None, DATA_AXIS, *([None] * (arr.ndim - 2))))
    else:
        sharding = batch_sharded(mesh, max(arr.ndim, 1))
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, arr)
    if arr.shape[bdim] % dp != 0:
        raise ValueError(
            f"feed '{name}' batch {arr.shape[bdim]} not divisible by "
            f"data-parallel degree {dp}")
    return jax.device_put(arr, sharding)
