"""Composed 3D-parallel GPT-style LM lane — dp × pp × sp (+ MoE expert
parallel) on ONE mesh (ROADMAP item 4).

Every parallelism axis this package ships is composed into a single
compiled train step on the `mesh3d` ("dp", "pp", "sp") mesh:

  * pipeline — the decoder stack is split into ``pp`` stages scheduled
    by `parallel.pipeline.gpipe` (compiled GPipe tick loop, ppermute
    stage handoff, reverse pipeline via the vjp transpose);
  * sequence — attention inside every stage is
    `ring_attention_local` over the "sp" axis (K/V blocks rotate over
    the manual-axis ppermute — the same body the standalone
    `ring_attention` shard_maps, here NESTED inside the gpipe stage);
  * data — the microbatch's batch dim shards over "dp"; gradient
    all-reduces fall out of the shard_map transpose;
  * experts — the MoE variant dispatches tokens over "dp" reused as the
    expert-parallel axis (`moe_ffn_local` all_to_all, per-expert
    capacity with COUNTED token drops surfaced through gpipe's
    ``with_aux`` schedule-total).

The numeric-fault plane composes across all axes at once: ONE fused
health scalar (`fluid.ir.fused_health` over every grad leaf + the
per-microbatch losses) guards the WHOLE microbatch schedule per step —
not per stage — with the PR 5 skip-mode discard (``where(health, new,
old)`` over every param) and, under ``amp=True``, the dynamic
loss-scaling transition (`fluid.executor._amp_scale_update`) consuming
the same scalar. The rng-fold contract holds across axes too: step keys
fold by GLOBAL step index, and every dropout site folds by (stage,
layer, microbatch) — `gpipe(pass_micro=True)` hands the stage body the
microbatch index its tick computes — so the single-device oracle
(`make_oracle_step`: same params, same folds, python loop over stages
and microbatches, degenerate n=1 collectives) draws identical masks.

Parity contract (tests/test_parallel3d.py, docs/PERF.md): per-step
losses of the composed lane match the oracle within documented fp32
tolerance (the dp/sp partial-sum orders differ from the oracle's
single-device reductions by last-ulp rounding; a pp-only composition is
observed bit-identical). Evidence lane: ``bench.py lm3d``.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import mesh3d
from .moe import expert_capacity, moe_ffn_local
from .pipeline import gpipe
from .ring_attention import ring_attention_local

__all__ = ["LMConfig", "mesh3d", "init_params", "param_count",
           "place_params", "place_window", "init_amp_state",
           "sample_window", "make_train_step", "make_window_step",
           "make_oracle_step", "make_oracle_window", "flops_per_step"]

# dynamic loss-scaling hyperparams (PR 5 defaults, reference
# update_loss_scaling contract — fluid/executor._amp_scale_update)
AMP_CFG = {"incr_every_n_steps": 8, "decr_every_n_nan_or_inf": 1,
           "incr_ratio": 2.0, "decr_ratio": 0.5}
INIT_LOSS_SCALE = 2.0 ** 10


class LMConfig:
    """Shapes + parallel degrees of the lane. ``n_experts == 0`` is the
    dense-FFN variant; ``n_experts > 0`` shards experts over "dp"."""

    def __init__(self, vocab=64, d_model=32, n_heads=4, d_ff=None,
                 seq_len=32, layers_per_stage=1, dp=1, pp=1, sp=1,
                 n_experts=0, capacity_factor=4.0, dropout=0.0,
                 lr=0.1, n_micro=2, batch=4, amp=False, seed=0):
        self.vocab, self.d_model, self.n_heads = vocab, d_model, n_heads
        self.d_ff = d_ff if d_ff is not None else 4 * d_model
        self.seq_len, self.layers_per_stage = seq_len, layers_per_stage
        self.dp, self.pp, self.sp = dp, pp, sp
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.dropout, self.lr = dropout, lr
        self.n_micro, self.batch = n_micro, batch
        self.amp, self.seed = amp, seed
        if d_model % n_heads:
            raise ValueError(f"d_model {d_model} % n_heads {n_heads}")
        if seq_len % sp:
            raise ValueError(f"seq_len {seq_len} not divisible by "
                             f"sp={sp}")
        if batch % n_micro:
            raise ValueError(f"batch {batch} % n_micro {n_micro}")
        if (batch // n_micro) % dp:
            raise ValueError(f"microbatch {batch // n_micro} not "
                             f"divisible by dp={dp}")
        if n_experts and n_experts % dp:
            raise ValueError(f"experts {n_experts} not divisible by "
                             f"the expert-parallel axis dp={dp}")

    @property
    def n_layers(self):
        return self.pp * self.layers_per_stage

    @property
    def n_devices(self):
        return self.dp * self.pp * self.sp

    def mesh(self, devices=None):
        return mesh3d(self.dp, self.pp, self.sp, devices=devices)

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.amp else jnp.float32


# ----------------------------------------------------------------- params
def init_params(cfg: LMConfig) -> Dict[str, Any]:
    """Deterministic fp32 params. Stage leaves stack [pp, Lps, ...]."""
    r = np.random.RandomState(cfg.seed)
    D, F, V, E = cfg.d_model, cfg.d_ff, cfg.vocab, cfg.n_experts
    pp, L = cfg.pp, cfg.layers_per_stage

    def n(*shape, scale=0.02):
        return jnp.asarray(r.normal(size=shape) * scale, jnp.float32)

    st = {
        "ln1_g": jnp.ones((pp, L, D), jnp.float32),
        "ln1_b": jnp.zeros((pp, L, D), jnp.float32),
        "wq": n(pp, L, D, D), "wk": n(pp, L, D, D),
        "wv": n(pp, L, D, D),
        "wo": n(pp, L, D, D, scale=0.02 / np.sqrt(2 * cfg.n_layers)),
        "ln2_g": jnp.ones((pp, L, D), jnp.float32),
        "ln2_b": jnp.zeros((pp, L, D), jnp.float32),
    }
    if E:
        st.update({
            "gate": n(pp, L, D, E),
            "w1": n(pp, L, E, D, F), "b1": jnp.zeros((pp, L, E, F),
                                                     jnp.float32),
            "w2": n(pp, L, E, F, D,
                    scale=0.02 / np.sqrt(2 * cfg.n_layers)),
            "b2": jnp.zeros((pp, L, E, D), jnp.float32),
        })
    else:
        st.update({
            "w1": n(pp, L, D, F), "b1": jnp.zeros((pp, L, F),
                                                  jnp.float32),
            "w2": n(pp, L, F, D,
                    scale=0.02 / np.sqrt(2 * cfg.n_layers)),
            "b2": jnp.zeros((pp, L, D), jnp.float32),
        })
    return {
        "emb": n(V, D), "pos": n(cfg.seq_len, D),
        "ln_f_g": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
        "head": n(D, V),
        "stages": st,
    }


def _stage_specs(cfg: LMConfig, stages: Dict[str, Any]):
    """PartitionSpecs of the stacked stage params on the 3D mesh: every
    leaf leads with "pp"; MoE expert-count dims additionally shard over
    "dp" (the expert-parallel axis)."""
    expert_leaves = {"w1", "b1", "w2", "b2"} if cfg.n_experts else set()

    def spec(name, x):
        if name in expert_leaves:
            # [pp, Lps, E, ...]: E over the expert axis. Specs stay in
            # their SHORT form (no trailing Nones): XLA normalizes
            # output shardings that way, and NamedSharding __eq__ —
            # which the jit cache keys on — treats P("pp") and
            # P("pp", None, None) as DIFFERENT, so a long-form
            # pre-placement would retrace on the second dispatch.
            return P("pp", None, "dp")
        return P("pp")
    return {k: spec(k, v) for k, v in stages.items()}


def param_count(params) -> int:
    return int(sum(np.prod(x.shape)
                   for x in jax.tree_util.tree_leaves(params)))


def flops_per_step(cfg: LMConfig, n_params: int) -> Dict[str, float]:
    """The longctx-lane methodology (bench.py): model FLOPs per
    optimizer step estimated as 6·N per trained token (2N fwd + 4N bwd)
    — the headline "achieved TFLOPs" numerator — plus the attention
    quadratic term (causal ⇒ halved; ×3.5 fwd+bwd) reported alongside.
    For MoE, top-1 routing activates ONE expert per token, so the
    active-parameter count (experts averaged to one) is what 6·N
    sees."""
    tokens = cfg.batch * cfg.seq_len
    n_active = n_params
    if cfg.n_experts:
        st_shape = dict(w1=(cfg.d_model, cfg.d_ff), b1=(cfg.d_ff,),
                        w2=(cfg.d_ff, cfg.d_model), b2=(cfg.d_model,))
        per_expert = sum(int(np.prod(s)) for s in st_shape.values())
        n_active = n_params - cfg.n_layers * (cfg.n_experts - 1) \
            * per_expert
    model = 6.0 * n_active * tokens
    Dh = cfg.d_model // cfg.n_heads
    attn = (4.0 * cfg.batch * cfg.n_heads * cfg.seq_len ** 2 * Dh
            / 2.0 * 3.5) * cfg.n_layers
    return {"tokens": float(tokens), "model_flops": model,
            "attn_flops": attn, "n_params": float(n_params),
            "n_active_params": float(n_active)}


# ------------------------------------------------------------------ model
def _ln(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _dropout(cfg: LMConfig, a, key, sidx, lidx, micro, site):
    """Inverted dropout whose mask folds by (stage, layer, site) and
    microbatch — the rng-fold contract that lets the oracle (python
    stage/micro indices) mirror the pipelined lane (traced indices)
    mask-for-mask. No-op at rate 0."""
    if cfg.dropout <= 0.0 or key is None:
        return a
    k = jax.random.fold_in(
        key, (sidx * cfg.layers_per_stage + lidx) * 2 + site)
    k = jax.random.fold_in(k, micro)
    keep = jax.random.bernoulli(k, 1.0 - cfg.dropout, a.shape)
    return jnp.where(keep, a / (1.0 - cfg.dropout),
                     jnp.zeros((), a.dtype)).astype(a.dtype)


def _layer(cfg: LMConfig, p, x, micro, key, sidx, lidx, sp_n):
    """One pre-LN decoder block on one device's activation shard
    x [mb_loc, S_loc, D]. ``sp_n`` is the sequence-axis degree (1 ⇒
    the degenerate no-collective oracle path; the expert-parallel
    degree is inferred from the local expert slice width).
    Returns (x, dropped) — dropped = this shard's MoE capacity
    overflow count (0 for the dense FFN)."""
    dt = x.dtype
    mb, S_l, D = x.shape
    H = cfg.n_heads
    Dh = D // H

    h = _ln(x, p["ln1_g"], p["ln1_b"])

    def heads(w):
        y = h @ w.astype(dt)
        return y.reshape(mb, S_l, H, Dh).transpose(0, 2, 1, 3)

    a = ring_attention_local(heads(p["wq"]), heads(p["wk"]),
                             heads(p["wv"]), causal=True, axis="sp",
                             n=sp_n)
    a = a.transpose(0, 2, 1, 3).reshape(mb, S_l, D) @ p["wo"].astype(dt)
    x = x + _dropout(cfg, a, key, sidx, lidx, micro, site=0)

    h = _ln(x, p["ln2_g"], p["ln2_b"])
    if cfg.n_experts:
        capacity = expert_capacity(mb * S_l, cfg.n_experts,
                                   cfg.capacity_factor)
        y, dropped = moe_ffn_local(
            h.reshape(-1, D), p["gate"], p["w1"], p["b1"], p["w2"],
            p["b2"], axis="dp", capacity=capacity)
        y = y.reshape(mb, S_l, D)
    else:
        y = jax.nn.gelu(h @ p["w1"].astype(dt)
                        + p["b1"].astype(dt)) @ p["w2"].astype(dt) \
            + p["b2"].astype(dt)
        dropped = jnp.zeros((), jnp.int32)
    x = x + _dropout(cfg, y, key, sidx, lidx, micro, site=1)
    return x, dropped


def _stage_body(cfg: LMConfig, p_stage, x, micro, key, sidx, sp_n):
    """All of one pipeline stage's layers. p_stage leaves [Lps, ...]."""
    dropped = jnp.zeros((), jnp.int32)
    for l in range(cfg.layers_per_stage):
        pl = {k: v[l] for k, v in p_stage.items()}
        x, d = _layer(cfg, pl, x, micro, key, sidx, l, sp_n)
        dropped = dropped + d
    return x, dropped


def _embed(cfg: LMConfig, params, xb):
    x = params["emb"][xb] + params["pos"][None, None]
    return x.astype(cfg.compute_dtype)


def _head_loss(cfg: LMConfig, params, ys, yb):
    """Final LN + LM head + per-microbatch mean xent. ys
    [n_micro, mb, S, D]; yb int targets [n_micro, mb, S]. Returns
    (mean loss, per-microbatch losses [n_micro]) in fp32."""
    h = _ln(ys.astype(jnp.float32), params["ln_f_g"], params["ln_f_b"])
    logits = h @ params["head"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, yb[..., None], axis=-1)[..., 0]
    losses = jnp.mean(nll, axis=(1, 2))
    return jnp.mean(losses), losses


def _forward_composed(cfg: LMConfig, params, xb, yb, key, mesh):
    x = _embed(cfg, params, xb)

    def stage_fn(p, xx, micro):
        sidx = lax.axis_index("pp")
        return _stage_body(cfg, p, xx, micro, key, sidx, cfg.sp)

    ys, dropped = gpipe(
        stage_fn, params["stages"], x, mesh=mesh,
        param_specs=_stage_specs(cfg, params["stages"]),
        xs_spec=P(None, "dp", "sp", None), with_aux=True,
        pass_micro=True)
    loss, losses = _head_loss(cfg, params, ys, yb)
    return loss, losses, dropped


def _forward_oracle(cfg: LMConfig, params, xb, yb, key):
    """Single-device reference: same params/folds, python loops over
    stages and microbatches, degenerate (n=1) collectives."""
    x = _embed(cfg, params, xb)
    outs, dropped = [], jnp.zeros((), jnp.int32)
    for m in range(cfg.n_micro):
        xi = x[m]
        for s in range(cfg.pp):
            p_s = {k: v[s] for k, v in params["stages"].items()}
            xi, d = _stage_body(cfg, p_s, xi, m, key, s, 1)
            dropped = dropped + d
        outs.append(xi)
    ys = jnp.stack(outs)
    loss, losses = _head_loss(cfg, params, ys, yb)
    return loss, losses, dropped


# ------------------------------------------------------------- train step
def init_amp_state(cfg: LMConfig, mesh=None):
    """Fresh dynamic loss-scaling state; pass ``mesh`` to pre-place it
    replicated (the steady-state sharding — same retrace rationale as
    `place_params`)."""
    if not cfg.amp:
        return {}
    st = {"scale": jnp.full((1,), INIT_LOSS_SCALE, jnp.float32),
          "good": jnp.zeros((1,), jnp.int32),
          "bad": jnp.zeros((1,), jnp.int32)}
    if mesh is not None:
        st = {k: jax.device_put(v, NamedSharding(mesh, P()))
              for k, v in st.items()}
    return st


def _make_step(cfg: LMConfig, forward, guard: bool = True):
    """The shared train-step epilogue around either forward — ONE
    implementation of the PR 5 composition for the lane and its oracle
    (so the parity the tests pin cannot drift): scaled loss → grads →
    unscale → ONE fused health scalar over every grad leaf + the
    per-microbatch losses → SGD update → skip-mode discard → AMP scale
    transition."""
    from ..fluid.ir import fused_health

    def loss_fn(params, xb, yb, key, scale):
        loss, losses, dropped = forward(params, xb, yb, key)
        return loss * scale.astype(loss.dtype), (losses, dropped)

    def step(params, amp_state, xb, yb, key):
        scale = (amp_state["scale"][0] if cfg.amp
                 else jnp.float32(1.0))
        grads, (losses, dropped) = jax.grad(
            loss_fn, has_aux=True)(params, xb, yb, key, scale)
        if cfg.amp:
            inv = (1.0 / scale).astype(jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv, grads)
        health = fused_health(
            jax.tree_util.tree_leaves(grads) + [losses])
        new_params = jax.tree_util.tree_map(
            lambda pv, g: pv - cfg.lr * g.astype(pv.dtype), params,
            grads)
        if guard:
            new_params = jax.tree_util.tree_map(
                lambda nv, ov: jnp.where(health, nv, ov), new_params,
                params)
        if cfg.amp:
            from ..fluid.executor import _amp_scale_update
            s, g, b = _amp_scale_update(
                health, amp_state["scale"], amp_state["good"],
                amp_state["bad"], AMP_CFG)
            amp_state = {"scale": s, "good": g, "bad": b}
        loss = jnp.mean(losses)
        return new_params, amp_state, (loss, losses, health, dropped)
    return step


def _param_shardings(cfg: LMConfig, mesh, params):
    specs = _stage_specs(cfg, params["stages"])
    return {k: (NamedSharding(mesh, P()) if k != "stages" else
                {k2: NamedSharding(mesh, specs[k2])
                 for k2 in params["stages"]})
            for k in params}


def make_train_step(cfg: LMConfig, mesh, guard: bool = True):
    """One composed 3D-parallel optimizer step:
    step(params, amp_state, xb, yb, key) →
    (params', amp_state', (loss, losses[n_micro], health, dropped)).
    Updated params are sharding-constrained back to their input layout
    (stage stacks over "pp"/"dp", the rest replicated) — without the
    pin, GSPMD re-shards e.g. the position table over "sp" on output
    and the NEXT dispatch retraces against the changed input sharding
    (the executor_retraces_total ≠ 0 failure mode)."""
    inner = _make_step(
        cfg, lambda params, xb, yb, key: _forward_composed(
            cfg, params, xb, yb, key, mesh), guard=guard)
    shardings = None

    def step(params, amp_state, xb, yb, key):
        nonlocal shardings
        if shardings is None:
            shardings = _param_shardings(cfg, mesh, params)
        new_params, amp_state, out = inner(params, amp_state, xb, yb,
                                           key)
        new_params = jax.lax.with_sharding_constraint(new_params,
                                                      shardings)
        return new_params, amp_state, out
    return step


def make_oracle_step(cfg: LMConfig, guard: bool = True):
    def forward(params, xb, yb, key):
        return _forward_oracle(cfg, params, xb, yb, key)
    return _make_step(cfg, forward, guard=guard)


def _window(cfg: LMConfig, step, constrain=None):
    def window(params, amp_state, windows, key_base, idx0):
        """K steps as ONE lax.scan — ``windows`` [K, n_micro, mb, S+1]
        int32 token stacks (one device_put per window; microbatch
        slices and the input/target shift are carved ON-DEVICE). The
        per-step key folds by GLOBAL step index idx0+i — the PR 2
        window rng contract, so a K-window run is bit-identical to K
        sequential step() calls."""
        k = windows.shape[0]

        def body(carry, x):
            params, amp_state = carry
            i, w = x
            key = jax.random.fold_in(key_base, i)
            xb, yb = w[..., :-1], w[..., 1:]
            params, amp_state, out = step(params, amp_state, xb, yb,
                                          key)
            return (params, amp_state), out
        (params, amp_state), outs = lax.scan(
            body, (params, amp_state),
            (idx0 + jnp.arange(k), windows))
        if constrain is not None:
            # the per-step constraint does not survive the scan-carry →
            # jit-output chain (XLA re-shards the final carry); re-pin
            # the window's param/amp outputs so window i+1 never
            # retraces
            params, amp_state = constrain(params, amp_state)
        return params, amp_state, outs
    return window


def make_window_step(cfg: LMConfig, mesh, guard: bool = True):
    shardings = None

    def constrain(params, amp_state):
        nonlocal shardings
        if shardings is None:
            shardings = _param_shardings(cfg, mesh, params)
        params = jax.lax.with_sharding_constraint(params, shardings)
        if amp_state:
            amp_state = jax.lax.with_sharding_constraint(
                amp_state, {k: NamedSharding(mesh, P())
                            for k in amp_state})
        return params, amp_state
    return _window(cfg, make_train_step(cfg, mesh, guard=guard),
                   constrain=constrain)


def make_oracle_window(cfg: LMConfig, guard: bool = True):
    return _window(cfg, make_oracle_step(cfg, guard=guard))


# ------------------------------------------------------------------- data
def sample_window(cfg: LMConfig, idx0: int, k: int = 1) -> np.ndarray:
    """K distinct step batches of structured synthetic sequences
    (per-row arithmetic progressions mod vocab — the delta is inferable
    from any adjacent pair, so a 1-layer causal transformer learns it)
    → [k, n_micro, mb, S+1] int32, deterministic in (seed, step)."""
    out = []
    for step in range(idx0, idx0 + k):
        r = np.random.RandomState(
            (cfg.seed * 1_000_003 + step * 7919) % (2 ** 31 - 1))
        start = r.randint(0, cfg.vocab, size=(cfg.batch, 1))
        delta = r.choice([1, 2, 3, 5], size=(cfg.batch, 1))
        toks = (start + delta * np.arange(cfg.seq_len + 1)[None]) \
            % cfg.vocab
        out.append(toks.reshape(cfg.n_micro, cfg.batch // cfg.n_micro,
                                cfg.seq_len + 1))
    return np.asarray(out, np.int32)


def place_params(cfg: LMConfig, mesh, params):
    """Pre-place params with their steady-state shardings (stage leaves
    per `_stage_specs`, everything else replicated) so the FIRST window
    dispatch already sees the same input shardings the step's outputs
    carry — without this the second call retraces against the
    now-sharded params (the PR 2 warm-twice note, solved at the source
    here)."""
    specs = _stage_specs(cfg, params["stages"])

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))
    out = {k: (jax.device_put(v, NamedSharding(mesh, P()))
               if k != "stages" else None)
           for k, v in params.items()}
    out["stages"] = {k: put(v, specs[k])
                     for k, v in params["stages"].items()}
    return out


def place_window(cfg: LMConfig, mesh, windows: np.ndarray):
    """ONE device_put of a [K, n_micro, mb, S+1] window stack: batch
    dim over "dp", everything else replicated (the sequence dim carries
    S+1 tokens — the shift to S-token inputs/targets happens on-device,
    after which gpipe reshards S over "sp")."""
    return jax.device_put(
        windows, NamedSharding(mesh, P(None, None, "dp", None)))
