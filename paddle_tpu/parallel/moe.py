"""Expert parallelism: Mixture-of-Experts FFN with token-choice top-1
routing and all-to-all dispatch over an "ep" mesh axis.

Beyond-reference capability (the reference has no MoE; its closest
analogue is the sparse PS plane) designed TPU-first: experts are sharded
over the mesh's "ep" axis, tokens are dispatched into static-shape
per-expert capacity buffers (no dynamic shapes under jit), and the
exchange is ONE jax.lax.all_to_all each way inside shard_map — the
canonical MoE dispatch that rides ICI (GShard/Switch recipe as described
in the public scaling-book material).

Capacity semantics: each expert accepts at most ``capacity`` tokens per
shard; overflow tokens are dropped (their combine weight is zero), the
standard Switch-style trade that keeps shapes static for XLA.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

EP_AXIS = "ep"


def expert_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (EP_AXIS,))


def _dispatch_local(x, gate_logits, n_experts, capacity):
    """Token→expert dispatch within one shard. Returns (buffers [E, C, D],
    combine info) with static shapes."""
    n_tok, d = x.shape
    top1 = jnp.argmax(gate_logits, axis=-1)               # [T]
    gate = jax.nn.softmax(gate_logits, axis=-1)
    top1_gate = jnp.take_along_axis(gate, top1[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(top1, n_experts, dtype=jnp.int32)   # [T, E]
    # position of each token inside its expert's capacity buffer
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot   # [T, E]
    pos = jnp.sum(pos_in_expert, axis=-1)                       # [T]
    keep = pos < capacity
    weight = jnp.where(keep, top1_gate, 0.0)
    buf = jnp.zeros((n_experts, capacity, d), x.dtype)
    buf = buf.at[top1, jnp.minimum(pos, capacity - 1)].add(
        x * keep[:, None].astype(x.dtype))
    return buf, (top1, jnp.minimum(pos, capacity - 1), weight)


def _combine_local(expert_out, info):
    top1, pos, weight = info
    gathered = expert_out[top1, pos]                      # [T, D]
    return gathered * weight[:, None]


def moe_ffn(x, gate_w, w1, b1, w2, b2, mesh: Mesh,
            capacity_factor: float = 2.0, activation=jax.nn.gelu):
    """MoE FFN layer: x [B, S, D] (tokens sharded over "ep" on B),
    gate_w [D, E]; w1 [E, D, F], b1 [E, F], w2 [E, F, D], b2 [E, D] with
    experts sharded over "ep" on E. Output [B, S, D], token-sharded.

    Each shard: route its local tokens, all_to_all the capacity buffers
    so every device holds ITS experts' tokens from all shards, run the
    local experts' FFN, all_to_all back, combine."""
    n_dev = mesh.shape[EP_AXIS]
    E = gate_w.shape[-1]
    assert E % n_dev == 0, (E, n_dev)

    B, S, D = x.shape
    tokens_per_shard = (B // n_dev) * S
    capacity = max(1, int(np.ceil(
        tokens_per_shard * capacity_factor / E)))

    def shard_fn(xs, gw, w1s, b1s, w2s, b2s):
        # xs: [B/n, S, D] local tokens; w1s: [E/n, D, F] local experts
        xt = xs.reshape(-1, D)                            # [T, D]
        logits = xt @ gw                                  # [T, E]
        buf, info = _dispatch_local(xt, logits, E, capacity)
        # [E, C, D] → exchange: split E across devices, concat the shard
        # dim → [E/n, n·C, D] (this device's experts, tokens of every
        # shard)
        mine = jax.lax.all_to_all(buf.reshape(n_dev, E // n_dev,
                                              capacity, D),
                                  EP_AXIS, 0, 0, tiled=False)
        mine = jnp.moveaxis(mine, 0, 1).reshape(E // n_dev,
                                                n_dev * capacity, D)
        h = activation(jnp.einsum("ecd,edf->ecf", mine, w1s)
                       + b1s[:, None, :])
        out = jnp.einsum("ecf,efd->ecd", h, w2s) + b2s[:, None, :]
        # inverse exchange: back to [E, C, D] on the token's home shard
        out = jnp.moveaxis(out.reshape(E // n_dev, n_dev, capacity, D),
                           1, 0)
        back = jax.lax.all_to_all(out, EP_AXIS, 0, 0, tiled=False)
        back = back.reshape(E, capacity, D)
        return _combine_local(back, info).reshape(xs.shape)

    from .mesh import shard_map
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(EP_AXIS, None, None), P(None, None),
                             P(EP_AXIS, None, None), P(EP_AXIS, None),
                             P(EP_AXIS, None, None), P(EP_AXIS, None)),
                   out_specs=P(EP_AXIS, None, None))
    return fn(x, gate_w, w1, b1, w2, b2)


def moe_ffn_reference(x, gate_w, w1, b1, w2, b2,
                      activation=jax.nn.gelu):
    """Dense single-device oracle: every token through its top-1 expert
    (ample capacity ⇒ moe_ffn must match this exactly)."""
    B, S, D = x.shape
    xt = x.reshape(-1, D)
    logits = xt @ gate_w
    top1 = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)
    w = jnp.take_along_axis(gate, top1[:, None], axis=1)[:, 0]
    h = activation(jnp.einsum("td,edf->tef", xt, w1) + b1[None])
    outs = jnp.einsum("tef,efd->ted", h, w2) + b2[None]
    sel = jnp.take_along_axis(
        outs, top1[:, None, None].repeat(D, -1), axis=1)[:, 0]
    return (sel * w[:, None]).reshape(x.shape)
