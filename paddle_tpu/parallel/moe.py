"""Expert parallelism: Mixture-of-Experts FFN with token-choice top-1
routing and all-to-all dispatch over an "ep" mesh axis.

Beyond-reference capability (the reference has no MoE; its closest
analogue is the sparse PS plane) designed TPU-first: experts are sharded
over the mesh's "ep" axis, tokens are dispatched into static-shape
per-expert capacity buffers (no dynamic shapes under jit), and the
exchange is ONE jax.lax.all_to_all each way inside shard_map — the
canonical MoE dispatch that rides ICI (GShard/Switch recipe as described
in the public scaling-book material).

Capacity semantics: each expert accepts at most ``capacity`` tokens per
shard; overflow tokens are dropped (their combine weight is zero), the
standard Switch-style trade that keeps shapes static for XLA. Drops are
COUNTED: the local dispatch returns the number of locally-routed tokens
that overflowed, so a training lane can watch expert balance instead of
silently losing tokens.

Two layers (mirrors parallel/pipeline.py):
  * ``moe_ffn_local`` — the per-device body, written against a NAMED
    mesh axis with raw ``lax.all_to_all`` collectives so it composes
    inside an ALREADY-OPEN ``shard_map`` region — e.g. nested in a
    GPipe stage over a dp×pp×sp mesh, where the expert axis is one of
    the other mesh axes (parallel/lm3d.py uses axis="dp").
  * ``moe_ffn`` — the standalone wrapper: one shard_map over the "ep"
    axis around ``moe_ffn_local``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

EP_AXIS = "ep"

__all__ = ["EP_AXIS", "expert_mesh", "expert_capacity", "moe_ffn_local",
           "moe_ffn", "moe_ffn_reference"]


def expert_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (EP_AXIS,))


def expert_capacity(tokens_per_shard: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert per-shard capacity buffer length (static shape)."""
    return max(1, int(np.ceil(
        tokens_per_shard * capacity_factor / n_experts)))


def _dispatch_local(x, gate_logits, n_experts, capacity):
    """Token→expert dispatch within one shard. Returns (buffers [E, C, D],
    combine info, dropped count) with static shapes."""
    n_tok, d = x.shape
    top1 = jnp.argmax(gate_logits, axis=-1)               # [T]
    gate = jax.nn.softmax(gate_logits, axis=-1)
    top1_gate = jnp.take_along_axis(gate, top1[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(top1, n_experts, dtype=jnp.int32)   # [T, E]
    # position of each token inside its expert's capacity buffer
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot   # [T, E]
    pos = jnp.sum(pos_in_expert, axis=-1)                       # [T]
    keep = pos < capacity
    weight = jnp.where(keep, top1_gate, 0.0)
    dropped = jnp.sum(jnp.logical_not(keep).astype(jnp.int32))
    buf = jnp.zeros((n_experts, capacity, d), x.dtype)
    buf = buf.at[top1, jnp.minimum(pos, capacity - 1)].add(
        x * keep[:, None].astype(x.dtype))
    return buf, (top1, jnp.minimum(pos, capacity - 1), weight), dropped


def _combine_local(expert_out, info):
    top1, pos, weight = info
    gathered = expert_out[top1, pos]                      # [T, D]
    return gathered * weight[:, None].astype(expert_out.dtype)


def moe_ffn_local(xt, gate_w, w1, b1, w2, b2, *, axis, capacity,
                  activation=jax.nn.gelu):
    """One device's MoE FFN inside an open shard_map region.

    xt   [T, D]           this shard's tokens
    gate_w [D, E]         replicated router (E = GLOBAL expert count)
    w1 [E/n, D, F], b1 [E/n, F], w2 [E/n, F, D], b2 [E/n, D]
                          THIS device's expert slice along ``axis``
    axis                  mesh axis name the experts shard over (must be
                          a manual axis of the enclosing shard_map)
    capacity              per-expert per-shard buffer length
                          (see ``expert_capacity``)

    Returns ``(out [T, D], dropped)`` — ``dropped`` is the int32 count
    of THIS shard's tokens that overflowed their expert's capacity (sum
    ``lax.psum(dropped, axis)`` for the global count). Route its local
    tokens, all_to_all the capacity buffers so every device holds ITS
    experts' tokens from all shards, run the local experts' FFN,
    all_to_all back, combine.
    """
    E = gate_w.shape[-1]
    e_local = w1.shape[0]
    if E % e_local:
        raise ValueError(f"global experts {E} not divisible into local "
                         f"slices of {e_local}")
    n_dev = E // e_local
    T, D = xt.shape
    logits = (xt @ gate_w.astype(xt.dtype)).astype(jnp.float32)  # [T, E]
    buf, info, dropped = _dispatch_local(xt, logits, E, capacity)
    if n_dev == 1:
        # every expert is local (the single-device oracle composition,
        # or ep degree 1 on a degenerate mesh) — no exchange to ride
        mine = buf.reshape(e_local, capacity, D)
    else:
        # [E, C, D] → exchange: split E across devices, concat the shard
        # dim → [E/n, n·C, D] (this device's experts, tokens of every
        # shard)
        mine = lax.all_to_all(buf.reshape(n_dev, e_local, capacity, D),
                              axis, 0, 0, tiled=False)
        mine = jnp.moveaxis(mine, 0, 1).reshape(e_local,
                                                n_dev * capacity, D)
    h = activation(jnp.einsum("ecd,edf->ecf", mine, w1.astype(xt.dtype))
                   + b1.astype(xt.dtype)[:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(xt.dtype)) \
        + b2.astype(xt.dtype)[:, None, :]
    if n_dev == 1:
        back = out.reshape(E, capacity, D)
    else:
        # inverse exchange: back to [E, C, D] on the token's home shard
        out = jnp.moveaxis(out.reshape(e_local, n_dev, capacity, D),
                           1, 0)
        back = lax.all_to_all(out, axis, 0, 0, tiled=False)
        back = back.reshape(E, capacity, D)
    return _combine_local(back, info), dropped


def moe_ffn(x, gate_w, w1, b1, w2, b2, mesh: Mesh,
            capacity_factor: float = 2.0, activation=jax.nn.gelu,
            return_dropped: bool = False):
    """MoE FFN layer: x [B, S, D] (tokens sharded over "ep" on B),
    gate_w [D, E]; w1 [E, D, F], b1 [E, F], w2 [E, F, D], b2 [E, D] with
    experts sharded over "ep" on E. Output [B, S, D], token-sharded;
    with ``return_dropped`` also the GLOBAL int32 count of tokens
    dropped by the per-expert capacity bound (replicated scalar)."""
    n_dev = mesh.shape[EP_AXIS]
    E = gate_w.shape[-1]
    assert E % n_dev == 0, (E, n_dev)

    B, S, D = x.shape
    capacity = expert_capacity((B // n_dev) * S, E, capacity_factor)

    def shard_fn(xs, gw, w1s, b1s, w2s, b2s):
        # xs: [B/n, S, D] local tokens; w1s: [E/n, D, F] local experts
        y, dropped = moe_ffn_local(xs.reshape(-1, D), gw, w1s, b1s, w2s,
                                   b2s, axis=EP_AXIS, capacity=capacity,
                                   activation=activation)
        return y.reshape(xs.shape), lax.psum(dropped, EP_AXIS)

    from .mesh import shard_map
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(EP_AXIS, None, None), P(None, None),
                             P(EP_AXIS, None, None), P(EP_AXIS, None),
                             P(EP_AXIS, None, None), P(EP_AXIS, None)),
                   out_specs=(P(EP_AXIS, None, None), P()))
    y, dropped = fn(x, gate_w, w1, b1, w2, b2)
    return (y, dropped) if return_dropped else y


def moe_ffn_reference(x, gate_w, w1, b1, w2, b2,
                      activation=jax.nn.gelu):
    """Dense single-device oracle: every token through its top-1 expert
    (ample capacity ⇒ moe_ffn must match this exactly)."""
    B, S, D = x.shape
    xt = x.reshape(-1, D)
    logits = xt @ gate_w
    top1 = jnp.argmax(logits, axis=-1)
    gate = jax.nn.softmax(logits, axis=-1)
    w = jnp.take_along_axis(gate, top1[:, None], axis=1)[:, 0]
    h = activation(jnp.einsum("td,edf->tef", xt, w1) + b1[None])
    outs = jnp.einsum("tef,efd->ted", h, w2) + b2[None]
    sel = jnp.take_along_axis(
        outs, top1[:, None, None].repeat(D, -1), axis=1)[:, 0]
    return (sel * w[:, None]).reshape(x.shape)
