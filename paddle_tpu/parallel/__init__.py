"""Parallelism runtime: mesh management, data/model/pipeline parallel,
Fleet API (reference: Fleet + transpiler + ParallelExecutor stack, re-built
on jax.sharding.Mesh + pjit/shard_map over ICI)."""
from . import env  # noqa: F401
