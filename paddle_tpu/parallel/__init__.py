"""Parallelism runtime: mesh management, data/model/pipeline/sequence
parallel, Fleet API (reference: Fleet + transpiler + ParallelExecutor
stack, re-built on jax.sharding.Mesh + pjit/shard_map over ICI)."""
from . import env  # noqa: F401
from .mesh import build_mesh  # noqa: F401
from .pipeline import gpipe, pipeline_mesh, stack_stage_params  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, sequence_mesh, ulysses_attention)
