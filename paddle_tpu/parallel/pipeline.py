"""Pipeline parallelism over a TPU mesh axis — GPipe schedule as a compiled
collective program.

The reference implements pipelining as a *runtime*: `PipelineOptimizer`
(reference: python/paddle/fluid/optimizer.py:3550) cuts the program into
sections, and `PipelineTrainer`/`SectionWorker` threads move scopes through
blocking queues between devices (reference:
paddle/fluid/framework/pipeline_trainer.cc:24, section_worker.cc:142,
trainer_desc.proto:77 SectionWorkerParameter).

On TPU the schedule is *compiled* instead: every stage lives on one slice of
a mesh axis (``"pp"``), stage parameters are sharded over that axis with a
leading stage dimension, and one `shard_map`-ped function runs the classic
GPipe tick loop — at tick t, stage s computes microbatch (t - s), then the
activation ring-shifts one stage forward via `lax.ppermute` over ICI. The
whole forward (and, through `jax.grad`, the reverse pipeline — ppermute
transposes to the opposite shift) is a single XLA computation: no queues, no
threads, no host in the loop.

Two layers:
  * `gpipe(...)` / `gpipe_het(...)` — the functional schedulers (this
    file): stacked stage params for homogeneous stages, a flat
    lax.switch ring for arbitrary per-stage bodies. Used directly by
    model code for peak MFU.
  * `PipelineOptimizer` (fluid/optimizer.py) — reference-API program
    splitter whose section metadata `fluid/pipeline_lowering.py` lowers
    onto `gpipe` (homogeneous sections) or `gpipe_het` (heterogeneous),
    falling back to fused execution when neither schedule applies.
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import axis_mesh, shard_map

PIPELINE_AXIS = "pp"

__all__ = ["PIPELINE_AXIS", "stack_stage_params", "pipeline_mesh", "gpipe",
           "gpipe_het", "gpipe_loss_fn"]


def pipeline_mesh(n_stages: int, devices=None) -> Mesh:
    return axis_mesh(n_stages, PIPELINE_AXIS, devices)


def stack_stage_params(per_stage: Sequence[Any]):
    """Stack N same-structure stage param trees along a new leading stage
    axis (the axis `gpipe` shards over ``"pp"``)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def _shard_stacked(mesh: Mesh, stacked, param_specs=None):
    """Place stacked stage params: leading (stage) dim over the pp axis
    (or the caller's explicit per-leaf specs, for params that ALSO shard
    over other mesh axes — e.g. MoE expert slices over "dp")."""
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(
            lambda x: P(PIPELINE_AXIS, *([None] * (x.ndim - 1))), stacked)

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, stacked, param_specs), param_specs


def gpipe(stage_fn: Callable[..., Any], stacked_params, xs, *,
          mesh: Mesh, axis: str = PIPELINE_AXIS, param_specs=None,
          xs_spec: P = P(), with_aux: bool = False,
          pass_micro: bool = False):
    """Run microbatches ``xs`` through an ``n_stages``-deep pipeline.

    stage_fn(params_i, x) -> y          one stage; same signature per stage
                                        (heterogeneity via lax.switch inside)
    stacked_params                      pytree, leading dim n_stages
                                        (see `stack_stage_params`)
    xs : [n_micro, mb, ...]             microbatched input (replicated)
    returns ys : [n_micro, mb, ...]     last stage's outputs (replicated)

    Stage activations must keep the input's shape/dtype contract
    (y.shape == stage input shape) — the usual transformer/MLP residual-width
    case. The tick loop runs n_micro + n_stages - 1 steps; bubbles compute on
    garbage and are masked out, exactly the GPipe cost model.

    Composition hooks (the 3D lane, parallel/lm3d.py):
      param_specs   pytree of PartitionSpecs matching ``stacked_params``
                    for leaves that shard over MORE than the leading
                    stage dim (every spec must still lead with ``axis``;
                    e.g. MoE expert weights P("pp", "dp", ...)). Default:
                    P(axis, None, ...) per leaf.
      xs_spec       PartitionSpec of ``xs`` (and of the returned ys) on
                    the non-pipeline mesh axes — dim 0 is the microbatch
                    dim and must stay unsharded (it is the scan axis);
                    e.g. P(None, "dp", "sp", None) for [n_micro, mb, S, D]
                    batch/sequence sharding. Default replicated.
      with_aux      stage_fn returns ``(y, aux_scalar)``; the aux values
                    of VALID ticks (bubbles excluded) are summed over
                    ticks, stages, and every other mesh axis, and
                    returned replicated as ``(ys, aux_total)`` — e.g.
                    counted MoE token drops across the whole schedule.
      pass_micro    stage_fn is called ``stage_fn(params_i, x, micro)``
                    with the (clamped) global microbatch index this tick
                    computes — the rng-fold hook: a stage body folding
                    its dropout key by (stage, layer, micro) draws the
                    same masks the sequential oracle does.
    """
    n_stages = mesh.shape[axis]
    n_micro = xs.shape[0]
    total = n_micro + n_stages - 1
    stacked_params, pspec_params = _shard_stacked(mesh, stacked_params,
                                                  param_specs)

    def per_device(params, xs_local):
        # params leaves arrive with leading dim 1 (this stage's slice)
        params = jax.tree_util.tree_map(lambda x: x[0], params)
        sidx = lax.axis_index(axis)
        right = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            inbuf, ys, aux_acc = carry
            # the microbatch THIS stage computes at tick t (stage 0
            # ingests mb t; stage s is s ticks behind; clamped — bubble
            # ticks compute on garbage and are masked below)
            midx = jnp.clip(t - sidx, 0, n_micro - 1)
            mb = lax.dynamic_index_in_dim(xs_local, jnp.clip(
                t, 0, n_micro - 1), keepdims=False)
            x = jnp.where(sidx == 0, mb, inbuf)
            args = (params, x, midx) if pass_micro else (params, x)
            y = stage_fn(*args)
            if with_aux:
                y, aux = y
                # a bubble tick's aux is garbage-in-garbage-out: count
                # only ticks where this stage holds a real microbatch
                live = jnp.logical_and(t - sidx >= 0,
                                       t - sidx < n_micro)
                aux_acc = aux_acc + jnp.where(live, aux,
                                              jnp.zeros_like(aux))
            # last stage writes microbatch (t - n_stages + 1) when valid
            oidx = t - (n_stages - 1)
            valid = jnp.logical_and(sidx == n_stages - 1, oidx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                ys, y, jnp.clip(oidx, 0, n_micro - 1), 0)
            ys = jnp.where(valid, upd, ys)
            nxt = lax.ppermute(y, axis, right)
            return (nxt, ys, aux_acc), None

        x0 = jnp.zeros_like(xs_local[0])
        aux0 = jnp.zeros((), jnp.int32)
        if with_aux:
            # discover the aux dtype/shape from an abstract stage eval
            aux_shape = jax.eval_shape(
                lambda p, x: stage_fn(*((p, x, jnp.int32(0))
                                        if pass_micro else (p, x)))[1],
                params, x0)
            aux0 = jnp.zeros(aux_shape.shape, aux_shape.dtype)
        init = (x0,
                jnp.zeros((n_micro,) + xs_local.shape[1:],
                          xs_local.dtype),
                aux0)
        # carry becomes device-varying after the first tick; mark it so
        # (older jax < 0.6 has neither primitive — there shard_map's
        # rep-tracking handles the transition without explicit marking)
        if hasattr(lax, "pcast"):
            init = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, (axis,), to="varying"), init)
        elif hasattr(lax, "pvary"):
            init = jax.tree_util.tree_map(
                lambda x: lax.pvary(x, (axis,)), init)
        (_, ys, aux_acc), _ = lax.scan(tick, init, jnp.arange(total))
        # ys is only populated on the last stage; zero elsewhere + psum
        # replicates it to every stage (single all-reduce over ICI).
        ys = lax.psum(jnp.where(sidx == n_stages - 1, ys,
                                jnp.zeros_like(ys)), axis)
        if with_aux:
            # total over stages AND the data/sequence shards — the
            # schedule-global count, replicated everywhere
            return ys, lax.psum(aux_acc, tuple(mesh.axis_names))
        return ys

    out_specs = (xs_spec, P()) if with_aux else xs_spec
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(pspec_params, xs_spec), out_specs=out_specs)
    return fn(stacked_params, xs)


def gpipe_het(stage_fns: Sequence[Callable[[Any, Any], Any]],
              per_stage_params: Sequence[Any], xs, *, mesh: Mesh,
              axis: str = PIPELINE_AXIS):
    """Heterogeneous GPipe: stage i runs ``stage_fns[i](params_i, x)``.

    Unlike `gpipe`, stages need NOT share an op body, parameter structure,
    or activation shape (the reference SectionWorker runs arbitrary
    per-device program sections — section_worker.cc:142; this is the
    compiled equivalent). The ppermute ring carries a flat buffer sized to
    the LARGEST stage boundary; each stage statically unflattens its input
    shape and re-pads its output, so uneven towers (embedding-heavy stage
    0, narrow head stage) still pipeline.

    xs : [n_micro, mb, ...]  microbatched stage-0 input (replicated)
    returns ys : [n_micro, mb_out, ...] last stage's outputs (replicated)

    Every stage body is compiled on every device but only the selected
    branch executes (lax.switch over the stage index), so per-device
    compute stays work-optimal; params are replicated. The homogeneous
    `gpipe` stacked-param path remains the memory-lean choice when stages
    do stack.

    shard_map runs with the varying-manual-axes checker OFF: jax 0.9.0's
    vma tracking mis-transposes lax.switch under scan+ppermute (observed:
    grads off by O(1) or NaN with the checker on, exact to 2e-7 against
    the sequential oracle with it off).
    """
    import numpy as np
    n_stages = mesh.shape[axis]
    if len(stage_fns) != n_stages or len(per_stage_params) != n_stages:
        raise ValueError(
            f"{len(stage_fns)} stage fns / {len(per_stage_params)} param "
            f"sets vs pp axis size {n_stages}")
    n_micro = xs.shape[0]
    total = n_micro + n_stages - 1

    # boundary shape chain (per microbatch), discovered abstractly
    shapes = [tuple(xs.shape[1:])]
    dtype = xs.dtype
    for i, (f, p) in enumerate(zip(stage_fns, per_stage_params)):
        a = jax.eval_shape(f, p, jax.ShapeDtypeStruct(shapes[-1], dtype))
        if a.dtype != dtype:
            raise ValueError(
                f"stage {i} output dtype {a.dtype} != ring dtype {dtype}")
        shapes.append(tuple(a.shape))
    sizes = [int(np.prod(s)) for s in shapes]
    buf_size = max(sizes)
    out_shape, out_size = shapes[-1], sizes[-1]

    def per_device(params_all, xs_local):
        sidx = lax.axis_index(axis)
        right = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def mk_branch(i):
            in_size, in_shape = sizes[i], shapes[i]

            def run(pall, bufv):
                x = bufv[:in_size].reshape(in_shape)
                y = stage_fns[i](pall[i], x).reshape(-1)
                return jnp.pad(y, (0, buf_size - y.size))
            return run

        branches = [mk_branch(i) for i in range(n_stages)]

        def tick(carry, t):
            inbuf, ys = carry
            mb = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, n_micro - 1), keepdims=False)
            mb_buf = jnp.pad(mb.reshape(-1), (0, buf_size - sizes[0]))
            x_buf = jnp.where(sidx == 0, mb_buf, inbuf)
            y_buf = lax.switch(sidx, branches, params_all, x_buf)
            oidx = t - (n_stages - 1)
            valid = jnp.logical_and(sidx == n_stages - 1, oidx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                ys, y_buf[:out_size], jnp.clip(oidx, 0, n_micro - 1), 0)
            ys = jnp.where(valid, upd, ys)
            nxt = lax.ppermute(y_buf, axis, right)
            return (nxt, ys), None

        init = (jnp.zeros((buf_size,), dtype),
                jnp.zeros((n_micro, out_size), dtype))
        (_, ys), _ = lax.scan(tick, init, jnp.arange(total))
        ys = lax.psum(jnp.where(sidx == n_stages - 1, ys,
                                jnp.zeros_like(ys)), axis)
        return ys

    pspec_params = jax.tree_util.tree_map(lambda x: P(),
                                          list(per_stage_params))
    try:  # vma checker off — see docstring (jax>=0.7 name, then legacy)
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(pspec_params, P()), out_specs=P(),
                       check_vma=False)
    except TypeError:
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(pspec_params, P()), out_specs=P(),
                       check_rep=False)
    ys = fn(list(per_stage_params), xs)
    return ys.reshape((n_micro,) + out_shape)


def gpipe_loss_fn(stage_fn, loss_fn):
    """Compose gpipe with a per-microbatch loss → mean scalar, for jax.grad.

    loss_fn(y, target_microbatch) -> scalar.  Targets shaped like xs'
    leading microbatch dim. Backward through the pipeline is automatic:
    jax.grad transposes the ppermute ring into the reverse schedule.
    """
    def fn(stacked_params, xs, targets, *, mesh, axis=PIPELINE_AXIS):
        ys = gpipe(stage_fn, stacked_params, xs, mesh=mesh, axis=axis)
        losses = jax.vmap(loss_fn)(ys, targets)
        return jnp.mean(losses)
    return fn
