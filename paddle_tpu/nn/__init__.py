"""paddle.nn 2.0-preview namespace (reference: python/paddle/nn/
__init__.py:18-37 — thin torch-style aliases over fluid ops/layers;
python/paddle/nn/functional/ re-exports the functional forms)."""
from __future__ import annotations

# layers (classes) — the dygraph module library
from ..fluid.dygraph.nn import (Conv2D, Conv3D, Pool2D, Linear, BatchNorm,
                               Dropout, Embedding, LayerNorm, GRUUnit,
                               InstanceNorm, PRelu, BilinearTensorProduct,
                               Conv2DTranspose, GroupNorm, SpectralNorm)
from ..fluid.dygraph.layers import Layer
from ..fluid.dygraph.container import Sequential, LayerList, ParameterList

# functional
from ..fluid import layers as _L
from . import functional

relu = _L.relu
sigmoid = _L.sigmoid
tanh = _L.tanh
softmax = _L.softmax
log_softmax = getattr(_L, "log_softmax", None)
elu = _L.elu
gelu = _L.gelu
leaky_relu = _L.leaky_relu
relu6 = _L.relu6
selu = _L.selu
hard_sigmoid = _L.hard_sigmoid
hard_swish = _L.hard_swish
swish = _L.swish
conv2d = _L.conv2d
conv3d = _L.conv3d
pool2d = _L.pool2d
pool3d = _L.pool3d
batch_norm = _L.batch_norm
layer_norm = _L.layer_norm
instance_norm = _L.instance_norm
group_norm = _L.group_norm
dropout = _L.dropout
embedding = _L.embedding
one_hot = _L.one_hot
cross_entropy = _L.cross_entropy
mse_loss = _L.mse_loss
nce = _L.nce
pad = _L.pad
pad2d = _L.pad2d
grid_sampler = _L.grid_sampler
pixel_shuffle = _L.pixel_shuffle
interpolate = getattr(_L, "image_resize", None)

# ---- activation / loss Layer classes (reference paddle/nn: thin class
# wrappers over the functional forms)
class ReLU(Layer):
    def forward(self, x):
        return _L.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return _L.sigmoid(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _L.softmax(x, axis=self._axis)


class _Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        if reduction not in ("mean", "sum", "none"):
            raise ValueError("reduction must be mean|sum|none")
        self.reduction = reduction

    def _reduce(self, loss):
        if self.reduction == "mean":
            return _L.reduce_mean(loss)
        if self.reduction == "sum":
            return _L.reduce_sum(loss)
        return loss


class CrossEntropyLoss(_Loss):
    """softmax + cross-entropy over logits (reference
    nn.CrossEntropyLoss)."""

    def forward(self, input, label):
        return self._reduce(
            _L.softmax_with_cross_entropy(input, label))


class MSELoss(_Loss):
    def forward(self, input, label):
        return self._reduce(_L.square_error_cost(input, label))


class L1Loss(_Loss):
    def forward(self, input, label):
        from . import functional as F
        return F.l1_loss(input, label, reduction=self.reduction)


class NLLLoss(_Loss):
    def forward(self, input, label):
        from . import functional as F
        return F.nll_loss(input, label, reduction=self.reduction)


class BCELoss(_Loss):
    def forward(self, input, label):
        from . import functional as F
        return F.bce_loss(input, label, reduction=self.reduction)


__all__ = [
    "Layer", "Sequential", "LayerList", "ParameterList", "Conv2D", "Conv3D",
    "Pool2D", "Linear", "BatchNorm", "Dropout", "Embedding", "LayerNorm",
    "GRUUnit", "InstanceNorm", "PRelu", "BilinearTensorProduct",
    "Conv2DTranspose", "GroupNorm", "SpectralNorm", "functional",
    "ReLU", "Sigmoid", "Softmax", "CrossEntropyLoss", "MSELoss", "L1Loss",
    "NLLLoss", "BCELoss",
]
