"""paddle.nn.functional (reference: python/paddle/nn/functional/ —
activation/common/conv/extension/norm/pooling/loss re-exports of the fluid
functional ops under torch-style names)."""
from __future__ import annotations

from ..fluid import layers as _L

# activations
relu = _L.relu
relu6 = _L.relu6
elu = _L.elu
selu = _L.selu
gelu = _L.gelu
sigmoid = _L.sigmoid
log_sigmoid = getattr(_L, "logsigmoid", None)
tanh = _L.tanh
tanhshrink = getattr(_L, "tanh_shrink", None)
softmax = _L.softmax
softplus = _L.softplus
softsign = _L.softsign
softshrink = getattr(_L, "softshrink", None)
hardshrink = getattr(_L, "hard_shrink", None)
hardsigmoid = _L.hard_sigmoid
hardswish = _L.hard_swish
swish = _L.swish
leaky_relu = _L.leaky_relu
prelu = _L.prelu
maxout = _L.maxout
thresholded_relu = getattr(_L, "thresholded_relu", None)
erf = _L.erf

# conv / pool
conv2d = _L.conv2d
conv3d = _L.conv3d
conv2d_transpose = _L.conv2d_transpose
conv3d_transpose = _L.conv3d_transpose
avg_pool2d = lambda x, **kw: _L.pool2d(x, pool_type="avg", **kw)
max_pool2d = lambda x, **kw: _L.pool2d(x, pool_type="max", **kw)
adaptive_avg_pool2d = lambda x, output_size, **kw: _L.adaptive_pool2d(
    x, output_size, pool_type="avg", **kw)
adaptive_max_pool2d = lambda x, output_size, **kw: _L.adaptive_pool2d(
    x, output_size, pool_type="max", **kw)

# norm
batch_norm = _L.batch_norm
layer_norm = _L.layer_norm
instance_norm = _L.instance_norm
group_norm = _L.group_norm
l2_normalize = _L.l2_normalize
normalize = _L.l2_normalize

# common
linear = _L.fc
dropout = _L.dropout
embedding = _L.embedding
one_hot = _L.one_hot
pad = _L.pad
pad2d = _L.pad2d
unfold = _L.unfold
interpolate = _L.image_resize
upsample = _L.image_resize
pixel_shuffle = _L.pixel_shuffle
grid_sample = _L.grid_sampler
affine_grid = _L.affine_grid
label_smooth = _L.label_smooth

# losses
cross_entropy = _L.cross_entropy
softmax_with_cross_entropy = _L.softmax_with_cross_entropy
mse_loss = _L.mse_loss
kl_div = _L.kldiv_loss


def l1_loss(input, label, reduction="mean", name=None):
    diff = _L.abs(_L.elementwise_sub(input, label))
    if reduction == "mean":
        return _L.reduce_mean(diff)
    if reduction == "sum":
        return _L.reduce_sum(diff)
    return diff


def _loss_op(op_type, ins, attrs=None, out_slot="Out"):
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper(op_type)
    dtype = next(v.dtype for vals in ins.values() for v in vals
                 if v is not None)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type=op_type, inputs=ins, outputs={out_slot: [out]},
                     attrs=attrs or {})
    return out


def nll_loss(input, label, weight=None, reduction="mean", name=None):
    ins = {"X": [input], "Label": [label]}
    if weight is not None:
        ins["Weight"] = [weight]
    return _loss_op("nll_loss", ins, {"reduction": reduction,
                                      "ignore_index": -100})


def bce_loss(input, label, reduction="mean", name=None):
    out = _loss_op("bce_loss", {"X": [input], "Label": [label]})
    if reduction == "mean":
        return _L.reduce_mean(out)
    if reduction == "sum":
        return _L.reduce_sum(out)
    return out


binary_cross_entropy = bce_loss
binary_cross_entropy_with_logits = \
    _L.sigmoid_cross_entropy_with_logits
margin_ranking_loss = _L.margin_rank_loss
smooth_l1_loss = getattr(_L, "smooth_l1", None)
ctc_loss = _L.warpctc
npair_loss = _L.npair_loss
square_error_cost = _L.square_error_cost
log_loss = _L.log_loss


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ..fluid import layers as _L
    return _L.log(_L.softmax(x, axis=axis))


def pool2d(x, **kw):
    from ..fluid import layers as _L
    return _L.pool2d(x, **kw)
