"""paddle.batch (reference: python/paddle/batch.py — wraps a sample reader
into a batched reader)."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """reader() yields samples → returns a reader yielding lists of
    ``batch_size`` samples (reference batch.py batch)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader
