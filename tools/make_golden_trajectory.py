"""Generate tests/fixtures/golden_mnist_trajectory.npz INDEPENDENTLY of
paddle_tpu: a pure-NumPy implementation of the MNIST-MLP smoke config
(BASELINE.md "loss-parity with reference CPU run" row; reference
tests/book/test_recognize_digits.py trains this exact shape) — fc(64,
relu) → fc(10, softmax) → cross_entropy mean, plain SGD. Same fixed
weights/data the fluid test builds via NumpyArrayInitializer, 10 steps,
per-step losses recorded in float64.

Regenerate with:
    python tools/make_golden_trajectory.py
"""
import os

import numpy as np

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures", "golden_mnist_trajectory.npz")

BATCH, D_IN, D_H, D_OUT, STEPS, LR = 32, 784, 64, 10, 10, 0.1


def init(seed=1234):
    r = np.random.RandomState(seed)
    return {
        "w1": (r.rand(D_IN, D_H) * 0.02 - 0.01).astype(np.float64),
        "b1": np.zeros(D_H, np.float64),
        "w2": (r.rand(D_H, D_OUT) * 0.02 - 0.01).astype(np.float64),
        "b2": np.zeros(D_OUT, np.float64),
        "X": r.rand(BATCH, D_IN).astype(np.float64),
        "Y": r.randint(0, D_OUT, (BATCH, 1)).astype(np.int64),
    }


def run(p):
    w1, b1, w2, b2 = (p[k].copy() for k in ("w1", "b1", "w2", "b2"))
    X, Y = p["X"], p["Y"]
    losses = []
    onehot = np.eye(D_OUT)[Y[:, 0]]
    for _ in range(STEPS):
        h_lin = X @ w1 + b1
        h = np.maximum(h_lin, 0.0)
        logits = h @ w2 + b2
        z = logits - logits.max(axis=1, keepdims=True)
        ez = np.exp(z)
        probs = ez / ez.sum(axis=1, keepdims=True)
        loss = float(np.mean(-np.log(
            probs[np.arange(BATCH), Y[:, 0]] + 0.0)))
        losses.append(loss)
        # backward (mean cross-entropy over softmax)
        dlogits = (probs - onehot) / BATCH
        dw2 = h.T @ dlogits
        db2 = dlogits.sum(0)
        dh = dlogits @ w2.T
        dh_lin = dh * (h_lin > 0.0)
        dw1 = X.T @ dh_lin
        db1 = dh_lin.sum(0)
        w1 -= LR * dw1
        b1 -= LR * db1
        w2 -= LR * dw2
        b2 -= LR * db2
    return np.asarray(losses, np.float64)


def main():
    p = init()
    losses = run(p)
    np.savez(OUT, losses=losses,
             **{k: p[k] for k in ("w1", "b1", "w2", "b2", "X", "Y")})
    print("wrote", OUT)
    print("losses:", np.round(losses, 6))


if __name__ == "__main__":
    main()
