"""Generate golden loss-trajectory fixtures INDEPENDENTLY of paddle_tpu
(reference role: the book tests' convergence contract, SURVEY §4.3 —
but checked numerically, step for step, not as an accuracy bar):

  mnist — pure-NumPy MLP: fc(64, relu) → fc(10, softmax) →
          cross_entropy mean, plain SGD, 10 steps (BASELINE.md
          "loss-parity with reference CPU run" row; reference
          tests/book/test_recognize_digits.py trains this shape).
  conv  — torch-float64 LeNet-tiny: conv2d(4, 5×5) + relu → maxpool2×2
          → fc softmax → cross_entropy mean, SGD, 10 steps. Pins the
          conv/pool/im2col grad paths.
  bert  — torch-float64 single transformer encoder layer (2-head
          attention, gelu FFN, two layer_norms, eps 1e-5) under an MSE
          loss, SGD, 8 steps. Pins the attention/layernorm/gelu paths.
  bert_adam — the same encoder under hand-rolled paddle-formula Adam
          (pow accumulators start at beta, eps scaled by sqrt(1-b2^t)).
          Pins the adam op and accumulator wiring.
  embedding — embedding (repeated in-batch ids) → mean pool → fc
          softmax → cross_entropy, SGD, 10 steps. Pins the gather /
          scatter-add sparse-lookup grad path.

torch (CPU) is an independent oracle: none of paddle_tpu's executor,
op registry, or JAX is involved in producing the fixtures.

Regenerate with:
    python tools/make_golden_trajectory.py [mnist|conv|bert|bert_adam|embedding|all]
"""
import os
import sys

import numpy as np

FIXDIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures")
OUT = os.path.join(FIXDIR, "golden_mnist_trajectory.npz")

BATCH, D_IN, D_H, D_OUT, STEPS, LR = 32, 784, 64, 10, 10, 0.1


def init(seed=1234):
    r = np.random.RandomState(seed)
    return {
        "w1": (r.rand(D_IN, D_H) * 0.02 - 0.01).astype(np.float64),
        "b1": np.zeros(D_H, np.float64),
        "w2": (r.rand(D_H, D_OUT) * 0.02 - 0.01).astype(np.float64),
        "b2": np.zeros(D_OUT, np.float64),
        "X": r.rand(BATCH, D_IN).astype(np.float64),
        "Y": r.randint(0, D_OUT, (BATCH, 1)).astype(np.int64),
    }


def run(p):
    w1, b1, w2, b2 = (p[k].copy() for k in ("w1", "b1", "w2", "b2"))
    X, Y = p["X"], p["Y"]
    losses = []
    onehot = np.eye(D_OUT)[Y[:, 0]]
    for _ in range(STEPS):
        h_lin = X @ w1 + b1
        h = np.maximum(h_lin, 0.0)
        logits = h @ w2 + b2
        z = logits - logits.max(axis=1, keepdims=True)
        ez = np.exp(z)
        probs = ez / ez.sum(axis=1, keepdims=True)
        loss = float(np.mean(-np.log(
            probs[np.arange(BATCH), Y[:, 0]] + 0.0)))
        losses.append(loss)
        # backward (mean cross-entropy over softmax)
        dlogits = (probs - onehot) / BATCH
        dw2 = h.T @ dlogits
        db2 = dlogits.sum(0)
        dh = dlogits @ w2.T
        dh_lin = dh * (h_lin > 0.0)
        dw1 = X.T @ dh_lin
        db1 = dh_lin.sum(0)
        w1 -= LR * dw1
        b1 -= LR * db1
        w2 -= LR * dw2
        b2 -= LR * db2
    return np.asarray(losses, np.float64)


def make_mnist():
    p = init()
    losses = run(p)
    np.savez(OUT, losses=losses,
             **{k: p[k] for k in ("w1", "b1", "w2", "b2", "X", "Y")})
    print("wrote", OUT)
    print("losses:", np.round(losses, 6))


# ------------------------------------------------------------------ conv
CONV = dict(B=16, C=4, K=5, IMG=14, CLS=10, STEPS=10, LR=0.1)


def conv_init(seed=4321):
    r = np.random.RandomState(seed)
    B, C, K, IMG, CLS = (CONV[k] for k in ("B", "C", "K", "IMG", "CLS"))
    pooled = ((IMG - K + 1) // 2) ** 2 * C
    return {
        "cw": (r.rand(C, 1, K, K) * 0.2 - 0.1).astype(np.float64),
        "cb": np.zeros(C, np.float64),
        "fw": (r.rand(pooled, CLS) * 0.02 - 0.01).astype(np.float64),
        "fb": np.zeros(CLS, np.float64),
        "X": r.rand(B, 1, IMG, IMG).astype(np.float64),
        "Y": r.randint(0, CLS, (B, 1)).astype(np.int64),
    }


def make_conv():
    import torch
    import torch.nn.functional as F
    p = conv_init()
    B, STEPS, LR = CONV["B"], CONV["STEPS"], CONV["LR"]
    cw = torch.tensor(p["cw"], requires_grad=True)
    cb = torch.tensor(p["cb"], requires_grad=True)
    fw = torch.tensor(p["fw"], requires_grad=True)
    fb = torch.tensor(p["fb"], requires_grad=True)
    X = torch.tensor(p["X"])
    yidx = torch.tensor(p["Y"][:, 0])
    losses = []
    for _ in range(STEPS):
        h = F.relu(F.conv2d(X, cw, cb))
        h = F.max_pool2d(h, 2, 2)
        logits = h.reshape(B, -1) @ fw + fb
        probs = F.softmax(logits, dim=1)
        loss = -torch.log(probs[torch.arange(B), yidx]).mean()
        losses.append(float(loss))
        for t in (cw, cb, fw, fb):
            t.grad = None
        loss.backward()
        with torch.no_grad():
            for t in (cw, cb, fw, fb):
                t -= LR * t.grad
    path = os.path.join(FIXDIR, "golden_lenet_trajectory.npz")
    np.savez(path, losses=np.asarray(losses, np.float64),
             **{k: p[k] for k in ("cw", "cb", "fw", "fb", "X", "Y")})
    print("wrote", path)
    print("losses:", np.round(losses, 6))


# ------------------------------------------------------------------ bert
ENC = dict(B=4, S=6, H=16, HEADS=2, FFN=32, STEPS=8, LR=0.05)


def enc_init(seed=777):
    r = np.random.RandomState(seed)
    B, S, H, FFN = (ENC[k] for k in ("B", "S", "H", "FFN"))

    def m(*shape, scale=0.2):
        return (r.rand(*shape) * 2 * scale - scale).astype(np.float64)

    return {
        "wq": m(H, H), "bq": np.zeros(H, np.float64),
        "wk": m(H, H), "bk": np.zeros(H, np.float64),
        "wv": m(H, H), "bv": np.zeros(H, np.float64),
        "wo": m(H, H), "bo": np.zeros(H, np.float64),
        "g1": np.ones(H, np.float64), "e1": np.zeros(H, np.float64),
        "w1": m(H, FFN), "b1": np.zeros(FFN, np.float64),
        "w2": m(FFN, H), "b2": np.zeros(H, np.float64),
        "g2": np.ones(H, np.float64), "e2": np.zeros(H, np.float64),
        "X": m(B, S, H, scale=1.0), "T": m(B, S, H, scale=1.0),
    }


ENC_NAMES = ("wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
             "g1", "e1", "w1", "b1", "w2", "b2", "g2", "e2")


def _enc_trajectory(update):
    """Run the encoder-layer model for ENC['STEPS'] steps, calling
    ``update(params_dict, step)`` UNDER torch.no_grad after each
    backward. Returns (init_dict, losses)."""
    import math

    import torch
    import torch.nn.functional as F
    p = enc_init()
    B, S, H, HEADS, STEPS = (ENC[k] for k in
                             ("B", "S", "H", "HEADS", "STEPS"))
    D = H // HEADS
    t = {k: torch.tensor(p[k], requires_grad=True) for k in ENC_NAMES}
    X, T = torch.tensor(p["X"]), torch.tensor(p["T"])

    def heads(x):  # [B,S,H] -> [B,HEADS,S,D]
        return x.reshape(B, S, HEADS, D).permute(0, 2, 1, 3)

    losses = []
    for step in range(STEPS):
        q, k, v = (heads(X @ t[f"w{n}"] + t[f"b{n}"]) for n in "qkv")
        scores = (q @ k.transpose(-1, -2)) / math.sqrt(D)
        ctx = F.softmax(scores, dim=-1) @ v
        ctx = ctx.permute(0, 2, 1, 3).reshape(B, S, H)
        attn = ctx @ t["wo"] + t["bo"]
        h1 = F.layer_norm(X + attn, (H,), t["g1"], t["e1"], eps=1e-5)
        f = F.gelu(h1 @ t["w1"] + t["b1"])
        f2 = f @ t["w2"] + t["b2"]
        out2 = F.layer_norm(h1 + f2, (H,), t["g2"], t["e2"], eps=1e-5)
        loss = ((out2 - T) ** 2).mean()
        losses.append(float(loss))
        for v_ in t.values():
            v_.grad = None
        loss.backward()
        with torch.no_grad():
            update(t, step)
    return p, losses


def _write_enc_fixture(name, p, losses):
    path = os.path.join(FIXDIR, name)
    np.savez(path, losses=np.asarray(losses, np.float64),
             X=p["X"], T=p["T"], **{k: p[k] for k in ENC_NAMES})
    print("wrote", path)
    print("losses:", np.round(losses, 6))


def make_bert():
    LR = ENC["LR"]

    def sgd(t, step):
        for v_ in t.values():
            v_ -= LR * v_.grad
    p, losses = _enc_trajectory(sgd)
    _write_enc_fixture("golden_encoder_trajectory.npz", p, losses)


ADAM = dict(LR=0.01, B1=0.9, B2=0.999, EPS=1e-8)


def make_bert_adam():
    """Same encoder model under Adam with the PADDLE update semantics
    (operators/optimizers/adam_op.h contract, mirrored by
    paddle_tpu/ops/optimizer_ops.py:46): pow accumulators START at
    beta (so step 1 corrects by 1-beta^1), lr_t = lr*sqrt(1-b2p)/(1-b1p),
    and epsilon scales by sqrt(1-b2p) inside the denominator — this
    differs from torch.optim.Adam's eps placement, so the update is
    hand-rolled on torch's float64 grads."""
    LR, B1, B2, EPS = ADAM["LR"], ADAM["B1"], ADAM["B2"], ADAM["EPS"]
    state = {}

    def adam(t, step):
        import torch
        for k, v_ in t.items():
            if k not in state:
                state[k] = [torch.zeros_like(v_), torch.zeros_like(v_)]
            m, v2 = state[k]
            g = v_.grad
            m.mul_(B1).add_(g, alpha=1 - B1)
            v2.mul_(B2).addcmul_(g, g, value=1 - B2)
            b1p, b2p = B1 ** (step + 1), B2 ** (step + 1)
            lr_t = LR * np.sqrt(1 - b2p) / (1 - b1p)
            v_ -= lr_t * m / (v2.sqrt() + EPS * np.sqrt(1 - b2p))
    p, losses = _enc_trajectory(adam)
    _write_enc_fixture("golden_encoder_adam_trajectory.npz", p, losses)


# ------------------------------------------------------------- embedding
EMB = dict(B=8, T=5, V=32, E=12, CLS=6, STEPS=10, LR=0.2)


def emb_init(seed=2468):
    r = np.random.RandomState(seed)
    B, T, V, E, CLS = (EMB[k] for k in ("B", "T", "V", "E", "CLS"))
    return {
        "ew": (r.rand(V, E) * 0.4 - 0.2).astype(np.float64),
        "fw": (r.rand(E, CLS) * 0.2 - 0.1).astype(np.float64),
        "fb": np.zeros(CLS, np.float64),
        # every id appears in the batch several times → the scatter-add
        # grad path accumulates colliding rows, the case worth pinning
        "IDS": r.randint(0, V, (B, T)).astype(np.int64),
        "Y": r.randint(0, CLS, (B, 1)).astype(np.int64),
    }


def make_embedding():
    """Sparse-lookup path oracle: embedding (lookup_table_v2) → mean
    pool over time → fc softmax → cross-entropy, SGD. Pins the
    gather fwd / scatter-add grad path (reference lookup_table_v2_op.cc
    + its _grad), the last numeric family without a golden fixture."""
    import torch
    import torch.nn.functional as F
    p = emb_init()
    B, STEPS, LR = EMB["B"], EMB["STEPS"], EMB["LR"]
    ew = torch.tensor(p["ew"], requires_grad=True)
    fw = torch.tensor(p["fw"], requires_grad=True)
    fb = torch.tensor(p["fb"], requires_grad=True)
    ids = torch.tensor(p["IDS"])
    yidx = torch.tensor(p["Y"][:, 0])
    losses = []
    for _ in range(STEPS):
        emb = F.embedding(ids, ew)             # [B, T, E] gather
        pooled = emb.mean(dim=1)               # [B, E]
        logits = pooled @ fw + fb
        probs = F.softmax(logits, dim=1)
        loss = -torch.log(probs[torch.arange(B), yidx]).mean()
        losses.append(float(loss))
        for t in (ew, fw, fb):
            t.grad = None
        loss.backward()
        with torch.no_grad():
            for t in (ew, fw, fb):
                t -= LR * t.grad
    path = os.path.join(FIXDIR, "golden_embedding_trajectory.npz")
    np.savez(path, losses=np.asarray(losses, np.float64),
             **{k: p[k] for k in ("ew", "fw", "fb", "IDS", "Y")})
    print("wrote", path)
    print("losses:", np.round(losses, 6))


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    kinds = ("mnist", "conv", "bert", "bert_adam", "embedding")
    if which not in kinds + ("all",):
        raise SystemExit(f"unknown fixture '{which}'; one of "
                         f"{'|'.join(kinds)}|all")
    if which in ("mnist", "all"):
        make_mnist()
    if which in ("conv", "all"):
        make_conv()
    if which in ("bert", "all"):
        make_bert()
    if which in ("bert_adam", "all"):
        make_bert_adam()
    if which in ("embedding", "all"):
        make_embedding()


if __name__ == "__main__":
    main()
